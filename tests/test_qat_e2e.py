"""QAT end-to-end: fake-quant training -> convert() -> deployable int8
artifact (VERDICT r4 missing #7).

Reference: python/paddle/quantization/{qat,ptq}.py + imperative quant
layers; observers per layer type via QuantConfig.add_type_config."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import static
from paddle_tpu.quantization import (
    QAT, PTQ, ChannelWiseAbsMaxObserver, FakeQuanterChannelWiseAbsMax,
    FakeQuanterWithAbsMaxObserver, Int8DeployedConv2D, Int8DeployedLinear,
    PercentileObserver, QuantConfig, quanter,
)


class _Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(64, 256)
        self.fc2 = nn.Linear(256, 4)

    def forward(self, x):
        return self.fc2(paddle.tanh(self.fc1(x)))


def _qat_config():
    cfg = QuantConfig(
        activation=quanter(FakeQuanterWithAbsMaxObserver, moving_rate=0.9),
        weight=quanter(FakeQuanterChannelWiseAbsMax, quant_bits=8),
    )
    return cfg


def test_qat_trains_converts_and_deploys(tmp_path):
    paddle.seed(0)
    model = _Net()
    q = QAT(_qat_config())
    qmodel = q.quantize(model, inplace=False)

    # the wrapped layers really fake-quantize per-channel
    from paddle_tpu.quantization import _QuantedWrapper

    wrappers = [m for m in qmodel.sublayers(True) if isinstance(m, _QuantedWrapper)]
    assert len(wrappers) == 2

    opt = paddle.optimizer.Adam(5e-3, parameters=qmodel.parameters())
    rng = np.random.default_rng(0)
    xv = rng.standard_normal((32, 64)).astype(np.float32)
    yv = rng.integers(0, 4, (32,)).astype(np.int64)
    ce = nn.CrossEntropyLoss()
    losses = []
    for _ in range(60):
        loss = ce(qmodel(paddle.to_tensor(xv)), paddle.to_tensor(yv))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss._value))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    # frozen fake-quant eval output == converted int8 output (same math)
    qmodel.eval()
    with paddle.no_grad():
        qat_eval = np.asarray(qmodel(paddle.to_tensor(xv))._value)
    deployed = q.convert(qmodel, inplace=False)
    linears = [m for m in deployed.sublayers(True)
               if isinstance(m, Int8DeployedLinear)]
    assert len(linears) == 2
    for lin in linears:
        assert str(lin.weight_int8._value.dtype) == "int8"
        assert lin.weight_scale._value.ndim == 1  # per-channel
    with paddle.no_grad():
        deployed_out = np.asarray(deployed(paddle.to_tensor(xv))._value)
    np.testing.assert_allclose(deployed_out, qat_eval, rtol=1e-4, atol=1e-4)

    # predictions survive quantization (trained under fake quant)
    float_acc = (qat_eval.argmax(-1) == yv).mean()
    int8_acc = (deployed_out.argmax(-1) == yv).mean()
    assert int8_acc >= float_acc - 0.05

    # deployable artifact: jit.save bakes the int8 weights; Predictor serves
    import paddle_tpu.jit as jit
    from paddle_tpu import inference

    path = str(tmp_path / "qat_int8")
    jit.save(deployed, path,
             input_spec=[static.InputSpec([32, 64], "float32", "x")])
    pred = inference.Predictor(path)
    (served,) = pred.run([xv])
    np.testing.assert_allclose(served, deployed_out, rtol=1e-5, atol=1e-5)

    # the artifact is visibly smaller than the float export
    fpath = str(tmp_path / "float_net")
    model.eval()
    jit.save(model, fpath,
             input_spec=[static.InputSpec([32, 64], "float32", "x")])
    assert os.path.getsize(path + ".pdmodel") < os.path.getsize(
        fpath + ".pdmodel") * 0.6


def test_per_type_observer_config_conv_and_linear():
    """Observers per layer TYPE (reference add_type_config): conv gets
    channel-wise weight scales over dim 0, linear over the last dim."""
    paddle.seed(1)

    class ConvNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.conv = nn.Conv2D(3, 8, 3, padding=1)
            self.fc = nn.Linear(8 * 4 * 4, 4)

        def forward(self, x):
            h = paddle.nn.functional.relu(self.conv(x))
            return self.fc(h.reshape([x.shape[0], -1]))

    cfg = QuantConfig()
    cfg.add_type_config(
        nn.Conv2D,
        activation=quanter(FakeQuanterWithAbsMaxObserver),
        weight=quanter(FakeQuanterChannelWiseAbsMax, quant_axis=0),
    )
    cfg.add_type_config(
        nn.Linear,
        activation=quanter(FakeQuanterWithAbsMaxObserver),
        weight=quanter(FakeQuanterChannelWiseAbsMax),
    )
    q = QAT(cfg)
    m = q.quantize(ConvNet(), inplace=False)
    x = paddle.to_tensor(
        np.random.default_rng(0).standard_normal((2, 3, 4, 4)).astype(np.float32))
    out = m(x)
    (out.sum()).backward()  # STE gradients flow
    m.eval()
    with paddle.no_grad():
        ref = np.asarray(m(x)._value)
    d = q.convert(m, inplace=False)
    convs = [s for s in d.sublayers(True) if isinstance(s, Int8DeployedConv2D)]
    lins = [s for s in d.sublayers(True) if isinstance(s, Int8DeployedLinear)]
    assert len(convs) == 1 and len(lins) == 1
    assert convs[0].weight_scale._value.shape == (8,)  # per out-channel
    with paddle.no_grad():
        got = np.asarray(d(x)._value)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_ptq_percentile_calibration_and_convert():
    paddle.seed(2)
    model = _Net()
    cfg = QuantConfig(
        activation=quanter(PercentileObserver, percentile=99.5),
        weight=quanter(ChannelWiseAbsMaxObserver),
    )
    p = PTQ(cfg)
    pm = p.quantize(model, inplace=False)
    rng = np.random.default_rng(3)
    for _ in range(8):  # calibration forwards
        pm(paddle.to_tensor(rng.standard_normal((16, 64)).astype(np.float32)))
    d = p.convert(pm, inplace=False)
    lins = [s for s in d.sublayers(True) if isinstance(s, Int8DeployedLinear)]
    assert len(lins) == 2
    xv = rng.standard_normal((8, 64)).astype(np.float32)
    with paddle.no_grad():
        ref = np.asarray(model(paddle.to_tensor(xv))._value)
        got = np.asarray(d(paddle.to_tensor(xv))._value)
    # int8 PTQ stays close to the float model
    assert np.abs(got - ref).max() < 0.1 * max(1.0, np.abs(ref).max())
