"""Kernel autotune cache + tile search (VERDICT r3 #3, #10).

Reference: paddle/cinn/auto_schedule/auto_tuner.h (measured-cost config
search) + paddle/phi/kernels/autotune/cache.h (per-(op, key) config cache).
"""

import json
import os
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops import autotune as at


@pytest.fixture()
def tmp_cache(tmp_path):
    """Fresh cache rooted in tmp_path under a synthetic device slug."""
    paddle.set_flags({"FLAGS_autotune_cache_dir": str(tmp_path)})
    at._CACHES.clear()
    yield tmp_path
    paddle.set_flags({"FLAGS_autotune_cache_dir": ""})
    at._CACHES.clear()


def test_cache_round_trip_and_persistence(tmp_cache):
    key = {"seq_q": 256, "seq_k": 256, "head_dim": 64, "dtype": "float32",
           "causal": True}
    assert at.lookup("flash_fwd", key, slug="testdev") is None
    at.record("flash_fwd", key, {"block_q": 64, "block_k": 128}, 1.5,
              slug="testdev")
    got = at.lookup("flash_fwd", key, slug="testdev")
    assert got == {"block_q": 64, "block_k": 128}
    # survives a cold reload
    at._CACHES.clear()
    got = at.lookup("flash_fwd", key, slug="testdev")
    assert got == {"block_q": 64, "block_k": 128}
    raw = json.load(open(os.path.join(tmp_cache, "testdev.json")))
    assert raw["flash_fwd"]
    # disabled via flag
    paddle.set_flags({"FLAGS_use_autotune_cache": False})
    try:
        assert at.lookup("flash_fwd", key, slug="testdev") is None
    finally:
        paddle.set_flags({"FLAGS_use_autotune_cache": True})


def test_tune_kernel_picks_fastest_and_skips_invalid(tmp_cache):
    costs = {16: 3.0, 32: 1.0, 64: 2.0}

    def build(cfg):
        if cfg["b"] == 8:  # invalid candidate: build explodes
            raise ValueError("bad tile")
        return lambda: cfg["b"]

    def timer(fn, args):
        return costs[fn()]

    cfg, ms = at.tune_kernel(
        "k", {"s": 1}, build,
        [{"b": 8}, {"b": 16}, {"b": 32}, {"b": 64}],
        (), timer=timer, slug="testdev")
    assert cfg == {"b": 32} and ms == 1.0
    assert at.lookup("k", {"s": 1}, slug="testdev") == {"b": 32}


def test_tune_kernel_all_invalid_is_loud(tmp_cache):
    def build(cfg):
        raise ValueError("nope")

    with pytest.raises(RuntimeError, match="no valid candidate"):
        at.tune_kernel("k2", {"s": 1}, build, [{"b": 1}], (),
                       timer=lambda f, a: 0.0, slug="testdev")


def test_validate_flash_tile_vmem_budget_v5p_geometry():
    # fine at training shapes
    assert at.validate_flash_tile(128, 128, 2048, 2048, 128) is None
    # long-context K/V residency blows the 16 MiB budget -> loud reason
    reason = at.validate_flash_tile(128, 128, 32768, 32768, 128)
    assert reason is not None and "VMEM" in reason
    # misaligned / non-dividing tiles
    assert "multiple of 8" in at.validate_flash_tile(12, 128, 256, 256, 64)
    assert "does not divide" in at.validate_flash_tile(128, 96, 256, 256, 64)


def test_block_sizes_precedence_flags_cache_default(tmp_cache):
    from paddle_tpu.ops.flash_attention import _block_sizes

    slug = at.device_kind_slug()
    # 3. default
    assert _block_sizes(256, 256, 64, np.float32, True) == (128, 128)
    # 2. cache hit
    at.record("flash_fwd", {"seq_q": 256, "seq_k": 256, "head_dim": 64,
                            "dtype": "float32", "causal": True},
              {"block_q": 64, "block_k": 64}, 1.0, slug=slug)
    assert _block_sizes(256, 256, 64, np.float32, True) == (64, 64)
    # 1. explicit flag overrides the cache
    paddle.set_flags({"FLAGS_flash_block_q": 32, "FLAGS_flash_block_k": 32})
    try:
        assert _block_sizes(256, 256, 64, np.float32, True) == (32, 32)
        # invalid flag: loud warning, falls back to the cache entry
        paddle.set_flags({"FLAGS_flash_block_q": 100})  # not a multiple of 8
        with pytest.warns(UserWarning, match="invalid"):
            assert _block_sizes(256, 256, 64, np.float32, True) == (64, 64)
    finally:
        paddle.set_flags({"FLAGS_flash_block_q": 0, "FLAGS_flash_block_k": 0})
    # invalid CACHED tile: loud warning, 128 default
    at.record("flash_fwd", {"seq_q": 512, "seq_k": 512, "head_dim": 64,
                            "dtype": "float32", "causal": False},
              {"block_q": 100, "block_k": 128}, 1.0, slug=slug)
    with pytest.warns(UserWarning, match="cached tile"):
        assert _block_sizes(512, 512, 64, np.float32, False) == (128, 128)


def test_fused_norm_and_swiglu_consult_cache(tmp_cache):
    from paddle_tpu.ops.fused_norm import _rows_block

    slug = at.device_kind_slug()
    assert _rows_block(4096, 4096, np.float32) == 256  # analytic default
    at.record("rms_rows", {"rows": 4096, "hidden": 4096, "dtype": "float32"},
              {"rows_block": 64}, 1.0, slug=slug)
    assert _rows_block(4096, 4096, np.float32) == 64
    # swiglu: cached tiles reach the kernel grid and numerics hold
    import jax.numpy as jnp

    from paddle_tpu.ops.swiglu import _swiglu_apply

    at.record("swiglu", {"rows": 8, "cols": 256, "dtype": "float32"},
              {"rows_block": 4, "cols_block": 128}, 1.0, slug=slug)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 256)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(8, 256)).astype(np.float32))
    out = _swiglu_apply(x, y)
    ref = np.asarray(x) * (1 / (1 + np.exp(-np.asarray(x)))) * np.asarray(y)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)


def test_tuner_end_to_end_with_fake_timer(tmp_cache):
    """tune_swiglu drives the real candidate space and kernel builder."""
    calls = []

    def timer(fn, args):
        calls.append(1)
        return float(len(calls))  # first valid candidate wins

    cfg, ms = at.tune_swiglu(rows=8, cols=256, dtype="float32",
                             timer=timer, slug="testdev")
    assert ms == 1.0 and cfg["cols_block"] in (128, 256)
    assert at.lookup("swiglu", {"rows": 8, "cols": 256, "dtype": "float32"},
                     slug="testdev") == cfg


def test_seeded_v5e_cache_is_well_formed():
    path = os.path.join(os.path.dirname(at.__file__), "tuned", "tpu_v5_lite.json")
    data = json.load(open(path))
    for key, entry in data["flash_fwd"].items():
        cfg = entry["config"]
        dims = dict(kv.split("=") for kv in key.split("|"))
        assert at.validate_flash_tile(
            cfg["block_q"], cfg["block_k"],
            int(dims["seq_q"]), int(dims["seq_k"]), int(dims["head_dim"])) is None


def test_v5p_readiness_geometry_and_peaks(tmp_cache):
    """VERDICT r3 #10: tile configs validated for v5p geometry, per-device-
    kind caches keyed by slug, peak table knows v5p, and no candidate that
    busts the VMEM budget is ever proposed."""
    from paddle_tpu.device.peaks import device_peak_tflops

    assert device_peak_tflops("TPU v5p", "tpu") == 459.0
    assert device_peak_tflops("TPU v5 lite", "tpu") == 197.0

    # candidates at training shapes are all VMEM-valid
    for seq in (2048, 4096):
        cands = at.flash_candidates(seq, seq, 128)
        assert cands, seq
        for c in cands:
            assert at.validate_flash_tile(
                c["block_q"], c["block_k"], seq, seq, 128) is None
    # beyond ~8k the whole-K/V-resident kernel cannot fit ANY tile in the
    # 16 MiB VMEM budget: the candidate space is EMPTY rather than silently
    # proposing an invalid tile (ring attention is the long-context path)
    assert at.flash_candidates(8192, 8192, 128) == []
    assert at.flash_candidates(32768, 32768, 128) == []

    # a v5p cache is consulted independently of the v5e cache
    key = {"seq_q": 4096, "seq_k": 4096, "head_dim": 128,
           "dtype": "bfloat16", "causal": True}
    at.record("flash_fwd", key, {"block_q": 256, "block_k": 128}, 1.0,
              slug="tpu_v5p")
    assert at.lookup("flash_fwd", key, slug="tpu_v5p") == {"block_q": 256, "block_k": 128}
    assert at.lookup("flash_fwd", key, slug="tpu_v5_lite") != {"block_q": 256, "block_k": 128}


def _tune_retry(search, attempts=3):
    """Run a tune_* driver, absorbing load-induced degenerate timings.

    Under parallel tier-1 load (run_tier1 --jobs 6) scheduler preemption
    between the back-to-back `inner` / `2*inner` batches can make every
    timing difference nonpositive, and _time_fn then refuses to record a
    winner (RuntimeError "every timing sample was degenerate") — correct
    tuner behavior, but this test is about END-TO-END candidate
    execution, not timing quality, so the whole search retries."""
    for i in range(attempts):
        try:
            return search()
        except RuntimeError as e:
            if "degenerate" not in str(e) or i == attempts - 1:
                raise


def test_tune_drivers_execute_real_kernels(tmp_cache):
    """The tune_* drivers must build AND RUN their kernels end-to-end.

    Regression: the ops package exports *functions* named flash_attention /
    swiglu that shadow the submodule attributes, so `from paddle_tpu.ops
    import flash_attention as fa` bound the function and every candidate
    died with AttributeError on-chip.  The fake-timer test never called the
    built fn, so only a real execution catches this class.
    """
    # inner=4 (not 1): a 4-vs-8 dispatch difference keeps a measurable
    # signal above scheduler jitter when six test jobs share the host
    cfg, ms = _tune_retry(lambda: at.tune_flash(
        batch=1, num_heads=1, seq=128, head_dim=8,
        dtype="float32", slug="testdev", iters=1, inner=4))
    # strictly above the degenerate-sample floor: a clamped/failed timing
    # must not satisfy this (1e-4 is _time_fn's failed-sample sentinel)
    assert cfg["block_q"] in (64, 128) and ms > 1e-4
    cfg, _ = _tune_retry(lambda: at.tune_fused_norm(
        rows=16, hidden=128, dtype="float32",
        slug="testdev", iters=1, inner=4))
    assert 16 % cfg["rows_block"] == 0
    cfg, _ = _tune_retry(lambda: at.tune_swiglu(
        rows=64, cols=128, dtype="float32",
        slug="testdev", iters=1, inner=4))
    assert 64 % cfg["rows_block"] == 0 and 128 % cfg["cols_block"] == 0


@pytest.fixture()
def fake_seed_dir(tmp_path, monkeypatch):
    """Redirect AutotuneCache.seed_path into a tmp dir so precedence tests
    never touch the installed package's ops/tuned/ (read-only on a
    site-packages install)."""
    d = tmp_path / "fake_seed"
    d.mkdir()
    monkeypatch.setattr(
        at.AutotuneCache, "seed_path",
        property(lambda self: str(d / f"{self.slug}.json")))
    at._CACHES.clear()
    yield d
    at._CACHES.clear()


def _write_seed(seed_dir, slug, data):
    """Plant a synthetic checked-in seed cache for `slug` in the fake dir."""
    path = os.path.join(str(seed_dir), f"{slug}.json")
    with open(path, "w") as f:
        json.dump(data, f)
    return path


def test_seed_vs_runtime_precedence_on_reload(tmp_cache, fake_seed_dir):
    """A runtime-tuned entry for a key PRESENT in the seed must win on a
    cold reload, while seed keys the runtime never touched must keep
    following the (possibly updated) seed — the runtime file may not
    fossilize a copy of the seed (regression: save() used to dump the
    whole seed-merged table into FLAGS_autotune_cache_dir, so a later
    seed update was silently shadowed by the stale copy)."""
    slug = "seeddev"
    k1, k2 = {"s": 1}, {"s": 2}
    seed_path = _write_seed(fake_seed_dir, slug, {"k": {
        at._key_str(k1): {"config": {"b": 1}, "ms": 1.0},
        at._key_str(k2): {"config": {"b": 2}, "ms": 1.0},
    }})
    try:
        at._CACHES.clear()
        assert at.lookup("k", k1, slug=slug) == {"b": 1}  # seed serves
        at.record("k", k1, {"b": 99}, 0.5, slug=slug)     # runtime retune
        at._CACHES.clear()
        assert at.lookup("k", k1, slug=slug) == {"b": 99}  # runtime wins
        assert at.lookup("k", k2, slug=slug) == {"b": 2}
        # the runtime file holds ONLY the runtime delta
        runtime = json.load(open(os.path.join(str(tmp_cache), f"{slug}.json")))
        assert at._key_str(k2) not in runtime.get("k", {})
        # simulate a package seed update for the untouched key
        _write_seed(fake_seed_dir, slug, {"k": {
            at._key_str(k1): {"config": {"b": 1}, "ms": 1.0},
            at._key_str(k2): {"config": {"b": 22}, "ms": 1.0},
        }})
        at._CACHES.clear()
        assert at.lookup("k", k2, slug=slug) == {"b": 22}  # update visible
        assert at.lookup("k", k1, slug=slug) == {"b": 99}  # runtime still wins
    finally:
        at._CACHES.clear()


def test_unwritable_cache_dir_falls_back_to_user_cache(tmp_cache, monkeypatch):
    """FLAGS_autotune_cache_dir pointing somewhere uncreatable (parent is a
    regular file — even root cannot mkdir through it) must fall back to
    the ~/.cache user path, and the entry must survive a cold reload while
    the flag still points at the bad dir.  user_path is monkeypatched into
    the pytest tmp dir so the test never touches the real home."""
    slug = "fallbackdev"
    blocker = os.path.join(str(tmp_cache), "blocker")
    with open(blocker, "w") as f:
        f.write("x")
    fake_home = tmp_cache / "fake_home_cache"
    monkeypatch.setattr(
        at.AutotuneCache, "user_path",
        property(lambda self: str(fake_home / f"{self.slug}.json")))
    user_path = str(fake_home / f"{slug}.json")
    paddle.set_flags(
        {"FLAGS_autotune_cache_dir": os.path.join(blocker, "sub")})
    at._CACHES.clear()
    try:
        c = at.cache(slug)
        c.put("k", {"s": 1}, {"b": 7}, 0.1)
        assert c.save() == user_path
        assert os.path.exists(user_path)
        at._CACHES.clear()
        assert at.lookup("k", {"s": 1}, slug=slug) == {"b": 7}
    finally:
        at._CACHES.clear()


def test_cost_model_table_keys_by_name_and_shape():
    """OpCostModel.load()/save() round-trips per-shape entries: two shapes
    of one op must not overwrite each other (regression: the table was
    keyed by bare name, so the docstring's round-trip contract silently
    kept only the last-measured shape)."""
    import jax.numpy as jnp

    from paddle_tpu.cost_model import OpCostModel

    m = OpCostModel()
    small = jnp.ones((8, 8), jnp.float32)
    big = jnp.ones((32, 32), jnp.float32)
    t_small = m.measure("mm", lambda a: a @ a, small, iters=1, warmup=0)
    t_big = m.measure("mm", lambda a: a @ a, big, iters=1, warmup=0)
    assert len(m.table) == 2  # both shapes present
    k_small = m.table_key("mm", (small,))
    k_big = m.table_key("mm", (big,))
    assert m.query(k_small) == t_small and m.query(k_big) == t_big
    # bare-name query on an ambiguous op is loud, not arbitrary
    with pytest.raises(KeyError, match="shape"):
        m.query("mm")
    assert m.query("mm", default=0.5) == 0.5
    # single-shape ops keep resolving by bare name (back-compat)
    t1 = m.measure("tanh", jnp.tanh, small, iters=1, warmup=0)
    assert m.query("tanh") == t1


def test_cost_model_round_trip_preserves_shape_entries(tmp_path):
    import jax.numpy as jnp

    from paddle_tpu.cost_model import OpCostModel

    m = OpCostModel()
    a = jnp.ones((8, 4), jnp.float32)
    b = jnp.ones((16, 4), jnp.float32)
    m.measure("sum", lambda v: v.sum(), a, iters=1, warmup=0)
    m.measure("sum", lambda v: v.sum(), b, iters=1, warmup=0)
    p = tmp_path / "table.json"
    m.save(str(p))
    m2 = OpCostModel.load(str(p))
    assert m2.table == m.table and len(m2.table) == 2


def test_validate_tile_generic_budget():
    """The generalized VMEM check shared by the kernel validators and the
    schedule searcher's candidate prune."""
    assert at.validate_tile(1024) is None
    reason = at.validate_tile(at._VMEM_BUDGET + 1)
    assert reason is not None and "VMEM" in reason
    assert at.validate_tile(2048, budget=1024) is not None
    # flash validator routes its VMEM tier through the shared check
    r = at.validate_flash_tile(1024, 1024, 8192, 8192, 256)
    assert r is not None and "VMEM" in r


def test_prefix_era_runtime_dump_is_healed_on_load(tmp_cache, fake_seed_dir):
    """A runtime cache file written by the PRE-fix save() (an UNMARKED full
    copy of the seed-merged table) must never shadow a later seed update:
    once the seed changes, a stale copy is value-indistinguishable from a
    genuine retune, so unmarked files keep only keys the seed lacks
    (seeded keys re-tune once).  Post-fix files carry the runtime marker
    and keep the runtime-wins contract."""
    slug = "healdev"
    k1, k2, k3 = {"s": 1}, {"s": 2}, {"s": 3}
    seed_entries = {
        at._key_str(k1): {"config": {"b": 1}, "ms": 1.0},
        at._key_str(k2): {"config": {"b": 2}, "ms": 1.0},
    }
    seed_path = _write_seed(fake_seed_dir, slug, {"k": dict(seed_entries)})
    try:
        # pre-fix era dump: whole seed copied + a retune of k1 + a key the
        # seed never had (k3) — NO runtime marker
        stale = {"k": dict(seed_entries)}
        stale["k"][at._key_str(k1)] = {"config": {"b": 99}, "ms": 0.5}
        stale["k"][at._key_str(k3)] = {"config": {"b": 3}, "ms": 0.5}
        with open(os.path.join(str(tmp_cache), f"{slug}.json"), "w") as f:
            json.dump(stale, f)
        # seed update for the never-retuned key
        _write_seed(fake_seed_dir, slug, {"k": {
            at._key_str(k1): {"config": {"b": 1}, "ms": 1.0},
            at._key_str(k2): {"config": {"b": 22}, "ms": 1.0},
        }})
        at._CACHES.clear()
        assert at.lookup("k", k2, slug=slug) == {"b": 22}  # update visible
        assert at.lookup("k", k3, slug=slug) == {"b": 3}   # unseeded key kept
        assert at.lookup("k", k1, slug=slug) == {"b": 1}   # one-time retune cost
        # a fresh retune writes a MARKED file whose entries win on reload
        at.record("k", k1, {"b": 100}, 0.4, slug=slug)
        raw = json.load(open(os.path.join(str(tmp_cache), f"{slug}.json")))
        assert raw.get(at._RUNTIME_MARKER) == 1
        assert at._key_str(k2) not in raw["k"]  # runtime delta only
        at._CACHES.clear()
        assert at.lookup("k", k1, slug=slug) == {"b": 100}
    finally:
        at._CACHES.clear()
