"""Kernel autotune cache + tile search (VERDICT r3 #3, #10).

Reference: paddle/cinn/auto_schedule/auto_tuner.h (measured-cost config
search) + paddle/phi/kernels/autotune/cache.h (per-(op, key) config cache).
"""

import json
import os
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops import autotune as at


@pytest.fixture()
def tmp_cache(tmp_path):
    """Fresh cache rooted in tmp_path under a synthetic device slug."""
    paddle.set_flags({"FLAGS_autotune_cache_dir": str(tmp_path)})
    at._CACHES.clear()
    yield tmp_path
    paddle.set_flags({"FLAGS_autotune_cache_dir": ""})
    at._CACHES.clear()


def test_cache_round_trip_and_persistence(tmp_cache):
    key = {"seq_q": 256, "seq_k": 256, "head_dim": 64, "dtype": "float32",
           "causal": True}
    assert at.lookup("flash_fwd", key, slug="testdev") is None
    at.record("flash_fwd", key, {"block_q": 64, "block_k": 128}, 1.5,
              slug="testdev")
    got = at.lookup("flash_fwd", key, slug="testdev")
    assert got == {"block_q": 64, "block_k": 128}
    # survives a cold reload
    at._CACHES.clear()
    got = at.lookup("flash_fwd", key, slug="testdev")
    assert got == {"block_q": 64, "block_k": 128}
    raw = json.load(open(os.path.join(tmp_cache, "testdev.json")))
    assert raw["flash_fwd"]
    # disabled via flag
    paddle.set_flags({"FLAGS_use_autotune_cache": False})
    try:
        assert at.lookup("flash_fwd", key, slug="testdev") is None
    finally:
        paddle.set_flags({"FLAGS_use_autotune_cache": True})


def test_tune_kernel_picks_fastest_and_skips_invalid(tmp_cache):
    costs = {16: 3.0, 32: 1.0, 64: 2.0}

    def build(cfg):
        if cfg["b"] == 8:  # invalid candidate: build explodes
            raise ValueError("bad tile")
        return lambda: cfg["b"]

    def timer(fn, args):
        return costs[fn()]

    cfg, ms = at.tune_kernel(
        "k", {"s": 1}, build,
        [{"b": 8}, {"b": 16}, {"b": 32}, {"b": 64}],
        (), timer=timer, slug="testdev")
    assert cfg == {"b": 32} and ms == 1.0
    assert at.lookup("k", {"s": 1}, slug="testdev") == {"b": 32}


def test_tune_kernel_all_invalid_is_loud(tmp_cache):
    def build(cfg):
        raise ValueError("nope")

    with pytest.raises(RuntimeError, match="no valid candidate"):
        at.tune_kernel("k2", {"s": 1}, build, [{"b": 1}], (),
                       timer=lambda f, a: 0.0, slug="testdev")


def test_validate_flash_tile_vmem_budget_v5p_geometry():
    # fine at training shapes
    assert at.validate_flash_tile(128, 128, 2048, 2048, 128) is None
    # long-context K/V residency blows the 16 MiB budget -> loud reason
    reason = at.validate_flash_tile(128, 128, 32768, 32768, 128)
    assert reason is not None and "VMEM" in reason
    # misaligned / non-dividing tiles
    assert "multiple of 8" in at.validate_flash_tile(12, 128, 256, 256, 64)
    assert "does not divide" in at.validate_flash_tile(128, 96, 256, 256, 64)


def test_block_sizes_precedence_flags_cache_default(tmp_cache):
    from paddle_tpu.ops.flash_attention import _block_sizes

    slug = at.device_kind_slug()
    # 3. default
    assert _block_sizes(256, 256, 64, np.float32, True) == (128, 128)
    # 2. cache hit
    at.record("flash_fwd", {"seq_q": 256, "seq_k": 256, "head_dim": 64,
                            "dtype": "float32", "causal": True},
              {"block_q": 64, "block_k": 64}, 1.0, slug=slug)
    assert _block_sizes(256, 256, 64, np.float32, True) == (64, 64)
    # 1. explicit flag overrides the cache
    paddle.set_flags({"FLAGS_flash_block_q": 32, "FLAGS_flash_block_k": 32})
    try:
        assert _block_sizes(256, 256, 64, np.float32, True) == (32, 32)
        # invalid flag: loud warning, falls back to the cache entry
        paddle.set_flags({"FLAGS_flash_block_q": 100})  # not a multiple of 8
        with pytest.warns(UserWarning, match="invalid"):
            assert _block_sizes(256, 256, 64, np.float32, True) == (64, 64)
    finally:
        paddle.set_flags({"FLAGS_flash_block_q": 0, "FLAGS_flash_block_k": 0})
    # invalid CACHED tile: loud warning, 128 default
    at.record("flash_fwd", {"seq_q": 512, "seq_k": 512, "head_dim": 64,
                            "dtype": "float32", "causal": False},
              {"block_q": 100, "block_k": 128}, 1.0, slug=slug)
    with pytest.warns(UserWarning, match="cached tile"):
        assert _block_sizes(512, 512, 64, np.float32, False) == (128, 128)


def test_fused_norm_and_swiglu_consult_cache(tmp_cache):
    from paddle_tpu.ops.fused_norm import _rows_block

    slug = at.device_kind_slug()
    assert _rows_block(4096, 4096, np.float32) == 256  # analytic default
    at.record("rms_rows", {"rows": 4096, "hidden": 4096, "dtype": "float32"},
              {"rows_block": 64}, 1.0, slug=slug)
    assert _rows_block(4096, 4096, np.float32) == 64
    # swiglu: cached tiles reach the kernel grid and numerics hold
    import jax.numpy as jnp

    from paddle_tpu.ops.swiglu import _swiglu_apply

    at.record("swiglu", {"rows": 8, "cols": 256, "dtype": "float32"},
              {"rows_block": 4, "cols_block": 128}, 1.0, slug=slug)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 256)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(8, 256)).astype(np.float32))
    out = _swiglu_apply(x, y)
    ref = np.asarray(x) * (1 / (1 + np.exp(-np.asarray(x)))) * np.asarray(y)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)


def test_tuner_end_to_end_with_fake_timer(tmp_cache):
    """tune_swiglu drives the real candidate space and kernel builder."""
    calls = []

    def timer(fn, args):
        calls.append(1)
        return float(len(calls))  # first valid candidate wins

    cfg, ms = at.tune_swiglu(rows=8, cols=256, dtype="float32",
                             timer=timer, slug="testdev")
    assert ms == 1.0 and cfg["cols_block"] in (128, 256)
    assert at.lookup("swiglu", {"rows": 8, "cols": 256, "dtype": "float32"},
                     slug="testdev") == cfg


def test_seeded_v5e_cache_is_well_formed():
    path = os.path.join(os.path.dirname(at.__file__), "tuned", "tpu_v5_lite.json")
    data = json.load(open(path))
    for key, entry in data["flash_fwd"].items():
        cfg = entry["config"]
        dims = dict(kv.split("=") for kv in key.split("|"))
        assert at.validate_flash_tile(
            cfg["block_q"], cfg["block_k"],
            int(dims["seq_q"]), int(dims["seq_k"]), int(dims["head_dim"])) is None


def test_v5p_readiness_geometry_and_peaks(tmp_cache):
    """VERDICT r3 #10: tile configs validated for v5p geometry, per-device-
    kind caches keyed by slug, peak table knows v5p, and no candidate that
    busts the VMEM budget is ever proposed."""
    from paddle_tpu.device.peaks import device_peak_tflops

    assert device_peak_tflops("TPU v5p", "tpu") == 459.0
    assert device_peak_tflops("TPU v5 lite", "tpu") == 197.0

    # candidates at training shapes are all VMEM-valid
    for seq in (2048, 4096):
        cands = at.flash_candidates(seq, seq, 128)
        assert cands, seq
        for c in cands:
            assert at.validate_flash_tile(
                c["block_q"], c["block_k"], seq, seq, 128) is None
    # beyond ~8k the whole-K/V-resident kernel cannot fit ANY tile in the
    # 16 MiB VMEM budget: the candidate space is EMPTY rather than silently
    # proposing an invalid tile (ring attention is the long-context path)
    assert at.flash_candidates(8192, 8192, 128) == []
    assert at.flash_candidates(32768, 32768, 128) == []

    # a v5p cache is consulted independently of the v5e cache
    key = {"seq_q": 4096, "seq_k": 4096, "head_dim": 128,
           "dtype": "bfloat16", "causal": True}
    at.record("flash_fwd", key, {"block_q": 256, "block_k": 128}, 1.0,
              slug="tpu_v5p")
    assert at.lookup("flash_fwd", key, slug="tpu_v5p") == {"block_q": 256, "block_k": 128}
    assert at.lookup("flash_fwd", key, slug="tpu_v5_lite") != {"block_q": 256, "block_k": 128}


def test_tune_drivers_execute_real_kernels(tmp_cache):
    """The tune_* drivers must build AND RUN their kernels end-to-end.

    Regression: the ops package exports *functions* named flash_attention /
    swiglu that shadow the submodule attributes, so `from paddle_tpu.ops
    import flash_attention as fa` bound the function and every candidate
    died with AttributeError on-chip.  The fake-timer test never called the
    built fn, so only a real execution catches this class.
    """
    cfg, ms = at.tune_flash(batch=1, num_heads=1, seq=128, head_dim=8,
                            dtype="float32", slug="testdev", iters=1, inner=1)
    # strictly above the degenerate-sample floor: a clamped/failed timing
    # must not satisfy this (1e-4 is _time_fn's failed-sample sentinel)
    assert cfg["block_q"] in (64, 128) and ms > 1e-4
    cfg, _ = at.tune_fused_norm(rows=16, hidden=128, dtype="float32",
                                slug="testdev", iters=1, inner=1)
    assert 16 % cfg["rows_block"] == 0
    cfg, _ = at.tune_swiglu(rows=64, cols=128, dtype="float32",
                            slug="testdev", iters=1, inner=1)
    assert 64 % cfg["rows_block"] == 0 and 128 % cfg["cols_block"] == 0
