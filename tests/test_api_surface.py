"""Top-level API surface parity + numerics of the round-2 closure ops.

The reference exports 387 names from python/paddle/__init__.py; every one
must resolve on paddle_tpu.  Plus NumPy-reference checks for the ops added
to close the gap (unflatten, index_fill, diagonal_scatter, select_scatter,
pdist, add_n, reverse) and the framework defaults surface.
"""

import re

import numpy as np
import pytest

import paddle_tpu as paddle


def test_no_dead_flags():
    """Every define_flag() name must be read back via flag() somewhere in
    the package.  FLAGS_eager_op_jit sat defined-but-unread for five rounds
    before the dispatch cache wired it; this lint stops flags rotting
    silently again."""
    import pathlib

    pkg = pathlib.Path(paddle.__file__).parent
    sources = [p.read_text() for p in pkg.rglob("*.py")]
    defined = set()
    reads = set()
    for src in sources:
        for m in re.finditer(r"define_flag\(\s*['\"]([A-Za-z0-9_]+)['\"]", src):
            name = m.group(1)
            defined.add(name if name.startswith("FLAGS_") else "FLAGS_" + name)
        # flag("...") reads, excluding define_flag/get_flags/set_flags
        for m in re.finditer(r"(?<![_A-Za-z])flag\(\s*['\"]([A-Za-z0-9_]+)['\"]", src):
            name = m.group(1)
            reads.add(name if name.startswith("FLAGS_") else "FLAGS_" + name)
    assert defined, "flag registry scan found nothing"
    dead = sorted(defined - reads)
    assert not dead, f"dead flags (defined but never read via flag()): {dead}"


def test_rewrite_pattern_op_types_resolve_in_registry():
    """Every op type the static rewrite patterns reference must resolve in
    the op registry (framework.op_registry.resolve_op_type): rename an op
    and a pattern silently stops matching — this lint (plus the IR
    verifier's unknown-op-type check) turns that into a failure."""
    import inspect

    import paddle_tpu.static.rewrite as rewrite
    from paddle_tpu.framework.op_registry import resolve_op_type
    from paddle_tpu.static.rewrite import RewritePattern

    referenced = set(rewrite._ELEMENTWISE)
    for obj in vars(rewrite).values():
        if (isinstance(obj, type) and issubclass(obj, RewritePattern)
                and obj is not RewritePattern):
            if obj.root_type:
                referenced.add(obj.root_type)
            referenced.update(getattr(obj, "_ROOTS", ()))
    src = inspect.getsource(rewrite)
    # anchor/producer literals: graph.def_op(vid, "type") and
    # _base_type(x) == "type" / in ("a", "b") comparisons
    referenced.update(re.findall(r"def_op\([^,()]+,\s*['\"](\w+)['\"]", src))
    referenced.update(re.findall(r"_base_type\([^)]*\)\s*==\s*['\"](\w+)['\"]", src))
    for m in re.finditer(r"_base_type\([^)]*\)\s*(?:not\s+)?in\s*\(([^)]*)\)", src):
        referenced.update(re.findall(r"['\"](\w+)['\"]", m.group(1)))
    assert len(referenced) > 10, "pattern scan found implausibly few op types"
    unresolved = sorted(t for t in referenced if not resolve_op_type(t))
    assert not unresolved, (
        f"rewrite patterns reference op types missing from the registry "
        f"(renamed op?): {unresolved}")


def test_reference_top_level_surface_complete():
    src = open("/root/reference/python/paddle/__init__.py").read()
    m = re.search(r"__all__ = \[(.*?)\]", src, re.S)
    ref_all = set(re.findall(r"'([^']+)'", m.group(1)))
    missing = sorted(n for n in ref_all if not hasattr(paddle, n))
    assert not missing, f"{len(missing)} missing top-level names: {missing[:20]}"


def test_unflatten():
    x = paddle.arange(24).reshape([2, 12])
    out = paddle.unflatten(x, 1, [3, 4])
    assert out.shape == [2, 3, 4]
    out2 = paddle.unflatten(x, 1, [3, -1])
    np.testing.assert_array_equal(np.asarray(out._value), np.asarray(out2._value))


def test_index_fill_and_inplace():
    x = paddle.zeros([4, 3])
    idx = paddle.to_tensor(np.array([0, 2], np.int32))
    out = paddle.index_fill(x, idx, 0, 7.0)
    ref = np.zeros((4, 3), np.float32)
    ref[[0, 2]] = 7.0
    np.testing.assert_array_equal(np.asarray(out._value), ref)
    x.index_fill_(idx, 0, 7.0)
    np.testing.assert_array_equal(np.asarray(x._value), ref)


@pytest.mark.parametrize("offset", [0, 1, -1])
def test_diagonal_scatter(offset):
    x = np.zeros((4, 5), np.float32)
    L = np.diagonal(x, offset=offset).shape[0]
    y = np.arange(1, L + 1, dtype=np.float32)
    out = paddle.diagonal_scatter(paddle.to_tensor(x), paddle.to_tensor(y), offset=offset)
    ref = x.copy()
    i = np.arange(L)
    if offset >= 0:
        ref[i, i + offset] = y
    else:
        ref[i - offset, i] = y
    np.testing.assert_array_equal(np.asarray(out._value), ref)


def test_select_scatter():
    x = paddle.zeros([3, 4])
    v = paddle.to_tensor(np.array([1.0, 2.0, 3.0, 4.0], np.float32))
    out = paddle.select_scatter(x, v, 0, 1)
    ref = np.zeros((3, 4), np.float32)
    ref[1] = [1, 2, 3, 4]
    np.testing.assert_array_equal(np.asarray(out._value), ref)


def test_pdist():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((5, 3)).astype(np.float32)
    out = np.asarray(paddle.pdist(paddle.to_tensor(x))._value)
    iu, ju = np.triu_indices(5, k=1)
    ref = np.linalg.norm(x[iu] - x[ju], axis=-1)
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_add_n_and_reverse():
    a, b = paddle.ones([2, 2]), paddle.full([2, 2], 2.0)
    np.testing.assert_array_equal(np.asarray(paddle.add_n([a, b])._value), np.full((2, 2), 3.0, np.float32))
    x = paddle.arange(4)
    np.testing.assert_array_equal(np.asarray(paddle.reverse(x, 0)._value), [3, 2, 1, 0])


def test_generated_inplace_tier():
    x = paddle.to_tensor(np.array([0.5, 1.0], np.float32))
    y = paddle.cos(x)
    x.cos_()
    np.testing.assert_allclose(np.asarray(x._value), np.asarray(y._value))
    z = paddle.to_tensor(np.ones((3, 3), np.float32))
    z.tril_()
    np.testing.assert_array_equal(np.asarray(z._value), np.tril(np.ones((3, 3), np.float32)))
    # module-level generated names are exported
    assert callable(paddle.log10_) and callable(paddle.bitwise_not_)


def test_random_inplace_fills():
    paddle.seed(7)
    x = paddle.zeros([2000])
    x.cauchy_(loc=1.0, scale=2.0)
    med = float(np.median(np.asarray(x._value)))
    assert abs(med - 1.0) < 0.3  # Cauchy median = loc
    g = paddle.zeros([2000])
    g.geometric_(0.5)
    vals = np.asarray(g._value)
    assert vals.min() >= 1.0 and abs(vals.mean() - 2.0) < 0.2  # E[X] = 1/p


def test_finfo_iinfo_default_dtype():
    assert paddle.finfo(paddle.bfloat16).bits == 16
    assert paddle.finfo("float32").eps == np.finfo(np.float32).eps
    assert paddle.iinfo(paddle.int8).max == 127
    assert paddle.get_default_dtype() == "float32"
    paddle.set_default_dtype("bfloat16")
    try:
        assert paddle.get_default_dtype() == "bfloat16"
        # float64 narrows to float32 (framework-wide no-64-bit policy)
        paddle.set_default_dtype("float64")
        assert paddle.get_default_dtype() == "float32"
    finally:
        paddle.set_default_dtype("float32")
    with pytest.raises(TypeError):
        paddle.set_default_dtype("int32")


def test_batch_reader():
    reader = paddle.batch(lambda: iter(range(10)), batch_size=4)
    batches = list(reader())
    assert batches == [[0, 1, 2, 3], [4, 5, 6, 7], [8, 9]]
    reader = paddle.batch(lambda: iter(range(10)), batch_size=4, drop_last=True)
    assert list(reader()) == [[0, 1, 2, 3], [4, 5, 6, 7]]


def test_create_parameter_and_param_attr():
    p = paddle.create_parameter([4, 4], "float32", attr=paddle.ParamAttr(learning_rate=0.5))
    assert p.shape == [4, 4] and p.optimize_attr["learning_rate"] == 0.5
    b = paddle.create_parameter([4], "float32", is_bias=True)
    np.testing.assert_array_equal(np.asarray(b._value), np.zeros(4, np.float32))


def test_lazy_guard_host_then_initialize():
    import jax

    with paddle.LazyGuard():
        lin = paddle.nn.Linear(8, 8)
    w = lin.weight
    assert "cpu" in str(next(iter(w._value.devices()))).lower()
    w.initialize()
    y = lin(paddle.ones([2, 8]))
    assert np.isfinite(np.asarray(y._value)).all()


def test_cuda_compat_place_and_rng():
    place = paddle.CUDAPlace(0)
    assert place.jax_device() is not None
    assert isinstance(paddle.CUDAPinnedPlace(), paddle.CPUPlace)
    st = paddle.get_cuda_rng_state()
    paddle.set_cuda_rng_state(st)


def test_tolist_and_t_():
    assert paddle.tolist(paddle.arange(3)) == [0, 1, 2]
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    x.t_()
    assert x.shape == [3, 2]


def test_tensor_method_surface_complete():
    src = open("/root/reference/python/paddle/tensor/__init__.py").read()
    m = re.search(r"tensor_method_func = \[(.*?)\]", src, re.S)
    methods = set(re.findall(r"'([^']+)'", m.group(1)))
    t = paddle.ones([2, 2])
    missing = sorted(n for n in methods if not hasattr(t, n))
    assert not missing, f"Tensor missing {len(missing)} methods: {missing[:20]}"


def test_distributed_surface_complete():
    src = open("/root/reference/python/paddle/distributed/__init__.py").read()
    m = re.search(r"__all__ = \[(.*?)\]", src, re.S)
    ref = set(re.findall(r'"([^"]+)"', m.group(1))) | set(re.findall(r"'([^']+)'", m.group(1)))
    import paddle_tpu.distributed as dist

    missing = sorted(n for n in ref if not hasattr(dist, n))
    assert not missing, missing


def test_top_p_sampling():
    paddle.seed(0)
    probs = paddle.to_tensor(np.array([[0.6, 0.3, 0.05, 0.05]], np.float32))
    ps = paddle.to_tensor(np.array([0.5], np.float32))
    scores, ids = paddle.tensor.top_p_sampling(probs, ps)
    # p=0.5 keeps only the top token (0.6 >= 0.5)
    assert int(np.asarray(ids._value)[0, 0]) == 0
    ps2 = paddle.to_tensor(np.array([0.95], np.float32))
    seen = set()
    for _ in range(20):
        _, i2 = paddle.tensor.top_p_sampling(probs, ps2)
        seen.add(int(np.asarray(i2._value)[0, 0]))
    assert seen <= {0, 1, 2}  # 0.05-tail token 3 excluded


def test_linalg_cond_and_inverse():
    a = np.diag([4.0, 1.0]).astype(np.float32)
    t = paddle.to_tensor(a)
    assert abs(float(paddle.linalg.cond(t)._value) - 4.0) < 1e-5
    assert abs(float(paddle.linalg.cond(t, 1)._value) - 4.0) < 1e-5
    np.testing.assert_allclose(np.asarray(paddle.inverse(t)._value), np.linalg.inv(a), atol=1e-6)


def test_stft_tensor_method():
    x = paddle.to_tensor(np.random.default_rng(0).standard_normal(512).astype(np.float32))
    spec = x.stft(n_fft=64, hop_length=16)
    assert spec.shape[0] == 33  # n_fft//2 + 1 bins


def test_distributed_split_world1():
    import paddle_tpu.distributed as dist

    paddle.seed(0)
    x = paddle.ones([2, 4])
    out = dist.split(x, (4, 6), operation="linear", axis=1)
    assert out.shape == [2, 6]
    ids = paddle.to_tensor(np.array([1, 3], np.int64))
    emb = dist.split(ids, (10, 8), operation="embedding")
    assert emb.shape == [2, 8]


def test_object_collectives_world1():
    import paddle_tpu.distributed as dist

    objs = []
    dist.broadcast_object_list(objs)
    out = [None]
    dist.scatter_object_list(out, [{"a": 1}])
    assert out == [{"a": 1}]
    gl = []
    dist.gather(paddle.ones([2]), gl)
    assert len(gl) == 1
    assert dist.get_backend().startswith("xla:")


def test_queue_and_inmemory_dataset():
    import paddle_tpu.distributed as dist

    ds = dist.InMemoryDataset(parse_fn=lambda line: int(line))
    import tempfile, os

    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "f.txt")
        open(p, "w").write("1\n2\n3\n")
        ds.load_into_memory([p])
    assert len(ds) == 3 and ds[0] == 1
    ds.global_shuffle(seed=1)
    q = dist.QueueDataset()
    with pytest.raises(RuntimeError):
        q.global_shuffle()


def test_dist_attr_and_enums():
    import paddle_tpu.distributed as dist

    assert dist.ParallelMode.DATA_PARALLEL == 0
    assert dist.ReduceType.kRedSum == 0
    da = dist.DistAttr()
    assert da.process_mesh is None
    e = dist.CountFilterEntry(5)
    assert "5" in e._to_attr()
    with pytest.raises(ValueError):
        dist.ProbabilityEntry(1.5)


def test_cond_one_vs_inf_nonsymmetric():
    rng = np.random.default_rng(3)
    a = (rng.standard_normal((4, 4)) + 4 * np.eye(4)).astype(np.float32)
    t = paddle.to_tensor(a)
    np.testing.assert_allclose(float(paddle.linalg.cond(t, 1)._value), np.linalg.cond(a, 1), rtol=1e-4)
    np.testing.assert_allclose(float(paddle.linalg.cond(t, np.inf)._value), np.linalg.cond(a, np.inf), rtol=1e-4)
    np.testing.assert_allclose(float(paddle.linalg.cond(t, "fro")._value), np.linalg.cond(a, "fro"), rtol=1e-4)


def test_ceil_mode_pooling():
    import paddle_tpu.nn.functional as F

    x = paddle.to_tensor(np.arange(8, dtype=np.float32).reshape(1, 1, 8))
    o = F.max_pool1d(x, 3, stride=2, ceil_mode=True)
    assert o.shape[-1] == 4  # ceil((8-3)/2)+1
    np.testing.assert_allclose(np.asarray(o._value)[0, 0], [2, 4, 6, 7])
    o2 = F.max_pool1d(x, 3, stride=2, ceil_mode=False)
    assert o2.shape[-1] == 3
    # asymmetric 2n-form padding + ceil + mask path
    x6 = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(1, 1, 6))
    om, mm = F.max_pool1d(x6, 2, stride=2, padding=[0, 1], ceil_mode=True, return_mask=True)
    assert om.shape[-1] == 4 and mm.shape[-1] == 4
    # avg pool ceil with exclusive counting stays finite
    oa = F.avg_pool1d(x, 3, stride=2, ceil_mode=True, exclusive=True)
    assert np.isfinite(np.asarray(oa._value)).all()
