"""Per-op micro-benchmark harness (tools/bench_ops.py).

Reference roles: test/legacy_test/benchmark.py + tools/ci_op_benchmark.sh /
check_op_benchmark_result.py (per-op timing + CI regression gate).
"""

import copy
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

import bench_ops  # noqa: E402


def test_quick_sweep_all_ops_time_cleanly(tmp_path):
    out = tmp_path / "ops.json"
    rc = bench_ops.main(["--quick", "--out", str(out)])
    assert rc == 0
    res = json.loads(out.read_text())
    assert res["ops"], "no ops ran"
    errors = {k: v for k, v in res["ops"].items() if "error" in v}
    assert not errors, errors
    for name, entry in res["ops"].items():
        assert entry["ms"] > 0, name


def test_compare_gate_flags_regressions_and_passes_clean(tmp_path):
    res = bench_ops.run(quick=True, iters=1)
    # identical runs pass at any threshold
    assert bench_ops.compare(res, res, threshold=0.0) == []
    # a 2x slowdown on one op is flagged at 5%
    slower = copy.deepcopy(res)
    name = next(k for k, v in res["ops"].items() if "ms" in v)
    slower["ops"][name]["ms"] = res["ops"][name]["ms"] * 2
    bad = bench_ops.compare(slower, res, threshold=0.05)
    assert len(bad) == 1 and name in bad[0]
    # faster is never a regression
    assert bench_ops.compare(res, slower, threshold=0.05) == []


def test_compare_gate_flags_broken_and_missing_ops():
    old = {"ops": {"matmul": {"ms": 2.0}, "softmax": {"ms": 1.0}}}
    new = {"ops": {"matmul": {"error": "TypeError: boom"}}}
    bad = bench_ops.compare(new, old, threshold=0.05)
    assert len(bad) == 2
    assert any("boom" in b for b in bad)
    assert any("MISSING" in b for b in bad)
