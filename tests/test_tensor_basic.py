"""Core tensor + op-surface tests (model: reference OpTest numpy comparisons,
test/legacy_test/op_test.py:417)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_basic():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert t.dtype == paddle.float32
    np.testing.assert_allclose(t.numpy(), [[1, 2], [3, 4]])


def test_dtype_conversion():
    # TPU-native width policy: integer creation lands on int32 (the hardware
    # int width); requesting int64 maps to int32 at the jax boundary.
    t = paddle.to_tensor([1, 2, 3])
    assert t.dtype == paddle.int32
    assert paddle.to_tensor([1], dtype="int64").dtype == paddle.int32
    f = t.astype("float32")
    assert f.dtype == paddle.float32
    b = t.astype(paddle.bfloat16)
    assert b.dtype == paddle.bfloat16


def test_creation_ops():
    assert paddle.zeros([2, 3]).numpy().sum() == 0
    assert paddle.ones([2, 3]).numpy().sum() == 6
    np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
    np.testing.assert_array_equal(paddle.eye(3).numpy(), np.eye(3, dtype=np.float32))
    np.testing.assert_array_equal(
        paddle.full([2, 2], 7, dtype="int32").numpy(), np.full((2, 2), 7, np.int32)
    )
    np.testing.assert_allclose(
        paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5, dtype=np.float32)
    )


def test_elementwise_math():
    x = paddle.to_tensor([1.0, 2.0, 3.0])
    y = paddle.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((x + y).numpy(), [5, 7, 9])
    np.testing.assert_allclose((x * y).numpy(), [4, 10, 18])
    np.testing.assert_allclose((y / x).numpy(), [4, 2.5, 2])
    np.testing.assert_allclose((x - y).numpy(), [-3, -3, -3])
    np.testing.assert_allclose((x**2).numpy(), [1, 4, 9])
    np.testing.assert_allclose(paddle.exp(x).numpy(), np.exp([1, 2, 3]), rtol=1e-5)
    np.testing.assert_allclose(paddle.sqrt(x).numpy(), np.sqrt([1, 2, 3]), rtol=1e-5)
    np.testing.assert_allclose(paddle.log(x).numpy(), np.log([1, 2, 3]), rtol=1e-4)


def test_scalar_broadcasting():
    x = paddle.to_tensor([1.0, 2.0])
    np.testing.assert_allclose((x + 1).numpy(), [2, 3])
    np.testing.assert_allclose((1 + x).numpy(), [2, 3])
    np.testing.assert_allclose((2 * x).numpy(), [2, 4])
    np.testing.assert_allclose((1 - x).numpy(), [0, -1])
    np.testing.assert_allclose((2 / x).numpy(), [2, 1])


def test_reductions():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert float(paddle.sum(x)) == 10.0
    assert float(paddle.mean(x)) == 2.5
    assert float(paddle.max(x)) == 4.0
    assert float(paddle.min(x)) == 1.0
    np.testing.assert_allclose(paddle.sum(x, axis=0).numpy(), [4, 6])
    np.testing.assert_allclose(paddle.sum(x, axis=1, keepdim=True).numpy(), [[3], [7]])
    assert float(paddle.prod(x)) == 24.0


def test_matmul():
    a = paddle.to_tensor(np.random.rand(3, 4).astype(np.float32))
    b = paddle.to_tensor(np.random.rand(4, 5).astype(np.float32))
    np.testing.assert_allclose(
        paddle.matmul(a, b).numpy(), a.numpy() @ b.numpy(), rtol=1e-5
    )
    np.testing.assert_allclose((a @ b).numpy(), a.numpy() @ b.numpy(), rtol=1e-5)
    # transpose flags
    np.testing.assert_allclose(
        paddle.matmul(a, a, transpose_y=True).numpy(), a.numpy() @ a.numpy().T, rtol=1e-5
    )


def test_manipulation():
    x = paddle.to_tensor(np.arange(24).reshape(2, 3, 4).astype(np.float32))
    assert paddle.reshape(x, [6, 4]).shape == [6, 4]
    assert paddle.transpose(x, [2, 0, 1]).shape == [4, 2, 3]
    assert paddle.flatten(x).shape == [24]
    assert paddle.flatten(x, 1, 2).shape == [2, 12]
    assert paddle.unsqueeze(x, 0).shape == [1, 2, 3, 4]
    assert paddle.squeeze(paddle.unsqueeze(x, 0), 0).shape == [2, 3, 4]
    c = paddle.concat([x, x], axis=1)
    assert c.shape == [2, 6, 4]
    s = paddle.split(x, 3, axis=1)
    assert len(s) == 3 and s[0].shape == [2, 1, 4]
    st = paddle.stack([x, x], axis=0)
    assert st.shape == [2, 2, 3, 4]


def test_indexing():
    x = paddle.to_tensor(np.arange(12).reshape(3, 4).astype(np.float32))
    np.testing.assert_allclose(x[0].numpy(), [0, 1, 2, 3])
    np.testing.assert_allclose(x[:, 1].numpy(), [1, 5, 9])
    np.testing.assert_allclose(x[1:, 2:].numpy(), [[6, 7], [10, 11]])
    x[0] = paddle.to_tensor([9.0, 9.0, 9.0, 9.0])
    np.testing.assert_allclose(x[0].numpy(), [9, 9, 9, 9])


def test_logic_and_comparison():
    x = paddle.to_tensor([1.0, 2.0, 3.0])
    y = paddle.to_tensor([3.0, 2.0, 1.0])
    np.testing.assert_array_equal((x == y).numpy(), [False, True, False])
    np.testing.assert_array_equal((x < y).numpy(), [True, False, False])
    np.testing.assert_array_equal((x >= y).numpy(), [False, True, True])
    assert bool(paddle.allclose(x, x))
    assert not bool(paddle.allclose(x, y))


def test_search_sort():
    x = paddle.to_tensor([[3.0, 1.0, 2.0], [6.0, 5.0, 4.0]])
    np.testing.assert_array_equal(paddle.argmax(x, axis=1).numpy(), [0, 0])
    np.testing.assert_array_equal(paddle.argmin(x, axis=1).numpy(), [1, 2])
    vals, idx = paddle.topk(x, 2, axis=1)
    np.testing.assert_allclose(vals.numpy(), [[3, 2], [6, 5]])
    s = paddle.sort(x, axis=1)
    np.testing.assert_allclose(s.numpy(), [[1, 2, 3], [4, 5, 6]])
    w = paddle.where(x > 2.0, x, paddle.zeros_like(x))
    np.testing.assert_allclose(w.numpy(), [[3, 0, 0], [6, 5, 4]])


def test_gather_scatter():
    x = paddle.to_tensor(np.arange(12).reshape(4, 3).astype(np.float32))
    idx = paddle.to_tensor([0, 2])
    g = paddle.gather(x, idx, axis=0)
    np.testing.assert_allclose(g.numpy(), [[0, 1, 2], [6, 7, 8]])
    upd = paddle.to_tensor([[10.0, 10, 10], [20, 20, 20]])
    s = paddle.scatter(x, idx, upd)
    np.testing.assert_allclose(s.numpy()[0], [10, 10, 10])
    np.testing.assert_allclose(s.numpy()[2], [20, 20, 20])


def test_einsum():
    a = np.random.rand(2, 3).astype(np.float32)
    b = np.random.rand(3, 4).astype(np.float32)
    out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)


def test_linalg():
    a = np.random.rand(4, 4).astype(np.float32) + 4 * np.eye(4, dtype=np.float32)
    t = paddle.to_tensor(a)
    np.testing.assert_allclose(paddle.linalg.inv(t).numpy(), np.linalg.inv(a), rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(float(paddle.linalg.det(t)), np.linalg.det(a), rtol=1e-4)
    q, r = paddle.linalg.qr(t)
    np.testing.assert_allclose((q @ r).numpy(), a, rtol=1e-4, atol=1e-4)
    n = paddle.linalg.norm(t)
    np.testing.assert_allclose(float(n), np.linalg.norm(a), rtol=1e-5)


def test_random_reproducibility():
    paddle.seed(42)
    a = paddle.randn([4, 4])
    paddle.seed(42)
    b = paddle.randn([4, 4])
    np.testing.assert_array_equal(a.numpy(), b.numpy())
    c = paddle.randn([4, 4])
    assert not np.array_equal(b.numpy(), c.numpy())


def test_stat():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    np.testing.assert_allclose(float(paddle.std(x)), np.std(x.numpy(), ddof=1), rtol=1e-6)
    np.testing.assert_allclose(float(paddle.var(x)), np.var(x.numpy(), ddof=1), rtol=1e-6)
    np.testing.assert_allclose(float(paddle.median(x)), 2.5)


def test_cast_chain_and_item():
    x = paddle.to_tensor(3.5)
    assert x.item() == 3.5
    assert int(paddle.to_tensor(7)) == 7
    assert paddle.to_tensor(True).dtype == paddle.bool


def test_cumsum_cumprod():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    np.testing.assert_allclose(paddle.cumsum(x, axis=0).numpy(), [[1, 2], [4, 6]])
    np.testing.assert_allclose(paddle.cumprod(x, dim=1).numpy(), [[1, 2], [3, 12]])


def test_clip_and_scale():
    x = paddle.to_tensor([-1.0, 0.5, 2.0])
    np.testing.assert_allclose(paddle.clip(x, 0.0, 1.0).numpy(), [0, 0.5, 1])
    np.testing.assert_allclose(paddle.scale(x, 2.0, 1.0).numpy(), [-1, 2, 5])


def test_to_unavailable_backend_warns():
    # A device move that cannot happen must warn, not silently no-op
    # (VERDICT r4 weak #3).
    import warnings

    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out = t.to("gpu")  # no CUDA backend on this image
        moved_or_warned = bool(w) or "cpu" not in str(out._value.devices()).lower()
    assert moved_or_warned
    if w:
        assert "backend available" in str(w[-1].message) or "backend unavailable" in str(w[-1].message)


def test_seeded_training_is_deterministic():
    """paddle.seed -> init + 2 train steps reproduces losses bit-for-bit
    (regression net for RNG-threading nondeterminism)."""
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt

    def run():
        paddle.seed(1234)
        m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 2))
        o = opt.Adam(1e-2, parameters=m.parameters())
        x = paddle.to_tensor(np.arange(32, dtype=np.float32).reshape(4, 8) / 32.0)
        y = paddle.to_tensor(np.zeros((4, 2), np.float32))
        losses = []
        for _ in range(2):
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            o.step()
            o.clear_grad()
            losses.append(float(loss._value))
        return losses

    assert run() == run()
