"""Vision model zoo + datasets tests (reference model:
test/legacy_test/test_vision_models.py — build each family, forward a
small input, check output shape; dataset parsers against synthesized
archives)."""

import gzip
import os
import struct
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import datasets, models


def npv(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


def _check(model, num_classes=10, size=64, in_ch=3):
    model.eval()
    x = paddle.randn([1, in_ch, size, size])
    out = model(x)
    if isinstance(out, tuple):
        out = out[0]
    assert tuple(out.shape) == (1, num_classes)
    assert np.isfinite(npv(out)).all()


class TestModelZoo:
    def test_lenet(self):
        m = models.LeNet(num_classes=10)
        m.eval()
        out = m(paddle.randn([2, 1, 28, 28]))
        assert tuple(out.shape) == (2, 10)

    @pytest.mark.slow
    def test_alexnet(self):
        _check(models.alexnet(num_classes=10), size=224)

    @pytest.mark.parametrize("factory", [models.vgg11, models.vgg16])
    @pytest.mark.slow
    def test_vgg(self, factory):
        _check(factory(num_classes=10, batch_norm=True), size=64)

    @pytest.mark.slow
    def test_squeezenet(self):
        _check(models.squeezenet1_0(num_classes=10), size=96)
        _check(models.squeezenet1_1(num_classes=10), size=96)

    @pytest.mark.slow
    def test_mobilenets(self):
        _check(models.mobilenet_v1(num_classes=10, scale=0.25), size=64)
        _check(models.mobilenet_v2(num_classes=10, scale=0.25), size=64)
        _check(models.mobilenet_v3_small(num_classes=10, scale=0.5), size=64)
        _check(models.mobilenet_v3_large(num_classes=10, scale=0.35), size=64)

    @pytest.mark.slow
    def test_shufflenet(self):
        _check(models.shufflenet_v2_x0_25(num_classes=10), size=64)
        _check(models.shufflenet_v2_swish(num_classes=10), size=64)

    @pytest.mark.slow
    def test_densenet(self):
        _check(models.densenet121(num_classes=10), size=64)

    @pytest.mark.slow
    def test_googlenet_aux_outputs(self):
        m = models.googlenet(num_classes=10)
        m.eval()
        out, aux1, aux2 = m(paddle.randn([1, 3, 96, 96]))
        assert tuple(out.shape) == (1, 10)
        assert tuple(aux1.shape) == (1, 10) and tuple(aux2.shape) == (1, 10)

    @pytest.mark.slow
    def test_inception_v3(self):
        _check(models.inception_v3(num_classes=10), size=160)

    @pytest.mark.slow
    def test_resnext(self):
        _check(models.resnext50_32x4d(num_classes=10), size=64)

    def test_export_list_complete(self):
        # every reference export name must exist
        ref = ['ResNet','resnet18','resnet34','resnet50','resnet101','resnet152',
               'resnext50_32x4d','resnext50_64x4d','resnext101_32x4d','resnext101_64x4d',
               'resnext152_32x4d','resnext152_64x4d','wide_resnet50_2','wide_resnet101_2',
               'VGG','vgg11','vgg13','vgg16','vgg19','MobileNetV1','mobilenet_v1',
               'MobileNetV2','mobilenet_v2','MobileNetV3Small','MobileNetV3Large',
               'mobilenet_v3_small','mobilenet_v3_large','LeNet','DenseNet','densenet121',
               'densenet161','densenet169','densenet201','densenet264','AlexNet','alexnet',
               'InceptionV3','inception_v3','SqueezeNet','squeezenet1_0','squeezenet1_1',
               'GoogLeNet','googlenet','ShuffleNetV2','shufflenet_v2_x0_25',
               'shufflenet_v2_x0_33','shufflenet_v2_x0_5','shufflenet_v2_x1_0',
               'shufflenet_v2_x1_5','shufflenet_v2_x2_0','shufflenet_v2_swish']
        missing = [n for n in ref if not hasattr(models, n)]
        assert missing == []

    @pytest.mark.slow
    def test_train_step_on_mobilenet(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt

        paddle.seed(0)
        m = models.mobilenet_v2(num_classes=4, scale=0.25)
        optimizer = opt.Adam(1e-3, parameters=m.parameters())
        ce = nn.CrossEntropyLoss()
        x = paddle.randn([4, 3, 32, 32])
        y = paddle.to_tensor(np.array([0, 1, 2, 3]))
        losses = []
        for _ in range(8):
            loss = ce(m(x), y)
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestDatasets:
    def _write_mnist(self, d, n=12):
        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 255, (n, 28, 28), dtype=np.uint8)
        labels = rng.integers(0, 10, n, dtype=np.uint8)
        ip = os.path.join(d, "imgs.idx3-ubyte.gz")
        lp = os.path.join(d, "labels.idx1-ubyte.gz")
        with gzip.open(ip, "wb") as f:
            f.write(struct.pack(">IIII", 2051, n, 28, 28) + imgs.tobytes())
        with gzip.open(lp, "wb") as f:
            f.write(struct.pack(">II", 2049, n) + labels.tobytes())
        return ip, lp, imgs, labels

    def test_mnist_parsing(self):
        with tempfile.TemporaryDirectory() as d:
            ip, lp, imgs, labels = self._write_mnist(d)
            ds = datasets.MNIST(image_path=ip, label_path=lp)
            assert len(ds) == 12
            x, y = ds[3]
            np.testing.assert_allclose(x, imgs[3].astype(np.float32))
            assert int(y) == int(labels[3])

    def test_mnist_requires_files(self):
        with pytest.raises(RuntimeError, match="local copy"):
            datasets.MNIST()

    def test_cifar10_parsing(self):
        import pickle
        import tarfile

        rng = np.random.default_rng(1)
        with tempfile.TemporaryDirectory() as d:
            arch = os.path.join(d, "cifar-10-python.tar.gz")
            with tarfile.open(arch, "w:gz") as tf:
                for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
                    batch = {
                        b"data": rng.integers(0, 255, (4, 3072), dtype=np.uint8),
                        b"labels": list(rng.integers(0, 10, 4)),
                    }
                    raw = pickle.dumps(batch)
                    p = os.path.join(d, name)
                    with open(p, "wb") as f:
                        f.write(raw)
                    tf.add(p, arcname=f"cifar-10-batches-py/{name}")
            train = datasets.Cifar10(data_file=arch, mode="train")
            test = datasets.Cifar10(data_file=arch, mode="test")
            assert len(train) == 20 and len(test) == 4
            x, y = train[0]
            assert x.shape == (3, 32, 32)

    def test_dataset_folder(self):
        with tempfile.TemporaryDirectory() as d:
            for cls in ["cat", "dog"]:
                os.makedirs(os.path.join(d, cls))
                for i in range(3):
                    np.save(os.path.join(d, cls, f"{i}.npy"),
                            np.zeros((4, 4, 3), np.float32))
            ds = datasets.DatasetFolder(d)
            assert ds.classes == ["cat", "dog"]
            assert len(ds) == 6
            img, target = ds[0]
            assert img.shape == (4, 4, 3) and int(target) == 0
            img, target = ds[5]
            assert int(target) == 1

    def test_image_folder(self):
        with tempfile.TemporaryDirectory() as d:
            for i in range(4):
                np.save(os.path.join(d, f"{i}.npy"), np.ones((2, 2), np.float32))
            ds = datasets.ImageFolder(d)
            assert len(ds) == 4
            (img,) = ds[1]
            assert img.shape == (2, 2)


def test_yolo_box_decode():
    """yolo_box (PP-YOLO decode, reference paddle.vision.ops.yolo_box):
    center cell of a uniform head decodes to the expected normalized box,
    traceable under jit."""
    import jax
    import paddle_tpu.vision.ops as V

    n, na, cls_n, h, w = 1, 2, 3, 4, 4
    c = na * (5 + cls_n)
    x = np.zeros((n, c, h, w), np.float32)  # sigmoid(0)=0.5 centers
    img = np.array([[64, 64]], np.int32)
    boxes, scores = V.yolo_box(
        paddle.to_tensor(x), paddle.to_tensor(img),
        anchors=[8, 8, 16, 16], class_num=cls_n, conf_thresh=0.1,
        downsample_ratio=16,
    )
    assert list(boxes.shape) == [1, na * h * w, 4]
    assert list(scores.shape) == [1, na * h * w, cls_n]
    b = np.asarray(boxes._value)
    # first anchor at cell (0,0): center (0.5/4, 0.5/4)*64 = 8, w=h=8/64*64=8
    np.testing.assert_allclose(b[0, 0], [4.0, 4.0, 12.0, 12.0], atol=1e-4)
    # conf=0.5 > 0.1 so scores kept: sigmoid(0)*0.5 = 0.25
    np.testing.assert_allclose(np.asarray(scores._value)[0, 0], 0.25 * np.ones(cls_n), atol=1e-5)
    # traceable
    jitted = jax.jit(lambda a, s: V.yolo_box(
        paddle.Tensor(a), paddle.Tensor(s), anchors=[8, 8, 16, 16],
        class_num=cls_n, conf_thresh=0.1, downsample_ratio=16)[0]._value)
    np.testing.assert_allclose(np.asarray(jitted(x, img)), b, atol=1e-5)


def test_yolo_box_iou_aware_layout():
    """iou_aware=True: the na IoU channels come FIRST in C (reference
    kernel layout); conf = obj^(1-f) * iou^f."""
    import paddle_tpu.vision.ops as V

    n, na, cls_n, h, w = 1, 2, 2, 2, 2
    c = na + na * (5 + cls_n)
    x = np.zeros((n, c, h, w), np.float32)
    x[:, :na] = 100.0  # iou logits -> sigmoid ~ 1.0
    img = np.array([[32, 32]], np.int32)
    _, scores = V.yolo_box(
        paddle.to_tensor(x), paddle.to_tensor(img), anchors=[8, 8, 16, 16],
        class_num=cls_n, conf_thresh=0.1, downsample_ratio=16,
        iou_aware=True, iou_aware_factor=0.5,
    )
    # conf = 0.5^0.5 * 1^0.5 ~ 0.7071; score = sigmoid(0)*conf ~ 0.3536
    np.testing.assert_allclose(np.asarray(scores._value)[0, 0], 0.3536 * np.ones(cls_n), atol=2e-3)
