"""Pipeline-schedule subsystem tests (fleet/meta_parallel/schedules.py +
the ZB-H1 split-backward engine in pipeline.py, docs/PIPELINE.md).

The engine-parity tests dispatch GSPMD pipeline programs over the
in-process 4/8-device CPU communicator — the known SIGSEGV class — so this
module rides a DEDICATED tools/run_tier1.py isolated worker
(ISOLATED_DEFAULT) instead of a slow mark.  The file name sorts at the
tail of the serial suite on purpose: the fixed serial tier-1 budget should
cut the newest coverage first, never displace pre-existing dots.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu._core import flags as _flags
from paddle_tpu.distributed import ProcessMesh
from paddle_tpu.distributed.fleet.meta_parallel import (
    PipelineStack,
    pipeline_parallel,
    segment_layers,
)
from paddle_tpu.distributed.fleet.meta_parallel import schedules as sched


# ------------------------------------------------------------- simulator
def test_registry_and_flag_resolution():
    assert set(sched.available_schedules()) >= {"FThenB", "1F1B", "ZB-H1"}
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        sched.get_schedule("ZB-H9000")
    assert sched.resolve_schedule_flag() in sched.available_schedules()
    # a bogus flag value fails loudly at resolution, not silently — while
    # set_flags itself survives (a listener must never blow up the walk)
    # and live flag-following stacks keep their current schedule
    mesh = ProcessMesh(np.arange(4), ["pp"])
    stack = PipelineStack(_blocks(4, 16, seed=9), mesh, pp_axis="pp",
                          num_microbatches=4)
    _flags.set_flags({"FLAGS_pipeline_schedule": "bogus"})
    try:
        assert stack._schedule == "1F1B"
        with pytest.raises(ValueError, match="unknown pipeline schedule"):
            sched.resolve_schedule_flag()
        with pytest.raises(ValueError, match="unknown pipeline schedule"):
            PipelineStack(_blocks(4, 16, seed=9), mesh, pp_axis="pp",
                          num_microbatches=4)
    finally:
        _flags.set_flags({"FLAGS_pipeline_schedule": "1F1B"})


@pytest.mark.parametrize("S", [2, 4])
def test_zbh1_bubble_strictly_below_1f1b_with_bounded_residency(S):
    """The acceptance-criterion proof, pure host math: at equal (S, M >=
    2S) ZB-H1's bubble fraction is STRICTLY below 1F1B's, and its peak
    activation residency does not exceed 1F1B's (ZB-H1 is the
    memory-neutral zero-bubble member: the greedy enforces the S - s
    in-flight cap as a hard bound)."""
    for M in (2 * S, 3 * S, 4 * S):
        r1 = sched.simulate("1F1B", S, M)
        rz = sched.simulate("ZB-H1", S, M)
        assert rz.bubble_fraction < r1.bubble_fraction, (S, M, rz, r1)
        assert rz.peak_residency <= r1.peak_residency, (S, M, rz, r1)
        # 1F1B in turn bounds memory far below FThenB's store-everything
        rf = sched.simulate("FThenB", S, M)
        assert r1.peak_residency < rf.peak_residency
        assert rf.peak_residency == float(M)


def test_simulator_closed_forms():
    """Unit costs: FThenB/1F1B makespan is (M + S - 1) * (f + b + w), the
    schedule-intrinsic (S-1)/(M+S-1) bubble; every schedule does the same
    total work."""
    for name in ("FThenB", "1F1B"):
        r = sched.simulate(name, 4, 8)
        assert r.makespan == (8 + 4 - 1) * 3.0
        assert abs(r.bubble_fraction - 3 / 11) < 1e-9
    rz = sched.simulate("ZB-H1", 4, 8)
    assert rz.total_work == sched.simulate("1F1B", 4, 8).total_work
    assert rz.makespan < 33.0


def test_zbh1_tick_table_is_classic_diagram():
    """S=2, M=4: the time-aligned table interleaves W into the waits (the
    stage-0 gap at tick 2 and the drain) while B stays on the critical
    path; every microbatch appears exactly once per {F, B, W} per stage."""
    rows = sched.get_schedule("ZB-H1").table(2, 4)
    flat = [(t, s, c) for t, row in enumerate(rows)
            for s, c in enumerate(row) if c]
    for s in range(2):
        for kind in "FBW":
            got = sorted(int(c[1:]) for t, st, c in flat
                         if st == s and c[0] == kind)
            assert got == [0, 1, 2, 3], (s, kind, got)
    # W fills the warmup gap: stage 1's first W lands before its second F
    s1 = [c for _t, st, c in sorted(flat) if st == 1]
    assert s1.index("W0") < s1.index("F2")


def test_engine_plan_tables():
    plan = sched.get_schedule("ZB-H1").engine_plan(4, 8)
    T, D, TB = plan["T"], plan["D"], plan["TB"]
    assert T == 11 and D == 3 and TB == 14
    # B ticks: strict reverse forward-tick order, then drain
    assert plan["b_tick"][:3] == [10, 9, 8] and plan["b_tick"][-3:] == [-1] * 3
    # every W lags its B by exactly D ticks and every tick appears once
    for r in range(TB):
        if plan["w_tick"][r] >= 0:
            assert plan["w_tick"][r] == plan["b_tick"][r - D]
    assert sorted(t for t in plan["w_tick"] if t >= 0) == list(range(T))
    with pytest.raises(ValueError, match="fused backward"):
        sched.get_schedule("1F1B").engine_plan(4, 8)


def test_segment_layers_param_weighted_reference_behavior():
    """Drive-by: the reference seg_method='param'-weighted cut, exercised
    directly (not just the uniform degenerate case): cuts follow the
    prefix-sum targets, keep >= 1 layer per stage, and beat the uniform
    cut's imbalance on skewed weights."""
    # uniform weights degenerate to the uniform cut
    assert segment_layers([3] * 8, 4, method="param") == [0, 2, 4, 6, 8]
    # skewed: embedding-like heavy head/tail (reference SegmentLayers puts
    # cuts where the prefix sum crosses total * s / S)
    w = [8, 1, 1, 1, 1, 1, 1, 8]
    cuts = segment_layers(w, 3, method="param")
    assert cuts[0] == 0 and cuts[-1] == len(w)
    assert all(b > a for a, b in zip(cuts, cuts[1:]))  # >= 1 layer/stage
    sums = [sum(w[a:b]) for a, b in zip(cuts, cuts[1:])]
    uni = segment_layers(w, 3)
    uni_sums = [sum(w[a:b]) for a, b in zip(uni, uni[1:])]
    assert max(sums) - min(sums) <= max(uni_sums) - min(uni_sums)
    assert max(sums) <= 10  # no stage hoards both heavy layers
    # a monotone ramp: later stages get fewer layers
    ramp = segment_layers(list(range(1, 13)), 3, method="param")
    lens = [b - a for a, b in zip(ramp, ramp[1:])]
    assert lens[0] > lens[-1]


# ------------------------------------------------------- engine parity
class Block(nn.Layer):
    def __init__(self, h):
        super().__init__()
        self.fc = nn.Linear(h, h)

    def forward(self, x):
        return paddle.tanh(self.fc(x))


def _blocks(n, h, seed=0):
    paddle.seed(seed)
    return [Block(h) for _ in range(n)]


def _copy_blocks(blocks, h):
    out = []
    for b in blocks:
        nb = Block(h)
        nb.set_state_dict({k: v for k, v in b.state_dict().items()})
        out.append(nb)
    return out


def test_zb_split_backward_matches_sequential_4dev():
    """ZB-H1 on a 4-device pp mesh: loss and per-layer grads match the
    sequential reference — the split backward's deferred grad-weight
    accumulation changes reassociation only."""
    mesh = ProcessMesh(np.arange(4), ["pp"])
    blocks = _blocks(4, 16, seed=1)
    M = 8
    x_np = np.random.default_rng(1).normal(size=(M * 2, 16)).astype(np.float32)

    ref_blocks = _copy_blocks(blocks, 16)
    h = paddle.to_tensor(x_np)
    for b in ref_blocks:
        h = b(h)
    loss_ref = paddle.sum(h * h)
    loss_ref.backward()

    stack = PipelineStack(_copy_blocks(blocks, 16), mesh, pp_axis="pp",
                          num_microbatches=M, schedule="ZB-H1")
    out = stack(paddle.to_tensor(x_np))
    loss = paddle.sum(out * out)
    loss.backward()

    np.testing.assert_allclose(float(loss._value), float(loss_ref._value),
                               rtol=1e-5)
    sp = stack.stacked_parameters()
    for ki, key in enumerate(stack._keys):
        g = np.asarray(sp[ki].grad._value).reshape(
            (4,) + tuple(sp[ki].shape[2:]))
        for li, b in enumerate(ref_blocks):
            bg = np.asarray(b.state_dict()[key].grad._value)
            np.testing.assert_allclose(g[li], bg, rtol=1e-4, atol=1e-5)


class _StackModel(nn.Layer):
    def __init__(self, mesh, schedule, M, n=4, h=16, seed=5):
        super().__init__()
        paddle.seed(seed)
        self.stack = PipelineStack([Block(h) for _ in range(n)], mesh,
                                   pp_axis="pp", num_microbatches=M,
                                   schedule=schedule)

    def forward(self, x):
        return self.stack(x)


def _mse_loss(model, x, y):
    out = model(x)
    return paddle.sum((out - y) * (out - y))


def test_zb_train_losses_match_1f1b_4dev():
    """The acceptance criterion end-to-end: a ZB-H1 train run on a
    4-device CPU mesh matches the 1F1B run's per-step losses within
    jit-reassociation tolerance."""
    from paddle_tpu.jit import TrainStep

    def train(schedule, steps=3):
        mesh = ProcessMesh(np.arange(4), ["pp"])
        m = _StackModel(mesh, schedule, M=8)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=m.parameters())
        step = TrainStep(m, opt, _mse_loss)
        rng = np.random.default_rng(1)
        x = rng.normal(size=(16, 16)).astype(np.float32)
        y = rng.normal(size=(16, 16)).astype(np.float32)
        return [float(step(paddle.to_tensor(x), paddle.to_tensor(y))._value)
                for _ in range(steps)]

    l_1f1b = train("1F1B")
    l_zb = train("ZB-H1")
    np.testing.assert_allclose(l_zb, l_1f1b, rtol=1e-5)
    assert l_zb[-1] < l_zb[0]  # it actually trains


def test_zb_sharded_step_overlap_8dev_lint_clean():
    """dp2 x pp4 hybrid: ZB-H1 under ShardedTrainStep with
    comm_overlap=True (reduce-scatter + ppermute-chain grad sync) matches
    the plain 1F1B step's losses, with FLAGS_verify_sharding linting the
    whole program — forward scan, split-backward scan, and the overlap
    chain — before any 8-device dispatch."""
    def train(schedule, overlap, verify, steps=3):
        mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
        m = _StackModel(mesh, schedule, M=8, n=8)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=m.parameters())
        step = dist.ShardedTrainStep(
            m, opt, _mse_loss, mesh, batch_spec=PartitionSpec("dp"),
            zero_stage=1, comm_overlap=overlap)
        rng = np.random.default_rng(1)
        x = rng.normal(size=(16, 16)).astype(np.float32)
        y = rng.normal(size=(16, 16)).astype(np.float32)
        if verify:
            _flags.set_flags({"FLAGS_verify_sharding": True})
        try:
            return [float(step(paddle.to_tensor(x),
                               paddle.to_tensor(y))._value)
                    for _ in range(steps)]
        finally:
            if verify:
                _flags.set_flags({"FLAGS_verify_sharding": False})

    base = train("1F1B", overlap=False, verify=False)
    sched.pipeline_stats(reset=True)
    zb = train("ZB-H1", overlap=True, verify=True)
    np.testing.assert_allclose(zb, base, rtol=1e-4)
    st = sched.pipeline_stats()
    assert st["w_slots"] > 0, st          # the split backward ran
    assert st["overlap_issued"] > 0, st   # the ring chain was issued


def test_mesh_lint_passes_statically_on_every_schedule():
    """Acceptance: the mesh lint passes on every registered schedule's
    program — for ZB-H1 that includes the hand-scheduled backward scan
    (ring ppermutes + grad psums), linted abstractly with no collective
    ever dispatched."""
    from paddle_tpu.profiler import mesh_lint_stats

    mesh = ProcessMesh(np.arange(4), ["pp"])
    x = paddle.to_tensor(
        np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32))
    _flags.set_flags({"FLAGS_verify_sharding": True})
    try:
        for name in sched.available_schedules():
            before = mesh_lint_stats()
            stack = PipelineStack(_blocks(4, 16, seed=2), mesh, pp_axis="pp",
                                  num_microbatches=4, schedule=name)
            stack(x)  # _maybe_mesh_lint raises MeshLintError on violation
            after = mesh_lint_stats()
            assert after["entries_linted"] > before["entries_linted"], name
            assert after["entries_failed"] == before["entries_failed"], name
            assert after["collectives_checked"] > before["collectives_checked"], name
    finally:
        _flags.set_flags({"FLAGS_verify_sharding": False})


def test_schedule_flag_listener_invalidates_cached_steps():
    """FLAGS_pipeline_schedule contract (same as FLAGS_decode_chunk): a
    stack built with schedule=None follows the flag; set_flags re-resolves
    it, drops its cached built steps, and the next forward runs the new
    schedule — numerics unchanged, telemetry proves the switch."""
    mesh = ProcessMesh(np.arange(4), ["pp"])
    stack = PipelineStack(_blocks(4, 16, seed=3), mesh, pp_axis="pp",
                          num_microbatches=8)  # schedule=None -> flag
    assert stack._schedule == "1F1B"
    x = paddle.to_tensor(
        np.random.default_rng(2).normal(size=(16, 16)).astype(np.float32))
    sched.pipeline_stats(reset=True)
    o1 = stack(x)
    assert sched.pipeline_stats()["w_slots"] == 0
    assert stack._fn_cache
    _flags.set_flags({"FLAGS_pipeline_schedule": "ZB-H1"})
    try:
        assert stack._schedule == "ZB-H1"
        o2 = stack(x)
        assert sched.pipeline_stats()["w_slots"] > 0
        np.testing.assert_allclose(np.asarray(o1._value),
                                   np.asarray(o2._value),
                                   rtol=1e-5, atol=1e-6)
    finally:
        _flags.set_flags({"FLAGS_pipeline_schedule": "1F1B"})
    assert stack._schedule == "1F1B"
    # explicit schedules never follow the flag
    pinned = PipelineStack(_blocks(4, 16, seed=3), mesh, pp_axis="pp",
                           num_microbatches=8, schedule="FThenB")
    _flags.set_flags({"FLAGS_pipeline_schedule": "ZB-H1"})
    try:
        assert pinned._schedule == "FThenB"
    finally:
        _flags.set_flags({"FLAGS_pipeline_schedule": "1F1B"})


def test_pipeline_stats_and_summary_footer():
    """profiler.pipeline_stats() is module-owned by schedules.py (one
    schema, no drift) and Profiler.summary() grows a "Pipeline:" footer
    once any pipeline program ran this process."""
    import paddle_tpu.profiler as profiler

    mesh = ProcessMesh(np.arange(4), ["pp"])
    stack = PipelineStack(_blocks(4, 16, seed=7), mesh, pp_axis="pp",
                          num_microbatches=4, schedule="ZB-H1")
    x = paddle.to_tensor(
        np.random.default_rng(7).normal(size=(8, 16)).astype(np.float32))
    profiler.pipeline_stats(reset=True)
    stack(x)
    st = profiler.pipeline_stats()
    assert st == sched.pipeline_stats()  # same owner, same schema
    plan = sched.get_schedule("ZB-H1").engine_plan(4, 4)
    assert st["programs"] == 1
    assert st["f_slots"] == st["b_slots"] == st["w_slots"] == 16
    assert st["ticks"] == plan["T"] + plan["TB"]
    p = profiler.Profiler(timer_only=True)
    p.start()
    p.stop()
    table = p.summary()
    assert "Pipeline: programs=" in table
    assert f"W={st['w_slots']}" in table
    # reset zeroes the counters and the footer disappears
    from paddle_tpu.profiler.statistics import pipeline_line

    profiler.pipeline_stats(reset=True)
    assert not pipeline_line(profiler.pipeline_stats())


def test_zb_structure_two_scans_and_tick_counts():
    """The ZB engine is exactly TWO scans: a forward of T = M + S - 1
    ticks storing only boundary activations, and a split-backward of
    T + D ticks consuming the schedule table (abstract trace only — no
    dispatch, no compile)."""
    mesh = ProcessMesh(np.arange(4), ["pp"])
    S, M = 4, 8
    stack = PipelineStack(_blocks(4, 16, seed=4), mesh, pp_axis="pp",
                          num_microbatches=M, schedule="ZB-H1")
    stack._bcast_template = []
    fn = stack._make_fn(M)
    params = [p._value for p in stack.stacked_parameters()]
    x = jnp.zeros((M, 2, 16), jnp.float32)

    def grad_prog(*args):
        out, vjp = jax.vjp(fn, *args)
        return vjp(jnp.ones_like(out))

    jaxpr = str(jax.make_jaxpr(grad_prog)(*params, x))
    plan = sched.get_schedule("ZB-H1").engine_plan(S, M)
    assert f"length={plan['T']}" in jaxpr     # forward scan
    assert f"length={plan['TB']}" in jaxpr    # split-backward scan
    assert jaxpr.count("scan[") == 2


def test_zb_full_model_llama_matches_single_device():
    """Embedding + trunk + head all inside the ZB-H1 pipelined region
    (stage-predicated edge conds recomputed inside the B/W vjps): loss and
    edge-layer grads match single-device."""
    from paddle_tpu.models.llama import (LlamaForCausalLM, llama_tiny,
                                         pipeline_llama)

    rng = np.random.default_rng(3)
    ids = rng.integers(0, 96, size=(4, 12)).astype(np.int32)
    labels = rng.integers(0, 96, size=(4, 12)).astype(np.int64)

    def make_model():
        paddle.seed(11)
        cfg = llama_tiny(vocab_size=96, hidden_size=32, intermediate_size=64,
                         num_hidden_layers=4, num_attention_heads=4,
                         num_key_value_heads=4, max_position_embeddings=32,
                         dtype="float32")
        return LlamaForCausalLM(cfg)

    ref = make_model()
    ref_loss, _ = ref(paddle.to_tensor(ids), labels=paddle.to_tensor(labels))
    ref_loss.backward()

    mesh = ProcessMesh(np.arange(4), ["pp"])
    pm = make_model()
    pipeline_llama(pm, mesh, pp_axis="pp", num_microbatches=2,
                   schedule="ZB-H1")
    loss, _ = pm(paddle.to_tensor(ids), labels=paddle.to_tensor(labels))
    np.testing.assert_allclose(float(loss._value), float(ref_loss._value),
                               rtol=1e-4)
    loss.backward()
    np.testing.assert_allclose(
        np.asarray(pm.model.embed_tokens.weight.grad._value),
        np.asarray(ref.model.embed_tokens.weight.grad._value),
        rtol=2e-3, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(pm.lm_head.weight.grad._value),
        np.asarray(ref.lm_head.weight.grad._value), rtol=2e-3, atol=1e-5)


def test_pipeline_parallel_entry_and_vpp_untouched():
    """pipeline_parallel() routes block lists to PipelineStack under the
    requested schedule; VPP keeps its own engine (schedule registry does
    not claim it)."""
    mesh = ProcessMesh(np.arange(4), ["pp"])
    st = pipeline_parallel(_blocks(4, 16, seed=6), mesh,
                           schedule="ZB-H1", num_microbatches=4)
    assert isinstance(st, PipelineStack) and st._schedule == "ZB-H1"
    with pytest.raises(TypeError, match="no pipeliner"):
        pipeline_parallel(object(), mesh)
    with pytest.raises(ValueError, match="schedule must be one of"):
        PipelineStack(_blocks(4, 16, seed=6), mesh, pp_axis="pp",
                      schedule="ZB-H9000")
    # a VPP-interleaved stack's weights live in chunk order; switching it
    # to any non-VPP schedule would silently compose blocks permuted —
    # set_schedule (the pipeline_scheduler pass face) must refuse
    vpp_mesh = ProcessMesh(np.arange(2), ["pp"])
    vpp = PipelineStack(_blocks(4, 16, seed=6), vpp_mesh, pp_axis="pp",
                        num_microbatches=2, schedule="VPP",
                        num_virtual_stages=2)
    with pytest.raises(ValueError, match="VPP chunk order"):
        vpp.set_schedule("ZB-H1")
    vpp.set_schedule("VPP")  # idempotent re-select stays fine
