#!/usr/bin/env python
"""Standalone protocol lint: model-check the cluster wire protocol.

Two modes (docs/PROTOCOL_LINT.md), mirroring tools/lint_ir.py and
tools/lint_mesh.py:

  python tools/lint_protocol.py
      Battery mode — (1) asserts the spec <-> handler binding both ways
      (every serving/protocol.py message with a router/worker handler,
      every handler with a spec row); (2) exhaustively model-checks the
      REAL protocol over all three transport semantics (ShmRing, the
      drop-as-death TCP stub, and the real TcpRing whose drop is a
      redial + at-least-once frame duplication) and requires ZERO
      invariant violations and ZERO deadlocks; (3) runs every
      seeded-violation scenario (dropped intake fsync, lethal ring
      timeout, two routers replaying one journal, a TcpRing teardown
      shrugged off as backpressure) and requires each to produce a minimal
      counterexample trace naming the violated invariant — printed, so
      the battery output doubles as protocol documentation; (4) runs
      the blocking-call AST lint over the real serving/ +
      distributed/collective/ trees (must be clean) and over seeded
      source fixtures (each must be flagged); (5) checks the generated
      wire table against docs/SERVING_CLUSTER.md.  Everything is
      abstract — no process is forked, no ring is created.

  python tools/lint_protocol.py --pytest tests/test_serving_cluster.py
      Sweep mode — runs the pytest node ids in-process, then the full
      protocol battery checks (the protocol is static: whatever the
      tests exercised dynamically, the model check re-proves
      exhaustively).

Exit status 0 = all scenarios behaved; 1 = a clean scenario violated or
a seeded scenario went unflagged (report on stdout).
"""

from __future__ import annotations

import os
import sys

from _lint_common import (pytest_failures, report as _report, run_cli,
                          setup_env)

setup_env()


def _protocol_checks() -> int:
    from paddle_tpu.serving import protocol
    from paddle_tpu.static import protocol_lint as pl

    failures = 0

    # ---- spec <-> handler binding, both directions ------------------
    from paddle_tpu.serving import cluster, cluster_worker

    try:
        protocol.bind_handlers(
            "router", protocol.handler_lookup(cluster.EngineCluster, "_ev_"),
            prefix="_ev_", label="EngineCluster event dispatch")
        cluster_worker.handler_tables()
        print("ok   spec-handler-binding: router + decode/prefill/standby "
              "tables bind bidirectionally")
    except protocol.ProtocolSpecError as e:
        print(f"FAIL spec-handler-binding: {e}")
        failures += 1

    # ---- the real spec must explore clean on EVERY transport --------
    for scenario in ("clean-shmring", "clean-tcp", "clean-tcp-ring"):
        res = pl.check_model(scenario)
        failures += _report(
            f"model-{scenario} ({res.states} states, "
            f"{res.transitions} transitions, complete={res.complete})",
            res.violations)

    # ---- seeded scenarios: each must yield a named counterexample ---
    for name, sc in pl.SCENARIOS.items():
        if not sc.expect:
            continue
        res = pl.check_model(name)
        failures += _report(f"model-{name}", res.violations,
                            expect_codes=set(sc.expect))
        for v in res.violations:
            if v.code in sc.expect:
                print("     " + pl.render_trace(v).replace("\n", "\n     "))

    # ---- blocking-call lint: the real trees must be clean -----------
    failures += _report("blocking-lint-real-tree (serving/ + "
                        "distributed/collective/)",
                        pl.lint_blocking_calls())

    # ---- blocking-call lint: seeded fixtures must be flagged --------
    fixtures = [
        ("blocking-unbounded-ring-wait",
         "def poll(ring_in):\n"
         "    return ring_in.pop()\n",
         {"unbounded-blocking"}),
        ("blocking-unbounded-store-wait",
         "def sync(store, key):\n"
         "    store.wait(key)\n",
         {"unbounded-blocking"}),
        ("blocking-lock-held-ring-push",
         "def forward(self, data):\n"
         "    with self._state_lock:\n"
         "        self.ring_out.push(data, timeout_ms=250)\n",
         {"lock-held-blocking"}),
        ("blocking-two-party-circular-wait",
         "def exchange(ring_in, ring_out, data):\n"
         "    ring_out.push(data)\n"
         "    return ring_in.pop()\n",
         {"circular-wait"}),
    ]
    for label, src, codes in fixtures:
        failures += _report(label, pl.lint_source(src, f"<{label}>"),
                            expect_codes=codes)
    # the retry_backoff shared deadline sanctions an untimed wait
    failures += _report(
        "blocking-retry-backoff-sanctioned",
        pl.lint_source(
            "def forward(worker, data):\n"
            "    def _push():\n"
            "        worker.ring_in.push(data)\n"
            "    retry_backoff(_push, timeout_s=5.0)\n",
            "<sanctioned>"))

    # ---- the generated wire table must match the committed doc ------
    doc = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "SERVING_CLUSTER.md")
    with open(doc, encoding="utf-8") as f:
        text = f.read()
    table = protocol.wire_table_markdown()
    if table in text:
        print("ok   wire-table-doc: docs/SERVING_CLUSTER.md embeds the "
              "generated table")
    else:
        print("FAIL wire-table-doc: docs/SERVING_CLUSTER.md drifted from "
              "protocol.wire_table_markdown() — regenerate the block "
              "between the wire-protocol markers")
        failures += 1

    print()
    print("protocol lint counters:", pl.protocol_lint_stats())
    return failures


def _battery() -> int:
    return _protocol_checks()


def _pytest_sweep(node_ids) -> int:
    import pytest

    rc = pytest.main(list(node_ids) + ["-q", "-p", "no:cacheprovider"])
    print(f"\npytest exit={rc}; running the full protocol battery")
    return _protocol_checks() + pytest_failures(rc)


def main(argv=None):
    return run_cli(
        "lint_protocol", _battery, _pytest_sweep, argv, doc=__doc__,
        ok_msg="all scenarios behaved (real spec explores clean, seeded "
               "violations produce counterexample traces)",
        fail_msg="{n} scenario(s) misbehaved",
        forward_extras=True,
        pytest_help="run these pytest node ids, then the full protocol "
                    "battery; unrecognized args are forwarded to pytest")


if __name__ == "__main__":
    sys.exit(main())
