"""Per-op micro-benchmark harness.

Reference roles: test/legacy_test/benchmark.py (per-op ms timing harness)
and tools/ci_op_benchmark.sh + tools/check_op_benchmark_result.py (CI gate
comparing per-op timings between two builds).

TPU-native: each case jit-compiles one hot op at a standard shape and
times it with the RTT-cancelling readback-synced timer the kernel
autotuner uses (`paddle_tpu.ops.autotune._time_fn` — block_until_ready
resolves at dispatch on the remote transport, so naive timing is
fiction).  Emits one JSON document; `--compare old.json` exits 1 on
relative regressions beyond `--threshold`, mirroring the reference CI.

Usage:
    python tools/bench_ops.py --out ops_v5e.json
    python tools/bench_ops.py --out new.json --compare ops_v5e.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _cases(quick=False):
    """-> list of (name, build) where build() returns (jitted_fn, args,
    flops, moved_bytes).  Shapes are the framework's hot tier; `quick`
    shrinks them so CPU CI can execute the harness end-to-end."""
    import jax
    import jax.numpy as jnp

    S = 256 if quick else 4096
    H = 128 if quick else 4096
    B = 2 if quick else 8
    L = 128 if quick else 1024
    dt = jnp.float32 if quick else jnp.bfloat16
    isz = jnp.dtype(dt).itemsize
    k0 = jax.random.PRNGKey(0)

    def matmul():
        a = jax.random.normal(k0, (S, H), dt)
        b = jax.random.normal(k0, (H, H), dt)
        return jax.jit(lambda a, b: a @ b), (a, b), 2 * S * H * H, (S * H + H * H + S * H) * isz

    def batched_matmul():
        a = jax.random.normal(k0, (B, L, H), dt)
        b = jax.random.normal(k0, (B, H, H), dt)
        return (jax.jit(lambda a, b: jnp.einsum("blh,bhk->blk", a, b)), (a, b),
                2 * B * L * H * H, (B * L * H * 2 + B * H * H) * isz)

    def softmax():
        x = jax.random.normal(k0, (B * L, H), dt)
        return jax.jit(lambda x: jax.nn.softmax(x, -1)), (x,), 5 * B * L * H, 2 * B * L * H * isz

    def layer_norm():
        from paddle_tpu.ops import fused_layer_norm

        x = jax.random.normal(k0, (B * L, H), dt)
        w = jnp.ones((H,), dt)
        bb = jnp.zeros((H,), dt)
        return (jax.jit(lambda x, w, b: fused_layer_norm(x, w, b, epsilon=1e-5)), (x, w, bb),
                8 * B * L * H, 2 * B * L * H * isz)

    def rms_norm():
        from paddle_tpu.ops import fused_rms_norm

        x = jax.random.normal(k0, (B * L, H), dt)
        w = jnp.ones((H,), dt)
        return (jax.jit(lambda x, w: fused_rms_norm(x, w, epsilon=1e-5)), (x, w),
                4 * B * L * H, 2 * B * L * H * isz)

    def swiglu():
        from paddle_tpu.ops import swiglu as _swiglu

        a = jax.random.normal(k0, (B * L, H), dt)
        b = jax.random.normal(k0, (B * L, H), dt)
        return (jax.jit(lambda a, b: _swiglu(a, b)), (a, b),
                5 * B * L * H, 3 * B * L * H * isz)

    def flash_attention():
        from paddle_tpu.ops import flash_attention as _fa

        n, hd = (2, 64) if quick else (8, 128)
        q, k, v = (jax.random.normal(kk, (1, L, n, hd), dt)
                   for kk in jax.random.split(k0, 3))
        return (jax.jit(lambda q, k, v: _fa(q, k, v, causal=True)), (q, k, v),
                2 * 2 * n * L * L * hd // 2, 4 * L * n * hd * isz)

    def embedding():
        tbl = jax.random.normal(k0, (32000, H), dt)
        ids = jax.random.randint(k0, (B * L,), 0, 32000)
        return (jax.jit(lambda t, i: jnp.take(t, i, axis=0)), (tbl, ids),
                0, B * L * H * isz * 2)

    def matmul_epilogue_fused():
        from paddle_tpu.ops import matmul_bias_act

        x = jax.random.normal(k0, (S, H), dt)
        w = jax.random.normal(k0, (H, H), dt)
        b = jnp.zeros((H,), dt)
        return (jax.jit(lambda x, w, b: matmul_bias_act(x, w, b, "gelu")),
                (x, w, b), 2 * S * H * H, (S * H * 2 + H * H) * isz)

    def matmul_epilogue_unfused():
        # the XLA chain the fusion replaces — same shapes, same JSON block,
        # so the gate can compare fused vs unfused directly on chip
        x = jax.random.normal(k0, (S, H), dt)
        w = jax.random.normal(k0, (H, H), dt)
        b = jnp.zeros((H,), dt)
        return (jax.jit(lambda x, w, b: jax.nn.gelu(x @ w + b, approximate=False)),
                (x, w, b), 2 * S * H * H, (S * H * 2 + H * H) * isz)

    def adamw_update():
        n = S * H
        p, g, m, v = (jax.random.normal(kk, (n,), jnp.float32)
                      for kk in jax.random.split(k0, 4))

        def upd(p, g, m, v):
            m = 0.9 * m + 0.1 * g
            v = 0.999 * v + 0.001 * g * g
            return p - 1e-3 * (m / (jnp.sqrt(v) + 1e-8) + 0.01 * p), m, v

        return jax.jit(upd), (p, g, m, v), 12 * n, 7 * n * 4

    return [(f.__name__, f) for f in (
        matmul, batched_matmul, softmax, layer_norm, rms_norm, swiglu,
        flash_attention, embedding, matmul_epilogue_fused,
        matmul_epilogue_unfused, adamw_update)]


def run(quick=False, iters=3):
    import jax

    from paddle_tpu.ops.autotune import _time_fn

    results = {}
    for name, build in _cases(quick):
        try:
            fn, args, flops, moved = build()
            ms = _time_fn(fn, args, iters=iters,
                          inner=1 if quick else None,
                          target_ms=50.0 if quick else 300.0)
        except Exception as e:  # noqa: BLE001 — record, don't abort the sweep
            results[name] = {"error": f"{type(e).__name__}: {e}"[:200]}
            print(f"  ERROR {name}: {results[name]['error']}", flush=True)
            continue
        entry = {"ms": round(ms, 4)}
        if flops:
            entry["tflops"] = round(flops / ms / 1e9, 2)
        if moved:
            entry["gbps"] = round(moved / ms / 1e6, 1)
        results[name] = entry
        print(f"  {name}: {entry}", flush=True)
    d = jax.devices()[0]
    return {"device_kind": d.device_kind, "platform": d.platform,
            "tier": "quick" if quick else "full",
            "timestamp": time.strftime("%Y-%m-%d %H:%M:%S"), "ops": results}


def compare(new, old, threshold):
    """-> list of regression strings (empty = gate passes).

    An op that timed cleanly in `old` but errors or disappears in `new`
    IS a regression — going from 2ms to broken must not pass the gate."""
    bad = []
    for name, prev in old.get("ops", {}).items():
        if "ms" not in prev or prev["ms"] <= 0:
            continue
        entry = new.get("ops", {}).get(name)
        if entry is None:
            bad.append(f"{name}: {prev['ms']:.4f} ms -> MISSING from new run")
            continue
        if "ms" not in entry:
            bad.append(f"{name}: {prev['ms']:.4f} ms -> "
                       f"{entry.get('error', 'no timing')}")
            continue
        rel = entry["ms"] / prev["ms"] - 1.0
        if rel > threshold:
            bad.append(f"{name}: {prev['ms']:.4f} -> {entry['ms']:.4f} ms "
                       f"(+{rel * 100:.1f}% > {threshold * 100:.0f}%)")
    return bad


def main(argv=None):
    p = argparse.ArgumentParser(description="per-op micro-benchmarks")
    p.add_argument("--out", default=None, help="write results JSON here")
    p.add_argument("--compare", default=None, help="old results to gate against")
    p.add_argument("--threshold", type=float, default=0.05)
    p.add_argument("--quick", action="store_true",
                   help="tiny shapes / cpu-safe (CI smoke)")
    args = p.parse_args(argv)

    res = run(quick=args.quick)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(res, f, indent=1)
        print(f"wrote {args.out}")
    errors = [k for k, v in res["ops"].items() if "error" in v]
    if errors:
        print(f"ERRORS in: {', '.join(errors)}")
    if args.compare:
        with open(args.compare) as f:
            old = json.load(f)
        mismatch = [f"{f} ({old.get(f)} vs {res.get(f)})"
                    for f in ("device_kind", "tier")
                    if old.get(f) != res.get(f)]
        if mismatch:
            print(f"compare: {', '.join(mismatch)} mismatch; not gating")
        else:
            bad = compare(res, old, args.threshold)
            for b in bad:
                print(f"REGRESSION {b}")
            if bad:
                return 1
            print("no regressions")
    return 2 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
