"""Shared plumbing for the static-analysis lint tools.

tools/lint_ir.py, tools/lint_mesh.py and tools/lint_protocol.py are the
same shape: an environment preamble that must run before jax imports, a
battery mode (clean scenarios must stay clean, seeded violations must be
flagged), a ``--pytest`` sweep mode riding the program-creation hook,
and a pass/fail CLI wrapper.  This module is that shape, once — each
tool keeps only its actual scenarios.

Import order matters: call ``setup_env()`` at module top, BEFORE any
paddle_tpu/jax import, exactly like the inline preambles it replaced.
"""

from __future__ import annotations

import argparse
import os
import sys


def setup_env(host_devices=None):
    """Repo-root on sys.path + CPU backend; optionally force an N-device
    XLA host platform (the 8-device mesh the mesh-lint battery runs on).
    Must run before jax is imported anywhere in the process."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if host_devices:
        xla_flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in xla_flags:
            os.environ["XLA_FLAGS"] = (
                xla_flags
                + f" --xla_force_host_platform_device_count={host_devices}")


def report(label, violations, expect_codes=None):
    """Print one scenario row; returns 1 on unexpected outcome.

    ``expect_codes=None`` means the scenario must be CLEAN; a set of
    codes means the seeded violation must be FLAGGED with (at least)
    those codes — the two outcomes every lint battery is made of."""
    if expect_codes is None:
        if violations:
            print(f"FAIL {label}: expected clean, got "
                  f"{len(violations)} violation(s):")
            for v in violations:
                print(f"    {v}")
            return 1
        print(f"ok   {label}: clean")
        return 0
    got = {v.code for v in violations}
    missing = set(expect_codes) - got
    if missing:
        print(f"FAIL {label}: seeded violation NOT flagged "
              f"(wanted {sorted(expect_codes)}, got {sorted(got)})")
        return 1
    print(f"ok   {label}: flagged {sorted(got & set(expect_codes))}")
    return 0


def tracked_pytest(node_ids):
    """Run pytest in-process with the Program-creation hook installed;
    returns (exit_code, traced_programs)."""
    import pytest

    from paddle_tpu.static.verify import track_programs

    with track_programs() as programs:
        rc = pytest.main(list(node_ids) + ["-q", "-p", "no:cacheprovider"])
    return rc, programs


def pytest_failures(rc):
    """pytest exit codes that count as a sweep failure (5 = no tests
    collected is tolerated: a node filter may legitimately match
    nothing)."""
    return 1 if rc not in (0, 5) else 0


def run_cli(name, battery, sweep, argv=None, *, doc=None, ok_msg,
            fail_msg, forward_extras=False,
            pytest_help="run these pytest node ids through the sweep "
                        "mode"):
    """The tools' shared CLI: no args = battery, ``--pytest NODE...`` =
    sweep.  ``forward_extras`` passes unrecognized argv (e.g. -m 'not
    slow', -k expr) through to pytest.  Returns the process exit code:
    0 = everything behaved, 1 = ``fail_msg`` (with the count)."""
    ap = argparse.ArgumentParser(description=doc)
    ap.add_argument("--pytest", nargs="+", metavar="NODE",
                    help=pytest_help)
    if forward_extras:
        args, extra = ap.parse_known_args(argv)
        node_ids = (list(args.pytest) + extra) if args.pytest else None
    else:
        args = ap.parse_args(argv)
        node_ids = args.pytest
    failures = sweep(node_ids) if node_ids else battery()
    if failures:
        print(f"\n{name}: " + fail_msg.format(n=failures))
        return 1
    print(f"\n{name}: {ok_msg}")
    return 0
