#!/usr/bin/env python
"""Standalone mesh lint: sweep sharded computations through the MeshLinter.

Two modes (docs/MESH_LINT.md), mirroring tools/lint_ir.py:

  python tools/lint_mesh.py
      Battery mode — builds the canonical distributed scenarios on the
      8-device CPU mesh (ZeRO-rewritten captured train step, dp x mp
      ShardedTrainStep, paged-KV GenerationEngine with TP pool sharding)
      and requires ZERO violations; then builds one seeded fixture per
      violation class (mismatched collective axis, axis-size mismatch,
      conditional collective, bad ppermute participation, use-after-
      donation, replicated-giant, over-budget) and requires each to be
      FLAGGED.  Everything is abstract — no device collective launches,
      so the battery cannot trip the 8-device XLA:CPU SIGSEGV class it
      guards against.

  python tools/lint_mesh.py --pytest tests/test_auto_parallel.py [more...]
      Sweep mode — runs pytest in-process with the program-creation hook
      installed (static.verify.track_programs) and mesh-lints EVERY
      Program those tests trace.

Exit status 0 = all scenarios behaved; 1 = a clean scenario violated or a
seeded fixture went unflagged (report on stdout).
"""

from __future__ import annotations

import sys

from _lint_common import (pytest_failures, report as _report, run_cli,
                          setup_env, tracked_pytest)

setup_env(host_devices=8)


def _battery() -> int:
    import numpy as np
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")

    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.static as static
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import ProcessMesh
    from paddle_tpu.distributed.shard_map_compat import shard_map
    from paddle_tpu.static.mesh_lint import (MeshLinter, lint_decode_chain,
                                             lint_engine, lint_program,
                                             lint_train_step,
                                             mesh_lint_stats)
    from paddle_tpu.static.passes import apply_pass

    failures = 0
    rng = np.random.default_rng(0)
    devs = jax.devices()
    dp8 = Mesh(np.array(devs[:8]).reshape(8), ("dp",))
    dpmp = ProcessMesh(np.arange(8).reshape(4, 2), ["dp", "mp"])

    # ---------------------------------------------------- clean scenarios
    # 1. captured train step + ZeRO sharding rewrite, linted at the same
    # boundary the Executor uses
    paddle.seed(0)
    layer = nn.Linear(16, 8)
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=layer.parameters())
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [8, 16], "float32")
        yt = static.data("yt", [8, 8], "float32")
        loss = paddle.mean((layer(x) - yt) ** 2)
        opt.minimize(loss)
    apply_pass(prog, "auto_parallel_sharding", mesh=dp8, stage=2)
    failures += _report(
        "zero-sharded-program",
        lint_program(prog, [loss._vid], mesh=dp8))

    # 2. dp x mp ShardedTrainStep — abstract build only (journaled
    # accumulator materialization + jaxpr trace; nothing dispatches)
    paddle.seed(1)
    model = nn.Linear(16, 16)
    opt2 = paddle.optimizer.AdamW(learning_rate=1e-2,
                                  parameters=model.parameters())
    step = dist.ShardedTrainStep(
        model, opt2, lambda m, bx, by: paddle.mean((m(bx) - by) ** 2),
        dpmp, batch_spec=P("dp"))
    bx = jax.ShapeDtypeStruct((8, 16), jnp.float32)
    by = jax.ShapeDtypeStruct((8, 16), jnp.float32)
    violations, est = lint_train_step(step, bx, by)
    failures += _report("sharded-train-step", violations)
    print(f"     per-device estimate: "
          f"{ {k: int(v) for k, v in est.items()} }")

    # 3. GenerationEngine with TP-sharded paged pools
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import GenerationEngine

    paddle.seed(2)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128)
    mp2 = ProcessMesh(np.arange(2).reshape(2), ["mp"])
    eng = GenerationEngine(LlamaForCausalLM(cfg), num_blocks=16, mesh=mp2)
    violations, est = lint_engine(eng)
    failures += _report("tp-sharded-engine", violations)
    print(f"     per-device estimate: "
          f"{ {k: int(v) for k, v in est.items()} }")

    # 4. the fused decode-chain kernel a TP-sharded engine adopts
    # (schedule search over the mesh): statically linted before dispatch
    # — the head-local shard_map chain must walk with ZERO collectives
    from paddle_tpu.ops.decode_chain import DecodeChainSpec

    chain_spec = DecodeChainSpec(batch=2, num_heads=4, num_kv_heads=2,
                                 head_dim=8, block_size=4, max_blocks=2,
                                 num_blocks=8, kv="int8", mesh=mp2)
    failures += _report(
        "mesh-decode-chain-kernel",
        lint_decode_chain(chain_spec,
                          {"layout": "batch", "gather": "take"}))

    # ------------------------------------------------- seeded violations
    aval = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    linter = MeshLinter(mesh=dp8)

    # mismatched collective axis: a shard_map built for an 'mp' mesh on a
    # session mesh that only has 'dp'
    mp_mesh = Mesh(np.array(devs[:2]), ("mp",))
    wrong_axis = shard_map(lambda v: lax.psum(v, "mp"), mesh=mp_mesh,
                           in_specs=P("mp"), out_specs=P())
    failures += _report("mismatched-collective-axis",
                        linter.lint_callable(wrong_axis, aval),
                        expect_codes={"unknown-axis"})

    # axis-size mismatch: shard_map binds dp=2 against the dp=8 session
    dp2 = Mesh(np.array(devs[:2]), ("dp",))
    small_world = shard_map(lambda v: lax.psum(v, "dp"), mesh=dp2,
                            in_specs=P("dp"), out_specs=P())
    failures += _report("axis-size-mismatch",
                        linter.lint_callable(small_world, aval),
                        expect_codes={"axis-size-mismatch"})

    # conditional collective: psum reachable only under a data-dependent
    # predicate — the deadlock/SIGSEGV class
    def cond_body(v):
        return lax.cond(v.sum() > 0, lambda t: lax.psum(t, "dp"),
                        lambda t: t, v)

    conditional = shard_map(cond_body, mesh=dp8, in_specs=P("dp"),
                            out_specs=P("dp"))
    failures += _report("conditional-collective",
                        linter.lint_callable(conditional, aval),
                        expect_codes={"conditional-collective"})

    # bad ppermute: duplicate source — jax traces it happily, runtime
    # participation is non-uniform
    bad_perm = shard_map(
        lambda v: lax.ppermute(v, "dp", [(0, 1), (0, 2)]), mesh=dp8,
        in_specs=P("dp"), out_specs=P("dp"))
    failures += _report("bad-ppermute-participation",
                        linter.lint_callable(bad_perm, aval),
                        expect_codes={"bad-permutation"})

    # decode-chain kernel on a foreign session mesh: the mp-sharded
    # chain judged against a dp-only session — the mesh-congruence class
    # the adopt path's pre-dispatch lint turns into a counted disable
    failures += _report(
        "decode-chain-foreign-mesh",
        lint_decode_chain(chain_spec, {"layout": "batch", "gather": "take"},
                          mesh=dp8),
        expect_codes={"unknown-axis"})

    # use-after-donation: fetch the PRE-update buffer of a donated,
    # in-place-written state var
    paddle.seed(3)
    layer2 = nn.Linear(4, 4)
    opt3 = paddle.optimizer.SGD(learning_rate=0.1,
                                parameters=layer2.parameters())
    prog2 = static.Program()
    with static.program_guard(prog2):
        x2 = static.data("x2", [4, 4], "float32")
        y2 = static.data("y2", [4, 4], "float32")
        loss2 = paddle.mean((layer2(x2) - y2) ** 2)
        opt3.minimize(loss2)
    donated_vid = next(iter(prog2.writes))
    failures += _report(
        "use-after-donation",
        lint_program(prog2, [loss2._vid, donated_vid], mesh=dp8),
        expect_codes={"use-after-donation"})

    # replicated-giant: a >threshold tensor fully replicated on the mesh
    big = jax.ShapeDtypeStruct((2048, 2048), jnp.float32)  # 16 MiB
    failures += _report(
        "replicated-giant",
        linter.lint_placements([("big_param", big, None)]),
        expect_codes={"replicated-giant"})

    # over-budget: per-device estimate above a deliberately tiny budget
    tight = MeshLinter(mesh=dp8, budget_bytes=1024)
    viol, est = tight.estimate_device_bytes(
        {"params": [("w", big, P("dp", None))]})
    failures += _report("over-budget-memory", viol,
                        expect_codes={"over-budget"})
    print(f"     per-device estimate: "
          f"{ {k: int(v) for k, v in est.items()} }")

    print()
    print("mesh lint counters:", mesh_lint_stats())
    del rng
    return failures


def _pytest_sweep(node_ids) -> int:
    from paddle_tpu.static.mesh_lint import lint_program, mesh_lint_stats

    rc, programs = tracked_pytest(node_ids)
    print(f"\npytest exit={rc}; {len(programs)} Program(s) traced — "
          "mesh-linting")
    failures = 0
    for i, prog in enumerate(programs):
        violations = lint_program(prog)
        failures += _report(f"program#{i} "
                            f"({len(prog.global_block().ops)} ops)",
                            violations)
    print()
    print("mesh lint counters:", mesh_lint_stats())
    return failures + pytest_failures(rc)


def main(argv=None):
    return run_cli(
        "lint_mesh", _battery, _pytest_sweep, argv, doc=__doc__,
        ok_msg="all scenarios behaved (clean paths clean, seeded "
               "violations flagged)",
        fail_msg="{n} scenario(s) misbehaved",
        forward_extras=True,
        pytest_help="run these pytest node ids and mesh-lint every "
                    "Program they trace; unrecognized args (e.g. "
                    "-m 'not slow', -k expr) are forwarded to pytest")


if __name__ == "__main__":
    sys.exit(main())
