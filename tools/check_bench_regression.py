"""Benchmark regression gate.

Reference: tools/ci_op_benchmark.sh + tools/check_op_benchmark_result.py —
the reference CI compares op-benchmark logs between base and PR builds and
fails on relative regressions beyond a threshold.

Usage:
    python tools/check_bench_regression.py BENCH_r03.json BENCH_r04.json \
        [--threshold 0.05]

Each file holds the driver-recorded bench payload: either the raw JSON line
bench.py prints ({"metric", "value", ...}) or the driver wrapper with
stdout/rc fields.  Exit 1 (loud) when the new value regresses more than
`threshold` relative to the old on the same metric; missing/failed runs
(rc != 0 or value 0) are reported but never counted as regressions — an
unhealthy tunnel must not mask or fabricate a perf signal.

Serving payloads carrying the SLO-percentile section (bench_decode.py
detail.slo.single: p50/p95/p99 time-to-first-token + inter-token latency)
are ALSO gated, with the direction inverted (latency growing beyond
--slo-threshold is the regression) and a wider default threshold — tail
percentiles jitter more than throughput means.  Payloads lacking the
section on either side skip the latency gate silently.

Serving payloads carrying the snapshot section (bench_decode.py
detail.snapshot: save_ms/restore_ms of a live mid-flight engine snapshot,
serving/snapshot.py) gate like the SLO percentiles — lower is better, so
growth beyond --slo-threshold is the regression (the wall cost of
honoring a preemption) — and skip silently on pre-snapshot payloads.

Serving payloads carrying the overload section (bench_decode.py
detail.overload: resident-stream p99 inter-token latency under a long
mid-decode prefill, chunked-interleaved vs atomic admission) gate both
ITL numbers lower-is-better at --slo-threshold — the chunked side is the
product, the atomic side the workload control — and skip silently on
pre-chunking payloads.

Cluster payloads carrying the fail-over section (bench_cluster.py
detail.failover: detect_ms from SIGKILL to the router's first re-dispatch,
recover_ms to every stream complete) gate like the SLO percentiles —
lower is better, growth beyond --slo-threshold is the regression — and
skip silently on pre-cluster payloads.  A fail-over run that LOST a
request records rc != 0 and is skipped as unhealthy rather than gated:
zero-loss is an acceptance criterion, not a trend.

Cluster payloads carrying the transport section (bench_cluster.py
detail.transport: {"kind", "tcp_bytes", "reconnects", "frames_sent",
"frames_recv"}) gate the SOCKET data plane when both sides ran
--transport tcp: reconnects must not grow at all (a localhost cluster
run never legitimately drops a connection — any new reconnect is a
transport bug, not jitter) and tcp_bytes growth beyond the regular
--threshold means framing overhead regressed.  Pre-transport payloads
(no section) and shm runs skip silently.

Training payloads carrying the pipeline-schedule section (bench.py
detail.pipeline.schedules: per-schedule bubble fraction from the static
simulator, fleet/meta_parallel/schedules.py) gate each schedule's bubble
LOWER-is-better at the regular --threshold — the numbers are
deterministic host math, so any growth means a schedule table got worse
— and skip silently on pre-schedule payloads.

Schedule-search payloads carrying the decode-chain section
(bench_schedule_search.py detail.decode_chain: per-variant
win-or-disabled verdicts — kv dtypes "bf16"/"int8", plus "mesh" for the
2-device sharded-engine verdict keyed by (device kind, mesh shape) and
"prefill" for the K-tiled fused prefill-attention candidate) gate each
variant's measured win like the headline metric; a DISABLED side (win 0
— an honest measured loss, e.g. CPU interpret mode) skips that variant
rather than fabricating a signal, and is never recorded as value=0 by
the bench in the first place.  The loop is generic over variant names,
so sides missing a variant (pre-mesh rounds) skip it silently.
"""

from __future__ import annotations

import argparse
import json
import sys


def _payload_dict(path):
    """The bench payload dict for a driver-recorded file, unwrapping the
    {"rc", "stdout"/"tail"} driver envelope -> (dict, None) or
    (None, reason)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return None, f"unreadable ({e})"
    if isinstance(data, dict) and ("stdout" in data or "tail" in data):
        rc = data.get("rc", data.get("returncode"))
        if rc not in (0, None):
            return None, f"rc={rc}"
        text = str(data.get("stdout") or data.get("tail") or "")
        for line in reversed(text.strip().splitlines()):
            try:
                inner = json.loads(line)
            except ValueError:
                continue
            if isinstance(inner, dict) and "metric" in inner:
                data = inner
                break
        else:
            return None, "no metric line in stdout"
    if not isinstance(data, dict):
        return None, "no metric field"
    return data, None


def load_payload(path):
    """-> (metric, value) or (None, reason)."""
    data, err = _payload_dict(path)
    if data is None:
        return None, err
    if "metric" not in data:
        return None, "no metric field"
    try:
        value = float(data.get("value", 0.0))
    except (TypeError, ValueError):
        return None, f"non-numeric value {data.get('value')!r}"
    if value <= 0.0:
        return None, "zero/failed value"
    return (data["metric"], value), None


def load_slo(path):
    """The SLO-percentile section of a serving bench payload
    (bench_decode.py detail.slo.single: {"ttft_ms": {p50, p95, p99},
    "itl_ms": {...}}), or None when the payload has no such section —
    pre-SLO rounds and non-serving benches simply skip the latency
    gate."""
    data, _err = _payload_dict(path)
    if not isinstance(data, dict):
        return None
    slo = (data.get("detail") or {}).get("slo")
    if not isinstance(slo, dict):
        return None
    return slo.get("single")


def load_snapshot(path):
    """The snapshot-timing section of a serving bench payload
    (bench_decode.py detail.snapshot: {"save_ms", "restore_ms", "bytes",
    "resume_tokens_match"}), or None when absent — pre-snapshot rounds
    and non-serving benches skip the gate."""
    data, _err = _payload_dict(path)
    if not isinstance(data, dict):
        return None
    snap = (data.get("detail") or {}).get("snapshot")
    return snap if isinstance(snap, dict) else None


def load_overload(path):
    """The overload section of a serving bench payload (bench_decode.py
    detail.overload: {"itl_p99_ms_chunked", "itl_p99_ms_atomic",
    "tokens_per_sec_chunked", ...}), or None when absent — pre-chunking
    rounds and non-serving benches skip the gate."""
    data, _err = _payload_dict(path)
    if not isinstance(data, dict):
        return None
    ov = (data.get("detail") or {}).get("overload")
    return ov if isinstance(ov, dict) else None


def load_failover(path):
    """The fail-over section of a cluster bench payload (bench_cluster.py
    detail.failover: {"detect_ms", "recover_ms", "lost", "streams_match",
    "first_token_ms": {"cold", "warm_respawn", "standby"}}), or None when
    absent — pre-cluster rounds and non-cluster benches skip the gate.
    Payloads written before the warm-start round carry no first_token_ms
    dict; that sub-gate skips silently for them."""
    data, _err = _payload_dict(path)
    if not isinstance(data, dict):
        return None
    fo = (data.get("detail") or {}).get("failover")
    return fo if isinstance(fo, dict) else None


def load_transport(path):
    """The transport section of a cluster bench payload (bench_cluster.py
    detail.transport: {"kind", "tcp_bytes", "reconnects", "frames_sent",
    "frames_recv"}), or None when absent — payloads written before the
    socket data plane existed skip the gate silently."""
    data, _err = _payload_dict(path)
    if not isinstance(data, dict):
        return None
    tr = (data.get("detail") or {}).get("transport")
    return tr if isinstance(tr, dict) else None


def load_pipeline(path):
    """The pipeline-schedule section of a training bench payload (bench.py
    detail.pipeline: {"S", "M", "schedules": {"1F1B": bubble, ...}}), or
    None when absent — pre-schedule rounds skip the gate."""
    data, _err = _payload_dict(path)
    if not isinstance(data, dict):
        return None
    pl = (data.get("detail") or {}).get("pipeline")
    if not isinstance(pl, dict):
        return None
    sch = pl.get("schedules")
    return sch if isinstance(sch, dict) else None


def load_decode_chain(path):
    """The decode-chain section of a schedule-search bench payload
    (bench_schedule_search.py detail.decode_chain: {"bf16": {"win": ...,
    "disabled_persisted": ...}, "int8": {...}}), or None when the payload
    has no such section — pre-phase-2 rounds skip the gate."""
    data, _err = _payload_dict(path)
    if not isinstance(data, dict):
        return None
    dec = (data.get("detail") or {}).get("decode_chain")
    return dec if isinstance(dec, dict) else None


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("old")
    p.add_argument("new")
    p.add_argument("--threshold", type=float, default=0.05,
                   help="max allowed relative regression (default 5%%)")
    p.add_argument("--slo-threshold", type=float, default=0.5,
                   help="max allowed relative latency-percentile growth "
                        "for the serving SLO section (default 50%% — "
                        "CPU-measured tail percentiles jitter far more "
                        "than throughput means)")
    args = p.parse_args(argv)

    old, old_err = load_payload(args.old)
    new, new_err = load_payload(args.new)
    if old is None or new is None:
        print(f"bench gate: SKIP — old: {old_err or 'ok'}; new: {new_err or 'ok'} "
              "(unhealthy runs are never counted as regressions)")
        return 0
    om, ov = old
    nm, nv = new
    if om != nm:
        print(f"bench gate: SKIP — metrics differ ({om} vs {nm})")
        return 0
    rel = (nv - ov) / ov
    status = "REGRESSION" if rel < -args.threshold else "ok"
    print(f"bench gate [{om}]: {ov:.2f} -> {nv:.2f} ({rel:+.2%}) {status}")
    rc = 1 if status == "REGRESSION" else 0

    # SLO-percentile gate (serving benches): latencies are LOWER-is-
    # better, so the regression direction inverts.  Percentiles present
    # on only one side (pre-SLO rounds) skip silently — an added metric
    # must not fail the round that adds it.
    old_slo, new_slo = load_slo(args.old), load_slo(args.new)
    if old_slo and new_slo:
        for section in ("ttft_ms", "itl_ms"):
            o, n = old_slo.get(section), new_slo.get(section)
            if not (isinstance(o, dict) and isinstance(n, dict)):
                continue
            for pk in ("p50", "p95", "p99"):
                if pk not in o or pk not in n or not o[pk] > 0:
                    continue
                rel = (float(n[pk]) - float(o[pk])) / float(o[pk])
                stat = ("REGRESSION" if rel > args.slo_threshold else "ok")
                print(f"bench gate [slo {section} {pk}]: {o[pk]:.2f} -> "
                      f"{n[pk]:.2f} ms ({rel:+.2%}) {stat}")
                if stat == "REGRESSION":
                    rc = 1

    # snapshot-timing gate (serving fault tolerance): save/restore wall
    # of a live-engine snapshot, lower-is-better like the SLO section
    # and sharing its wider threshold (single-shot wall timings jitter).
    # Sides missing the section (pre-snapshot rounds) skip silently.
    old_snap, new_snap = load_snapshot(args.old), load_snapshot(args.new)
    if old_snap and new_snap:
        for sk in ("save_ms", "restore_ms"):
            try:
                o, n = float(old_snap.get(sk, 0)), float(new_snap.get(sk, 0))
            except (TypeError, ValueError):
                continue
            if not o > 0 or not n > 0:
                continue
            rel = (n - o) / o
            stat = "REGRESSION" if rel > args.slo_threshold else "ok"
            print(f"bench gate [snapshot {sk}]: {o:.2f} -> {n:.2f} ms "
                  f"({rel:+.2%}) {stat}")
            if stat == "REGRESSION":
                rc = 1

    # overload inter-token-latency gate (chunked prefill interleaving):
    # the adversarial mix's resident-stream p99 ITL under the long-prompt
    # disturbance, lower-is-better at the SLO threshold like the other
    # tail-latency walls.  Both the chunked and the atomic sides gate —
    # the chunked number is the product, the atomic one the control (a
    # regression there means the workload drifted, not the interleaver).
    # Sides missing the section (pre-chunking rounds) skip silently.
    old_ov, new_ov = load_overload(args.old), load_overload(args.new)
    if old_ov and new_ov:
        for ok in ("itl_p99_ms_chunked", "itl_p99_ms_atomic"):
            try:
                o, n = float(old_ov.get(ok, 0)), float(new_ov.get(ok, 0))
            except (TypeError, ValueError):
                continue
            if not o > 0 or not n > 0:
                continue
            rel = (n - o) / o
            stat = "REGRESSION" if rel > args.slo_threshold else "ok"
            print(f"bench gate [overload {ok}]: {o:.2f} -> {n:.2f} ms "
                  f"({rel:+.2%}) {stat}")
            if stat == "REGRESSION":
                rc = 1

    # fail-over latency gate (serving cluster): SIGKILL-to-detection and
    # SIGKILL-to-recovery walls, lower-is-better at the SLO threshold
    # (single-shot process-kill timings jitter like tail percentiles).
    # Sides missing the section (pre-cluster rounds) skip silently; a
    # side that lost a request never got here (its rc != 0 already
    # skipped the whole payload as unhealthy).
    old_fo, new_fo = load_failover(args.old), load_failover(args.new)
    if old_fo and new_fo:
        for fk in ("detect_ms", "recover_ms"):
            try:
                o, n = float(old_fo.get(fk, 0)), float(new_fo.get(fk, 0))
            except (TypeError, ValueError):
                continue
            if not o > 0 or not n > 0:
                continue
            rel = (n - o) / o
            stat = "REGRESSION" if rel > args.slo_threshold else "ok"
            print(f"bench gate [failover {fk}]: {o:.1f} -> {n:.1f} ms "
                  f"({rel:+.2%}) {stat}")
            if stat == "REGRESSION":
                rc = 1
        # detect -> first-token per recovery mode (warm-start round):
        # the user-visible outage per path, lower-is-better at the SLO
        # threshold.  Pre-warm-start payloads carry no first_token_ms
        # dict — the sub-gate skips silently for them.
        oft, nft = old_fo.get("first_token_ms"), new_fo.get("first_token_ms")
        if isinstance(oft, dict) and isinstance(nft, dict):
            for mode in sorted(set(oft) & set(nft)):
                try:
                    o, n = float(oft[mode]), float(nft[mode])
                except (TypeError, ValueError):
                    continue
                if not o > 0 or not n > 0:
                    continue
                rel = (n - o) / o
                stat = "REGRESSION" if rel > args.slo_threshold else "ok"
                print(f"bench gate [failover first_token {mode}]: "
                      f"{o:.1f} -> {n:.1f} ms ({rel:+.2%}) {stat}")
                if stat == "REGRESSION":
                    rc = 1

    # transport gate (socket data plane): only when BOTH sides ran the
    # tcp transport.  Pre-transport payloads (no detail.transport) and
    # shm runs skip silently — a silent skip, never a fabricated signal.
    old_tr, new_tr = load_transport(args.old), load_transport(args.new)
    if (old_tr and new_tr
            and old_tr.get("kind") == "tcp" and new_tr.get("kind") == "tcp"):
        try:
            o_rc = int(old_tr.get("reconnects", 0))
            n_rc = int(new_tr.get("reconnects", 0))
        except (TypeError, ValueError):
            o_rc = n_rc = 0
        # reconnects are not jitter: a localhost bench never legitimately
        # drops a connection, so ANY growth is a transport regression
        stat = "REGRESSION" if n_rc > o_rc else "ok"
        print(f"bench gate [transport reconnects]: {o_rc} -> {n_rc} {stat}")
        if stat == "REGRESSION":
            rc = 1
        try:
            o_b = float(old_tr.get("tcp_bytes", 0))
            n_b = float(new_tr.get("tcp_bytes", 0))
        except (TypeError, ValueError):
            o_b = n_b = 0.0
        if o_b > 0 and n_b > 0:
            rel = (n_b - o_b) / o_b
            stat = "REGRESSION" if rel > args.threshold else "ok"
            print(f"bench gate [transport tcp_bytes]: {o_b:.0f} -> "
                  f"{n_b:.0f} ({rel:+.2%}) {stat}")
            if stat == "REGRESSION":
                rc = 1

    # pipeline-schedule gate: per-schedule simulator bubble fraction,
    # LOWER is better (growth means the schedule table regressed — the
    # numbers are deterministic host math, so the regular threshold
    # applies, not the jittery SLO one).  Sides missing the section
    # (pre-schedule rounds) skip silently.
    old_pl, new_pl = load_pipeline(args.old), load_pipeline(args.new)
    if old_pl and new_pl:
        for name in sorted(set(old_pl) & set(new_pl)):
            try:
                o, n = float(old_pl[name]), float(new_pl[name])
            except (TypeError, ValueError):
                continue
            if o <= 0:
                # zero is the BEST bubble (unlike throughput, where 0 is
                # unhealthy): any growth from a true zero-bubble baseline
                # is a regression, never a skip
                stat = "REGRESSION" if n > 1e-9 else "ok"
                print(f"bench gate [pipeline {name}]: bubble {o:.4f} -> "
                      f"{n:.4f} {stat}")
            else:
                rel = (n - o) / o
                stat = "REGRESSION" if rel > args.threshold else "ok"
                print(f"bench gate [pipeline {name}]: bubble {o:.4f} -> "
                      f"{n:.4f} ({rel:+.2%}) {stat}")
            if stat == "REGRESSION":
                rc = 1

    # decode-chain gate (schedule search phase 2): per-variant measured
    # wins, higher-is-better like the headline.  A disabled side (win 0)
    # is an honest measured loss, not a regression — skip that variant.
    old_dc, new_dc = load_decode_chain(args.old), load_decode_chain(args.new)
    if old_dc and new_dc:
        for kv in sorted(set(old_dc) & set(new_dc)):
            try:
                ow = float((old_dc[kv] or {}).get("win", 0.0) or 0.0)
                nw = float((new_dc[kv] or {}).get("win", 0.0) or 0.0)
            except (TypeError, ValueError):
                continue
            if ow <= 0.0 or nw <= 0.0:
                print(f"bench gate [decode_chain {kv}]: SKIP — "
                      f"{ow:.2f} -> {nw:.2f} (disabled side: an honest "
                      "loss is never a regression)")
                continue
            rel = (nw - ow) / ow
            stat = "REGRESSION" if rel < -args.threshold else "ok"
            print(f"bench gate [decode_chain {kv}]: {ow:.2f} -> {nw:.2f} "
                  f"({rel:+.2%}) {stat}")
            if stat == "REGRESSION":
                rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
