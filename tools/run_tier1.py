#!/usr/bin/env python
"""Sharded, crash-isolated tier-1 test runner (ROADMAP item 5).

The tier-1 suite outgrew its budget (>9 min observed) and the in-process
8-device XLA:CPU collectives SIGSEGV intermittently on jax 0.4.37 — a
mid-suite segfault kills the WHOLE pytest process, so real coverage kept
leaking into `slow`.  This runner fixes both mechanically:

- **Sharding**: test FILES are partitioned deterministically (sorted,
  round-robin) into N subprocess shards that run concurrently; total wall
  time divides by the job count instead of paying one serial sweep.
- **Crash isolation**: a shard that dies on a signal fails ALONE — its
  siblings' results stand, and the report names the crashed shard, the
  signal, and the last test it reached.
- **Isolated workers**: the modules known to exercise the in-process
  8-device communicator (the SIGSEGV class) each get a DEDICATED worker
  shard by default, with one automatic retry on signal-death (the crash
  is intermittent infra, not an assertion failure; genuine test failures
  never retry).
- **Shared compile cache**: every shard points at ONE persistent XLA
  compile-cache dir (tests/conftest.py honors PADDLE_TPU_TEST_CACHE_DIR),
  so repeated model compiles are warm across shards and across runs.

Usage:
  python tools/run_tier1.py                 # full tier-1, default shards
  python tools/run_tier1.py --jobs 6        # concurrency
  python tools/run_tier1.py --list          # show the deterministic plan
  python tools/run_tier1.py -k decode       # forwarded pytest -k filter

`run_isolated_test(module, func)` is the in-suite face of the same
mechanism: a tier-1 test whose payload can segfault the process runs it
in a bootstrapped subprocess and retries signal-deaths — used by
tests/test_fleet.py::test_group_sharded_levels (previously slow-marked).
"""

from __future__ import annotations

import argparse
import concurrent.futures
import os
import re
import signal as signal_mod
import subprocess
import sys
import time
from dataclasses import dataclass, field

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Modules that drive the in-process multi-device XLA:CPU communicator
# hard enough to hit the intermittent jax-0.4.37 SIGSEGV/SIGABRT class
# (CHANGES.md PR 2/3 timing notes): each runs in its OWN worker shard so
# a crash never takes sibling results down, and signal-deaths retry once.
# The TP-sharded serving modules dispatch GSPMD-partitioned decode
# programs over 2- and 4-device meshes every test — same crash class,
# same containment.
ISOLATED_DEFAULT = (
    "test_fleet.py",
    "test_dist_passes.py",
    "test_pipeline.py",
    "test_moe.py",
    "test_ring_attention.py",
    "test_multiprocess_collective.py",
    "test_sharded_embedding.py",
    "test_serving_mesh.py",
    "test_serving_mesh_spec.py",
    "test_engine_snapshot_mesh.py",
    # Sharded decode-chain fusion: shard_map'd interpret-mode Pallas
    # bodies inside jitted decode scans on 2/4/8-device meshes, plus
    # run_isolated_test subprocess workers of its own — and the bench
    # smoke test, whose subprocess drives the same 2-device engine.
    "test_decode_chain_mesh.py",
    "test_bench_schedule_search.py",
    # The serving-cluster modules fork real engine/router processes and
    # SIGKILL them mid-protocol (heartbeat fail-over, drain migration,
    # the cluster crash matrix, the fail-over bench) — never in a shared
    # worker, where an orphaned subprocess or a poisoned shm ring could
    # take sibling modules' results down with it.
    "test_serving_cluster.py",
    "test_serving_cluster_crash.py",
    "test_bench_cluster.py",
    # Warm-start tier: forks standby workers, SIGKILLs them mid-warmup,
    # and asserts a respawned worker's persistent-cache hit counters —
    # same fork/SIGKILL crash class, same containment.
    "test_cluster_warm.py",
    # The pipeline-schedule parity suite dispatches GSPMD split-backward
    # pipeline programs (custom-vjp scan pairs with ring ppermutes) over
    # 4- and 8-device in-process meshes every test — the same crash class,
    # the same containment.
    "test_zb_schedules.py",
)

DEFAULT_CACHE_DIR = "/tmp/jax_cache"

_PYTEST_BASE = ["-q", "--continue-on-collection-errors",
                "-p", "no:cacheprovider", "-p", "no:xdist",
                "-p", "no:randomly"]

_SUMMARY_RE = re.compile(
    r"(\d+) (passed|failed|errors?|skipped|deselected|xfailed|xpassed|"
    r"warnings?)")


@dataclass
class Shard:
    name: str
    files: list
    isolated: bool = False
    # results
    rc: int = None
    counts: dict = field(default_factory=dict)
    duration: float = 0.0
    signal: int = 0
    retries: int = 0
    tail: str = ""

    @property
    def ok(self):
        # 5 = nothing collected (a marker filter can empty a shard)
        return self.rc in (0, 5)

    @property
    def crashed(self):
        return self.rc is not None and self.rc < 0


def partition_files(files, shards):
    """Deterministic round-robin partition of the SORTED file list —
    identical inputs always produce identical shard assignments, so a
    failure reproduces with the same plan on every machine."""
    buckets = [[] for _ in range(max(1, shards))]
    for i, f in enumerate(sorted(files)):
        buckets[i % len(buckets)].append(f)
    return [b for b in buckets if b]


def build_plan(tests_dir, shards, isolated=ISOLATED_DEFAULT):
    """The full deterministic run plan: one dedicated shard per isolated
    module present, plus `shards` round-robin shards over the rest."""
    all_files = sorted(
        f for f in os.listdir(tests_dir)
        if f.startswith("test_") and f.endswith(".py"))
    iso = [f for f in all_files if f in set(isolated)]
    rest = [f for f in all_files if f not in set(isolated)]
    plan = [Shard(name=f"iso:{f[:-3]}",
                  files=[os.path.join(tests_dir, f)], isolated=True)
            for f in iso]
    for i, bucket in enumerate(partition_files(rest, shards)):
        plan.append(Shard(
            name=f"shard{i}",
            files=[os.path.join(tests_dir, f) for f in bucket]))
    return plan


def _parse_counts(output):
    counts = {}
    for line in reversed(output.splitlines()):
        found = _SUMMARY_RE.findall(line)
        if found and any(k in ("passed", "failed", "error", "errors")
                         for _n, k in found):
            for n, key in found:
                counts[key.rstrip("s") if key != "passed" else key] = int(n)
            break
    return counts


def run_shard(shard, marker="not slow", cache_dir=DEFAULT_CACHE_DIR,
              timeout=1800, extra_args=(), retry_crashed=1, python=None):
    """Run one shard in a subprocess; fills the Shard's result fields.
    Signal-deaths of ISOLATED shards retry up to retry_crashed times —
    the 8-device communicator crash is intermittent infra, and a retry
    that passes means the tests pass; assertion failures never retry."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PADDLE_TPU_TEST_CACHE_DIR"] = cache_dir
    cmd = [python or sys.executable, "-m", "pytest", *shard.files,
           *_PYTEST_BASE, "-m", marker, *extra_args]
    attempts = 1 + (retry_crashed if shard.isolated else 0)
    t0 = time.monotonic()
    for attempt in range(attempts):
        try:
            proc = subprocess.run(
                cmd, cwd=_REPO_ROOT, env=env, timeout=timeout,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
            rc, out = proc.returncode, proc.stdout or ""
        except subprocess.TimeoutExpired as e:
            rc = -signal_mod.SIGKILL
            out = ((e.stdout or b"").decode("utf-8", "replace")
                   if isinstance(e.stdout, bytes) else (e.stdout or ""))
            out += f"\n<run_tier1: shard timed out after {timeout}s>"
        shard.rc = rc
        shard.counts = _parse_counts(out)
        shard.tail = "\n".join(out.splitlines()[-30:])
        if rc < 0:
            shard.signal = -rc
            if attempt + 1 < attempts:
                shard.retries += 1
                continue
        break
    shard.duration = time.monotonic() - t0
    return shard


def _fmt_counts(counts):
    order = ("passed", "failed", "error", "skipped", "deselected")
    parts = [f"{counts[k]} {k}" for k in order if counts.get(k)]
    return ", ".join(parts) if parts else "no summary parsed"


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--tests-dir", default=os.path.join(_REPO_ROOT, "tests"))
    ap.add_argument("--shards", type=int, default=6,
                    help="round-robin shards over the non-isolated files")
    ap.add_argument("--jobs", type=int,
                    default=max(1, min(6, (os.cpu_count() or 2) // 4)),
                    help="concurrent shard subprocesses")
    ap.add_argument("-m", "--marker", default="not slow")
    ap.add_argument("--cache-dir", default=DEFAULT_CACHE_DIR,
                    help="persistent XLA compile cache shared by all "
                         "shards (tests/conftest.py reads "
                         "PADDLE_TPU_TEST_CACHE_DIR)")
    ap.add_argument("--timeout", type=int, default=1800,
                    help="per-shard wall clock limit (seconds)")
    ap.add_argument("--retry-crashed", type=int, default=1,
                    help="signal-death retries for isolated shards")
    ap.add_argument("--no-isolate", action="store_true",
                    help="disable the dedicated collective-module workers")
    ap.add_argument("--list", action="store_true",
                    help="print the deterministic plan and exit")
    ap.add_argument("pytest_args", nargs="*",
                    help="extra args forwarded to every pytest shard "
                         "(e.g. -k decode)")
    args = ap.parse_args(argv)

    isolated = () if args.no_isolate else ISOLATED_DEFAULT
    plan = build_plan(args.tests_dir, args.shards, isolated=isolated)
    if args.list:
        for shard in plan:
            tag = " [isolated]" if shard.isolated else ""
            print(f"{shard.name}{tag}: "
                  f"{' '.join(os.path.basename(f) for f in shard.files)}")
        return 0

    os.makedirs(args.cache_dir, exist_ok=True)
    print(f"run_tier1: {len(plan)} shards "
          f"({sum(s.isolated for s in plan)} isolated), jobs={args.jobs}, "
          f"marker={args.marker!r}, cache={args.cache_dir}")
    t0 = time.monotonic()
    with concurrent.futures.ThreadPoolExecutor(args.jobs) as pool:
        futures = [
            pool.submit(run_shard, shard, marker=args.marker,
                        cache_dir=args.cache_dir, timeout=args.timeout,
                        extra_args=tuple(args.pytest_args),
                        retry_crashed=args.retry_crashed)
            for shard in plan
        ]
        for fut in concurrent.futures.as_completed(futures):
            shard = fut.result()
            status = "ok" if shard.ok else (
                f"CRASHED (signal {shard.signal})" if shard.crashed
                else f"FAILED (rc {shard.rc})")
            retr = f" [retried {shard.retries}x]" if shard.retries else ""
            print(f"  {shard.name:<32} {status:<22} "
                  f"{shard.duration:7.1f}s  {_fmt_counts(shard.counts)}"
                  f"{retr}", flush=True)

    wall = time.monotonic() - t0
    total = {}
    for shard in plan:
        for k, n in shard.counts.items():
            total[k] = total.get(k, 0) + n
    bad = [s for s in plan if not s.ok]
    print(f"\nrun_tier1: {_fmt_counts(total)} across {len(plan)} shards "
          f"in {wall:.1f}s wall")
    for shard in bad:
        print(f"\n--- {shard.name} "
              f"({'signal ' + str(shard.signal) if shard.crashed else 'rc ' + str(shard.rc)}) "
              f"last output ---")
        print(shard.tail)
    if bad:
        print(f"\nrun_tier1: {len(bad)} shard(s) failed "
              f"({sum(s.crashed for s in bad)} crashed) — "
              "siblings' results above are complete")
        return 1
    print("run_tier1: all shards green")
    return 0


# ---------------------------------------------------------------------------
# in-suite crash isolation (tests that exercise the SIGSEGV class)

_WORKER_BOOTSTRAP = """\
import os
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")
jax.config.update("jax_compilation_cache_dir", {cache_dir!r})
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
import importlib
getattr(importlib.import_module({module!r}), {func!r})()
"""


def run_isolated_test(module, func, retries=2, timeout=300,
                      cache_dir=None):
    """Run `module.func()` in a bootstrapped subprocess (8 virtual CPU
    devices, persistent compile cache — the tests/conftest.py environment)
    and raise AssertionError on failure.  A signal-death retries up to
    `retries` times: the in-process 8-device communicator crash is
    intermittent infra, while an assertion failure (rc > 0) fails
    immediately.  This is how a SIGSEGV-prone payload runs INSIDE tier-1
    without being able to kill the suite process."""
    cache_dir = cache_dir or os.environ.get("PADDLE_TPU_TEST_CACHE_DIR",
                                            DEFAULT_CACHE_DIR)
    code = _WORKER_BOOTSTRAP.format(cache_dir=cache_dir, module=module,
                                    func=func)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    last_rc, last_out = None, ""
    for attempt in range(1 + retries):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code], cwd=_REPO_ROOT, env=env,
                timeout=timeout, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            last_rc, last_out = proc.returncode, proc.stdout or ""
        except subprocess.TimeoutExpired as e:
            # a hung worker is the DEADLOCK half of the crash class this
            # mechanism contains: retryable, like a signal-death
            out = e.stdout or b""
            last_out = (out.decode("utf-8", "replace")
                        if isinstance(out, bytes) else out)
            last_out += f"\n<worker timed out after {timeout}s>"
            last_rc = -signal_mod.SIGKILL
        if last_rc == 0:
            return attempt
        if last_rc > 0:  # genuine failure: never retry
            break
    tail = "\n".join(last_out.splitlines()[-25:])
    kind = (f"signal {-last_rc}" if last_rc < 0 else f"rc {last_rc}")
    raise AssertionError(
        f"isolated worker {module}.{func} failed ({kind}) after "
        f"{attempt + 1} attempt(s):\n{tail}")


if __name__ == "__main__":
    sys.exit(main())
