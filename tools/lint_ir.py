#!/usr/bin/env python
"""Standalone static-IR lint: sweep Programs through the ProgramVerifier.

Two modes (docs/VERIFIER.md):

  python tools/lint_ir.py
      Battery mode — builds the canonical capture paths (arith capture,
      layer capture, append_backward + optimizer step, cond/while, vanilla
      attention/rms-norm/swiglu with the Pallas fusion pipeline applied,
      weight-only quant export) and verifies every resulting Program,
      including a pass-differential replay of the fused attention program.

  python tools/lint_ir.py --pytest tests/test_static.py [more node ids...]
      Sweep mode — runs pytest in-process with the program-creation hook
      installed (static.verify.track_programs) and verifies EVERY Program
      those tests trace.

Exit status 0 = no violations; 1 = violations found (report on stdout).
"""

from __future__ import annotations

import sys

from _lint_common import (pytest_failures, run_cli, setup_env,
                          tracked_pytest)

setup_env()


def _verify_all(programs, labels=None):
    from paddle_tpu.static.verify import ProgramVerifier

    verifier = ProgramVerifier()
    failures = 0
    for i, prog in enumerate(programs):
        label = labels[i] if labels else f"program#{i}"
        if isinstance(prog, list):  # pre-computed violations (differential)
            n_ops, violations = None, prog
        else:
            n_ops, violations = len(prog.global_block().ops), verifier.verify(prog)
        ops = f" ({n_ops} ops)" if n_ops is not None else ""
        if violations:
            failures += 1
            print(f"FAIL {label}{ops}:")
            for v in violations:
                print(f"    {v}")
        else:
            print(f"ok   {label}{ops}")
    return failures


def _battery() -> int:
    import numpy as np
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.static as static
    from paddle_tpu.static.rewrite import PallasFusionPass
    from paddle_tpu.static.verify import differential_check, verify_stats

    paddle.seed(0)
    programs, labels = [], []

    # arithmetic capture
    p = static.Program()
    with static.program_guard(p):
        x = static.data("x", [2, 3], "float32")
        y = static.data("y", [2, 3], "float32")
        z = paddle.sum(paddle.add(x, y) * 2.0)
    programs.append(p), labels.append("arith")

    # layer capture + backward + optimizer step
    layer = nn.Linear(4, 2)
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=layer.parameters())
    p = static.Program()
    with static.program_guard(p):
        x = static.data("xt", [8, 4], "float32")
        yt = static.data("yt", [8, 2], "float32")
        loss = paddle.mean((layer(x) - yt) ** 2)
        opt.minimize(loss)
    programs.append(p), labels.append("train-step")

    # control flow
    p = static.Program()
    with static.program_guard(p):
        x = static.data("cf", [4], "float32")
        c = static.nn.cond(x.sum() > 0, lambda: x * 2, lambda: x - 1)
        i0 = paddle.zeros([], dtype="int32")
        s0 = paddle.ones([])
        _, sv = static.nn.while_loop(lambda i, s: s < 16.0,
                                     lambda i, s: (i + 1, s * 2.0), [i0, s0])
    programs.append(p), labels.append("control-flow")

    # vanilla attention/rms-norm/swiglu -> Pallas fusion + differential
    B, N, S, D, H, F_ = 2, 2, 64, 8, 16, 32
    p = static.Program()
    with static.program_guard(p):
        q = static.data("q", [B, N, S, D], "float32")
        k = static.data("k", [B, N, S, D], "float32")
        v = static.data("v", [B, N, S, D], "float32")
        xh = static.data("xh", [B, S, H], "float32")
        w = static.data("w", [H], "float32")
        g = static.data("g", [B, S, F_], "float32")
        u = static.data("u", [B, S, F_], "float32")
        probs = F.softmax(paddle.matmul(q, k, transpose_y=True) / (D ** 0.5),
                          axis=-1)
        attn = paddle.matmul(probs, v)
        normed = xh * paddle.rsqrt((xh * xh).mean(axis=-1, keepdim=True)
                                   + 1e-6) * w
        sw = F.silu(g) * u
    fetch = [attn._vid, normed._vid, sw._vid]
    reference = p.clone()
    n = PallasFusionPass(fetch).apply(p)
    print(f"fusion pass substituted {n} subgraphs")
    diff = differential_check(reference, p, fetch, raise_on_error=False)
    programs.append(p), labels.append("pallas-fused")
    if diff:
        programs.append(diff), labels.append("pallas-fused-differential")

    # schedule-searched fusion: matmul→bias→act→reduce tail (no named
    # pattern matches it) through ScheduleSearchPass with a deterministic
    # injected measure + scratch cache dir, then verify + differential
    import shutil
    import tempfile

    from paddle_tpu.ops import autotune as _at
    from paddle_tpu.static import schedule_search as _ss
    from paddle_tpu.static.rewrite import ScheduleSearchPass

    prev_cache_dir = paddle.get_flags("FLAGS_autotune_cache_dir")[
        "FLAGS_autotune_cache_dir"]
    scratch_dir = tempfile.mkdtemp(prefix="lint_ir_sched_")
    paddle.set_flags({"FLAGS_autotune_cache_dir": scratch_dir})
    _at._CACHES.clear()
    try:
        p = static.Program()
        with static.program_guard(p):
            xs = static.data("xs", [32, 16], "float32")
            ws = static.data("ws", [16, 64], "float32")
            bs = static.data("bs", [64], "float32")
            hid = F.relu(paddle.matmul(xs, ws) + bs)
            red = paddle.mean(hid, axis=-1, keepdim=True)
        fetch = [red._vid]
        reference = p.clone()
        with _ss.measure_override(
                lambda fn, args, label, config: 1.0 if config is None else 0.5):
            n = ScheduleSearchPass(
                fetch, searcher=_ss.ScheduleSearcher(budget=2)).apply(p)
        print(f"schedule search substituted {n} subgraphs")
        diff = differential_check(reference, p, fetch, raise_on_error=False)
        programs.append(p), labels.append("schedule-searched")
        if diff:
            programs.append(diff), labels.append("schedule-searched-differential")
    finally:
        paddle.set_flags({"FLAGS_autotune_cache_dir": prev_cache_dir})
        _at._CACHES.clear()
        shutil.rmtree(scratch_dir, ignore_errors=True)

    # weight-only quant
    layer2 = nn.Linear(8, 8)
    p = static.Program()
    with static.program_guard(p):
        x = static.data("xq", [2, 8], "float32")
        out = paddle.tanh(layer2(x))
    from paddle_tpu.static.passes import apply_pass

    apply_pass(p, "weight_only_quant", algo="weight_only_int8")
    programs.append(p), labels.append("weight-only-quant")

    failures = _verify_all(programs, labels)
    print()
    print("verify counters:", verify_stats())
    return failures


def _pytest_sweep(node_ids) -> int:
    from paddle_tpu.static.verify import verify_stats

    rc, programs = tracked_pytest(node_ids)
    print(f"\npytest exit={rc}; {len(programs)} Program(s) traced — verifying")
    failures = _verify_all(programs)
    print()
    print("verify counters:", verify_stats())
    return failures + pytest_failures(rc)


def main(argv=None):
    return run_cli(
        "lint_ir", _battery, _pytest_sweep, argv, doc=__doc__,
        ok_msg="all programs verified clean",
        fail_msg="{n} failing program(s)",
        pytest_help="run these pytest node ids and verify every Program "
                    "they trace")


if __name__ == "__main__":
    sys.exit(main())
