"""On-chip bench fleet runner: probe the TPU tunnel, then drain the queue.

The axon tunnel flaps for hours at a time (rounds 2-4 history in BASELINE.md).
This harness makes bench capture a background activity instead of a vigil:

  python tools/onchip_queue.py            # one pass: probe; if healthy, drain
  python tools/onchip_queue.py --watch    # loop forever until queue drained

Queue order follows VERDICT.md round-4 item 1: autotune sweep first (so every
later bench picks up tuned tiles), then the flagship, then the fleet.  Each
item runs in its own subprocess with a hard timeout; stdout/stderr land in
profiler_log/onchip_r05/<name>.log and the final JSON line (when the item
emits one) in <name>.json.  State persists in state.json so a tunnel flap
mid-queue resumes at the first unfinished item, and a completed item is never
re-run.  All timing inside the benches barriers via device.hard_sync
(BASELINE.md measurement-integrity note).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "profiler_log", "onchip_r05")
STATE = os.path.join(OUT, "state.json")
CACHE_DIR = "/tmp/jax_cache"

PROBE_TIMEOUT = 120
WATCH_SLEEP = 180  # between probe attempts while the tunnel is down

# (name, argv, timeout_seconds)
QUEUE = [
    ("autotune", [sys.executable, "-m", "paddle_tpu.ops.autotune",
                  "--budget-seconds", "420"], 900),
    # full-mode schedule search right after the tile sweep: the moment a
    # TPU appears the first REAL measured Pallas-beats-XLA table (Program
    # chains + decode hot chain, win-or-disabled verdicts) records itself
    # into the per-device-kind autotune cache without a human in the loop
    ("bench_schedule_search",
     [sys.executable, "benchmarks/bench_schedule_search.py"], 1200),
    ("bench_llama", [sys.executable, "bench.py"], 1800),
    ("bench_resnet", [sys.executable, "benchmarks/bench_resnet.py"], 1800),
    ("audit_resnet", [sys.executable, "benchmarks/audit_resnet.py"], 1800),
    ("bench_bert", [sys.executable, "benchmarks/bench_bert.py"], 1200),
    ("bench_moe", [sys.executable, "benchmarks/bench_moe.py"], 1200),
    ("bench_decode", [sys.executable, "benchmarks/bench_decode.py"], 1200),
    ("bench_yolo", [sys.executable, "benchmarks/bench_yolo.py"], 1200),
    ("bench_ocr", [sys.executable, "benchmarks/bench_ocr.py"], 1200),
    ("bench_ops", [sys.executable, "tools/bench_ops.py",
                   "--out", os.path.join(OUT, "bench_ops_results.json")], 1800),
]


def _load_state() -> dict:
    try:
        with open(STATE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"done": {}, "attempts": {}}


def _save_state(state: dict) -> None:
    os.makedirs(OUT, exist_ok=True)
    tmp = STATE + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=1)
    os.replace(tmp, STATE)


def probe() -> bool:
    """Bounded-subprocess backend init; True only on a live device."""
    code = (
        "import jax; "
        f"jax.config.update('jax_compilation_cache_dir', {CACHE_DIR!r}); "
        "import jax.numpy as jnp; "
        "x = jnp.ones((128, 128)); v = float((x @ x).sum()); "
        "print('PROBE_OK', jax.devices()[0].platform, v, flush=True)"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=PROBE_TIMEOUT, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return False
    return r.returncode == 0 and "PROBE_OK" in r.stdout


def run_item(name: str, argv: list[str], timeout: int) -> tuple[bool, str]:
    os.makedirs(OUT, exist_ok=True)
    log_path = os.path.join(OUT, name + ".log")
    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
    t0 = time.time()
    try:
        with open(log_path, "a") as log:
            log.write(f"\n===== {time.strftime('%F %T')} start {argv}\n")
            log.flush()
            r = subprocess.run(argv, stdout=log, stderr=subprocess.STDOUT,
                               timeout=timeout, cwd=REPO, env=env)
        rc = r.returncode
    except subprocess.TimeoutExpired:
        with open(log_path, "a") as log:
            log.write(f"===== TIMEOUT after {timeout}s\n")
        return False, "timeout"
    dt = time.time() - t0
    # Pull the last JSON object line out of the log for the .json artifact.
    last_json = None
    try:
        with open(log_path) as f:
            for line in f:
                line = line.strip()
                if line.startswith("{") and line.endswith("}"):
                    try:
                        last_json = json.loads(line)
                    except ValueError:
                        pass
    except OSError:
        pass
    if last_json is not None:
        with open(os.path.join(OUT, name + ".json"), "w") as f:
            json.dump(last_json, f, indent=1)
    ok = rc == 0 and not (isinstance(last_json, dict) and last_json.get("error"))
    status = f"rc={rc} dt={dt:.0f}s json={'yes' if last_json else 'no'}"
    return ok, status


def drain(state: dict) -> bool:
    """Run every unfinished item.  Returns True when the whole queue is done."""
    for name, argv, timeout in QUEUE:
        if state["done"].get(name):
            continue
        if not probe():
            print(f"[onchip_queue] tunnel dropped before {name}", flush=True)
            return False
        print(f"[onchip_queue] running {name} ...", flush=True)
        ok, status = run_item(name, argv, timeout)
        state["attempts"][name] = state["attempts"].get(name, 0) + 1
        print(f"[onchip_queue] {name}: {status} ok={ok}", flush=True)
        if ok:
            state["done"][name] = {"at": time.strftime("%F %T"), "status": status}
        _save_state(state)
        if not ok and state["attempts"][name] >= 5:
            # Persistent non-tunnel failure: mark failed-final so the queue
            # can finish; the log keeps the evidence.
            state["done"][name] = {"at": time.strftime("%F %T"),
                                   "status": status, "failed": True}
            _save_state(state)
    return all(state["done"].get(name) for name, _, _ in QUEUE)


def main(argv=None) -> int:
    watch = "--watch" in (argv or sys.argv[1:])
    state = _load_state()
    while True:
        if all(state["done"].get(n) for n, _, _ in QUEUE):
            print("[onchip_queue] queue fully drained", flush=True)
            return 0
        if probe():
            print("[onchip_queue] tunnel HEALTHY — draining queue", flush=True)
            if drain(state):
                print("[onchip_queue] queue fully drained", flush=True)
                return 0
        else:
            print(f"[onchip_queue] tunnel down ({time.strftime('%T')})",
                  flush=True)
        if not watch:
            return 1
        time.sleep(WATCH_SLEEP)


if __name__ == "__main__":
    sys.exit(main())
