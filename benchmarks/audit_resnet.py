"""ResNet-50 on-chip perf audit (VERDICT r4 item 2 diagnostics).

Prints, per batch size: measured img/s, compiled-executable FLOPs/bytes
(profiler.cost_analysis), achieved vs peak FLOPs (MFU), and the HLO fusion
census (how many convolution/fusion ops the compiled step contains — a
conv+BN+ReLU that did NOT fuse shows up as extra elementwise fusions).
Run on the real chip (the tunnel watcher queues it); CPU runs exercise the
harness on resnet18 tiny shapes.
"""

from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import json
import sys


def main():
    import os

    import jax

    if os.environ.get("PADDLE_TPU_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    on_accel = jax.devices()[0].platform != "cpu"

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.device import hard_sync, time_step_ms
    from paddle_tpu.device.peaks import device_peak_tflops
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.vision.models import resnet18, resnet50

    paddle.seed(0)
    model = resnet50() if on_accel else resnet18()
    B_list = (64, 128, 256) if on_accel else (4,)
    H = 224 if on_accel else 64
    ce = paddle.nn.CrossEntropyLoss()
    opt = paddle.optimizer.Momentum(0.1, parameters=model.parameters())

    def loss_fn(m, x, y):
        with paddle.amp.auto_cast(enable=on_accel):
            return ce(m(x), y)

    step = TrainStep(model, opt, loss_fn)
    rng = np.random.default_rng(0)
    d = jax.devices()[0]
    peak = device_peak_tflops(d.device_kind, d.platform) or 0.0

    for B in B_list:
        x = paddle.to_tensor(rng.standard_normal((B, 3, H, H)).astype(np.float32))
        y = paddle.to_tensor(rng.integers(0, 1000, (B,)).astype(np.int32))
        step(x, y)
        hard_sync(step(x, y))
        ms = time_step_ms(lambda: step(x, y), inner=5 if on_accel else 2)
        ips = B / (ms / 1e3)

        flops = bytes_moved = None
        fusion_census = {}
        try:
            from paddle_tpu import rng as rng_mod

            state_vals = [t._value for t in step._state]
            batch_vals = (x._value, y._value)
            key = rng_mod.next_key()
            lowered = step._compiled.lower(state_vals, batch_vals, key)
            exe = lowered.compile()  # cache hit: already compiled this sig
            cost = exe.cost_analysis()
            cost = cost[0] if isinstance(cost, (list, tuple)) else cost
            cost = dict(cost or {})
            flops = cost.get("flops")
            bytes_moved = cost.get("bytes accessed")
            hlo = exe.as_text()
            for marker in ("convolution", "fusion", "all-reduce", "transpose",
                           "custom-call"):
                fusion_census[marker] = hlo.count(f"{marker}(") + hlo.count(
                    f"{marker}.")
        except Exception as e:  # cost introspection is best-effort
            print(f"audit: cost introspection failed: {e}", file=sys.stderr)

        mfu = None
        if flops and peak:
            mfu = (flops / (ms / 1e3)) / (peak * 1e12)
        print(json.dumps({
            "audit": "resnet",
            "batch": B,
            "images_per_sec": round(ips, 2),
            "step_ms": round(ms, 3),
            "flops_per_step": flops,
            "bytes_per_step": bytes_moved,
            "mfu": round(mfu, 4) if mfu is not None else None,
            "hlo_census": fusion_census,
        }), flush=True)


if __name__ == "__main__":
    main()
