"""PP-OCR-class recognizer training throughput (BASELINE.md row 4).

Prints ONE JSON line like bench.py.  vs_baseline is 0.0 ("track" level —
BASELINE.md records no written-down A100 reference point for this row)."""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    if os.environ.get("PADDLE_TPU_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    on_accel = jax.devices()[0].platform != "cpu"

    import paddle_tpu as paddle
    from paddle_tpu.device import hard_sync
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.vision.models import CRNN, ppocr_rec_tiny

    paddle.seed(0)
    model = CRNN(num_classes=96) if on_accel else ppocr_rec_tiny(num_classes=16)
    B, W, L = (64, 320, 24) if on_accel else (2, 48, 3)
    iters = 10 if on_accel else 2
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())

    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((B, 3, 32, W)).astype(np.float32))
    labels = paddle.to_tensor(
        rng.integers(1, model.num_classes + 1, (B, L)).astype(np.int64))
    lens = paddle.to_tensor(np.full((B,), L, np.int64))

    def loss_fn(m, xb, lb, ln):
        with paddle.amp.auto_cast(enable=on_accel):
            logp = m(xb)
        return m.loss(logp.astype("float32"), lb, ln)

    step = TrainStep(model, opt, loss_fn)
    step(x, labels, lens)
    hard_sync(step(x, labels, lens))
    from paddle_tpu.device import time_step_ms

    rate_denom_s = time_step_ms(lambda: step(x, labels, lens), inner=iters) / 1e3
    print(json.dumps({
        "metric": "ppocr_rec_train_images_per_sec",
        "value": round(B / rate_denom_s, 2),
        "unit": "images/s",
        "vs_baseline": 0.0,
        "batch": B,
    }))


if __name__ == "__main__":
    sys.exit(main())
