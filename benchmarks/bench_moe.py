"""ERNIE-MoE-shaped semi-auto training throughput (BASELINE.md stretch row).

Prints ONE JSON line like bench.py.  vs_baseline is 0.0 ("track" level).
Single-chip runs exercise the dense expert compute + gating; the EP
all-to-all path is validated by dryrun_multichip / tests/test_moe.py on
the virtual mesh."""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    if os.environ.get("PADDLE_TPU_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    on_accel = jax.devices()[0].platform != "cpu"

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.incubate.distributed.models.moe import MoELayer
    from paddle_tpu.device import hard_sync
    from paddle_tpu.jit import TrainStep

    d, n_exp, V = (512, 8, 32000) if on_accel else (32, 4, 128)
    B, S = (8, 1024) if on_accel else (2, 16)
    iters = 10 if on_accel else 2

    def expert(i):
        paddle.seed(100 + i)
        return nn.Sequential(nn.Linear(d, 2 * d), nn.Silu(), nn.Linear(2 * d, d))

    class MoEBlock(nn.Layer):
        def __init__(self):
            super().__init__()
            self.norm = nn.LayerNorm(d)
            self.attn = nn.MultiHeadAttention(d, 8 if on_accel else 2)
            self.norm2 = nn.LayerNorm(d)
            self.moe = MoELayer(d, [expert(i) for i in range(n_exp)],
                                gate="gshard", capacity_factor=2.0)

        def forward(self, h):
            h = h + self.attn(self.norm(h))
            return h + self.moe(self.norm2(h))

    class MoELM(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(V, d)
            self.blocks = nn.LayerList([MoEBlock(), MoEBlock()])
            self.head = nn.Linear(d, V)

        def forward(self, ids):
            h = self.emb(ids)
            for b in self.blocks:
                h = b(h)
            return self.head(h)

        def aux_loss(self):
            import functools

            losses = [b.moe.aux_loss for b in self.blocks if b.moe.aux_loss is not None]
            return functools.reduce(lambda a, c: a + c, losses) if losses else None

    paddle.seed(0)
    model = MoELM()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())

    def loss_fn(m, ids, labels):
        with paddle.amp.auto_cast(enable=on_accel):
            logits = m(ids)
        loss = F.cross_entropy(
            logits.astype("float32").reshape([-1, V]), labels.reshape([-1]))
        aux = m.aux_loss()
        return loss + 0.01 * aux.astype("float32") if aux is not None else loss

    step = TrainStep(model, opt, loss_fn)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, V, (B, S)).astype(np.int32))
    labels = paddle.to_tensor(rng.integers(0, V, (B, S)).astype(np.int64))
    step(ids, labels)
    hard_sync(step(ids, labels))
    from paddle_tpu.device import time_step_ms

    rate_denom_s = time_step_ms(lambda: step(ids, labels), inner=iters) / 1e3
    print(json.dumps({
        "metric": "moe_train_tokens_per_sec",
        "value": round(B * S / rate_denom_s, 2),
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "batch": B,
    }))


if __name__ == "__main__":
    sys.exit(main())
