"""Serving decode: macro-step (chunked) continuous batching vs per-token
dispatch, plus a depth sweep showing decode trace+compile is depth-constant
under the LayerStack scan (BASELINE.md serving tier; reference lineage
block_multi_head_attention + the decode servers over it).

Two claims measured:
- **Macro-step speedup**: `GenerationEngine` with FLAGS_decode_chunk D
  emits [B, D] tokens per compiled dispatch (one host round-trip + one
  device sync per chunk instead of per token) — tokens/s vs the per-token
  path (D=1), with bit-identical greedy token streams.
- **Depth-constant decode compile**: with `fuse_layer_stack` the paged KV
  pools thread through the LayerStack scan body as per-layer state, so the
  first macro-step's trace+compile no longer scales ~linearly in layer
  count (16-layer vs 4-layer first-step wall within ~1.5x).
- **Prefix-cache KV reuse**: N requests sharing one long system prompt —
  with `prefix_cache=True` admission matches the cached prefix at page
  granularity and prefills only the suffix.  Reports end-to-end tokens/s
  on vs off (admission + decode in the wall), prefill-avoided tokens, and
  per-token latency percentiles (p50/p95), with a greedy-parity gate.
- **int8 KV capacity**: at IDENTICAL pool-block bytes, how many requests
  an int8-quantized pool admits before queueing vs a bf16 pool —
  allocator arithmetic, so the ratio is deterministic and timing-free.
- **SLO load percentiles**: an oversubscribed (2x max_batch) workload
  reporting p50/p95/p99 time-to-first-token (prefill + queueing delay)
  and inter-token latency, replayed on a TP-sharded twin over 2 (virtual
  when on CPU) devices with a greedy stream-parity gate
  (tools/check_bench_regression.py gates the percentiles too).
- **Snapshot/restore**: save a LIVE mid-flight engine through the atomic
  commit protocol and restore it (serving/snapshot.py) — save_ms /
  restore_ms / committed bytes, with a resume-parity gate (the restored
  engine's continued streams must equal an uninterrupted run's).  The
  timings feed check_bench_regression's snapshot gate (growth beyond the
  SLO threshold is the regression — the preemption budget this buys).
- **Overload discipline**: the adversarial mix — one very long prompt
  submitted mid-decode of a full batch of short streams.  Atomic
  admission stalls every resident stream for the whole prefill; chunked
  interleaving (FLAGS_prefill_chunk_blocks) bounds the stall at one
  block per macro-step, so the residents' p99 inter-token latency must
  drop at equal throughput, with ALL streams bit-identical between the
  two engines.  A preemption sub-scenario parks a LOW-priority stream
  under a HIGH arrival and re-admits it: the resumed stream must equal
  an uninterrupted reference token for token
  (check_bench_regression's overload gate consumes the p99 ITL).

Prints ONE JSON line like the other benches.  vs_baseline is 0.0 until a
reference serving point is recorded (none published in-repo).
`--smoke` / PADDLE_TPU_BENCH_SMOKE shrinks sizes for CI
(tests/test_bench_decode.py)."""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _drain(eng, prompts, max_new):
    """Run requests to completion; return {rid: generated tokens}."""
    for rid, p in prompts.items():
        eng.add_request(rid, p, max_new_tokens=max_new)
    while eng.has_work():
        eng.step()
    return {rid: eng.result(rid) for rid in prompts}


def main():
    # the SLO load benchmark's TP twin needs >= 2 devices even on a CPU
    # box (tunnel down): force 2 virtual host devices BEFORE jax's
    # backend initializes (tests/conftest.py does the same with 8).
    # Only the host platform is affected; real accelerators ignore it.
    _xla = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _xla:
        os.environ["XLA_FLAGS"] = (
            _xla + " --xla_force_host_platform_device_count=2")
    import jax

    if os.environ.get("PADDLE_TPU_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    # fresh compilation cache: the depth sweep times real trace+compile
    # (TemporaryDirectory so the populated cache is removed at exit)
    cache_dir = tempfile.TemporaryDirectory(prefix="bench_decode_jaxcache_")
    jax.config.update("jax_compilation_cache_dir", cache_dir.name)
    smoke = os.environ.get("PADDLE_TPU_BENCH_SMOKE") or "--smoke" in sys.argv
    on_accel = jax.devices()[0].platform != "cpu"

    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import GenerationEngine

    paddle.seed(0)
    if on_accel:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=4096,
            dtype="bfloat16")
        B, prompt_len, iters, chunk = 8, 128, 8, 8
    elif smoke:
        cfg = llama_tiny(vocab_size=256, hidden_size=64, intermediate_size=176,
                         num_attention_heads=4, num_key_value_heads=4,
                         max_position_embeddings=8192, dtype="float32")
        B, prompt_len, iters, chunk = 2, 8, 2, 8
    else:
        # CPU proxy: a thin-width model keeps per-step device compute small
        # so the measured contrast is the per-dispatch host overhead the
        # macro-step amortizes (the TPU-relevant quantity; the accel branch
        # measures a serving-scale config instead)
        cfg = llama_tiny(vocab_size=256, hidden_size=64, intermediate_size=176,
                         num_attention_heads=4, num_key_value_heads=4,
                         max_position_embeddings=8192, dtype="float32")
        B, prompt_len, iters, chunk = 2, 8, 4, 8
    model = LlamaForCausalLM(cfg)
    model.eval()

    rng = np.random.default_rng(0)
    prompts = {f"r{i}": list(rng.integers(0, cfg.vocab_size, prompt_len))
               for i in range(B)}

    # ---- greedy parity: chunked == per-token, bit for bit ---------------
    par_new = 24
    par_blocks = B * (-(-(prompt_len + par_new) // 16) + 1)
    ref = _drain(GenerationEngine(model, max_batch=B, block_size=16,
                                  num_blocks=par_blocks, decode_chunk=1),
                 prompts, par_new)
    got = _drain(GenerationEngine(model, max_batch=B, block_size=16,
                                  num_blocks=par_blocks, decode_chunk=chunk),
                 prompts, par_new)
    tokens_match = ref == got
    if not tokens_match:
        print(f"bench_decode: PARITY FAILURE {ref} vs {got}", file=sys.stderr)

    # ---- tokens/s: per-token dispatch vs macro-step ---------------------
    # Direct timing with an EXACT call budget: step() ends in a device
    # sync (np.asarray of the tokens), so wall time over N macro-steps
    # already includes the per-dispatch round trip — which is precisely
    # the cost macro-stepping amortizes.  An adaptive difference timer
    # (time_step_ms) is wrong here: its retry escalation makes the call
    # count nondeterministic (draining slots mid-measurement), and the
    # bigger max_new it forces inflates the paged pool, so the per-token
    # scatter's pool copy — identical work on both paths — swamps the
    # dispatch contrast being measured.
    def measure(D):
        ticks = 3 * iters
        max_new = (ticks + 2) * D + prompt_len
        nb = B * (-(-(prompt_len + max_new) // 16) + 1)
        eng = GenerationEngine(model, max_batch=B, block_size=16,
                               num_blocks=nb, decode_chunk=D)
        for rid, p in prompts.items():
            eng.add_request(rid, p, max_new_tokens=max_new)
        eng.step()  # compile
        t0 = time.perf_counter()
        for _ in range(ticks):
            eng.step()
        dt = time.perf_counter() - t0
        assert eng.has_work(), "slots drained mid-measurement; raise max_new"
        return B * D * ticks / dt

    from paddle_tpu.serving import decode_stats, reset_decode_stats

    per_token_tps = measure(1)
    # counters reported below must describe the CHUNKED claim, not the
    # parity/per-token phases that ran in this same process
    reset_decode_stats()
    chunked_tps = measure(chunk)
    st = decode_stats()
    speedup = chunked_tps / per_token_tps if per_token_tps else 0.0

    # ---- depth sweep: first macro-step wall (trace + compile) -----------
    # fuse_layer_stack threads the paged pools through the LayerStack scan
    # body, so the step program holds ONE layer body regardless of depth
    depth_sweep = {}
    if not on_accel:
        depths = (2, 6) if smoke else (4, 16)

        def first_step_wall(n_layers):
            paddle.seed(1)
            dcfg = llama_tiny(vocab_size=256, hidden_size=64,
                              intermediate_size=176, num_attention_heads=4,
                              num_key_value_heads=4,
                              num_hidden_layers=n_layers,
                              max_position_embeddings=256, dtype="float32",
                              fuse_layer_stack=True)
            m = LlamaForCausalLM(dcfg)
            m.eval()
            eng = GenerationEngine(m, max_batch=2, block_size=16,
                                   num_blocks=8, decode_chunk=chunk)
            eng.add_request("d", [3, 1, 4, 1], max_new_tokens=chunk * 2 + 2)
            t0 = time.perf_counter()
            eng.step()  # traces + compiles the macro-step program
            return time.perf_counter() - t0

        shallow, deep = depths
        t_shallow = first_step_wall(shallow)
        t_deep = first_step_wall(deep)
        depth_sweep = {
            "scan_layers": True,
            "shallow_layers": shallow,
            "deep_layers": deep,
            "shallow_first_step_s": round(t_shallow, 3),
            "deep_first_step_s": round(t_deep, 3),
            "ratio": round(t_deep / t_shallow, 3) if t_shallow else 0.0,
        }

    # ---- shared-prefix workload: prefix cache on vs off -----------------
    # N requests over ONE long system prompt (+ a small distinct user
    # tail): cache-on prefills the shared prefix once and every later
    # admission references its pages — end-to-end wall includes admission,
    # which is exactly where the win lives.
    from paddle_tpu.serving import GenerationEngine as _GE

    n_req = 4 if smoke else 8
    pre_len = 32 if smoke else 192
    tail_len, sp_new = 4, 4 if smoke else 16
    sp_s0 = pre_len + tail_len
    sp_rng = np.random.default_rng(7)
    shared = list(sp_rng.integers(0, cfg.vocab_size, pre_len))
    sp_prompts = {f"s{i}": shared + list(sp_rng.integers(0, cfg.vocab_size,
                                                         tail_len))
                  for i in range(n_req)}
    sp_blocks = n_req * (-(-(sp_s0 + sp_new) // 16) + 1)

    def run_shared(prefix_on):
        reset_decode_stats()
        eng = _GE(model, max_batch=n_req, block_size=16,
                  num_blocks=sp_blocks, decode_chunk=chunk,
                  prefix_cache=prefix_on)
        lat_ms = []
        t0 = time.perf_counter()
        for rid, p in sp_prompts.items():
            eng.add_request(rid, p, max_new_tokens=sp_new)
        while eng.has_work():
            ts = time.perf_counter()
            emitted = sum(len(v) if isinstance(v, list) else 1
                          for v in eng.step().values())
            if emitted:
                lat_ms += [1e3 * (time.perf_counter() - ts) / emitted] * emitted
        wall = time.perf_counter() - t0
        toks = sum(len(eng.result(r)) for r in sp_prompts)
        return {"tokens_per_sec": toks / wall,
                "results": {r: eng.result(r) for r in sp_prompts},
                "prefill_avoided_tokens": decode_stats()["prefix_hit_tokens"],
                "latency_p50_ms": float(np.percentile(lat_ms, 50)),
                "latency_p95_ms": float(np.percentile(lat_ms, 95))}

    sp_off = run_shared(False)
    sp_on = run_shared(True)
    prefix_match = sp_off["results"] == sp_on["results"]
    if not prefix_match:
        print("bench_decode: PREFIX PARITY FAILURE", file=sys.stderr)
    shared_prefix = {
        "requests": n_req,
        "prefix_tokens": pre_len,
        "prefix_speedup": round(
            sp_on["tokens_per_sec"] / sp_off["tokens_per_sec"], 2)
        if sp_off["tokens_per_sec"] else 0.0,
        "prefill_avoided_tokens": sp_on["prefill_avoided_tokens"],
        "tokens_match": prefix_match,
        "off": {k: round(v, 3) for k, v in sp_off.items()
                if k not in ("results",)},
        "on": {k: round(v, 3) for k, v in sp_on.items()
               if k not in ("results",)},
    }

    # ---- int8 KV capacity: resident requests at identical pool bytes ----
    # bf16 pools on a bf16 model vs int8 pools sized to the SAME block-pool
    # byte budget; admit identical-shape requests until one queues.  Pure
    # allocator arithmetic — deterministic, no timing.
    paddle.seed(2)
    from paddle_tpu.models.llama import llama_tiny as _tiny

    qcfg = _tiny(vocab_size=256, hidden_size=64, intermediate_size=176,
                 num_attention_heads=4, num_key_value_heads=4,
                 max_position_embeddings=8192, dtype="bfloat16")
    qmodel = LlamaForCausalLM(qcfg)
    qmodel.eval()
    q_nkv = qcfg.num_key_value_heads
    q_hd = qcfg.hidden_size // qcfg.num_attention_heads
    q_layers = qcfg.num_hidden_layers
    elems = q_nkv * 16 * q_hd
    per_block_bf16 = q_layers * 2 * elems * 2            # K+V, 2B/elem
    per_block_int8 = q_layers * 2 * (elems + q_nkv * 4)  # + f32 scales
    nb_bf16 = 10 if smoke else 16
    budget = nb_bf16 * per_block_bf16
    nb_int8 = budget // per_block_int8
    cap_prompt_len, cap_new = 28, 4  # 2 blocks per request at bs=16

    def admitted(kv_dtype, nb):
        eng = _GE(qmodel, max_batch=nb, block_size=16, num_blocks=nb,
                  kv_cache_dtype=kv_dtype)
        count = 0
        crng = np.random.default_rng(3)
        while True:
            p = list(crng.integers(0, qcfg.vocab_size, cap_prompt_len))
            if eng.add_request(f"c{count}", p, max_new_tokens=cap_new) is None:
                return count
            count += 1

    res_bf16 = admitted("bf16", nb_bf16)
    res_int8 = admitted("int8", int(nb_int8))
    capacity = {
        "pool_block_bytes": budget,
        "bf16_blocks": nb_bf16,
        "int8_blocks": int(nb_int8),
        "bf16_resident_requests": res_bf16,
        "int8_resident_requests": res_int8,
        "capacity_ratio": round(res_int8 / res_bf16, 2) if res_bf16 else 0.0,
    }

    # ---- SLO load benchmark: TTFT + inter-token latency percentiles ----
    # An oversubscribed workload: 2x max_batch requests submit up front,
    # so half QUEUE and admit as slots drain — time-to-first-token then
    # includes prefill AND queueing delay, the quantity an SLO actually
    # bounds.  Inter-token latency spreads each macro-step's wall over
    # the tokens it emitted per row (tokens surface per-chunk by design).
    # The same workload replays on a TP-sharded twin over the 2 (virtual)
    # devices forced above, with a greedy-parity gate: the sharded engine
    # must emit bit-identical streams (docs/DECODE.md sharded serving).
    lb = 2 if smoke else 4
    l_new = 6 if smoke else 24
    l_prompt = 8 if smoke else 32
    l_rng = np.random.default_rng(5)
    l_prompts = {f"l{i}": list(l_rng.integers(0, cfg.vocab_size, l_prompt))
                 for i in range(2 * lb)}
    l_blocks = lb * (-(-(l_prompt + l_new) // 16) + 1)

    def run_load(mesh):
        paddle.seed(0)
        lmodel = LlamaForCausalLM(cfg)  # fresh: shard_llama mutates
        lmodel.eval()
        eng = GenerationEngine(lmodel, max_batch=lb, block_size=16,
                               num_blocks=l_blocks, decode_chunk=chunk,
                               mesh=mesh)
        # warm the compiled prefill/decode paths: the percentiles should
        # describe steady-state serving, not the first-trace compile
        eng.add_request("warm", l_prompts["l0"], max_new_tokens=l_new)
        while eng.has_work():
            eng.step()
        submit, ttft, itl, last = {}, {}, [], {}
        t0 = time.perf_counter()
        for rid, p in l_prompts.items():
            submit[rid] = time.perf_counter()
            first = eng.add_request(rid, p, max_new_tokens=l_new)
            if first is not None:
                now = time.perf_counter()
                ttft[rid] = now - submit[rid]
                last[rid] = now
        while eng.has_work():
            ts = time.perf_counter()
            out = eng.step()
            now = time.perf_counter()
            for rid, toks in out.items():
                n = len(toks) if isinstance(toks, list) else 1
                if rid not in ttft:  # queue-admitted: first token is here
                    ttft[rid] = now - submit[rid]
                    # the rest of this chunk spreads over THIS step's
                    # wall (anchoring at `now` would record zero-length
                    # gaps and deflate the ITL percentiles)
                    last[rid] = ts
                    n -= 1
                if n > 0:
                    gap = (now - last[rid]) / n
                    itl.extend([gap] * n)
                    last[rid] = now
        wall = time.perf_counter() - t0

        def pct(xs):
            return {p: round(float(np.percentile(xs, int(p[1:]))) * 1e3, 3)
                    for p in ("p50", "p95", "p99")}

        toks = sum(len(eng.result(r)) for r in l_prompts)
        return {"ttft_ms": pct(list(ttft.values())), "itl_ms": pct(itl),
                "tokens_per_sec": round(toks / wall, 2),
                "results": {r: eng.result(r) for r in l_prompts}}

    slo_single = run_load(None)
    slo_tp, tp_match = None, True
    if len(jax.devices()) >= 2:
        from paddle_tpu.distributed.auto_parallel import ProcessMesh

        slo_tp = run_load(ProcessMesh(np.arange(2), ["mp"]))
        tp_match = slo_tp["results"] == slo_single["results"]
        if not tp_match:
            print("bench_decode: TP LOAD PARITY FAILURE", file=sys.stderr)
    slo = {
        "requests": 2 * lb,
        "max_batch": lb,
        "new_tokens": l_new,
        "tp_tokens_match": tp_match,
        "single": {k: v for k, v in slo_single.items() if k != "results"},
        "tp": (None if slo_tp is None
               else {k: v for k, v in slo_tp.items() if k != "results"}),
    }

    # ---- snapshot/restore: live-engine fault tolerance timing ----------
    # One mid-flight engine (resident greedy requests) snapshots through
    # the atomic commit protocol and restores onto a fresh engine; the
    # restored engine must finish every stream exactly as an
    # uninterrupted twin — the bit-exact-resume contract, timed.  Wall
    # numbers are the preemption budget: what a SIGTERM costs to honor.
    import shutil as _shutil

    from paddle_tpu.serving import restore_engine, snapshot_stats

    def run_snap(snap_dir):
        eng = GenerationEngine(model, max_batch=B, block_size=16,
                               num_blocks=par_blocks, decode_chunk=chunk)
        for rid, p in prompts.items():
            eng.add_request(rid, p, max_new_tokens=par_new)
        eng.step()  # mid-flight: pools poured, streams open
        if snap_dir is None:
            while eng.has_work():
                eng.step()
            return {r: eng.result(r) for r in prompts}, None
        t0 = time.perf_counter()
        eng.snapshot(snap_dir)
        save_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        eng2 = restore_engine(model, snap_dir)
        restore_s = time.perf_counter() - t0
        while eng2.has_work():
            eng2.step()
        return ({r: eng2.result(r) for r in prompts},
                {"save_ms": round(save_s * 1e3, 3),
                 "restore_ms": round(restore_s * 1e3, 3)})

    snap_ref, _ = run_snap(None)
    snap_stats0 = snapshot_stats()
    snap_dir = tempfile.mkdtemp(prefix="bench_decode_snap_")
    try:
        snap_got, snap_timing = run_snap(snap_dir)
    finally:
        _shutil.rmtree(snap_dir, ignore_errors=True)
    snap_match = snap_got == snap_ref
    if not snap_match:
        print("bench_decode: SNAPSHOT RESUME PARITY FAILURE", file=sys.stderr)
    snapshot = dict(
        snap_timing,
        bytes=snapshot_stats()["bytes"] - snap_stats0["bytes"],
        resume_tokens_match=snap_match,
    )

    # ---- overload: long prefill vs resident streams' inter-token SLO ----
    # The adversarial mix: ov_b short streams are mid-decode when one
    # long prompt arrives.  The atomic engine prefills it in one stall at
    # the admission boundary; the chunked engine pours one block per
    # macro-step between decode dispatches.  Measured on the RESIDENT
    # streams only — the long request's prefill is the disturbance, the
    # residents' p99 ITL is the quantity under test.
    from paddle_tpu.profiler import decode_stats as _dstats

    # chunk = one pool block.  On the CPU proxy the eager forward has a
    # ~90-200ms per-dispatch floor, so the contrast only shows once the
    # prompt's quadratic attention dwarfs it: at 4096 tokens in 512-token
    # blocks the atomic stall is ~8x the worst single chunk (measured
    # ~1.9s vs ~0.26s) AND chunked throughput is higher because the
    # residents never stop decoding (on a TPU the fused prefill chain
    # makes far smaller chunks pay off; the direction is what gates).
    if on_accel:
        ov_bs, ov_b, ov_prompt, ov_long, ov_new = 512, 8, 16, 2048, 32
    elif smoke:
        ov_bs, ov_b, ov_prompt, ov_long, ov_new = 512, 8, 8, 2048, 8
    else:
        ov_bs, ov_b, ov_prompt, ov_long, ov_new = 512, 8, 8, 4096, 16
    ov_rng = np.random.default_rng(9)
    ov_shorts = {f"o{i}": list(ov_rng.integers(0, cfg.vocab_size, ov_prompt))
                 for i in range(ov_b)}
    ov_lp = list(ov_rng.integers(0, cfg.vocab_size, ov_long))
    # per-seq table width is num_blocks // max_batch: size the pool so
    # every slot's table can hold the LONG request's pages
    ov_blocks = (ov_b + 1) * (-(-(ov_long + ov_new) // ov_bs) + 1)

    def run_overload(chunked):
        eng = GenerationEngine(model, max_batch=ov_b + 1, block_size=ov_bs,
                               num_blocks=ov_blocks, decode_chunk=2,
                               prefill_chunk_blocks=1 if chunked else None)
        # warm with the LONG prompt shape: both the atomic full-length
        # prefill and the block-wide chunk forwards compile here, so the
        # measured stall is prefill COMPUTE, not trace+compile
        eng.add_request("warm", ov_lp, max_new_tokens=ov_new)
        while eng.has_work():
            eng.step()
        for rid, p in ov_shorts.items():
            eng.add_request(rid, p, max_new_tokens=ov_new)
        eng.step()  # residents mid-decode when the long prompt lands
        itl, last, t0 = [], {}, time.perf_counter()
        steps = 0
        while eng.has_work() or steps == 0:
            if steps == 1:
                # submitted INSIDE the measured window, after the first
                # step anchored every resident's `last`: the atomic
                # engine's synchronous admission prefill lands between
                # two measured steps instead of hiding before t0
                eng.add_request("long", ov_lp, max_new_tokens=ov_new)
            ts = time.perf_counter()
            out = eng.step()
            now = time.perf_counter()
            steps += 1
            for rid, toks in out.items():
                if rid == "long":
                    continue
                n = len(toks) if isinstance(toks, list) else 1
                if rid not in last:
                    last[rid] = ts
                    n -= 1
                if n > 0:
                    itl.extend([(now - last[rid]) / n] * n)
                    last[rid] = now
        wall = time.perf_counter() - t0
        toks = sum(len(eng.result(r)) for r in ov_shorts) + \
            len(eng.result("long"))
        return {"itl_p99_ms": round(float(np.percentile(itl, 99)) * 1e3, 3),
                "tokens_per_sec": round(toks / wall, 2),
                "results": {r: eng.result(r)
                            for r in list(ov_shorts) + ["long"]}}

    ov_chunks0 = _dstats()["prefill_chunks"]
    ov_atomic = run_overload(chunked=False)
    ov_atomic_chunks = _dstats()["prefill_chunks"] - ov_chunks0
    ov_chunked = run_overload(chunked=True)
    ov_prefill_chunks = (_dstats()["prefill_chunks"] - ov_chunks0
                         - ov_atomic_chunks)
    ov_match = ov_chunked["results"] == ov_atomic["results"]
    if not ov_match:
        print("bench_decode: OVERLOAD PARITY FAILURE", file=sys.stderr)

    # preemption sub-scenario: a seeded LOW stream parked by a HIGH
    # arrival (single slot forces the eviction), re-admitted, and checked
    # token-for-token against a never-preempted reference
    pre_p = ov_shorts["o1"]

    def run_preempt(preempt):
        eng = GenerationEngine(model, max_batch=1, block_size=16,
                               num_blocks=ov_blocks, decode_chunk=2)
        eng.add_request("low", pre_p, max_new_tokens=ov_new,
                        temperature=0.7, seed=11,
                        priority="low" if preempt else "normal")
        eng.step()
        if preempt:
            eng.add_request("high", ov_shorts["o2"], max_new_tokens=4,
                            priority="high")
        while eng.has_work():
            eng.step()
        return eng.result("low")

    pre_ref = run_preempt(False)
    pre_stats0 = _dstats()
    pre_got = run_preempt(True)
    pre_stats = _dstats()
    preemptions = pre_stats["preemptions"] - pre_stats0["preemptions"]
    readmits = (pre_stats["preempt_readmits"]
                - pre_stats0["preempt_readmits"])
    preempt_match = pre_got == pre_ref and preemptions >= 1 and readmits >= 1
    if not preempt_match:
        print("bench_decode: PREEMPT RESUME PARITY FAILURE", file=sys.stderr)

    overload = {
        "residents": ov_b,
        "long_prompt_tokens": ov_long,
        "itl_p99_ms_chunked": ov_chunked["itl_p99_ms"],
        "itl_p99_ms_atomic": ov_atomic["itl_p99_ms"],
        "tokens_per_sec_chunked": ov_chunked["tokens_per_sec"],
        "tokens_per_sec_atomic": ov_atomic["tokens_per_sec"],
        "streams_identical": ov_match,
        "prefill_chunks": ov_prefill_chunks,
        "preemptions": preemptions,
        "preempt_readmits": readmits,
        "preempted_stream_identical": pre_got == pre_ref,
    }

    print(json.dumps({
        "metric": "serving_decode_chunked_speedup",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": 0.0,
        "tokens_match": tokens_match,
        "detail": {
            "batch": B,
            "chunk": chunk,
            "per_token_tokens_per_sec": round(per_token_tps, 2),
            "chunked_tokens_per_sec": round(chunked_tps, 2),
            "depth_sweep": depth_sweep,
            "shared_prefix": shared_prefix,
            "int8_kv_capacity": capacity,
            "slo": slo,
            "snapshot": snapshot,
            "overload": overload,
            "decode_stats": {
                "dispatches": st["dispatches"],
                "tokens": st["tokens"],
                "sync_seconds": round(st["sync_seconds"], 4),
            },
        },
    }))
    return 0 if (tokens_match and prefix_match and tp_match
                 and snap_match and ov_match and preempt_match) else 1


if __name__ == "__main__":
    sys.exit(main())
