"""Serving decode throughput: continuous-batching engine tokens/s, plain
vs speculative (BASELINE.md serving tier; reference lineage
block_multi_head_attention + the decode servers over it).

Prints ONE JSON line like the other benches.  vs_baseline is 0.0 until a
reference serving point is recorded (none published in-repo)."""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    if os.environ.get("PADDLE_TPU_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    on_accel = jax.devices()[0].platform != "cpu"

    import contextlib

    import paddle_tpu as paddle
    from paddle_tpu.device import time_step_ms
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import GenerationEngine

    paddle.seed(0)
    cpu = None
    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError:
        pass
    with (jax.default_device(cpu) if cpu else contextlib.nullcontext()):
        if on_accel:
            cfg = LlamaConfig(
                vocab_size=32000, hidden_size=2048, intermediate_size=5632,
                num_hidden_layers=8, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=2048,
                dtype="bfloat16")
            model = LlamaForCausalLM(cfg)
            B, prompt_len, iters = 8, 128, 16
            max_new = 256  # > total timed ticks: slots stay live throughout
        else:
            model = LlamaForCausalLM(llama_tiny(dtype="float32"))
            B, prompt_len, iters = 2, 8, 3
            max_new = 64
    model.eval()

    rng = np.random.default_rng(0)
    blocks_per_seq = -(-(prompt_len + max_new) // 16) + 1

    def measure(batch):
        eng = GenerationEngine(model, max_batch=batch, block_size=16,
                               num_blocks=batch * blocks_per_seq)
        for i in range(batch):
            eng.add_request(
                f"r{i}",
                list(rng.integers(0, model.config.vocab_size, prompt_len)),
                max_new_tokens=max_new)
        eng.step()  # compile
        ms = time_step_ms(eng.step, inner=iters)
        return batch / (ms / 1e3)  # one token per live slot per tick

    if on_accel:
        # decode is bandwidth-bound: throughput scales with live slots
        # until the KV pool saturates HBM — sweep largest-first, OOM falls
        # through like the training benches
        tokens_per_sec = 0.0
        for batch in (64, 32, 16, 8):
            try:
                tps = measure(batch)
            except Exception as e:  # noqa: BLE001
                msg = f"{type(e).__name__}: {e}"
                print(f"bench_decode: B={batch} failed ({msg[:200]})",
                      file=sys.stderr)
                if "RESOURCE_EXHAUSTED" not in msg and "Out of memory" not in msg:
                    raise
                continue
            if tps > tokens_per_sec:
                tokens_per_sec, B = tps, batch
        if tokens_per_sec == 0.0:
            raise SystemExit("bench_decode: every sweep batch hit device OOM")
    else:
        tokens_per_sec = measure(B)
    print(json.dumps({
        "metric": "serving_decode_tokens_per_sec",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "batch": B,
    }))


if __name__ == "__main__":
    sys.exit(main())
