"""BERT-base finetune throughput (BASELINE.md row 3)."""

from __future__ import annotations

import json
import sys

import os

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import os

    import jax

    if os.environ.get("PADDLE_TPU_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    on_accel = jax.devices()[0].platform != "cpu"

    import paddle_tpu as paddle
    from paddle_tpu.device import hard_sync
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import BertConfig, BertForSequenceClassification, bert_tiny

    paddle.seed(0)
    cfg = BertConfig(num_hidden_layers=12) if on_accel else bert_tiny()
    B, S = (32, 128) if on_accel else (4, 32)
    iters = 10 if on_accel else 2
    import contextlib

    cpu = None
    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError:
        pass
    with (jax.default_device(cpu) if cpu else contextlib.nullcontext()):
        model = BertForSequenceClassification(cfg)
    opt = paddle.optimizer.AdamW(2e-5, parameters=model.parameters())

    def loss_fn(m, i, y):
        with paddle.amp.auto_cast(enable=on_accel):
            return m(i, labels=y)[0]

    step = TrainStep(model, opt, loss_fn)
    rng = np.random.default_rng(0)
    amp_level = "O1"

    from paddle_tpu.device import time_step_ms

    def measure(batch):
        ids = paddle.to_tensor(rng.integers(1, cfg.vocab_size, (batch, S)).astype(np.int32))
        y = paddle.to_tensor(rng.integers(0, 2, (batch,)).astype(np.int32))
        step(ids, y)
        hard_sync(step(ids, y))
        ms = time_step_ms(lambda: step(ids, y), inner=iters)
        return batch * S / (ms / 1e3)

    if on_accel:
        # batch sweep, largest first (the A100 point is a large-batch AMP
        # run; B=32 under-fills the v5e MXU) — OOM falls through
        tokens_per_sec = 0.0
        for batch in (256, 128, 64, 32):
            try:
                tps = measure(batch)
            except Exception as e:  # noqa: BLE001
                msg = f"{type(e).__name__}: {e}"
                print(f"bench_bert: B={batch} failed ({msg[:200]})",
                      file=sys.stderr)
                if "RESOURCE_EXHAUSTED" not in msg and "Out of memory" not in msg:
                    raise
                continue
            if tps > tokens_per_sec:
                tokens_per_sec, B = tps, batch
        if tokens_per_sec == 0.0:
            raise SystemExit("bench_bert: every sweep batch hit device OOM")
        # O2 arm at the winning batch: bf16 params + fp32 masters cut the
        # per-op cast traffic of O1 (the A100 point is full AMP)
        try:
            with (jax.default_device(cpu) if cpu else contextlib.nullcontext()):
                model2 = BertForSequenceClassification(cfg)
            opt2 = paddle.optimizer.AdamW(2e-5, parameters=model2.parameters())
            model2, opt2 = paddle.amp.decorate(model2, opt2, level="O2")

            def loss_fn2(m, i, y):
                with paddle.amp.auto_cast(enable=True, level="O2"):
                    return m(i, labels=y)[0]

            step2 = TrainStep(model2, opt2, loss_fn2)
            ids = paddle.to_tensor(rng.integers(1, cfg.vocab_size, (B, S)).astype(np.int32))
            y = paddle.to_tensor(rng.integers(0, 2, (B,)).astype(np.int32))
            step2(ids, y)
            hard_sync(step2(ids, y))
            tps_o2 = B * S / (time_step_ms(lambda: step2(ids, y), inner=iters) / 1e3)
            if tps_o2 > tokens_per_sec:
                tokens_per_sec, amp_level = tps_o2, "O2"
        except Exception as e:  # additive arm: never sinks the bench
            print(f"bench_bert: O2 arm failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
    else:
        tokens_per_sec = measure(B)

    # vs_baseline: peak-normalized chip-efficiency parity against the
    # written-down A100 reference point (BASELINE.md "A100 reference
    # points"): BERT-base AMP S=128 1xA100 = 139,264 tok/s (1,088 seq/s).
    from paddle_tpu.device.peaks import A100_PEAK_TFLOPS, device_peak_tflops

    d = jax.devices()[0]
    peak = device_peak_tflops(d.device_kind, d.platform)
    vs_baseline = (tokens_per_sec / peak) / (139264.0 / A100_PEAK_TFLOPS) if peak else 0.0
    print(json.dumps({
        "metric": "bert_finetune_tokens_per_sec",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 4),
        "batch": B,
        "amp": amp_level,
    }))


if __name__ == "__main__":
    sys.exit(main())
