"""PP-YOLOE-class detector training throughput (BASELINE.md row 4).

Prints ONE JSON line like bench.py.  vs_baseline is 0.0 ("track" level —
BASELINE.md records no written-down A100 reference point for this row)."""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    if os.environ.get("PADDLE_TPU_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    on_accel = jax.devices()[0].platform != "cpu"

    import paddle_tpu as paddle
    from paddle_tpu.device import hard_sync
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.vision.models import ppyolo_s, ppyolo_tiny

    paddle.seed(0)
    model = ppyolo_s() if on_accel else ppyolo_tiny(num_classes=4)
    B, H = (32, 320) if on_accel else (2, 64)
    iters = 10 if on_accel else 2
    opt = paddle.optimizer.Momentum(0.01, parameters=model.parameters())

    def loss_fn(m, x):
        with paddle.amp.auto_cast(enable=on_accel):
            outs = m(x)
        # dense surrogate objective over the head maps: exercises the full
        # backbone/neck/head compute the detection losses ride on
        return sum((o.astype("float32") ** 2).mean() for o in outs)

    step = TrainStep(model, opt, loss_fn)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((B, 3, H, H)).astype(np.float32))
    step(x)
    hard_sync(step(x))
    from paddle_tpu.device import time_step_ms

    rate_denom_s = time_step_ms(lambda: step(x), inner=iters) / 1e3
    print(json.dumps({
        "metric": "ppyolo_train_images_per_sec",
        "value": round(B / rate_denom_s, 2),
        "unit": "images/s",
        "vs_baseline": 0.0,
        "batch": B,
    }))


if __name__ == "__main__":
    sys.exit(main())
