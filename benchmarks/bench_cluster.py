"""Disaggregated serving cluster: throughput + fail-over latency, with a
zero-loss / bit-exact-fail-over parity gate (serving/cluster.py,
docs/SERVING_CLUSTER.md; ROADMAP items 2 and 5).

Phases, all over REAL OS processes (router + N decode replicas + a
prefill worker on TCPStore/ShmRing):

- **Baseline**: an unkilled cluster serves the workload; the headline
  metric is end-to-end cluster tokens/s (submit -> last completion wall),
  with KV pages shipped prefill->decode counted (int8-halved wire bytes
  when the pool is int8).
- **Fail-over matrix**: the same workload three times; once every stream
  is in flight, the busiest replica is SIGKILLed.  One run per recovery
  mode:

    cold          warmup=False, no standby — respawn pays fork + jax
                  import + model build + LAZY first-step compile on the
                  recovery critical path (the pre-warm-start behaviour)
    warm_respawn  warmup=True, no standby — the respawned worker AOT-
                  warms (persistent-cache-served) BEFORE claiming its
                  snapshot, so compiles never land mid-serving; its boot
                  report must show persistent_cache_hits > 0
    standby       warmup=True, standby=1 — a pre-forked warm standby is
                  PROMOTED into the dead slot: no fork, no import, no
                  compile on the recovery path at all

  Reported per mode: first_token_ms — failure DETECTION to the first NEW
  token on a victim-owned stream (the user-visible recovery latency).
  The top-level detect_ms/recover_ms describe the standby run (the
  recovery path this cluster actually prefers when the tier is armed);
  per-mode numbers ride detail.failover.first_token_ms.  lost counts
  accepted requests that never completed (MUST be 0 in every mode) and
  streams_match requires every mode's streams to equal the unkilled
  run's bit for bit — the fail-over contract, re-asserted on every
  promotion path.

rc is 0 only when lost == 0 AND streams_match across ALL modes — the
latency numbers are never reported off a run that dropped or corrupted a
request.  Prints ONE JSON line like the other benches;
tools/check_bench_regression.py gates the failover latencies and the
per-mode first-token numbers (lower is better, SLO threshold).
`--smoke` / PADDLE_TPU_BENCH_SMOKE shrinks sizes for CI
(tests/test_bench_cluster.py).  `--transport tcp` (or
PADDLE_TPU_BENCH_TRANSPORT=tcp) runs every phase over the TcpRing
socket data plane between two localhost "hosts" — same zero-loss /
bit-exact gates, plus a detail.transport section (kind, tcp_bytes,
reconnects, frames) that check_bench_regression.py gates (skipping
silently on pre-transport payloads).  PADDLE_TPU_BENCH_DEADLINE_S
widens every internal wait wall on loaded CI hosts.  This bench forks
and kills processes: CPU-runnable by construction, no accelerator
required (the axon-tunnel-down standing constraint)."""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_bench_model():
    """Deterministic tiny llama built identically in EVERY cluster
    process (the worker imports this file by path)."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    paddle.seed(7)
    cfg = llama_tiny(vocab_size=256, hidden_size=64, intermediate_size=176,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=256,
                     dtype="float32")
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _workload(n_req, max_new):
    shared = [5, 9, 17, 33, 2, 8, 7, 4, 11, 29, 3, 31, 6, 12, 20, 17]
    out = []
    for i in range(n_req):
        out.append((f"r{i}", shared + [i + 1, (i * 7) % 200 + 1],
                    max_new))
    return out


def _run_cluster(workdir, spec, ekw, work, kill_busiest=False, *,
                 warmup=True, standby=0, snapshot_interval=0,
                 transport="shm"):
    from paddle_tpu.serving.cluster import EngineCluster, cluster_stats

    shutil.rmtree(workdir, ignore_errors=True)
    c = EngineCluster(spec, num_replicas=2, num_prefill=1,
                      engine_kwargs=ekw, workdir=workdir,
                      heartbeat_ms=100, miss_threshold=10,
                      snapshot_interval=snapshot_interval,
                      warmup=warmup, standby=standby,
                      transport=transport)
    fo = {"detect_ms": 0.0, "first_token_ms": 0.0, "recover_ms": 0.0}
    try:
        # the shared wall for every wait below: CI hosts running six
        # test jobs stretch fork/compile walls, so the budget is
        # env-tunable (tests/test_bench_cluster.py raises it under load)
        budget = float(os.environ.get("PADDLE_TPU_BENCH_DEADLINE_S", 240))
        deadline = time.monotonic() + budget
        if standby:
            # the mode under test is PROMOTION: killing before the
            # standby is warm would measure the respawn fallback instead
            while cluster_stats()["standbys_warm"] < standby:
                c.poll()
                if time.monotonic() > deadline:
                    raise TimeoutError("standby tier never warmed")
                time.sleep(0.005)
        t0 = time.monotonic()
        for rid, prompt, max_new in work:
            c.submit(rid, prompt, max_new_tokens=max_new)
        if kill_busiest:
            # wait until every stream is genuinely in flight
            while any(not c.router.request(rid).tokens
                      for rid, _p, _m in work):
                c.poll()
                if time.monotonic() > deadline:
                    raise TimeoutError("streams never all started")
                time.sleep(0.002)
            victim = max(c.router.replicas(), key=c.router.load)
            w = c._workers[("decode", victim)]
            # victim-owned unfinished streams: the first NEW token on any
            # of them is the user-visible end of the recovery outage.
            # Ownership must be read BEFORE the kill (death releases it)
            victims = [rid for rid, _p, _m in work
                       if c.router.request(rid).owner == victim
                       and not c.router.request(rid).done]
            before = cluster_stats()
            t_kill = time.monotonic()
            os.kill(w.proc.pid, 9)  # SIGKILL: no goodbye, no flush
            # detection is visible as a re-dispatch (replay fail-over),
            # the replacement spawn (restore/claim path), or a standby
            # promotion (warm-start path)
            def _detected():
                st = cluster_stats()
                return any(st[k] != before[k] for k in
                           ("redispatches", "respawns", "promotions"))
            while not _detected():
                c.poll()
                if time.monotonic() > deadline:
                    raise TimeoutError("death never detected")
                time.sleep(0.001)
            t_detect = time.monotonic()
            fo["detect_ms"] = (t_detect - t_kill) * 1000
            # baseline counts AFTER detection: the dead worker's ring may
            # still have held pre-kill tokens that the detection polls
            # merged — those are delivery backlog, not recovery, and must
            # not zero the first-token clock
            counts = {rid: len(c.router.request(rid).tokens)
                      for rid in victims}

            def _first_new_token():
                return any(len(c.router.request(rid).tokens) > n0
                           for rid, n0 in counts.items())
            while counts and not _first_new_token():
                c.poll()
                if time.monotonic() > deadline:
                    raise TimeoutError("victim streams never resumed")
                time.sleep(0.001)
            fo["first_token_ms"] = (time.monotonic() - t_detect) * 1000
            c.serve(timeout_s=budget)
            fo["recover_ms"] = (time.monotonic() - t_kill) * 1000
        else:
            c.serve(timeout_s=budget)
        wall = time.monotonic() - t0
        results = {rid: c.result(rid) for rid, _p, _m in work}
        stats = cluster_stats(reset=True)
        return results, wall, stats, fo
    finally:
        c.shutdown()


def main():
    import jax

    if os.environ.get("PADDLE_TPU_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    smoke = os.environ.get("PADDLE_TPU_BENCH_SMOKE") or "--smoke" in sys.argv
    # --transport tcp (or PADDLE_TPU_BENCH_TRANSPORT=tcp) runs the SAME
    # phases over the socket data plane: two localhost "hosts", every
    # parity gate unchanged — zero lost, bit-exact fail-over streams
    transport = os.environ.get("PADDLE_TPU_BENCH_TRANSPORT", "shm")
    if "--transport" in sys.argv:
        i = sys.argv.index("--transport")
        if i + 1 >= len(sys.argv):
            raise SystemExit("--transport needs a value: shm | tcp")
        transport = sys.argv[i + 1]
    # workers share the tier-1 persistent compile cache when present
    os.environ.setdefault("PADDLE_TPU_TEST_CACHE_DIR", "/tmp/jax_cache")

    spec = os.path.abspath(__file__) + ":make_bench_model"
    ekw = dict(max_batch=2, block_size=8, num_blocks=48, decode_chunk=4)
    # streams must OUTLIVE the kill: short smoke streams complete before
    # the SIGKILL lands and leave nothing to fail over
    n_req, max_new = (3, 32) if smoke else (6, 48)
    work = _workload(n_req, max_new)
    base = tempfile.mkdtemp(prefix="bench_cluster_")
    modes = (("cold", dict(warmup=False, standby=0)),
             ("warm_respawn", dict(warmup=True, standby=0)),
             ("standby", dict(warmup=True, standby=1)))
    try:
        ref, wall, base_stats, _fo = _run_cluster(
            os.path.join(base, "ref"), spec, ekw, work,
            transport=transport)
        total_tokens = sum(len(v) for v in ref.values() if v)
        tps = total_tokens / wall if wall else 0.0

        runs = {}
        for mode, kw in modes:
            got, _w, stats, fo = _run_cluster(
                os.path.join(base, mode), spec, ekw, work,
                kill_busiest=True, snapshot_interval=1,
                transport=transport, **kw)
            runs[mode] = {
                "got": got, "stats": stats, "fo": fo,
                "lost": sum(1 for rid, _p, _m in work if not got.get(rid)),
                "match": got == ref,
            }
    finally:
        shutil.rmtree(base, ignore_errors=True)

    lost = sum(r["lost"] for r in runs.values())
    streams_match = all(r["match"] for r in runs.values())
    sb, wr = runs["standby"], runs["warm_respawn"]
    print(json.dumps({
        "metric": "cluster_tokens_per_sec",
        "value": round(tps, 2),
        "unit": "tok/s",
        "vs_baseline": 0.0,
        "tokens_match": streams_match,
        "detail": {
            "replicas": 2,
            "prefill_workers": 1,
            "requests": n_req,
            "total_tokens": total_tokens,
            "failover": {
                "detect_ms": round(sb["fo"]["detect_ms"], 1),
                "recover_ms": round(sb["fo"]["recover_ms"], 1),
                "first_token_ms": {
                    m: round(runs[m]["fo"]["first_token_ms"], 1)
                    for m, _kw in modes},
                "lost": lost,
                "streams_match": streams_match,
                "redispatches": sum(
                    r["stats"]["redispatches"] for r in runs.values()),
                "promotions": sb["stats"]["promotions"],
                "respawn_compile_hits":
                    wr["stats"]["respawn_compile_hits"],
            },
            "ship": {
                "pages": base_stats["pages_shipped"],
                "bytes": base_stats["ship_bytes"],
                "retries": base_stats["ship_retries"],
            },
            "transport": {
                "kind": transport,
                "tcp_bytes": base_stats.get("tcp_bytes", 0),
                "reconnects": base_stats.get("reconnects", 0),
                "frames_sent": base_stats.get("frames_sent", 0),
                "frames_recv": base_stats.get("frames_recv", 0),
            },
        },
    }))
    return 0 if (lost == 0 and streams_match) else 1


if __name__ == "__main__":
    sys.exit(main())
