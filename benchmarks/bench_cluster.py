"""Disaggregated serving cluster: throughput + fail-over latency, with a
zero-loss / bit-exact-fail-over parity gate (serving/cluster.py,
docs/SERVING_CLUSTER.md; ROADMAP item 2).

Two phases, both over REAL OS processes (router + N decode replicas + a
prefill worker on TCPStore/ShmRing):

- **Baseline**: an unkilled cluster serves the workload; the headline
  metric is end-to-end cluster tokens/s (submit -> last completion wall),
  with KV pages shipped prefill->decode counted (int8-halved wire bytes
  when the pool is int8).
- **Fail-over**: the same workload; once every stream is in flight, the
  busiest replica is SIGKILLed.  Reported: detect_ms (kill -> the router's
  failure detection, observed as the first re-dispatch) and recover_ms
  (kill -> every stream complete), plus lost (accepted requests that never
  completed — MUST be 0) and streams_match (killed-run streams equal the
  unkilled run's bit for bit — the fail-over contract).

rc is 0 only when lost == 0 AND streams_match — the latency numbers are
never reported off a run that dropped or corrupted a request.  Prints ONE
JSON line like the other benches; tools/check_bench_regression.py gates
the failover latencies (lower is better, SLO threshold).  `--smoke` /
PADDLE_TPU_BENCH_SMOKE shrinks sizes for CI (tests/test_bench_cluster.py).
This bench forks and kills processes: CPU-runnable by construction, no
accelerator required (the axon-tunnel-down standing constraint)."""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_bench_model():
    """Deterministic tiny llama built identically in EVERY cluster
    process (the worker imports this file by path)."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    paddle.seed(7)
    cfg = llama_tiny(vocab_size=256, hidden_size=64, intermediate_size=176,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=256,
                     dtype="float32")
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _workload(n_req, max_new):
    shared = [5, 9, 17, 33, 2, 8, 7, 4, 11, 29, 3, 31, 6, 12, 20, 17]
    out = []
    for i in range(n_req):
        out.append((f"r{i}", shared + [i + 1, (i * 7) % 200 + 1],
                    max_new))
    return out


def _run_cluster(workdir, spec, ekw, work, kill_busiest=False):
    from paddle_tpu.serving.cluster import EngineCluster, cluster_stats

    shutil.rmtree(workdir, ignore_errors=True)
    c = EngineCluster(spec, num_replicas=2, num_prefill=1,
                      engine_kwargs=ekw, workdir=workdir,
                      heartbeat_ms=100, miss_threshold=10)
    out = {}
    try:
        t0 = time.monotonic()
        for rid, prompt, max_new in work:
            c.submit(rid, prompt, max_new_tokens=max_new)
        detect_ms = recover_ms = 0.0
        if kill_busiest:
            # wait until every stream is genuinely in flight
            deadline = time.monotonic() + 240
            while any(not c.router.request(rid).tokens
                      for rid, _p, _m in work):
                c.poll()
                if time.monotonic() > deadline:
                    raise TimeoutError("streams never all started")
                time.sleep(0.002)
            victim = max(c.router.replicas(), key=c.router.load)
            w = c._workers[("decode", victim)]
            before = cluster_stats()
            t_kill = time.monotonic()
            os.kill(w.proc.pid, 9)  # SIGKILL: no goodbye, no flush
            # detection is visible as either a re-dispatch (replay
            # fail-over) or the replacement spawn (restore/claim path)
            while (cluster_stats()["redispatches"]
                   == before["redispatches"]
                   and cluster_stats()["respawns"] == before["respawns"]):
                c.poll()
                if time.monotonic() > deadline:
                    raise TimeoutError("death never detected")
                time.sleep(0.001)
            detect_ms = (time.monotonic() - t_kill) * 1000
            c.serve(timeout_s=240)
            recover_ms = (time.monotonic() - t_kill) * 1000
        else:
            c.serve(timeout_s=240)
        wall = time.monotonic() - t0
        results = {rid: c.result(rid) for rid, _p, _m in work}
        stats = cluster_stats(reset=True)
        return results, wall, stats, detect_ms, recover_ms
    finally:
        c.shutdown()


def main():
    import jax

    if os.environ.get("PADDLE_TPU_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    smoke = os.environ.get("PADDLE_TPU_BENCH_SMOKE") or "--smoke" in sys.argv
    # workers share the tier-1 persistent compile cache when present
    os.environ.setdefault("PADDLE_TPU_TEST_CACHE_DIR", "/tmp/jax_cache")

    spec = os.path.abspath(__file__) + ":make_bench_model"
    ekw = dict(max_batch=2, block_size=8, num_blocks=48, decode_chunk=4)
    # streams must OUTLIVE the kill: short smoke streams complete before
    # the SIGKILL lands and leave nothing to fail over
    n_req, max_new = (3, 32) if smoke else (6, 48)
    work = _workload(n_req, max_new)
    base = tempfile.mkdtemp(prefix="bench_cluster_")
    try:
        ref, wall, base_stats, _d, _r = _run_cluster(
            os.path.join(base, "ref"), spec, ekw, work)
        total_tokens = sum(len(v) for v in ref.values() if v)
        tps = total_tokens / wall if wall else 0.0

        got, _wall2, fo_stats, detect_ms, recover_ms = _run_cluster(
            os.path.join(base, "kill"), spec, ekw, work, kill_busiest=True)
        lost = sum(1 for rid, _p, _m in work if not got.get(rid))
        streams_match = got == ref
    finally:
        shutil.rmtree(base, ignore_errors=True)

    print(json.dumps({
        "metric": "cluster_tokens_per_sec",
        "value": round(tps, 2),
        "unit": "tok/s",
        "vs_baseline": 0.0,
        "tokens_match": streams_match,
        "detail": {
            "replicas": 2,
            "prefill_workers": 1,
            "requests": n_req,
            "total_tokens": total_tokens,
            "failover": {
                "detect_ms": round(detect_ms, 1),
                "recover_ms": round(recover_ms, 1),
                "lost": lost,
                "streams_match": streams_match,
                "redispatches": fo_stats["redispatches"],
            },
            "ship": {
                "pages": base_stats["pages_shipped"],
                "bytes": base_stats["ship_bytes"],
                "retries": base_stats["ship_retries"],
            },
        },
    }))
    return 0 if (lost == 0 and streams_match) else 1


if __name__ == "__main__":
    sys.exit(main())
