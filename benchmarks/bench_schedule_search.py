"""Benchmark: cost-model-driven Pallas schedule search + measured-win gate.

Exercises the full ROADMAP-item-2/item-4 loop on four searched subjects no
named pattern covers (the XLA fusion-miss classes of arXiv 2301.13062):

- **matmul chain** — matmul→bias-add→relu→mean tail (matmul-rooted with a
  reduction tail): searched, gated, and — when the schedule wins —
  substituted, with fused-vs-XLA numerics asserted either way.
- **K-tiled matmul chain** (phase 2) — the same class at a contraction dim
  large enough that ``block_k`` splits enter the candidate space; smoke
  mode pins a genuinely K-tiled config as the winner so the accumulator
  kernel path is the one asserted.
- **softmax chain** — a manually decomposed softmax (reduction-rooted DAG):
  in smoke mode its schedule deliberately LOSES so the gate's disable path
  is exercised: the decision persists as a disabled entry in the
  per-device autotune cache and a cold reload must skip the subgraph
  without a single re-measurement.
- **decode hot chain** (phase 2) — the serving macro-step's paged gather →
  dequant → sdpa core → quant-write sequence (ops/decode_chain.py), bf16
  AND int8 variants, searched through the same enumerate→prune→parity→
  measure→gate loop; every candidate must pass the numerics parity gate
  vs the unfused twin BEFORE it may be measured, and the disabled int8
  verdict must serve a cold reload with zero re-measures.
- **sharded decode chain** (schedule search over the mesh) — the same hot
  chain searched by a REAL 2-device TP engine workload: the verdict
  caches under the (device kind, mesh shape) key, an adoption builds the
  chain inside shard_map over the engine's committed pool sharding
  (decode_chains_mesh_fused counts it), and the token streams must stay
  bit-identical to the search-off sharded twin whether the verdict is
  adopt or an honest disable.
- **fused prefill attention** — the K-tiled long-prompt-pour candidate
  (ops/decode_chain.PrefillChainSpec) joins the same search with a
  BIT-EXACT parity gate on every candidate.

Timing: in full mode candidates are measured for real through
cost_model.OpCostModel.measure (hard_sync device barrier — meaningful on
TPU; on CPU the kernels run in Pallas interpret mode, where XLA-only
usually wins and the gate honestly disables — a win-or-disabled verdict is
recorded either way, never a faked value).  Smoke mode (--smoke or
PADDLE_TPU_BENCH_SMOKE=1) injects a deterministic roofline-shaped cost
model via schedule_search.measure_override so CI asserts the DECISION
LOGIC — accept vs disable vs never-refire — bit-stably offline, with
numerics always checked for real.

Prints ONE JSON line shaped like bench.py: {"metric", "value", ...}.
value = the best accepted schedule's measured win ratio over XLA (0.0 when
the gate disabled everything — an honest loss is not a regression signal;
tools/check_bench_regression.py skips zero values).  detail.decode_chain
carries the per-variant decode verdicts the regression gate compares
win-to-win, skipping disabled sides honestly.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the mesh decode-chain case dispatches a REAL 2-device engine workload:
# the host-platform device count must be pinned before jax initializes
# (a no-op on TPU backends — the flag only shapes the CPU platform)
_xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xla_flags:
    os.environ["XLA_FLAGS"] = (
        _xla_flags + " --xla_force_host_platform_device_count=8")


def main() -> int:
    smoke = "--smoke" in sys.argv or bool(os.environ.get("PADDLE_TPU_BENCH_SMOKE"))

    import jax

    if jax.default_backend() != "tpu":
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.ops import autotune as at
    from paddle_tpu.ops import decode_chain as dc
    from paddle_tpu.static import schedule_search as ss
    from paddle_tpu.static.program import Program, program_guard
    from paddle_tpu.static.rewrite import ScheduleSearchPass
    from paddle_tpu.static.verify import differential_check

    # decisions land in a scratch per-device cache, not the checked-in seeds
    cache_dir = tempfile.mkdtemp(prefix="sched_bench_")
    paddle.set_flags({"FLAGS_autotune_cache_dir": cache_dir})
    at._CACHES.clear()
    ss.reset_schedule_search_stats()

    if smoke:
        M, K, N = 32, 16, 64
        B, S, H = 2, 8, 32
        MT, KT, NT = 32, 256, 64
        DEC = dict(batch=2, num_heads=4, num_kv_heads=2, head_dim=8,
                   block_size=4, max_blocks=2, num_blocks=8)
        PS = 8  # prefill chunk length (kv span = 2*PS)
    elif jax.default_backend() == "tpu":
        M, K, N = 1024, 512, 512
        B, S, H = 8, 128, 512
        MT, KT, NT = 1024, 2048, 1024
        DEC = dict(batch=8, num_heads=16, num_kv_heads=8, head_dim=128,
                   block_size=16, max_blocks=16, num_blocks=136)
        PS = 128
    else:
        # full mode off-chip: real timing of interpret-mode kernels — keep
        # shapes small enough that an honest all-disabled outcome is cheap
        M, K, N = 128, 64, 128
        B, S, H = 4, 32, 64
        MT, KT, NT = 64, 512, 128
        DEC = dict(batch=2, num_heads=4, num_kv_heads=2, head_dim=16,
                   block_size=8, max_blocks=4, num_blocks=16)
        PS = 16

    def _feed(prog, name, shape):
        return prog.add_feed(
            prog.new_var(jax.ShapeDtypeStruct(shape, np.float32), name))

    def capture_matmul_chain():
        prog = Program()
        with program_guard(prog):
            x = _feed(prog, "x", (M, K))
            w = _feed(prog, "w", (K, N))
            b = _feed(prog, "b", (N,))
            h = paddle.matmul(x, w)
            h = h + b
            h = F.relu(h)
            out = paddle.mean(h, axis=-1, keepdim=True)
        return prog, out

    def capture_ktiled_chain():
        prog = Program()
        with program_guard(prog):
            x = _feed(prog, "x", (MT, KT))
            w = _feed(prog, "w", (KT, NT))
            b = _feed(prog, "b", (NT,))
            out = F.relu(paddle.matmul(x, w) + b)
        return prog, out

    def capture_softmax_chain():
        prog = Program()
        with program_guard(prog):
            x = _feed(prog, "x", (B, S, H))
            m = paddle.max(x, axis=-1, keepdim=True)
            t = paddle.exp(x - m)
            s = paddle.sum(t, axis=-1, keepdim=True)
            out = t / s
        return prog, out

    measured_labels = []

    def smoke_measure(fn, args, *, label, config):
        """Deterministic roofline-shaped cost model: the matmul chains'
        schedules win (the large-K twin only through a genuinely K-tiled
        config; grid overhead mildly penalizes tiny blocks), the softmax
        chain's and the int8 decode chain's schedules deliberately LOSE
        to XLA, the bf16 decode chain (single-device AND its mesh-keyed
        twin) and the prefill chain win."""
        measured_labels.append(label)
        if config is None:
            return 1.0
        if label.startswith("schedule/reduce"):
            return 4.0  # the deliberately-bad schedule family
        if label.startswith("schedule/decode_int8"):
            return 4.0  # exercise the decode disable path
        if label.startswith("schedule/decode_bf16"):
            return 0.4
        if label.startswith("schedule/prefill"):
            return 0.4
        if f"k={KT}" in label:
            # the K-tiled twin: only a contraction split beats XLA here
            return 0.3 if config.get("block_k", KT) < KT else 4.0
        steps = (M // config["block_rows"]) * (N // config["block_cols"])
        return 0.4 + 0.002 * steps

    def cache_entries(kernel):
        slug_file = os.path.join(cache_dir, at.device_kind_slug() + ".json")
        if not os.path.exists(slug_file):
            return {}
        raw = json.load(open(slug_file))
        return raw.get(kernel, {})

    def cache_entry(kernel, key_sub=""):
        for k, v in cache_entries(kernel).items():
            if key_sub in k:
                return v
        return None

    def run_case(name, capture, kernel, key_sub="", budget=3):
        """Search one Program subgraph; return its decision record with
        REAL fused-vs-XLA numerics parity."""
        prog, out = capture()
        reference = prog.clone()
        searcher = ss.ScheduleSearcher(budget=budget, iters=1, warmup=1)
        n = ScheduleSearchPass([out._vid], searcher=searcher).apply(prog)
        types = [op.type for op in prog.global_block().ops]
        fused_type = next((t for t in types if t.startswith("sched_chain_")),
                          None)
        numerics_ok = True
        if n:
            numerics_ok = differential_check(
                reference, prog, [out._vid], raise_on_error=False) == []
        return {
            "substituted": n,
            "fused_op": fused_type,
            "numerics_identical": bool(numerics_ok),
            "cache_entry": cache_entry(kernel, key_sub),
        }

    def run_decode_case(kv, budget=3):
        """Search the decode hot chain at the bench geometry.  Numerics
        ride the searcher's parity gate: a candidate that fails the
        bit-exact (bf16) / drift-bounded (int8) check vs the unfused twin
        is rejected before it may be measured."""
        spec = dc.DecodeChainSpec(kv=kv, dtype=np.float32, **DEC)
        decision = dc.ensure_decision(
            spec, ss.ScheduleSearcher(budget=budget, iters=1, warmup=1))
        entry = cache_entry(spec.kernel_name()) or {}
        meta = entry.get("meta") or {}
        return {
            "status": decision.status,
            "accepted": bool(decision.accepted),
            "config": dict(decision.config) if decision.config else None,
            "win": float(meta.get("win", 0.0) or 0.0)
            if not entry.get("config", {}).get("disabled") else 0.0,
            "disabled_persisted": bool(entry.get("config", {})
                                       .get("disabled")),
        }

    def run_prefill_case(budget=3):
        """Search the K-tiled fused prefill-attention candidate (the
        long-prompt pour's attention core at the canonical chunk
        geometry) through the same loop; every candidate's parity gate
        is BIT-EXACT vs the jax.nn reference."""
        spec = dc.PrefillChainSpec(seq=PS, kv_len=2 * PS,
                                   num_heads=DEC["num_heads"],
                                   head_dim=DEC["head_dim"],
                                   dtype=np.float32)
        decision = dc.ensure_decision(
            spec, ss.ScheduleSearcher(budget=budget, iters=1, warmup=1))
        entry = cache_entry(spec.kernel_name()) or {}
        meta = entry.get("meta") or {}
        return {
            "status": decision.status,
            "accepted": bool(decision.accepted),
            "config": dict(decision.config) if decision.config else None,
            "win": float(meta.get("win", 0.0) or 0.0)
            if not entry.get("config", {}).get("disabled") else 0.0,
        }

    def run_mesh_decode_case():
        """Schedule search over the mesh: a REAL 2-device TP engine
        workload under FLAGS_schedule_search.  The sharded searcher's
        verdict caches under the (device kind, mesh shape) key; an
        adoption builds the chain inside shard_map over the engine's
        committed pool sharding and MUST leave the token streams
        bit-identical to the search-off sharded twin; an honest disable
        keeps the unfused GSPMD path (streams compare identically
        either way)."""
        from paddle_tpu.distributed.auto_parallel import ProcessMesh
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        from paddle_tpu.serving import (GenerationEngine,
                                        reset_schedule_decode_stats,
                                        schedule_decode_stats)

        if len(jax.devices()) < 2:
            return {"skipped": "needs >= 2 devices"}

        def build_model():
            paddle.seed(41)
            m = LlamaForCausalLM(llama_tiny(
                vocab_size=128, hidden_size=32, intermediate_size=64,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=4, max_position_embeddings=64,
                dtype="float32"))
            m.eval()
            return m

        def workload(eng):
            eng.add_request("g", [5, 9, 17, 33, 2], max_new_tokens=8)
            eng.step()
            eng.add_request("s", [7, 11, 3], max_new_tokens=6,
                            temperature=3.0, seed=42)  # joins mid-flight
            while eng.has_work():
                eng.step()
            return {"g": eng.result("g"), "s": eng.result("s")}

        mesh = ProcessMesh(np.arange(2), ["mp"])
        kw = dict(max_batch=2, block_size=8, num_blocks=16,
                  kv_cache_dtype="bf16", mesh=mesh)
        ref = workload(GenerationEngine(build_model(), **kw))
        reset_schedule_decode_stats()
        paddle.set_flags({"FLAGS_schedule_search": True})
        try:
            got = workload(GenerationEngine(build_model(), **kw))
        finally:
            paddle.set_flags({"FLAGS_schedule_search": False})
        stats = schedule_decode_stats()
        entry = cache_entry("schedule/decode_bf16",
                            key_sub="mesh=mp2") or {}
        meta = entry.get("meta") or {}
        disabled = bool(entry.get("config", {}).get("disabled"))
        return {
            "mesh_fused": int(stats["decode_chains_mesh_fused"]),
            "mesh_skipped": int(stats["decode_chains_mesh_skipped"]),
            "streams_identical": bool(got == ref),
            "win": 0.0 if disabled
            else float(meta.get("win", 0.0) or 0.0),
            # the verdict's cache key must carry the mesh shape — the
            # single-device bf16 verdict above lives beside it, distinct
            "cache_key_mesh": next(
                (k for k in cache_entries("schedule/decode_bf16")
                 if "mesh=mp2" in k), None),
        }

    ctx = (ss.measure_override(smoke_measure) if smoke
           else contextlib.nullcontext())
    with ctx:
        matmul_case = run_case("matmul_chain", capture_matmul_chain,
                               "schedule/matmul", key_sub=f"k={K}|")
        ktiled_case = run_case("ktiled_matmul", capture_ktiled_chain,
                               "schedule/matmul", key_sub=f"k={KT}|")
        softmax_case = run_case("softmax_chain", capture_softmax_chain,
                                "schedule/reduce")
        decode_bf16 = run_decode_case("bf16")
        decode_int8 = run_decode_case("int8")
        prefill_case = run_prefill_case()
        mesh_case = run_mesh_decode_case()

        # never-refire: cold cache reload, a disabled subgraph must be
        # skipped without a single new measurement
        at._CACHES.clear()
        before = len(measured_labels) if smoke else \
            ss.schedule_search_stats()["measured"]
        prog2, out2 = capture_softmax_chain()
        ScheduleSearchPass(
            [out2._vid],
            searcher=ss.ScheduleSearcher(budget=3, iters=1, warmup=1)
        ).apply(prog2)
        # ... and the decode verdicts serve a cold reload with zero
        # re-measures too (accepted bf16 config AND disabled int8)
        dc.ensure_decision(
            dc.DecodeChainSpec(kv="bf16", dtype=np.float32, **DEC),
            ss.ScheduleSearcher(budget=3, iters=1, warmup=1))
        dc.ensure_decision(
            dc.DecodeChainSpec(kv="int8", dtype=np.float32, **DEC),
            ss.ScheduleSearcher(budget=3, iters=1, warmup=1))
        after = len(measured_labels) if smoke else \
            ss.schedule_search_stats()["measured"]
        never_refired = (after == before)

    stats = ss.schedule_search_stats()
    # headline value: the best accepted schedule's measured win over XLA
    # (any case may win or lose under real timing; smoke pins the set)
    win = 0.0
    for case in (matmul_case, ktiled_case, softmax_case):
        entry = case["cache_entry"] or {}
        if case["substituted"] and not entry.get("config", {}).get("disabled"):
            win = max(win, float((entry.get("meta") or {}).get("win", 0.0)
                                 or 0.0))
    for case in (decode_bf16, decode_int8, prefill_case, mesh_case):
        win = max(win, float(case.get("win", 0.0) or 0.0))
    disabled_entry = softmax_case["cache_entry"] or {}
    numerics_ok = (matmul_case["numerics_identical"]
                   and ktiled_case["numerics_identical"]
                   and softmax_case["numerics_identical"])
    min_win = float(paddle.get_flags("FLAGS_schedule_search_min_win")[
        "FLAGS_schedule_search_min_win"])

    paddle.set_flags({"FLAGS_autotune_cache_dir": ""})
    at._CACHES.clear()
    shutil.rmtree(cache_dir, ignore_errors=True)

    print(
        json.dumps(
            {
                "metric": "schedule_search_measured_win",
                "value": round(win, 4),
                "unit": "x",
                "vs_baseline": round(win / min_win, 4) if win else 0.0,
                "numerics_identical": bool(numerics_ok),
                "detail": {
                    "matmul_chain": matmul_case,
                    "ktiled_matmul": ktiled_case,
                    "softmax_chain": softmax_case,
                    "decode_chain": {"bf16": decode_bf16,
                                     "int8": decode_int8,
                                     "mesh": mesh_case,
                                     "prefill": prefill_case},
                    "disabled_persisted": bool(disabled_entry.get(
                        "config", {}).get("disabled")),
                    "never_refired": bool(never_refired),
                    "counters": stats,
                },
                "config": ("smoke" if smoke
                           else f"mm{M}x{K}x{N}_kt{MT}x{KT}x{NT}"
                                f"_sm{B}x{S}x{H}"),
            }
        ),
        flush=True,
    )
    # stream parity on the sharded engine is a numerics claim, valid in
    # smoke AND full mode (adopt or disable, the streams must match)
    ok = (numerics_ok and never_refired
          and bool(mesh_case.get("streams_identical", True)))
    if smoke:
        # the deterministic cost model must produce exactly these decisions
        ktc = (ktiled_case["cache_entry"] or {}).get("config", {})
        mesh_ok = ("skipped" in mesh_case) or (
            mesh_case["mesh_fused"] > 0
            and mesh_case["win"] > 1.0
            and bool(mesh_case["cache_key_mesh"]))
        ok = ok and matmul_case["substituted"] == 1 and win > 1.0 \
            and softmax_case["substituted"] == 0 \
            and bool(disabled_entry.get("config", {}).get("disabled")) \
            and ktiled_case["substituted"] == 1 \
            and 0 < ktc.get("block_k", 0) < KT \
            and decode_bf16["accepted"] and decode_bf16["win"] > 1.0 \
            and decode_int8["status"] in ("disabled", "cache_disabled") \
            and decode_int8["disabled_persisted"] \
            and prefill_case["accepted"] and prefill_case["win"] > 1.0 \
            and mesh_ok
    return 0 if ok else 4



if __name__ == "__main__":
    sys.exit(main())
