"""Benchmark: cost-model-driven Pallas schedule search + measured-win gate.

Exercises the full ROADMAP-item-2 loop on two discovered subgraphs no named
pattern matches (the XLA fusion-miss classes of arXiv 2301.13062):

- **matmul chain** — matmul→bias-add→relu→mean tail (matmul-rooted with a
  reduction tail): searched, gated, and — when the schedule wins —
  substituted, with fused-vs-XLA numerics asserted either way.
- **softmax chain** — a manually decomposed softmax (reduction-rooted DAG):
  same loop; in smoke mode its schedule deliberately LOSES so the gate's
  disable path is exercised: the decision persists as a disabled entry in
  the per-device autotune cache and a cold reload must skip the subgraph
  without a single re-measurement.

Timing: in full mode candidates are measured for real through
cost_model.OpCostModel.measure (hard_sync device barrier — meaningful on
TPU; on CPU the kernels run in Pallas interpret mode, where XLA-only
usually wins and the gate honestly disables).  Smoke mode (--smoke or
PADDLE_TPU_BENCH_SMOKE=1) injects a deterministic roofline-shaped cost
model via schedule_search.measure_override so CI asserts the DECISION
LOGIC — accept vs disable vs never-refire — bit-stably offline, with
numerics always checked for real.

Prints ONE JSON line shaped like bench.py: {"metric", "value", ...}.
value = the accepted schedule's measured win ratio over XLA (0.0 when the
gate disabled everything — an honest loss is not a regression signal;
tools/check_bench_regression.py skips zero values).
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    smoke = "--smoke" in sys.argv or bool(os.environ.get("PADDLE_TPU_BENCH_SMOKE"))

    import jax

    if jax.default_backend() != "tpu":
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.ops import autotune as at
    from paddle_tpu.static import schedule_search as ss
    from paddle_tpu.static.program import Program, program_guard
    from paddle_tpu.static.rewrite import ScheduleSearchPass
    from paddle_tpu.static.verify import differential_check

    # decisions land in a scratch per-device cache, not the checked-in seeds
    cache_dir = tempfile.mkdtemp(prefix="sched_bench_")
    paddle.set_flags({"FLAGS_autotune_cache_dir": cache_dir})
    at._CACHES.clear()
    ss.reset_schedule_search_stats()

    if smoke:
        M, K, N = 32, 16, 64
        B, S, H = 2, 8, 32
    elif jax.default_backend() == "tpu":
        M, K, N = 1024, 512, 512
        B, S, H = 8, 128, 512
    else:
        # full mode off-chip: real timing of interpret-mode kernels — keep
        # shapes small enough that an honest all-disabled outcome is cheap
        M, K, N = 128, 64, 128
        B, S, H = 4, 32, 64

    def _feed(prog, name, shape):
        return prog.add_feed(
            prog.new_var(jax.ShapeDtypeStruct(shape, np.float32), name))

    def capture_matmul_chain():
        prog = Program()
        with program_guard(prog):
            x = _feed(prog, "x", (M, K))
            w = _feed(prog, "w", (K, N))
            b = _feed(prog, "b", (N,))
            h = paddle.matmul(x, w)
            h = h + b
            h = F.relu(h)
            out = paddle.mean(h, axis=-1, keepdim=True)
        return prog, out

    def capture_softmax_chain():
        prog = Program()
        with program_guard(prog):
            x = _feed(prog, "x", (B, S, H))
            m = paddle.max(x, axis=-1, keepdim=True)
            t = paddle.exp(x - m)
            s = paddle.sum(t, axis=-1, keepdim=True)
            out = t / s
        return prog, out

    measured_labels = []

    def smoke_measure(fn, args, *, label, config):
        """Deterministic roofline-shaped cost model: the matmul chain's
        schedules win (grid overhead mildly penalizes tiny blocks), the
        softmax chain's schedules deliberately LOSE to XLA."""
        measured_labels.append(label)
        if config is None:
            return 1.0
        if label.startswith("schedule/reduce"):
            return 4.0  # the deliberately-bad schedule family
        steps = (M // config["block_rows"]) * (N // config["block_cols"])
        return 0.4 + 0.002 * steps

    def run_case(name, capture, budget=3):
        """Search one subgraph; return its decision record with REAL
        fused-vs-XLA numerics parity."""
        prog, out = capture()
        reference = prog.clone()
        searcher = ss.ScheduleSearcher(budget=budget, iters=1, warmup=1)
        n = ScheduleSearchPass([out._vid], searcher=searcher).apply(prog)
        types = [op.type for op in prog.global_block().ops]
        fused_type = next((t for t in types if t.startswith("sched_chain_")),
                          None)
        numerics_ok = True
        if n:
            numerics_ok = differential_check(
                reference, prog, [out._vid], raise_on_error=False) == []
        kernel = ("schedule/matmul" if name == "matmul_chain"
                  else "schedule/reduce")
        slug_file = os.path.join(cache_dir, at.device_kind_slug() + ".json")
        entry = None
        if os.path.exists(slug_file):
            raw = json.load(open(slug_file))
            entries = list(raw.get(kernel, {}).values())
            entry = entries[0] if entries else None
        return {
            "substituted": n,
            "fused_op": fused_type,
            "numerics_identical": bool(numerics_ok),
            "cache_entry": entry,
        }

    ctx = (ss.measure_override(smoke_measure) if smoke
           else contextlib.nullcontext())
    with ctx:
        matmul_case = run_case("matmul_chain", capture_matmul_chain)
        softmax_case = run_case("softmax_chain", capture_softmax_chain)

        # never-refire: cold cache reload, a disabled subgraph must be
        # skipped without a single new measurement
        at._CACHES.clear()
        before = len(measured_labels) if smoke else \
            ss.schedule_search_stats()["measured"]
        prog2, out2 = capture_softmax_chain()
        ScheduleSearchPass(
            [out2._vid],
            searcher=ss.ScheduleSearcher(budget=3, iters=1, warmup=1)
        ).apply(prog2)
        after = len(measured_labels) if smoke else \
            ss.schedule_search_stats()["measured"]
        never_refired = (after == before)

    stats = ss.schedule_search_stats()
    # headline value: the accepted schedule's measured win over XLA (either
    # case may win or lose under real timing; smoke pins matmul=win)
    win = 0.0
    for case in (matmul_case, softmax_case):
        entry = case["cache_entry"] or {}
        if case["substituted"] and not entry.get("config", {}).get("disabled"):
            win = max(win, float((entry.get("meta") or {}).get("win", 0.0)
                                 or 0.0))
    disabled_entry = softmax_case["cache_entry"] or {}
    numerics_ok = (matmul_case["numerics_identical"]
                   and softmax_case["numerics_identical"])
    min_win = float(paddle.get_flags("FLAGS_schedule_search_min_win")[
        "FLAGS_schedule_search_min_win"])

    paddle.set_flags({"FLAGS_autotune_cache_dir": ""})
    at._CACHES.clear()
    shutil.rmtree(cache_dir, ignore_errors=True)

    print(
        json.dumps(
            {
                "metric": "schedule_search_measured_win",
                "value": round(win, 4),
                "unit": "x",
                "vs_baseline": round(win / min_win, 4) if win else 0.0,
                "numerics_identical": bool(numerics_ok),
                "detail": {
                    "matmul_chain": matmul_case,
                    "softmax_chain": softmax_case,
                    "disabled_persisted": bool(disabled_entry.get(
                        "config", {}).get("disabled")),
                    "never_refired": bool(never_refired),
                    "counters": stats,
                },
                "config": ("smoke" if smoke
                           else f"mm{M}x{K}x{N}_sm{B}x{S}x{H}"),
            }
        ),
        flush=True,
    )
    ok = numerics_ok and never_refired
    if smoke:
        # the deterministic cost model must produce exactly these decisions
        ok = ok and matmul_case["substituted"] == 1 and win > 1.0 \
            and softmax_case["substituted"] == 0 \
            and bool(disabled_entry.get("config", {}).get("disabled"))
    return 0 if ok else 4



if __name__ == "__main__":
    sys.exit(main())
