"""ResNet-50 ImageNet-shape training throughput (BASELINE.md row 2).

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} like
bench.py; vs_baseline tracks images/sec against the Paddle-on-A100
reference point once recorded (none published in-repo — BASELINE.md)."""

from __future__ import annotations

import json
import sys

import os

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    if os.environ.get("PADDLE_TPU_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    on_accel = jax.devices()[0].platform != "cpu"

    import paddle_tpu as paddle
    from paddle_tpu.device import hard_sync
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.vision.models import resnet50, resnet18

    paddle.seed(0)
    cpu = None
    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError:
        pass
    import contextlib

    with (jax.default_device(cpu) if cpu else contextlib.nullcontext()):
        model = resnet50() if on_accel else resnet18()
    B, H = (64, 224) if on_accel else (4, 64)
    iters = 10 if on_accel else 2
    opt = paddle.optimizer.Momentum(0.1, parameters=model.parameters())
    ce = paddle.nn.CrossEntropyLoss()

    def loss_fn(m, x, y):
        # AMP O1 (bf16 matmul/conv inputs, fp32 loss) — the config the
        # reference's A100 ResNet baseline uses (fp16 AMP there).
        with paddle.amp.auto_cast(enable=on_accel):
            return ce(m(x), y)

    step = TrainStep(model, opt, loss_fn)
    rng = np.random.default_rng(0)

    def measure(batch, n_iters):
        x = paddle.to_tensor(rng.standard_normal((batch, 3, H, H)).astype(np.float32))
        y = paddle.to_tensor(rng.integers(0, 1000, (batch,)).astype(np.int32))
        step(x, y)
        hard_sync(step(x, y))
        from paddle_tpu.device import time_step_ms

        return batch / (time_step_ms(lambda: step(x, y), inner=n_iters) / 1e3)

    amp_level = "O1"
    if on_accel:
        # batch sweep: the MXU wants large batches (the A100 reference point
        # runs B=256-class AMP batches); pick the best-throughput config
        # that fits, largest first so an OOM falls through to smaller B
        images_per_sec, best_b = 0.0, B
        for batch in (512, 256, 128, 64):
            try:
                ips = measure(batch, iters)
            except Exception as e:
                # only resource exhaustion is an expected sweep outcome;
                # anything else is a real regression and must be visible
                msg = f"{type(e).__name__}: {e}"
                print(f"bench_resnet: B={batch} failed ({msg[:200]})",
                      file=sys.stderr)
                if "RESOURCE_EXHAUSTED" not in msg and "Out of memory" not in msg:
                    raise
                continue
            if ips > images_per_sec:
                images_per_sec, best_b = ips, batch
        B = best_b
        if images_per_sec == 0.0:
            images_per_sec = measure(B, iters)
        # O2 arm: bf16 parameters + fp32 master weights — less cast traffic
        # per step than O1's per-op casts (the A100 reference point is full
        # AMP); keep whichever measures faster at the winning batch
        try:
            model2 = resnet50()
            opt2 = paddle.optimizer.Momentum(0.1, parameters=model2.parameters())
            model2, opt2 = paddle.amp.decorate(model2, opt2, level="O2")

            def loss_fn2(m, x, y):
                with paddle.amp.auto_cast(enable=True, level="O2"):
                    return ce(m(x), y)

            step2 = TrainStep(model2, opt2, loss_fn2)
            x = paddle.to_tensor(rng.standard_normal((B, 3, H, H)).astype(np.float32))
            y = paddle.to_tensor(rng.integers(0, 1000, (B,)).astype(np.int32))
            step2(x, y)
            hard_sync(step2(x, y))
            from paddle_tpu.device import time_step_ms

            ips_o2 = B / (time_step_ms(lambda: step2(x, y), inner=iters) / 1e3)
            if ips_o2 > images_per_sec:
                images_per_sec, amp_level = ips_o2, "O2"
        except Exception as e:  # O2 arm is additive: never sinks the bench
            print(f"bench_resnet: O2 arm failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
    else:
        images_per_sec = measure(B, iters)

    # vs_baseline: peak-normalized chip-efficiency parity against the
    # written-down A100 reference point (BASELINE.md "A100 reference
    # points"): ResNet-50 AMP 1xA100 = 2,900 img/s.
    # vs_baseline = (ours/our_peak) / (2900/A100_peak).
    from paddle_tpu.device.peaks import A100_PEAK_TFLOPS, device_peak_tflops

    d = jax.devices()[0]
    peak = device_peak_tflops(d.device_kind, d.platform)
    vs_baseline = (images_per_sec / peak) / (2900.0 / A100_PEAK_TFLOPS) if peak else 0.0
    print(json.dumps({
        "metric": "resnet_train_images_per_sec",
        "value": round(images_per_sec, 2),
        "unit": "images/s",
        "vs_baseline": round(vs_baseline, 4),
        "batch": B,
        "amp": amp_level,
    }))


if __name__ == "__main__":
    sys.exit(main())
