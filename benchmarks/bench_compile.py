"""Benchmark: time-to-first-step and steps/sec for a deep LLaMA config,
scan-over-layers (nn.LayerStack) on vs off, plus persistent-cache warm start.

What it measures (the costs ISSUE 2's tentpole attacks):

- **ttfs**: time-to-first-step = build TrainStep + run step 0 (trace + XLA
  compile + execute).  With the unrolled loop this grows linearly with
  depth (N copies of the block in the HLO); with fuse_layer_stack the
  program is one lax.scan body — O(1) in depth.  Headline value =
  ttfs_unrolled / ttfs_scan (target >= 3x for >= 12 layers).
- **steps/sec**: compiled steady-state rate, scan vs unrolled (same fused
  executable quality is the goal; scan must not cost steady-state).
- **loss parity**: the first 5 training losses of both modes must agree
  within tolerance — the speedup must not change the optimization.
- **warm start**: two child PROCESSES (real restarts) point
  FLAGS_compilation_cache_dir at one directory and TrainStep.warmup() the
  same step; the second must serve its XLA compiles from disk — reports
  cold vs warm warmup wall time, XLA compile seconds, and hit/miss counts.

Prints ONE JSON line shaped like bench.py: {"metric", "value", "unit",
"vs_baseline", ...}; value = the ttfs speedup, vs_baseline divides by the
3.0x acceptance target.  CPU-runnable and tunnel-independent (forces
JAX_PLATFORMS=cpu).  Smoke mode (--smoke / PADDLE_TPU_BENCH_SMOKE=1)
shrinks width/steps but keeps >= 12 layers so depth still dominates.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    smoke = "--smoke" in sys.argv or bool(os.environ.get("PADDLE_TPU_BENCH_SMOKE"))

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu import jit, profiler
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    if smoke:
        layers, hidden, inter, heads, seq, batch = 12, 32, 64, 2, 16, 2
        steps, timed_steps = 5, 5
    else:
        layers, hidden, inter, heads, seq, batch = 16, 128, 256, 4, 64, 4
        steps, timed_steps = 5, 20

    vocab = 256

    def build(fuse):
        paddle.seed(0)
        cfg = llama_tiny(
            num_hidden_layers=layers, hidden_size=hidden,
            intermediate_size=inter, num_attention_heads=heads,
            num_key_value_heads=heads, vocab_size=vocab,
            max_position_embeddings=max(seq, 32), dtype="float32",
            fuse_layer_stack=fuse)
        m = LlamaForCausalLM(cfg)
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        return jit.TrainStep(m, o, lambda mm, x, y: mm(x, y)[0])

    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.integers(0, vocab, (batch, seq)).astype(np.int32))
    y = paddle.to_tensor(rng.integers(0, vocab, (batch, seq)).astype(np.int32))

    def measure(fuse):
        from paddle_tpu._core import random as rng_mod

        rng_mod.seed(0)
        profiler.compile_stats(reset=True)
        step = build(fuse)
        t0 = time.perf_counter()
        losses = [float(step(x, y)._value)]          # step 0: trace+compile+run
        ttfs = time.perf_counter() - t0
        losses += [float(step(x, y)._value) for _ in range(steps - 1)]
        t0 = time.perf_counter()
        for _ in range(timed_steps):
            step(x, y)
        rate = timed_steps / (time.perf_counter() - t0)
        stats = profiler.compile_stats()
        return {
            "ttfs_s": round(ttfs, 3),
            "steps_per_sec": round(rate, 2),
            "losses": [round(l, 6) for l in losses],
            "trace_s": round(stats["trace_seconds"], 3),
            "compile_s": round(stats["compile_seconds"], 3),
        }

    unrolled = measure(False)
    scan = measure(True)

    loss_match = bool(np.allclose(unrolled["losses"], scan["losses"],
                                  rtol=5e-4, atol=1e-5))
    ttfs_speedup = unrolled["ttfs_s"] / scan["ttfs_s"]
    tracecompile_speedup = (
        (unrolled["trace_s"] + unrolled["compile_s"])
        / max(scan["trace_s"] + scan["compile_s"], 1e-9))

    # ---- warm start: persistent compilation cache across real restarts ---
    import subprocess

    cache_dir = tempfile.mkdtemp(prefix="bench_compile_cache_")
    child = f"""
import json, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu import jit, profiler
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

paddle.seed(0)
cfg = llama_tiny(num_hidden_layers={layers}, hidden_size={hidden},
                 intermediate_size={inter}, num_attention_heads={heads},
                 num_key_value_heads={heads}, vocab_size={vocab},
                 max_position_embeddings={max(seq, 32)}, dtype="float32",
                 fuse_layer_stack=True)
m = LlamaForCausalLM(cfg)
o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
step = jit.TrainStep(m, o, lambda mm, x, y: mm(x, y)[0])
rng = np.random.default_rng(0)
x = paddle.to_tensor(rng.integers(0, {vocab}, ({batch}, {seq})).astype(np.int32))
y = paddle.to_tensor(rng.integers(0, {vocab}, ({batch}, {seq})).astype(np.int32))
t0 = time.perf_counter(); step.warmup(x, y); dt = time.perf_counter() - t0
s = profiler.compile_stats()
print(json.dumps({{"warmup_s": round(dt, 3), "compile_s": round(s["compile_seconds"], 3),
                   "hits": s["persistent_cache_hits"], "misses": s["persistent_cache_misses"]}}))
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               FLAGS_compilation_cache_dir=cache_dir)

    def restart():
        r = subprocess.run([sys.executable, "-c", child], env=env,
                           capture_output=True, text=True, timeout=900)
        line = next((ln for ln in reversed(r.stdout.splitlines())
                     if ln.startswith("{")), None)
        if r.returncode != 0 or line is None:
            return {"error": (r.stderr or r.stdout)[-400:]}
        return json.loads(line)

    cold, warmed = restart(), restart()
    warm = {"cold": cold, "warm": warmed}
    if "error" not in cold and "error" not in warmed:
        warm["compile_speedup"] = round(
            cold["compile_s"] / max(warmed["compile_s"], 1e-9), 2)

    print(
        json.dumps(
            {
                "metric": "scan_layers_ttfs_speedup",
                "value": round(ttfs_speedup, 3),
                "unit": "x",
                "vs_baseline": round(ttfs_speedup / 3.0, 4),  # target >= 3x
                "trace_compile_speedup": round(tracecompile_speedup, 3),
                "loss_trajectories_match": loss_match,
                "detail": {"unrolled": unrolled, "scan": scan,
                           "warm_start": warm},
                "config": ("smoke_" if smoke else "")
                          + f"llama_L{layers}_d{hidden}_B{batch}xS{seq}",
            }
        ),
        flush=True,
    )
    return 0 if loss_match else 4


if __name__ == "__main__":
    sys.exit(main())
