"""Multi-tenant LoRA serving: mixed-adapter batched decode vs per-adapter
serial serving (docs/LORA.md; ROADMAP item 4).

The claim measured: with adapter A/B matrices stacked on a slot axis and
gathered per batch row INSIDE the jitted decode step, requests using
DIFFERENT adapters (plus base-model requests at slot 0) share one
macro-step — so a mixed-tenant workload decodes at batched throughput
instead of paying one engine drain per adapter.

- **batched**: ONE engine, every tenant's requests resident together;
  each dispatch advances all of them.
- **serial**: the same requests grouped by adapter and drained one group
  at a time on an engine of the SAME max_batch capacity — the shape a
  pack-less server is forced into (swap weights, serve one tenant's
  traffic, swap again).  Engine capacity is the provisioned constant;
  without cross-tenant batching most of each macro-step's lanes ride
  masked, so the serial side pays the same per-dispatch cost for a
  fraction of the tokens.

Both sides are warmed (compile excluded — the contrast is steady-state
serving), greedy streams must match bit-for-bit across the two shapes,
and the reported value is batched/serial tokens-per-second.

Prints ONE JSON line like the other benches.  vs_baseline is 0.0 until a
reference point is recorded.  `--smoke` / PADDLE_TPU_BENCH_SMOKE shrinks
sizes for CI (tests/test_bench_lora.py).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mk_adapter(model, cfg_kw, key_seed, rank, alpha):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    from paddle_tpu.nn.lora import apply_lora, lora_state_dict

    ft = LlamaForCausalLM(llama_tiny(**cfg_kw))
    ft.set_state_dict(model.state_dict())
    ft.eval()
    apply_lora(ft, rank=rank, alpha=alpha)
    key = jax.random.PRNGKey(key_seed)
    for name, p in ft.named_parameters():
        if name.endswith(("lora_A", "lora_B")):
            key, sk = jax.random.split(key)
            scale = 0.1 if name.endswith("lora_B") else 0.05
            p._bind(jax.random.normal(sk, p._value.shape,
                                      jnp.float32) * scale)
    return lora_state_dict(ft)


def _drain(eng):
    while eng.has_work():
        eng.step()


def _serve(eng, requests, max_new):
    """Admit `requests` ({rid: (prompt, adapter)}), drain, return streams
    and emitted-token count."""
    for rid, (prompt, adapter) in requests.items():
        eng.add_request(rid, prompt, max_new_tokens=max_new, adapter=adapter)
    _drain(eng)
    out = {rid: eng.result(rid) for rid in requests}
    return out, sum(len(v) for v in out.values())


def main():
    import jax

    if os.environ.get("PADDLE_TPU_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    smoke = os.environ.get("PADDLE_TPU_BENCH_SMOKE") or "--smoke" in sys.argv
    on_accel = jax.devices()[0].platform != "cpu"

    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import GenerationEngine

    paddle.seed(0)
    if on_accel:
        cfg_kw = dict(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=16,
                      max_position_embeddings=4096, dtype="bfloat16")
        n_adapters, per_tenant, max_new, rank = 4, 2, 64, 8
    elif smoke:
        cfg_kw = dict(vocab_size=256, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=256,
                      dtype="float32")
        n_adapters, per_tenant, max_new, rank = 3, 1, 8, 4
    else:
        # CPU proxy: thin model so the measured contrast is the
        # per-dispatch overhead batching amortizes (the TPU-relevant
        # quantity), not raw matmul width
        cfg_kw = dict(vocab_size=256, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=256,
                      dtype="float32")
        # decode-heavy workload: the contrast under measure is macro-step
        # lane occupancy, and prefill (identical on both sides) dilutes it
        n_adapters, per_tenant, max_new, rank = 3, 2, 128, 4
    model = LlamaForCausalLM(llama_tiny(**cfg_kw))
    model.eval()

    adapters = {f"t{i}": _mk_adapter(model, cfg_kw, 10 + i, rank, 2 * rank)
                for i in range(n_adapters)}
    rng = np.random.default_rng(0)
    V = cfg_kw["vocab_size"]
    groups = {name: {} for name in [*adapters, "base"]}
    for name, reqs in groups.items():
        for j in range(per_tenant):
            prompt = rng.integers(1, V, 8 + 2 * j).tolist()
            reqs[f"{name}.{j}"] = (prompt,
                                   None if name == "base" else name)
    all_reqs = {rid: spec for reqs in groups.values()
                for rid, spec in reqs.items()}
    n_req = len(all_reqs)
    eng_kw = dict(block_size=16, num_blocks=16 * n_req,
                  adapters={"rank": rank, "max_adapters": n_adapters})

    # every prompt length the workload uses — warmup must cover them all
    # so neither side pays first-signature prefill compiles in its timed
    # window (the eager dispatch cache is process-global: whoever runs a
    # fresh shape first would foot the bill for everyone after)
    prompt_lens = sorted({8 + 2 * j for j in range(per_tenant)})

    def build(max_batch):
        eng = GenerationEngine(model, max_batch=max_batch, **eng_kw)
        for name, sd in adapters.items():
            eng.register_adapter(name, sd, alpha=2 * rank)
        # warmup: compile the macro-step + settle the eager prefill
        # ramp so both sides time steady-state serving only
        tenants = [None, *adapters]
        warm = {}
        k = 0
        for _rep in range(2):
            for ln in prompt_lens:
                for t in tenants:
                    warm[f"w{k}"] = (rng.integers(1, V, ln).tolist(), t)
                    k += 1
        # warm at the WORKLOAD's max_new: the per-request block-table
        # geometry (pour/gather shapes) must match or the first timed
        # side pays the fresh-shape compiles for both
        _serve(eng, warm, max_new)
        return eng

    # ---- batched: every tenant in one continuous batch ------------------
    eng = build(max_batch=n_req)
    t0 = time.perf_counter()
    batched_streams, batched_tokens = _serve(eng, all_reqs, max_new)
    batched_s = time.perf_counter() - t0

    # ---- serial: one adapter group at a time (the pack-less shape) ------
    # same provisioned capacity, lanes beyond the group ride masked
    serial_eng = build(max_batch=n_req)
    serial_streams = {}
    serial_tokens = 0
    serial_s = 0.0
    for name, reqs in groups.items():
        t0 = time.perf_counter()
        out, toks = _serve(serial_eng, reqs, max_new)
        serial_s += time.perf_counter() - t0
        serial_streams.update(out)
        serial_tokens += toks

    tokens_match = all(batched_streams[r] == serial_streams[r]
                       for r in all_reqs)
    batched_tps = batched_tokens / batched_s if batched_s else 0.0
    serial_tps = serial_tokens / serial_s if serial_s else 0.0
    speedup = batched_tps / serial_tps if serial_tps else 0.0

    from paddle_tpu import profiler

    lora = profiler.lora_stats()
    print(json.dumps({
        "metric": "serving_lora_mixed_batch_speedup",
        "unit": "x",
        "value": round(speedup, 3),
        "vs_baseline": 0.0,
        "tokens_match": tokens_match,
        "detail": {
            "adapters": n_adapters,
            "requests": n_req,
            "rank": rank,
            "max_new_tokens": max_new,
            "batched_tokens_per_sec": round(batched_tps, 2),
            "serial_tokens_per_sec": round(serial_tps, 2),
            "batched_wall_s": round(batched_s, 4),
            "serial_wall_s": round(serial_s, 4),
            "lora_stats": {k: lora[k] for k in
                           ("swaps", "gather_dispatches", "slots_total")},
            "device": str(jax.devices()[0].device_kind),
            "smoke": bool(smoke),
        },
    }))
    return 0 if tokens_match else 1


if __name__ == "__main__":
    sys.exit(main())
