"""Microbenchmark: eager op-dispatch throughput, dispatch cache on vs off.

Measures the hot path this framework actually spends Python time in — the
`apply` funnel (_core/autograd.py) — with FLAGS_eager_op_jit on and off:

- **train**: steps/sec for a small MLP train loop (forward + backward +
  SGD).  With the cache off every op call pays a fresh jax.vjp trace; with
  it on the traced forward+pullback pair is reused — this is the headline
  "repeated-call op throughput" number.
- **grad_ops**: raw differentiable op calls/sec (matmul+tanh chain under
  grad recording, no backward walk) — isolates per-op dispatch cost.
- **fwd_ops**: no-grad composite op calls/sec (softmax chain).  On CPU this
  is roughly break-even (eager jax already serves single primitives from
  its C++ cache; a 1-2 primitive op intentionally stays eager — see
  _core/dispatch._prefers_eager); on a real accelerator the fused single
  dispatch wins.

Prints ONE JSON line shaped like bench.py: {"metric", "value", "unit",
"vs_baseline", ...}.  value is the train-loop speedup (cache on / off);
vs_baseline divides by the 2.0x target, so >= 1.0 means the fast path
delivers.  CPU-runnable and tunnel-independent: the benchmark forces
JAX_PLATFORMS=cpu semantics itself.

Smoke mode (--smoke or PADDLE_TPU_BENCH_SMOKE=1): tiny sizes and iteration
counts so CI can assert the harness emits valid JSON in seconds.  Numerics
parity cache-on vs cache-off is asserted in both modes before timing.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    smoke = "--smoke" in sys.argv or bool(os.environ.get("PADDLE_TPU_BENCH_SMOKE"))

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as opt
    from paddle_tpu import profiler

    if smoke:
        B, D, H, iters, warmup = 2, 8, 16, 5, 2
    else:
        B, D, H, iters, warmup = 16, 64, 128, 200, 10

    rng = np.random.default_rng(0)
    x_np = rng.standard_normal((B, D)).astype(np.float32)
    y_np = rng.standard_normal((B, 1)).astype(np.float32)
    w_np = rng.standard_normal((D, D)).astype(np.float32)

    def build_model():
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(D, H), nn.Tanh(), nn.Linear(H, 1))
        o = opt.SGD(learning_rate=0.01, parameters=m.parameters())
        return m, o

    def train_loop(n, collect=False):
        m, o = build_model()
        x, y = paddle.to_tensor(x_np), paddle.to_tensor(y_np)
        losses = []
        for _ in range(n):
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            o.step()
            o.clear_grad()
            if collect:
                losses.append(float(np.asarray(loss._value)))
        return losses

    def grad_ops_loop(n):
        x = paddle.to_tensor(x_np)
        w = paddle.to_tensor(w_np, stop_gradient=False)
        out = None
        for _ in range(n):
            out = paddle.tanh(paddle.matmul(x, w))
        return np.asarray(out._value)

    def fwd_ops_loop(n):
        x = paddle.to_tensor(x_np)
        w = paddle.to_tensor(w_np)
        out = None
        for _ in range(n):
            out = F.softmax(paddle.matmul(x, w), axis=-1)
        return np.asarray(out._value)

    def timed_rate(fn, n):
        fn(warmup)
        t0 = time.perf_counter()
        fn(n)
        return n / (time.perf_counter() - t0)

    # ---- numerics parity gate: cache on must be bit-identical to off
    paddle.set_flags({"FLAGS_eager_op_jit": True})
    on_losses = train_loop(3, collect=True)
    on_g, on_f = grad_ops_loop(3), fwd_ops_loop(3)
    paddle.set_flags({"FLAGS_eager_op_jit": False})
    off_losses = train_loop(3, collect=True)
    off_g, off_f = grad_ops_loop(3), fwd_ops_loop(3)
    numerics_ok = (on_losses == off_losses
                   and np.array_equal(on_g, off_g)
                   and np.array_equal(on_f, off_f))

    # ---- throughput, cache on then off
    results = {}
    for label, fn in (("train", train_loop), ("grad_ops", grad_ops_loop),
                      ("fwd_ops", fwd_ops_loop)):
        paddle.set_flags({"FLAGS_eager_op_jit": True})
        profiler.reset_dispatch_cache()
        on_rate = timed_rate(fn, iters)
        stats = profiler.dispatch_cache_stats()
        paddle.set_flags({"FLAGS_eager_op_jit": False})
        off_rate = timed_rate(fn, iters)
        results[label] = {
            "on_per_sec": round(on_rate, 1),
            "off_per_sec": round(off_rate, 1),
            "speedup": round(on_rate / off_rate, 3),
            "cache_hits": stats["hits"],
            "cache_misses": stats["misses"],
            "cache_traces": stats["traces"],
        }
    paddle.set_flags({"FLAGS_eager_op_jit": True})

    speedup = results["train"]["speedup"]
    print(
        json.dumps(
            {
                "metric": "eager_dispatch_cached_train_speedup",
                "value": speedup,
                "unit": "x",
                "vs_baseline": round(speedup / 2.0, 4),  # target: >= 2x
                "numerics_identical": bool(numerics_ok),
                "detail": results,
                "config": "smoke" if smoke else f"mlp_{D}x{H}_B{B}_it{iters}",
            }
        ),
        flush=True,
    )
    return 0 if numerics_ok else 4


if __name__ == "__main__":
    sys.exit(main())
