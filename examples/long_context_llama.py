"""Long-context LLaMA with context parallelism (ring attention over 'sep').

The sequence is sharded over the 'sep' mesh axis: each rank holds a
contiguous chunk, rope tables are sliced at the rank's global offset, and
K/V shards rotate around the ring over ICI — O(S_local) attention memory
per chip instead of O(S).

Virtual 4-device mesh:  python examples/long_context_llama.py
On a real pod slice drop the jax_platforms override.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"

import numpy as np


def main():
    import jax

    # force the CPU backend unless explicitly asked for TPU: probing the
    # default backend would INITIALIZE it first (and hang on a dead tunnel)
    if "--tpu" not in sys.argv:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu._core.tensor import Tensor
    from paddle_tpu.distributed.communication import collective_axis_scope
    from paddle_tpu.models.llama import (
        LlamaForCausalLM,
        context_parallel_llama,
        llama_tiny,
    )

    paddle.seed(0)
    W = 4  # sep degree
    cfg = llama_tiny(max_position_embeddings=4096, dtype="float32")
    model = context_parallel_llama(LlamaForCausalLM(cfg), mode="ring")
    model.eval()
    state = list(model.state_dict().values())

    B, S = 1, 2048  # global sequence; each rank sees S/W = 512 tokens
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (B, S)).astype(np.int32)

    mesh = Mesh(np.array(jax.devices()[:W]), ("sep",))

    def body(ids_local, *vals):
        originals = [t._value for t in state]
        try:
            for t, v in zip(state, vals):
                t._bind(v)
            with paddle.no_grad(), collective_axis_scope({"sep": "sep"}):
                return model(Tensor(ids_local))._value
        finally:
            for t, v in zip(state, originals):
                t._bind(v)

    f = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(None, "sep"),) + tuple(P() for _ in state),
        out_specs=P(None, "sep", None), check_vma=False,
    ))
    logits = f(jnp.asarray(ids), *[t._value for t in state])
    print(f"context-parallel logits: {logits.shape} over {W} sequence shards "
          f"({S // W} tokens/chip), finite={bool(jnp.isfinite(logits).all())}")


if __name__ == "__main__":
    main()
