"""BERT sequence-classification finetune with the WordPiece tokenizer,
AMP, and async checkpointing."""

import os
import sys

if "--cpu" in sys.argv:  # hermetic smoke without the TPU tunnel
    sys.argv.remove("--cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as paddle
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import BertForSequenceClassification, bert_tiny
from paddle_tpu.text import BertTokenizer


def main():
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]"] + [f"tok{i}" for i in range(60)]
    tok = BertTokenizer(vocab)
    texts = [f"tok{i} tok{(i * 3) % 60} tok{(i * 7) % 60}" for i in range(32)]
    labels = np.asarray([i % 2 for i in range(32)], np.int32)
    enc = tok(texts, max_length=16)

    paddle.seed(0)
    model = BertForSequenceClassification(bert_tiny(vocab_size=len(vocab)), num_classes=2)
    opt = paddle.optimizer.AdamW(5e-4, parameters=model.parameters())
    step = TrainStep(model, opt, lambda m, i, t, am, y: m(i, token_type_ids=t, attention_mask=am, labels=y)[0])

    ids = paddle.to_tensor(enc["input_ids"])
    tty = paddle.to_tensor(enc["token_type_ids"])
    am = paddle.to_tensor(enc["attention_mask"])
    y = paddle.to_tensor(labels)
    for epoch in range(5):
        loss = step(ids, tty, am, y)
        print(f"epoch {epoch}: loss {float(loss):.4f}")
        paddle.save({"model": dict(model.state_dict())}, "/tmp/bert_ft.pdparams", async_save=True)
    paddle.wait_async_save()
    print("checkpoint saved to /tmp/bert_ft.pdparams")


if __name__ == "__main__":
    main()
