"""LLaMA pretraining on a TPU mesh — the flagship hybrid-parallel recipe.

Single chip:            python examples/pretrain_llama.py
Virtual 8-device mesh:  python examples/pretrain_llama.py --virtual-mesh
Real pod: run one process per host under `python -m paddle_tpu.distributed.launch`.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--virtual-mesh", action="store_true", help="8 virtual CPU devices (dp2 x pp2 x mp2)")
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    if args.virtual_mesh:
        import os

        os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny, pipeline_llama, shard_llama

    paddle.seed(0)
    cfg = llama_tiny(dtype="float32")
    model = LlamaForCausalLM(cfg)

    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (8, 64)).astype(np.int32))
    labels = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (8, 64)).astype(np.int32))

    if args.virtual_mesh and jax.device_count() >= 8:
        from paddle_tpu.distributed import ProcessMesh, ShardedTrainStep

        mesh = ProcessMesh(np.arange(8).reshape(2, 2, 2), ["dp", "pp", "mp"])
        shard_llama(model, mesh, mp_axis="mp")
        pipeline_llama(model, mesh, pp_axis="pp", num_microbatches=2)
        opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters(), weight_decay=0.01)
        step = ShardedTrainStep(model, opt, lambda m, i, l: m(i, labels=l)[0], mesh)
    else:
        from paddle_tpu.jit import TrainStep

        opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters(), weight_decay=0.01)
        step = TrainStep(model, opt, lambda m, i, l: m(i, labels=l)[0])

    for s in range(args.steps):
        loss = step(ids, labels)
        print(f"step {s}: loss {float(loss.astype('float32')):.4f}")


if __name__ == "__main__":
    main()
