"""SOT bytecode capture: guards, graph breaks, replay, fallback.

python examples/sot_capture.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    # force the CPU backend unless explicitly asked for TPU: probing the
    # default backend would INITIALIZE it first (and hang on a dead tunnel)
    if "--tpu" not in sys.argv:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.jit import to_static
    from paddle_tpu.jit.sot import sot_stats

    @to_static(mode="sot")
    def policy(x, n):
        # python loop: unrolled at capture time, no graph break
        for _ in range(n):
            x = paddle.tanh(x * 1.5)
        # TENSOR predicate: graph break — the prefix segment executes,
        # the branch concretizes, capture resumes per decision path
        if x.sum() > 0:
            return x * 2.0
        return x - 1.0

    t = paddle.to_tensor(np.float32([0.5, 1.0, -0.2]))
    print("positive path:", np.asarray(policy(t, 3)._value))
    print("negative path:", np.asarray(policy(-t, 3)._value))
    print("replay (cached segments):", np.asarray(policy(t, 3)._value))
    print("stats:", sot_stats())


if __name__ == "__main__":
    main()
