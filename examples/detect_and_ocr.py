"""PP-YOLO-class detection + PP-OCR-class recognition walkthrough.

Run (CPU): python examples/detect_and_ocr.py
Shows the BASELINE.md row-4 model families end to end: a detector forward
with yolo_box decode, and a CRNN recognizer trained with CTC until its
greedy decode emits the target sequence.
"""

import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.vision.models import ctc_greedy_decode, ppocr_rec_tiny, ppyolo_tiny


def detect():
    paddle.seed(0)
    model = ppyolo_tiny(num_classes=4)
    model.eval()
    x = paddle.randn([1, 3, 64, 64])
    with paddle.no_grad():
        outs = model(x)                       # 3 FPN levels of head maps
        boxes, scores = model.decode(outs, 64)
    print(f"detector: {len(outs)} levels -> boxes {tuple(boxes.shape)}, "
          f"scores {tuple(scores.shape)}")


def recognize():
    paddle.seed(5)
    model = ppocr_rec_tiny(num_classes=6)
    opt = paddle.optimizer.AdamW(5e-3, parameters=model.parameters())
    rng = np.random.default_rng(2)
    x = paddle.to_tensor(rng.normal(size=(1, 3, 32, 48)).astype(np.float32))
    target = [2, 4, 1]
    labels = paddle.to_tensor(np.array([target], np.int64))
    lens = paddle.to_tensor(np.array([3], np.int64))

    for i in range(60):
        loss = model.loss(model(x), labels, lens)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if i % 20 == 0:
            print(f"ocr ctc loss[{i}] = {float(loss._value):.4f}")
    model.eval()
    with paddle.no_grad():
        decoded = ctc_greedy_decode(model(x))
    print(f"ocr: target {target} -> decoded {decoded[0]}")
    assert decoded[0] == target


if __name__ == "__main__":
    detect()
    recognize()
    print("ok")
