"""Serving: paged-KV incremental decode + weight-only int8 head."""

import os
import sys

if "--cpu" in sys.argv:  # hermetic smoke without the TPU tunnel
    sys.argv.remove("--cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


def main():
    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny(dtype="float32"))
    model.eval()
    prompt = paddle.to_tensor(np.random.default_rng(0).integers(0, 1024, (2, 12)).astype(np.int32))
    out = model.generate(prompt, max_new_tokens=16, cache="paged", block_size=16)
    print("generated:", np.asarray(out._value))


if __name__ == "__main__":
    main()
