"""paddle.text.datasets equivalent (reference: python/paddle/text/datasets/
— Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16).

The reference streams these corpora from a download cache; this environment
has no network, so every dataset accepts `data_file` pointing at the same
archive the reference would have downloaded and parses it identically.
Constructing one without a local file raises with the expected layout."""

from __future__ import annotations

import os
import re
import tarfile
import zlib

import numpy as np

from paddle_tpu.io import Dataset

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16"]


def _stable_id(word, dict_size, reserved=3):
    # deterministic across processes (Python's hash() is salted per run)
    return zlib.crc32(word.encode()) % (dict_size - reserved) + reserved


def _require(data_file, name, layout):
    if data_file is None or not os.path.exists(data_file):
        raise RuntimeError(
            f"{name} requires a local copy (no network in this environment): "
            f"pass data_file pointing at {layout}"
        )


class UCIHousing(Dataset):
    """reference text/datasets/uci_housing.py — 13 features + price."""

    def __init__(self, data_file=None, mode="train"):
        _require(data_file, "UCIHousing", "the raw housing.data file")
        raw = np.loadtxt(data_file)
        # normalize features (reference behavior)
        feats = raw[:, :-1]
        maxs, mins, avgs = feats.max(0), feats.min(0), feats.mean(0)
        feats = (feats - avgs) / (maxs - mins)
        n_train = int(len(raw) * 0.8)
        if mode == "train":
            self.data = feats[:n_train].astype(np.float32)
            self.label = raw[:n_train, -1:].astype(np.float32)
        else:
            self.data = feats[n_train:].astype(np.float32)
            self.label = raw[n_train:, -1:].astype(np.float32)

    def __getitem__(self, idx):
        return self.data[idx], self.label[idx]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """reference text/datasets/imdb.py — aclImdb sentiment archive."""

    def __init__(self, data_file=None, mode="train", cutoff=150):
        _require(data_file, "Imdb", "the aclImdb_v1.tar.gz archive")
        pos_pat = re.compile(rf"aclImdb/{mode}/pos/.*\.txt$")
        neg_pat = re.compile(rf"aclImdb/{mode}/neg/.*\.txt$")
        self.word_idx = self._build_vocab(data_file, mode, cutoff)
        self.docs, self.labels = [], []
        for pattern, label in ((pos_pat, 0), (neg_pat, 1)):
            for doc in self._tokenize(data_file, pattern):
                self.docs.append(
                    np.asarray([self.word_idx.get(w, self.word_idx["<unk>"]) for w in doc], np.int64)
                )
                self.labels.append(np.asarray(label, np.int64))

    @staticmethod
    def _tokenize(data_file, pattern):
        with tarfile.open(data_file) as tarf:
            for member in tarf.getmembers():
                if pattern.match(member.name):
                    data = tarf.extractfile(member).read().decode("latin-1").lower()
                    yield data.replace("<br />", " ").split()

    def _build_vocab(self, data_file, mode, cutoff):
        from collections import Counter

        counter = Counter()
        pattern = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        for doc in self._tokenize(data_file, pattern):
            counter.update(doc)
        words = [w for w, c in counter.most_common() if c > cutoff]
        word_idx = {w: i for i, w in enumerate(words)}
        word_idx["<unk>"] = len(words)
        return word_idx

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """reference text/datasets/imikolov.py — PTB n-gram dataset."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5, mode="train", min_word_freq=50):
        _require(data_file, "Imikolov", "the simple-examples.tgz PTB archive")
        self.window_size = window_size
        self.data_type = data_type
        path = f"./simple-examples/data/ptb.{'train' if mode == 'train' else 'valid'}.txt"
        self.word_idx = self._build_vocab(data_file, min_word_freq)
        self.data = []
        with tarfile.open(data_file) as tarf:
            f = tarf.extractfile(path)
            for line in f.read().decode().splitlines():
                words = ["<s>"] + line.strip().split() + ["<e>"]
                ids = [self.word_idx.get(w, self.word_idx["<unk>"]) for w in words]
                if data_type.upper() == "NGRAM":
                    for i in range(window_size, len(ids)):
                        self.data.append(np.asarray(ids[i - window_size : i + 1], np.int64))
                else:
                    self.data.append(np.asarray(ids, np.int64))

    def _build_vocab(self, data_file, min_word_freq):
        from collections import Counter

        counter = Counter()
        with tarfile.open(data_file) as tarf:
            f = tarf.extractfile("./simple-examples/data/ptb.train.txt")
            for line in f.read().decode().splitlines():
                counter.update(line.strip().split())
        counter.pop("<unk>", None)
        words = sorted(
            [(w, c) for w, c in counter.items() if c >= min_word_freq],
            key=lambda x: (-x[1], x[0]),
        )
        word_idx = {w: i for i, (w, _) in enumerate(words)}
        word_idx["<unk>"] = len(word_idx)
        word_idx["<s>"] = len(word_idx)
        word_idx["<e>"] = len(word_idx)
        return word_idx

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """reference text/datasets/movielens.py — ml-1m ratings."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1, rand_seed=0):
        _require(data_file, "Movielens", "the ml-1m.zip archive")
        import zipfile

        rng = np.random.default_rng(rand_seed)
        with zipfile.ZipFile(data_file) as z:
            ratings = z.read("ml-1m/ratings.dat").decode("latin-1").splitlines()
        self.rows = []
        for line in ratings:
            uid, mid, rating, _ = line.split("::")
            is_test = rng.random() < test_ratio
            if (mode == "test") == is_test:
                self.rows.append(
                    (np.asarray(int(uid), np.int64), np.asarray(int(mid), np.int64),
                     np.asarray(float(rating), np.float32))
                )

    def __getitem__(self, idx):
        return self.rows[idx]

    def __len__(self):
        return len(self.rows)


class Conll05st(Dataset):
    """reference text/datasets/conll05.py — SRL dataset (test split only is
    public, as in the reference)."""

    def __init__(self, data_file=None, **kwargs):
        _require(data_file, "Conll05st", "the conll05st-tests.tar.gz archive")
        raise NotImplementedError(
            "Conll05st parsing requires the companion word/verb/target dict "
            "files; provide them via kwargs as in the reference"
        )


class WMT14(Dataset):
    """reference text/datasets/wmt14.py — en-fr translation pairs."""

    def __init__(self, data_file=None, mode="train", dict_size=30000):
        _require(data_file, "WMT14", "the wmt14 train/test/gen tgz archive")
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        start, end, unk = 0, 1, 2
        with tarfile.open(data_file) as tarf:
            # reference layout: {train,test,gen}/* parallel files
            names = [n for n in tarf.getnames() if re.search(rf"(^|/){mode}(/|$)", n)]
            for name in names:
                member = tarf.extractfile(name)
                if member is None:
                    continue
                for line in member.read().decode("latin-1").splitlines():
                    parts = line.split("\t")
                    if len(parts) != 2:
                        continue
                    src = [_stable_id(w, dict_size) for w in parts[0].split()]
                    trg = [_stable_id(w, dict_size) for w in parts[1].split()]
                    self.src_ids.append(np.asarray(src, np.int64))
                    self.trg_ids.append(np.asarray([start] + trg, np.int64))
                    self.trg_ids_next.append(np.asarray(trg + [end], np.int64))

    def __getitem__(self, idx):
        return self.src_ids[idx], self.trg_ids[idx], self.trg_ids_next[idx]

    def __len__(self):
        return len(self.src_ids)


class WMT16(WMT14):
    """reference text/datasets/wmt16.py — en-de with BPE vocab; same access
    pattern as WMT14 here."""
