"""Tokenizers for the text pipeline.

Reference: the strings tensor ops + faster_tokenizer integration
(paddle/phi/kernels/strings/, python/paddle/incubate's faster tokenizer
wrapping a C++ WordPiece) feeding BERT/ERNIE pipelines.

TPU-native scope: tokenization is host-side data preparation (strings never
reach the device), so a string TENSOR type adds nothing on TPU — the
capability is the tokenizer itself producing int32 id arrays for the input
pipeline.  BasicTokenizer + WordPieceTokenizer implement the BERT algorithm
(lowercase/punct split, greedy longest-match-first subwords with ##
continuation), and BertTokenizer packages them with padding/truncation into
DataLoader-ready numpy batches.
"""

from __future__ import annotations

import unicodedata

import numpy as np

__all__ = ["BasicTokenizer", "WordPieceTokenizer", "BertTokenizer"]


def _is_punct(ch):
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


class BasicTokenizer:
    def __init__(self, do_lower_case=True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text):
        if self.do_lower_case:
            text = text.lower()
            text = "".join(
                c for c in unicodedata.normalize("NFD", text)
                if unicodedata.category(c) != "Mn"
            )
        out = []
        word = []
        for ch in text:
            if ch.isspace():
                if word:
                    out.append("".join(word))
                    word = []
            elif _is_punct(ch):
                if word:
                    out.append("".join(word))
                    word = []
                out.append(ch)
            else:
                word.append(ch)
        if word:
            out.append("".join(word))
        return out


class WordPieceTokenizer:
    def __init__(self, vocab, unk_token="[UNK]", max_input_chars_per_word=100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_chars = max_input_chars_per_word

    def tokenize(self, word):
        if len(word) > self.max_chars:
            return [self.unk_token]
        tokens = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = sub
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            tokens.append(cur)
            start = end
        return tokens


class BertTokenizer:
    """End-to-end text -> padded int32 id batches (BERT convention:
    [CLS] tokens [SEP], token_type, attention mask)."""

    def __init__(self, vocab, do_lower_case=True, unk_token="[UNK]",
                 cls_token="[CLS]", sep_token="[SEP]", pad_token="[PAD]"):
        if isinstance(vocab, (list, tuple)):
            vocab = {t: i for i, t in enumerate(vocab)}
        self.vocab = dict(vocab)
        self.inv_vocab = {i: t for t, i in self.vocab.items()}
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordPieceTokenizer(self.vocab, unk_token)
        self.cls_token, self.sep_token, self.pad_token, self.unk_token = (
            cls_token, sep_token, pad_token, unk_token,
        )

    @property
    def vocab_size(self):
        return len(self.vocab)

    def tokenize(self, text):
        out = []
        for word in self.basic.tokenize(text):
            out.extend(self.wordpiece.tokenize(word))
        return out

    def convert_tokens_to_ids(self, tokens):
        unk = self.vocab[self.unk_token]
        return [self.vocab.get(t, unk) for t in tokens]

    def convert_ids_to_tokens(self, ids):
        return [self.inv_vocab.get(int(i), self.unk_token) for i in ids]

    def __call__(self, texts, text_pairs=None, max_length=None, padding=True,
                 truncation=True, return_attention_mask=True):
        single = isinstance(texts, str)
        texts = [texts] if single else list(texts)
        pairs = [text_pairs] if isinstance(text_pairs, str) else (list(text_pairs) if text_pairs else None)
        encoded, type_ids = [], []
        for i, t in enumerate(texts):
            toks = [self.cls_token] + self.tokenize(t) + [self.sep_token]
            types = [0] * len(toks)
            if pairs is not None:
                ptoks = self.tokenize(pairs[i]) + [self.sep_token]
                toks += ptoks
                types += [1] * len(ptoks)
            if truncation and max_length and len(toks) > max_length:
                toks, types = toks[:max_length], types[:max_length]
            encoded.append(self.convert_tokens_to_ids(toks))
            type_ids.append(types)
        width = max_length if (padding and max_length) else max(len(e) for e in encoded)
        pad_id = self.vocab[self.pad_token]
        n = len(encoded)
        ids = np.full((n, width), pad_id, np.int32)
        tty = np.zeros((n, width), np.int32)
        mask = np.zeros((n, width), np.int32)
        for i, (e, ty) in enumerate(zip(encoded, type_ids)):
            ids[i, : len(e)] = e
            tty[i, : len(ty)] = ty
            mask[i, : len(e)] = 1
        out = {"input_ids": ids, "token_type_ids": tty}
        if return_attention_mask:
            out["attention_mask"] = mask
        return out
