"""paddle.text equivalent (reference: python/paddle/text/__init__.py —
viterbi_decode/ViterbiDecoder + 7 datasets).

TPU-first: the Viterbi forward recursion runs as a lax.scan over time with
batched max/argmax (one compiled kernel, no per-step Python), and the
backtrace is a second scan over the stored argmax pointers."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu._core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer

from . import datasets  # noqa: F401
from .datasets import (  # noqa: F401
    Conll05st,
    Imdb,
    Imikolov,
    Movielens,
    UCIHousing,
    WMT14,
    WMT16,
)

__all__ = [
    "viterbi_decode", "ViterbiDecoder",
    "Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16",
]


def viterbi_decode(potentials, transition_params, lengths, include_bos_eos_tag=True, name=None):
    """CRF Viterbi decoding (reference python/paddle/text/viterbi_decode.py:25).

    potentials: [B, T, N] emissions; transition_params: [N, N];
    lengths: [B].  Returns (scores [B], paths [B, max_len])."""
    pot = potentials._value if isinstance(potentials, Tensor) else jnp.asarray(potentials)
    trans = (
        transition_params._value
        if isinstance(transition_params, Tensor)
        else jnp.asarray(transition_params)
    )
    lens = lengths._value if isinstance(lengths, Tensor) else jnp.asarray(lengths)
    b, t, n = pot.shape

    if include_bos_eos_tag:
        # last tag = BOS, second-to-last = EOS (reference semantics)
        bos, eos = n - 1, n - 2
        init = pot[:, 0] + trans[bos][None, :]
    else:
        init = pot[:, 0]

    def step(carry, inputs):
        alpha, step_i = carry
        emit = inputs  # [B, N]
        # scores[b, i, j] = alpha[b, i] + trans[i, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)  # [B, N]
        new_alpha = jnp.max(scores, axis=1) + emit
        # sequences shorter than step_i keep their alpha frozen
        active = (step_i < lens)[:, None]
        new_alpha = jnp.where(active, new_alpha, alpha)
        return (new_alpha, step_i + 1), best_prev

    (alpha, _), pointers = jax.lax.scan(
        step, (init, jnp.ones((), jnp.int32)), jnp.swapaxes(pot[:, 1:], 0, 1)
    )
    # pointers: [T-1, B, N]
    if include_bos_eos_tag:
        alpha = alpha + trans[:, eos][None, :]

    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1)  # [B]

    # backtrace: walk pointers from each sequence's end
    def back(carry, ptr_t):
        tag, step_i = carry
        # ptr_t: [B, N]; step_i counts down from t-1
        prev = jnp.take_along_axis(ptr_t, tag[:, None], axis=1)[:, 0]
        active = (step_i < lens)  # pointer at step_i maps tag at step_i to step_i-1
        new_tag = jnp.where(active, prev, tag)
        return (new_tag, step_i - 1), new_tag

    (first_tag, _), rev_tags = jax.lax.scan(
        back, (last_tag, jnp.asarray(t - 1, jnp.int32)), pointers, reverse=True
    )
    # rev_tags[k] is the tag at time k (scan in reverse emits per input row)
    paths = jnp.concatenate([rev_tags, last_tag[None]], axis=0)  # [T, B]
    paths = jnp.swapaxes(paths, 0, 1)  # [B, T]
    # positions beyond each length are padded with 0
    mask = jnp.arange(t)[None, :] < lens[:, None]
    paths = jnp.where(mask, paths, 0)
    return Tensor(scores), Tensor(paths)


class ViterbiDecoder(Layer):
    """reference python/paddle/text/viterbi_decode.py:100"""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths, self.include_bos_eos_tag)

from .tokenizer import BasicTokenizer, BertTokenizer, WordPieceTokenizer  # noqa: F401,E402
