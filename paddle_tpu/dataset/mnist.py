"""Reference: python/paddle/dataset/mnist.py — readers over the IDX files
yielding (normalized flat float32[784] image, int label)."""

from __future__ import annotations

import numpy as np

__all__ = ["train", "test"]


def _reader(mode, image_path, label_path):
    def reader():
        from paddle_tpu.vision.datasets import MNIST

        ds = MNIST(image_path=image_path, label_path=label_path, mode=mode)
        for i in range(len(ds)):
            img, label = ds[i]
            arr = np.asarray(img, np.float32).reshape(-1)
            # reference normalization: [0, 255] -> [-1, 1]
            yield arr / 127.5 - 1.0, int(np.asarray(label).reshape(()))

    return reader


def train(image_path=None, label_path=None):
    return _reader("train", image_path, label_path)


def test(image_path=None, label_path=None):
    return _reader("test", image_path, label_path)
