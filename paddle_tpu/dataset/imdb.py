"""Reference: python/paddle/dataset/imdb.py — readers yielding
(word-id list, 0/1 label) plus word_dict()."""

from __future__ import annotations

import re

__all__ = ["train", "test", "word_dict"]


def _reader(mode, word_idx, data_file, cutoff):
    def reader():
        from paddle_tpu.text.datasets import Imdb

        if word_idx is None:
            ds = Imdb(data_file=data_file, mode=mode, cutoff=cutoff)
            for i in range(len(ds)):
                doc, label = ds[i]
                yield [int(w) for w in doc], int(label)
            return
        # reference semantics: docs are encoded with the CALLER's dict —
        # ids must index an embedding sized to it, not a rebuilt vocab
        if data_file is None:
            raise ValueError("imdb reader needs data_file (the "
                             "aclImdb_v1.tar.gz archive)")
        unk = word_idx.get("<unk>", len(word_idx))
        for pat, label in ((re.compile(rf"aclImdb/{mode}/pos/.*\.txt$"), 0),
                           (re.compile(rf"aclImdb/{mode}/neg/.*\.txt$"), 1)):
            for doc in Imdb._tokenize(data_file, pat):
                yield [word_idx.get(w, unk) for w in doc], label

    return reader


def train(word_idx=None, data_file=None, cutoff=150):
    return _reader("train", word_idx, data_file, cutoff)


def test(word_idx=None, data_file=None, cutoff=150):
    return _reader("test", word_idx, data_file, cutoff)


def word_dict(data_file=None, cutoff=150):
    from paddle_tpu.text.datasets import Imdb

    return Imdb(data_file=data_file, mode="train", cutoff=cutoff).word_idx
