"""Reference: python/paddle/dataset/imdb.py — readers yielding
(word-id list, 0/1 label) plus word_dict()."""

from __future__ import annotations

__all__ = ["train", "test", "word_dict"]


def _reader(mode, data_file, cutoff):
    def reader():
        from paddle_tpu.text.datasets import Imdb

        ds = Imdb(data_file=data_file, mode=mode, cutoff=cutoff)
        for i in range(len(ds)):
            doc, label = ds[i]
            yield [int(w) for w in doc], int(label)

    return reader


def train(word_idx=None, data_file=None, cutoff=150):
    return _reader("train", data_file, cutoff)


def test(word_idx=None, data_file=None, cutoff=150):
    return _reader("test", data_file, cutoff)


def word_dict(data_file=None, cutoff=150):
    from paddle_tpu.text.datasets import Imdb

    return Imdb(data_file=data_file, mode="train", cutoff=cutoff).word_idx
