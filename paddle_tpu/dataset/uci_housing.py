"""Reference: python/paddle/dataset/uci_housing.py — readers yielding
(feature float32[13], target float32[1])."""

from __future__ import annotations

import numpy as np

__all__ = ["train", "test"]


def _reader(mode, data_file):
    def reader():
        from paddle_tpu.text.datasets import UCIHousing

        ds = UCIHousing(data_file=data_file, mode=mode)
        for i in range(len(ds)):
            x, y = ds[i]
            yield np.asarray(x, np.float32), np.asarray(y, np.float32)

    return reader


def train(data_file=None):
    return _reader("train", data_file)


def test(data_file=None):
    return _reader("test", data_file)
