"""Reference: python/paddle/dataset/common.py (download/md5 helpers)."""

from __future__ import annotations

import hashlib
import os

__all__ = ["DATA_HOME", "download", "md5file"]

DATA_HOME = os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                         "dataset")


def md5file(fname: str) -> str:
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url: str, module_name: str, md5sum: str | None = None,
             save_name: str | None = None) -> str:
    """Return the cached path if the file is already present; otherwise
    raise — this environment has no network egress.  Drop the file into
    DATA_HOME/<module_name>/ yourself (reference layout)."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(dirname,
                            save_name or url.split("/")[-1].split("?")[0])
    if os.path.exists(filename):
        if md5sum and md5file(filename) != md5sum:
            raise RuntimeError(f"{filename} exists but fails its md5 check")
        return filename
    raise RuntimeError(
        f"dataset download needs network access (wanted {url}); this "
        f"environment has none. Place the file at {filename} and retry")
