"""Legacy reader-style dataset loaders (reference: python/paddle/dataset/
— mnist.train() etc. return sample-yielding reader callables).

TPU-native: these adapt the class-based datasets (paddle_tpu.vision.datasets,
paddle_tpu.text.datasets, which parse reference-layout local files) into
the reader protocol.  `common.download` raises in hermetic environments
instead of hanging — pass local paths to the loaders.
"""

from . import cifar, common, imdb, mnist, uci_housing  # noqa: F401

__all__ = ["mnist", "cifar", "imdb", "uci_housing", "common"]
