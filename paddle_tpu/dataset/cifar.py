"""Reference: python/paddle/dataset/cifar.py — readers yielding
(flat float32[3072] image scaled to [0, 1], int label)."""

from __future__ import annotations

import numpy as np

__all__ = ["train10", "test10", "train100", "test100"]


def _reader(cls_name, mode, data_file):
    def reader():
        import paddle_tpu.vision.datasets as vd

        ds = getattr(vd, cls_name)(data_file=data_file, mode=mode)
        for i in range(len(ds)):
            img, label = ds[i]
            arr = np.asarray(img, np.float32).reshape(-1) / 255.0
            yield arr, int(np.asarray(label).reshape(()))

    return reader


def train10(data_file=None):
    return _reader("Cifar10", "train", data_file)


def test10(data_file=None):
    return _reader("Cifar10", "test", data_file)


def train100(data_file=None):
    return _reader("Cifar100", "train", data_file)


def test100(data_file=None):
    return _reader("Cifar100", "test", data_file)
