"""sparse.nn layers (reference: python/paddle/sparse/nn/__init__.py — 11
layer exports over layer/activation.py, layer/norm.py, layer/conv.py,
layer/pooling.py)."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu._core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer

from . import functional  # noqa: F401
from . import functional as F

__all__ = [
    "ReLU", "ReLU6", "LeakyReLU", "Softmax",
    "BatchNorm", "SyncBatchNorm",
    "Conv2D", "Conv3D", "SubmConv2D", "SubmConv3D",
    "MaxPool3D",
]


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class BatchNorm(Layer):
    """Batch norm over sparse values (reference sparse/nn/layer/norm.py:
    BatchNorm normalizes the channel axis of stored values)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC", name=None):
        super().__init__()
        from paddle_tpu.nn.initializer import Constant

        self._momentum = momentum
        self._epsilon = epsilon
        self.weight = self.create_parameter([num_features], default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_features], default_initializer=Constant(0.0))
        self._mean = jnp.zeros(num_features)
        self._variance = jnp.ones(num_features)

    def forward(self, x):
        import paddle_tpu.sparse as sp

        vals = x._values
        if self.training:
            mean = jnp.mean(vals, axis=0)
            var = jnp.var(vals, axis=0)
            self._mean = self._momentum * self._mean + (1 - self._momentum) * mean
            self._variance = self._momentum * self._variance + (1 - self._momentum) * var
        else:
            mean, var = self._mean, self._variance
        w = self.weight._value
        b = self.bias._value
        out = (vals - mean) / jnp.sqrt(var + self._epsilon) * w + b
        if isinstance(x, sp.SparseCsrTensor):
            return sp.SparseCsrTensor(x._crows, x._cols, out, x._shape)
        return sp.SparseCooTensor(x._indices, out, x._shape, x._coalesced)


class SyncBatchNorm(BatchNorm):
    """Cross-replica batch norm: identical math; under pjit/shard_map the
    mean/var reductions become XLA collectives automatically (no manual
    NCCL sync as in reference sparse/nn/layer/norm.py SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, BatchNorm) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer.weight.shape[0], layer._momentum, layer._epsilon)
            new.weight = layer.weight
            new.bias = layer.bias
            return new
        for name, sub in getattr(layer, "_sub_layers", {}).items():
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride, padding,
                 dilation, groups, subm, nd, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None):
        super().__init__()
        from paddle_tpu.nn.initializer import XavierUniform

        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * nd
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._subm = subm
        self._nd = nd
        wshape = tuple(kernel_size) + (in_channels // groups, out_channels)
        self.weight = self.create_parameter(list(wshape), default_initializer=XavierUniform())
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], is_bias=True
        )

    def forward(self, x):
        fn = {
            (2, False): F.conv2d,
            (2, True): F.subm_conv2d,
            (3, False): F.conv3d,
            (3, True): F.subm_conv3d,
        }[(self._nd, self._subm)]
        return fn(x, self.weight, self.bias, self._stride, self._padding,
                  self._dilation, self._groups)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, False, 3, padding_mode, weight_attr, bias_attr)


class SubmConv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", key=None, weight_attr=None,
                 bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, True, 3, padding_mode, weight_attr, bias_attr)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, False, 2, padding_mode, weight_attr, bias_attr)


class SubmConv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", key=None, weight_attr=None,
                 bias_attr=None, data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, True, 2, padding_mode, weight_attr, bias_attr)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NDHWC", name=None):
        super().__init__()
        self._kernel_size = kernel_size
        self._stride = stride
        self._padding = padding

    def forward(self, x):
        return F.max_pool3d(x, self._kernel_size, self._stride, self._padding)
