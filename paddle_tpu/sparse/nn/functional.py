"""sparse.nn.functional (reference: python/paddle/sparse/nn/functional/ —
activation.py, conv.py, pooling.py, transformer.py).

TPU mapping: activations act on values; sparse 3D/2D convolution densifies
the sparse voxel grid and runs one XLA conv (the MXU path — for the
moderate densities these layers see on TPU, a dense conv beats gather-
scatter kernel emulation), then re-sparsifies; submanifold variants sample
the dense output at the input's active sites, preserving the pattern the
way the reference's subm kernels do."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu._core.tensor import Tensor


def _sp():
    import paddle_tpu.sparse as sp

    return sp


def relu(x, name=None):
    return _value_act(x, jax.nn.relu)


def relu6(x, name=None):
    return _value_act(x, lambda v: jnp.clip(v, 0, 6))


def leaky_relu(x, negative_slope=0.01, name=None):
    return _value_act(x, lambda v: jnp.where(v >= 0, v, negative_slope * v))


def _value_act(x, fn):
    sp = _sp()
    if isinstance(x, sp.SparseCooTensor):
        return sp.SparseCooTensor(x._indices, fn(x._values), x._shape, x._coalesced)
    if isinstance(x, sp.SparseCsrTensor):
        return sp.SparseCsrTensor(x._crows, x._cols, fn(x._values), x._shape)
    return Tensor(fn(x._value))


def softmax(x, axis=-1, name=None):
    """Row-wise softmax over stored entries (reference
    sparse/nn/functional/activation.py softmax: only the last axis, treating
    absent entries as -inf)."""
    sp = _sp()
    if axis not in (-1, None) and axis != len(x.shape) - 1:
        raise ValueError("sparse softmax supports the last axis only")
    if isinstance(x, sp.SparseCsrTensor):
        coo = x.to_sparse_coo()
        out = softmax(coo, axis)
        return sp.SparseCsrTensor(x._crows, x._cols, out._values, x._shape)
    rows = x._indices[0]
    n_rows = x._shape[0]
    if x.sparse_dim != 2:
        # flatten leading sparse dims into row keys (row-major)
        rows = jnp.zeros_like(x._indices[0])
        mult = 1
        for i in reversed(range(x.sparse_dim - 1)):
            rows = rows + x._indices[i] * mult
            mult *= x._shape[i]
        n_rows = mult
    row_max = jax.ops.segment_max(x._values, rows, num_segments=n_rows)
    shifted = x._values - row_max[rows]
    ex = jnp.exp(shifted)
    denom = jax.ops.segment_sum(ex, rows, num_segments=n_rows)
    return sp.SparseCooTensor(x._indices, ex / denom[rows], x._shape, x._coalesced)


def attention(query, key, value, sparse_mask, key_padding_mask=None, attn_mask=None, name=None):
    """Sparse-pattern attention (reference
    sparse/nn/functional/transformer.py attention): scores computed only at
    sparse_mask's positions via SDDMM, softmax over stored entries, then
    SpMM with value."""
    sp = _sp()
    q = query._value if isinstance(query, Tensor) else jnp.asarray(query)
    k = key._value if isinstance(key, Tensor) else jnp.asarray(key)
    v = value._value if isinstance(value, Tensor) else jnp.asarray(value)
    b, h, seq, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, q.dtype))
    coo = sparse_mask.to_sparse_coo() if isinstance(sparse_mask, sp.SparseCsrTensor) else sparse_mask
    # batched: mask pattern shared across (b, h)
    rows, cols = coo._indices[-2], coo._indices[-1]
    # SDDMM: compute scores only at stored positions — O(nnz·d), never the
    # dense n×n QK^T
    sampled = jnp.sum(q[:, :, rows, :] * k[:, :, cols, :], axis=-1) * scale
    if key_padding_mask is not None:
        kpm = key_padding_mask._value if isinstance(key_padding_mask, Tensor) else jnp.asarray(key_padding_mask)
        sampled = sampled + kpm[:, None, cols]
    if attn_mask is not None:
        am = attn_mask._value if isinstance(attn_mask, Tensor) else jnp.asarray(attn_mask)
        sampled = sampled + am[rows, cols]
    row_max = jax.ops.segment_max(sampled.reshape(b * h, -1).T, rows, num_segments=seq)
    ex = jnp.exp(sampled.reshape(b * h, -1).T - row_max[rows])
    denom = jax.ops.segment_sum(ex, rows, num_segments=seq)
    probs = (ex / denom[rows]).T.reshape(b, h, -1)
    gathered = probs[..., None] * v[:, :, cols]
    out = jax.vmap(jax.vmap(lambda g: jax.ops.segment_sum(g, rows, num_segments=seq)))(gathered)
    return Tensor(out)


def _conv_dense(x, weight, bias, stride, padding, dilation, groups, nd, subm):
    """Shared dense-path sparse conv: densify → lax.conv_general_dilated →
    (subm: sample at input sites | conv: re-sparsify nonzeros)."""
    sp = _sp()
    dense = x.to_dense()._value  # [N, *spatial, C]
    w = weight._value if isinstance(weight, Tensor) else jnp.asarray(weight)
    # paddle sparse conv weight layout: [*kernel, C_in/groups, C_out]
    kdims = w.shape[:nd]
    cin, cout = w.shape[nd], w.shape[nd + 1]
    if isinstance(stride, int):
        stride = (stride,) * nd
    if isinstance(dilation, int):
        dilation = (dilation,) * nd
    if isinstance(padding, int):
        padding = (padding,) * nd
    pad = [(p, p) for p in padding]
    spec_in = "N" + "DHW"[-nd:] + "C"
    spec_w = "DHW"[-nd:] + "IO"
    spec_out = "N" + "DHW"[-nd:] + "C"
    out = jax.lax.conv_general_dilated(
        dense, w,
        window_strides=stride,
        padding=pad,
        rhs_dilation=dilation,
        dimension_numbers=(spec_in, spec_w, spec_out),
        feature_group_count=groups,
    )
    if bias is not None:
        bv = bias._value if isinstance(bias, Tensor) else jnp.asarray(bias)
        out = out + bv
    if subm:
        # sample at the input's active sites (pattern-preserving)
        sd = x.sparse_dim
        idx = tuple(x._indices[i] for i in range(sd))
        vals = out[idx]
        return sp.SparseCooTensor(x._indices, vals, out.shape, x._coalesced)
    return Tensor(out).to_sparse_coo(nd + 1)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NDHWC", name=None):
    """reference sparse/nn/functional/conv.py conv3d."""
    return _conv_dense(x, weight, bias, stride, padding, dilation, groups, 3, False)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NDHWC", key=None, name=None):
    return _conv_dense(x, weight, bias, stride, padding, dilation, groups, 3, True)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NHWC", name=None):
    return _conv_dense(x, weight, bias, stride, padding, dilation, groups, 2, False)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NHWC", key=None, name=None):
    return _conv_dense(x, weight, bias, stride, padding, dilation, groups, 2, True)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NDHWC", name=None):
    """reference sparse/nn/functional/pooling.py max_pool3d.  Pools over
    ACTIVE sites only, like the reference's sparse kernel: empty sites are
    scattered as -inf so they never win the max, and windows containing no
    active site stay empty in the output."""
    import numpy as np

    sp = _sp()
    sd = x.sparse_dim
    neg = jnp.full(x._shape, -jnp.inf, x._values.dtype)
    idx = tuple(x._indices[i] for i in range(sd))
    dense = neg.at[idx].max(x._values)
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size,) * 3
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride,) * 3
    if isinstance(padding, int):
        padding = (padding,) * 3
    dims = (1,) + tuple(kernel_size) + (1,)
    strides = (1,) + tuple(stride) + (1,)
    pads = ((0, 0),) + tuple((p, p) for p in padding) + ((0, 0),)
    out = jax.lax.reduce_window(dense, -jnp.inf, jax.lax.max, dims, strides, pads)
    # re-sparsify: a site is active if any channel is finite (can't use
    # to_sparse_coo — it would drop legitimate zero values)
    active = np.asarray(jnp.any(jnp.isfinite(out), axis=-1))
    new_idx = np.stack(np.nonzero(active)).astype(np.int64)
    vals = jnp.where(jnp.isfinite(out), out, 0)[tuple(new_idx)]
    return sp.SparseCooTensor(jnp.asarray(new_idx), vals, out.shape, True)
