"""paddle.sparse equivalent (reference: python/paddle/sparse/__init__.py —
35 exports; COO/CSR tensor types in paddle/phi/core/sparse_coo_tensor.h,
sparse kernels in paddle/phi/kernels/sparse/).

TPU-first design: COO is (indices [sparse_dim, nnz], values [nnz, *dense]),
CSR is (crows, cols, values) — all plain jnp arrays, so every op here is
traceable/differentiable through values.  Compute maps to XLA-friendly
primitives: scatter for densify, segment_sum for reductions and SpMM rows,
gather for elementwise; there is deliberately NO CUDA-style sparse kernel
emulation — on TPU the fast path for moderate density IS a dense op over a
scattered buffer, and ops document when they take it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu._core.dtype import to_jax_dtype
from paddle_tpu._core.tensor import Tensor

from . import nn  # noqa: F401

__all__ = [
    "sparse_coo_tensor",
    "sparse_csr_tensor",
    "sin", "tan", "asin", "atan", "sinh", "tanh", "asinh", "atanh",
    "sqrt", "square", "log1p", "abs", "pow", "cast", "neg", "deg2rad",
    "rad2deg", "expm1", "isnan",
    "coalesce", "transpose", "sum", "reshape", "slice",
    "mv", "matmul", "masked_matmul", "addmm",
    "add", "subtract", "multiply", "divide", "is_same_shape",
    "pca_lowrank",
    "SparseCooTensor", "SparseCsrTensor",
]


def _v(x):
    if isinstance(x, Tensor):
        return x._value
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        raise TypeError("expected dense input")
    return jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor (reference paddle/phi/core/sparse_coo_tensor.h:37)."""

    is_sparse_coo = True
    is_sparse_csr = False

    def __init__(self, indices, values, shape, coalesced=False):
        self._indices = jnp.asarray(indices, jnp.int32)
        self._values = values if isinstance(values, jnp.ndarray) else jnp.asarray(values)
        self._shape = tuple(int(s) for s in shape)
        self._coalesced = bool(coalesced)

    # paddle Tensor-like surface ------------------------------------------
    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def ndim(self):
        return len(self._shape)

    def nnz(self):
        return int(self._indices.shape[1])

    def indices(self):
        return Tensor(self._indices)

    def values(self):
        return Tensor(self._values)

    @property
    def sparse_dim(self):
        return int(self._indices.shape[0])

    @property
    def dense_dim(self):
        return self.ndim - self.sparse_dim

    def to_dense(self):
        sd = self.sparse_dim
        idx = tuple(self._indices[i] for i in range(sd))
        vals = self._values
        if vals.dtype == jnp.bool_:  # scatter-add has no bool variant
            dense = jnp.zeros(self._shape, jnp.int8).at[idx].add(vals.astype(jnp.int8))
            return Tensor(dense.astype(jnp.bool_))
        dense = jnp.zeros(self._shape, vals.dtype)
        return Tensor(dense.at[idx].add(vals))

    def to_sparse_csr(self):
        if self.sparse_dim != 2 or self.dense_dim != 0:
            raise ValueError("to_sparse_csr requires a 2-D COO matrix")
        c = coalesce(self)
        rows, cols = c._indices[0], c._indices[1]
        m = self._shape[0]
        crows = jnp.zeros(m + 1, jnp.int32).at[rows + 1].add(1)
        crows = jnp.cumsum(crows)
        return SparseCsrTensor(crows, cols, c._values, self._shape)

    def numpy(self):
        return np.asarray(self.to_dense()._value)

    def __repr__(self):
        return (
            f"SparseCooTensor(shape={self._shape}, nnz={self.nnz()}, "
            f"dtype={self._values.dtype})"
        )


class SparseCsrTensor:
    """CSR sparse matrix (reference paddle/phi/core/sparse_csr_tensor.h:30)."""

    is_sparse_coo = False
    is_sparse_csr = True

    def __init__(self, crows, cols, values, shape):
        self._crows = jnp.asarray(crows, jnp.int32)
        self._cols = jnp.asarray(cols, jnp.int32)
        self._values = values if isinstance(values, jnp.ndarray) else jnp.asarray(values)
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def ndim(self):
        return len(self._shape)

    def nnz(self):
        return int(self._cols.shape[0])

    def crows(self):
        return Tensor(self._crows)

    def cols(self):
        return Tensor(self._cols)

    def values(self):
        return Tensor(self._values)

    def _row_indices(self):
        # expand crows → per-nnz row ids: row[i] = #{j : crows[j+1] <= i}
        nnz = self._cols.shape[0]
        pos = jnp.arange(nnz)
        return jnp.searchsorted(self._crows[1:], pos, side="right")

    def to_sparse_coo(self, sparse_dim=2):
        rows = self._row_indices()
        return SparseCooTensor(jnp.stack([rows, self._cols]), self._values, self._shape, True)

    def to_dense(self):
        return self.to_sparse_coo().to_dense()

    def numpy(self):
        return np.asarray(self.to_dense()._value)

    def __repr__(self):
        return (
            f"SparseCsrTensor(shape={self._shape}, nnz={self.nnz()}, "
            f"dtype={self._values.dtype})"
        )


# creation -----------------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None, stop_gradient=True):
    """reference python/paddle/sparse/creation.py:60"""
    idx = _v(indices).astype(jnp.int32)
    vals = _v(values)
    if dtype is not None:
        vals = vals.astype(to_jax_dtype(dtype))
    if shape is None:
        sparse_max = jnp.max(idx, axis=1) + 1
        shape = tuple(int(s) for s in np.asarray(sparse_max)) + vals.shape[1:]
    return coalesce(SparseCooTensor(idx, vals, shape))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None, stop_gradient=True):
    """reference python/paddle/sparse/creation.py:150"""
    vals = _v(values)
    if dtype is not None:
        vals = vals.astype(to_jax_dtype(dtype))
    return SparseCsrTensor(_v(crows), _v(cols), vals, shape)


def _dense_to_coo(x, sparse_dim):
    xv = _v(x)
    lead = xv.shape[:sparse_dim]
    flat = xv.reshape(lead + (-1,)) if xv.ndim > sparse_dim else xv
    mask = np.asarray(jnp.any(flat != 0, axis=-1) if xv.ndim > sparse_dim else (xv != 0))
    idx = np.stack(np.nonzero(mask)).astype(np.int64)
    vals = np.asarray(xv)[tuple(idx)]
    return SparseCooTensor(jnp.asarray(idx), jnp.asarray(vals), xv.shape, True)


def _dense_to_csr(x):
    return _dense_to_coo(x, 2).to_sparse_csr()


# patch dense Tensor with conversion methods (reference
# tensor_patch_methods.py:1157)
Tensor.to_sparse_coo = lambda self, sparse_dim: _dense_to_coo(self, sparse_dim)
Tensor.to_sparse_csr = lambda self: _dense_to_csr(self)


# unary --------------------------------------------------------------------

def _unary(fn, zero_preserving=True):
    def op(x, *args, **kwargs):
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(x._indices, fn(x._values, *args), x._shape, x._coalesced)
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(x._crows, x._cols, fn(x._values, *args), x._shape)
        return Tensor(fn(_v(x), *args))

    return op


sin = _unary(jnp.sin)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
tanh = _unary(jnp.tanh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
abs = _unary(jnp.abs)  # noqa: A001
neg = _unary(jnp.negative)
expm1 = _unary(jnp.expm1)
deg2rad = _unary(jnp.deg2rad)
rad2deg = _unary(jnp.rad2deg)
isnan = _unary(jnp.isnan)


def pow(x, factor):  # noqa: A001
    return _unary(lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None):
    vd = to_jax_dtype(value_dtype) if value_dtype else None
    if isinstance(x, SparseCooTensor):
        idx = x._indices.astype(to_jax_dtype(index_dtype)) if index_dtype else x._indices
        return SparseCooTensor(idx, x._values.astype(vd) if vd else x._values, x._shape, x._coalesced)
    idx_d = to_jax_dtype(index_dtype) if index_dtype else None
    return SparseCsrTensor(
        x._crows.astype(idx_d) if idx_d else x._crows,
        x._cols.astype(idx_d) if idx_d else x._cols,
        x._values.astype(vd) if vd else x._values,
        x._shape,
    )


# structural ---------------------------------------------------------------

def _flat_keys_np(indices, strides, nd):
    """Row-major flat keys on the HOST in int64.

    Sparse structural ops are host-resident (indices drive data-dependent
    shapes, so they cannot be traced anyway); int64 host math avoids the
    silent int32 overflow a >2^31-element sparse shape would hit under the
    framework's device-side no-64-bit policy (_core/dtype.py).
    """
    idx = np.asarray(indices).astype(np.int64)
    keys = np.zeros(idx.shape[1], np.int64)
    for i in range(nd):
        keys += idx[i] * np.int64(strides[i])
    return keys


def _unflatten_np(keys, strides, nd):
    idx = []
    rem = np.asarray(keys, np.int64)
    for i in range(nd):
        idx.append(rem // np.int64(strides[i]))
        rem = rem % np.int64(strides[i])
    return np.stack(idx)


def coalesce(x):
    """Sort indices and merge duplicates (reference sparse/unary.py coalesce)."""
    if isinstance(x, SparseCsrTensor):
        return x
    if x._coalesced:
        return x
    sd = x.sparse_dim
    strides = np.cumprod([1] + list(x._shape[:sd][::-1]))[::-1][1:]  # row-major keys
    keys = _flat_keys_np(x._indices, strides, sd)
    order = np.argsort(keys, kind="stable")
    uniq, inv = np.unique(keys[order], return_inverse=True)
    vals_s = x._values[jnp.asarray(order)]
    merged = jax.ops.segment_sum(vals_s, jnp.asarray(inv, jnp.int32), num_segments=len(uniq))
    return SparseCooTensor(jnp.asarray(_unflatten_np(uniq, strides, sd), jnp.int32), merged, x._shape, True)


def transpose(x, perm):
    """reference sparse/unary.py transpose — permutes sparse dims."""
    if isinstance(x, SparseCsrTensor):
        return transpose(x.to_sparse_coo(), perm).to_sparse_csr()
    sd = x.sparse_dim
    if sorted(perm[:sd]) != list(range(sd)):
        raise ValueError("transpose across sparse/dense boundary unsupported")
    new_idx = jnp.stack([x._indices[p] for p in perm[:sd]])
    dense_perm = [p - sd for p in perm[sd:]]
    vals = jnp.transpose(x._values, [0] + [d + 1 for d in dense_perm]) if x.dense_dim else x._values
    new_shape = tuple(x._shape[p] for p in perm)
    return coalesce(SparseCooTensor(new_idx, vals, new_shape))


def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    """reference sparse/unary.py sum."""
    if isinstance(x, SparseCsrTensor):
        d = jnp.sum(x._values)
        if axis is None:
            return Tensor(d)
        return sum(x.to_sparse_coo(), axis, dtype, keepdim)
    if axis is None:
        out = jnp.sum(x._values)
        return Tensor(out.astype(to_jax_dtype(dtype)) if dtype else out)
    ax = axis if axis >= 0 else axis + x.ndim
    sd = x.sparse_dim
    if ax >= sd:
        vals = jnp.sum(x._values, axis=ax - sd + 1, keepdims=keepdim)
        shape = list(x._shape)
        if keepdim:
            shape[ax] = 1
        else:
            shape.pop(ax)
        return SparseCooTensor(x._indices, vals, shape, x._coalesced)
    keep = [i for i in range(sd) if i != ax]
    new_idx = x._indices[jnp.asarray(keep)] if keep else jnp.zeros((1, x._indices.shape[1]), x._indices.dtype)
    shape = list(x._shape)
    if keepdim:
        shape[ax] = 1
        new_idx = jnp.insert(new_idx, ax, jnp.zeros_like(x._indices[0]), axis=0)
    else:
        shape.pop(ax)
        if not keep:
            shape = [1] + shape if not shape[:0] else shape
    return coalesce(SparseCooTensor(new_idx, x._values, shape))


def reshape(x, shape):
    """reference sparse/unary.py reshape — re-linearize sparse indices."""
    if isinstance(x, SparseCsrTensor):
        return reshape(x.to_sparse_coo(), shape).to_sparse_csr()
    if x.dense_dim:
        raise ValueError("reshape with dense dims unsupported")
    old_shape = x._shape
    total = int(np.prod(old_shape))
    shape = list(shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = total // known
    strides_old = np.cumprod([1] + list(old_shape[::-1]))[::-1][1:]
    flat = _flat_keys_np(x._indices, strides_old, len(old_shape))
    strides_new = np.cumprod([1] + list(shape[::-1]))[::-1][1:]
    idx = _unflatten_np(flat, strides_new, len(shape))
    return SparseCooTensor(jnp.asarray(idx, jnp.int32), x._values, tuple(shape), x._coalesced)


def slice(x, axes, starts, ends):  # noqa: A001
    """reference sparse/unary.py slice (COO, sparse dims only)."""
    if isinstance(x, SparseCsrTensor):
        return slice(x.to_sparse_coo(), axes, starts, ends).to_sparse_csr()
    shape = list(x._shape)
    mask = jnp.ones(x._indices.shape[1], bool)
    shifts = {}
    for ax, st, en in zip(axes, starts, ends):
        ax = ax if ax >= 0 else ax + x.ndim
        st = max(st + shape[ax], 0) if st < 0 else min(st, shape[ax])
        en = max(en + shape[ax], 0) if en < 0 else min(en, shape[ax])
        mask = mask & (x._indices[ax] >= st) & (x._indices[ax] < en)
        shifts[ax] = st
        shape[ax] = en - st
    keep = np.asarray(mask)
    idx = np.asarray(x._indices)[:, keep]
    for ax, st in shifts.items():
        idx[ax] -= st
    vals = x._values[jnp.asarray(np.nonzero(keep)[0])]
    return SparseCooTensor(jnp.asarray(idx), vals, shape, x._coalesced)


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


# binary -------------------------------------------------------------------

def _coo_binary(x, y, fn):
    x, y = coalesce(x), coalesce(y)
    if x._shape != y._shape:
        raise ValueError("shape mismatch")
    # union of patterns via merged keys
    sd = x.sparse_dim
    strides = np.cumprod([1] + list(x._shape[:sd][::-1]))[::-1][1:]
    kx = _flat_keys_np(x._indices, strides, sd)
    ky = _flat_keys_np(y._indices, strides, sd)
    uniq = np.unique(np.concatenate([kx, ky]))
    pos_x = np.searchsorted(uniq, kx)
    pos_y = np.searchsorted(uniq, ky)
    dense_shape = x._values.shape[1:]
    vx = jnp.zeros((len(uniq),) + dense_shape, x._values.dtype).at[jnp.asarray(pos_x)].set(x._values)
    vy = jnp.zeros((len(uniq),) + dense_shape, y._values.dtype).at[jnp.asarray(pos_y)].set(y._values)
    out = fn(vx, vy)
    return SparseCooTensor(jnp.asarray(_unflatten_np(uniq, strides, sd), jnp.int32), out, x._shape, True)


def _binary(x, y, fn):
    if isinstance(x, SparseCsrTensor) and isinstance(y, SparseCsrTensor):
        return _coo_binary(x.to_sparse_coo(), y.to_sparse_coo(), fn).to_sparse_csr()
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        return _coo_binary(x, y, fn)
    raise TypeError("sparse binary ops need two sparse tensors of the same format")


def add(x, y, name=None):
    return _binary(x, y, jnp.add)


def subtract(x, y, name=None):
    return _binary(x, y, jnp.subtract)


def multiply(x, y, name=None):
    return _binary(x, y, jnp.multiply)


def divide(x, y, name=None):
    return _binary(x, y, jnp.divide)


# matmul family ------------------------------------------------------------

def _coo_spmm(x, dense):
    """SpMM rows = segment_sum(vals · dense[cols]) — XLA-friendly SpMM."""
    rows, cols = x._indices[0], x._indices[1]
    gathered = x._values[:, None] * dense[cols]
    return jax.ops.segment_sum(gathered, rows, num_segments=x._shape[0])


def matmul(x, y, name=None):
    """reference sparse/binary.py matmul: sparse @ dense (COO/CSR 2D)."""
    if isinstance(x, SparseCsrTensor):
        return matmul(x.to_sparse_coo(), y, name)
    yv = _v(y)
    if isinstance(x, SparseCooTensor):
        if x.ndim != 2:
            raise ValueError("matmul supports 2-D sparse")
        return Tensor(_coo_spmm(coalesce(x), yv))
    raise TypeError("matmul: x must be sparse")


def mv(x, vec, name=None):
    out = matmul(x, _v(vec)[:, None])
    return Tensor(out._value[:, 0])


def masked_matmul(x, y, mask, name=None):
    """dense @ dense sampled at mask's pattern (SDDMM, reference
    sparse/binary.py masked_matmul)."""
    xv, yv = _v(x), _v(y)
    if isinstance(mask, SparseCsrTensor):
        coo = mask.to_sparse_coo()
        rows, cols = coo._indices[0], coo._indices[1]
        vals = jnp.sum(xv[rows] * yv[:, cols].T, axis=-1)
        return SparseCsrTensor(mask._crows, mask._cols, vals, mask._shape)
    rows, cols = mask._indices[0], mask._indices[1]
    vals = jnp.sum(xv[rows] * yv[:, cols].T, axis=-1)
    return SparseCooTensor(mask._indices, vals, mask._shape, mask._coalesced)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    """beta·input + alpha·(x @ y) (reference sparse/multiary.py:21)."""
    prod = matmul(x, y)
    return Tensor(beta * _v(input) + alpha * prod._value)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA on a sparse matrix via SpMM power iterations
    (reference sparse/unary.py pca_lowrank)."""
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    m, n = x._shape
    q = q or min(6, m, n)
    key = jax.random.key(0)
    xv = x.to_dense()._value
    if center:
        xv = xv - jnp.mean(xv, axis=0, keepdims=True)
    g = jax.random.normal(key, (n, q), xv.dtype)
    y = xv @ g
    for _ in range(niter):
        y = xv @ (xv.T @ y)
    qmat, _ = jnp.linalg.qr(y)
    b = qmat.T @ xv
    u, s, vt = jnp.linalg.svd(b, full_matrices=False)
    return Tensor(qmat @ u), Tensor(s), Tensor(vt.T)
