"""Concrete optimizers (reference: python/paddle/optimizer/{sgd,momentum,adam,
adamw,adagrad,adadelta,rmsprop,lamb}.py).  Update math is pure jnp so XLA
fuses the whole update sweep into one program under jit."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu._core.tensor import Tensor
from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adam", "AdamW", "Adagrad", "Adadelta", "RMSProp", "Lamb", "NAdam", "RAdam", "Adamax", "ASGD", "Rprop"]


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _single_update(self, p, g, lr):
        return p._value - lr.astype(g.dtype) * g

    def _sparse_update(self, p, sr, lr):
        # scatter-add touches only the looked-up rows
        return p._value.at[sr.rows].add(-lr.astype(sr.values.dtype) * sr.values)


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None, use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _single_update(self, p, g, lr):
        vel = self._acc("velocity", p, dtype=g.dtype)
        new_v = self._momentum * vel._value + g
        vel._bind(new_v)
        if self._nesterov:
            return p._value - lr.astype(g.dtype) * (g + self._momentum * new_v)
        return p._value - lr.astype(g.dtype) * new_v

    def _sparse_update(self, p, sr, lr):
        rows, g = sr.rows, sr.values
        vel = self._acc("velocity", p, dtype=g.dtype)
        v_rows = self._momentum * vel._value[rows] + g
        vel._bind(vel._value.at[rows].set(v_rows))
        step = (g + self._momentum * v_rows) if self._nesterov else v_rows
        return p._value.at[rows].add(-lr.astype(g.dtype) * step)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._multi_precision = multi_precision

    def _update_moments(self, p, g):
        m = self._acc("moment1", p, dtype=jnp.float32)
        v = self._acc("moment2", p, dtype=jnp.float32)
        b1p = self._acc("beta1_pow", p, init=lambda: jnp.asarray(1.0, jnp.float32))
        b2p = self._acc("beta2_pow", p, init=lambda: jnp.asarray(1.0, jnp.float32))
        g32 = g.astype(jnp.float32)
        new_m = self._beta1 * m._value + (1 - self._beta1) * g32
        new_v = self._beta2 * v._value + (1 - self._beta2) * jnp.square(g32)
        new_b1p = b1p._value * self._beta1
        new_b2p = b2p._value * self._beta2
        m._bind(new_m)
        v._bind(new_v)
        b1p._bind(new_b1p)
        b2p._bind(new_b2p)
        m_hat = new_m / (1 - new_b1p)
        v_hat = new_v / (1 - new_b2p)
        return m_hat, v_hat

    def _single_update(self, p, g, lr):
        m_hat, v_hat = self._update_moments(p, g)
        master = p._value.astype(jnp.float32)
        new32 = master - lr * m_hat / (jnp.sqrt(v_hat) + self._eps)
        return new32

    def _update_moments_rows(self, p, rows, g32):
        """Lazy (touched-rows-only) moment update — the reference's Adam
        lazy_mode (adam_functors.h SparseAdamFunctor): untouched rows keep
        stale moments, exactly paddle's sparse semantics."""
        m = self._acc("moment1", p, dtype=jnp.float32)
        v = self._acc("moment2", p, dtype=jnp.float32)
        b1p = self._acc("beta1_pow", p, init=lambda: jnp.asarray(1.0, jnp.float32))
        b2p = self._acc("beta2_pow", p, init=lambda: jnp.asarray(1.0, jnp.float32))
        m_rows = self._beta1 * m._value[rows] + (1 - self._beta1) * g32
        v_rows = self._beta2 * v._value[rows] + (1 - self._beta2) * jnp.square(g32)
        new_b1p = b1p._value * self._beta1
        new_b2p = b2p._value * self._beta2
        m._bind(m._value.at[rows].set(m_rows))
        v._bind(v._value.at[rows].set(v_rows))
        b1p._bind(new_b1p)
        b2p._bind(new_b2p)
        m_hat = m_rows / (1 - new_b1p)
        v_hat = v_rows / (1 - new_b2p)
        return m_hat, v_hat

    def _sparse_update(self, p, sr, lr):
        rows = sr.rows
        g32 = sr.values.astype(jnp.float32)
        m_hat, v_hat = self._update_moments_rows(p, rows, g32)
        upd = lr * m_hat / (jnp.sqrt(v_hat) + self._eps)
        return p._value.astype(jnp.float32).at[rows].add(-upd)


class AdamW(Adam):
    """Decoupled weight decay (reference python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None, grad_clip=None, lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters, None, grad_clip, lazy_mode, multi_precision, name)
        from paddle_tpu.regularizer import L2Decay

        if isinstance(weight_decay, (int, float)):
            self._wd_coeff = float(weight_decay)
        elif isinstance(weight_decay, L2Decay):
            # decoupled decay IS L2-style; the coeff carries over
            self._wd_coeff = float(weight_decay.coeff)
        elif weight_decay is None:
            self._wd_coeff = 0.01
        else:
            # L1Decay etc. cannot be expressed as multiplicative decoupled
            # decay — refusing beats silently applying the wrong penalty
            raise TypeError(
                f"AdamW weight_decay must be a float or L2Decay, got "
                f"{type(weight_decay).__name__}")
        self._apply_decay_fn = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _decoupled_wd(self):
        return True

    def _single_update(self, p, g, lr):
        m_hat, v_hat = self._update_moments(p, g)
        master = p._value.astype(jnp.float32)
        decay = self._wd_coeff
        if self._apply_decay_fn is not None and not self._apply_decay_fn(p.name):
            decay = 0.0
        lr_eff = lr * (self._lr_ratio(p) if self._lr_ratio is not None else 1.0)
        master = master * (1.0 - lr_eff * decay)
        return master - lr_eff * m_hat / (jnp.sqrt(v_hat) + self._eps)

    def _sparse_update(self, p, sr, lr):
        rows = sr.rows
        g32 = sr.values.astype(jnp.float32)
        m_hat, v_hat = self._update_moments_rows(p, rows, g32)
        decay = self._wd_coeff
        if self._apply_decay_fn is not None and not self._apply_decay_fn(p.name):
            decay = 0.0
        lr_eff = lr * (self._lr_ratio(p) if self._lr_ratio is not None else 1.0)
        master = p._value.astype(jnp.float32)
        # decoupled decay on touched rows only (lazy semantics)
        row_vals = master[rows] * (1.0 - lr_eff * decay)
        row_vals = row_vals - lr_eff * m_hat / (jnp.sqrt(v_hat) + self._eps)
        return master.at[rows].set(row_vals)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None, grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _single_update(self, p, g, lr):
        acc = self._acc("moment", p, init=lambda: jnp.full(p._value.shape, self._init_acc, jnp.float32))
        new_acc = acc._value + jnp.square(g.astype(jnp.float32))
        acc._bind(new_acc)
        return p._value.astype(jnp.float32) - lr * g.astype(jnp.float32) / (jnp.sqrt(new_acc) + self._eps)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps, self._rho = epsilon, rho

    def _single_update(self, p, g, lr):
        g32 = g.astype(jnp.float32)
        avg_sq = self._acc("avg_squared_grad", p, dtype=jnp.float32)
        avg_upd = self._acc("avg_squared_update", p, dtype=jnp.float32)
        new_avg_sq = self._rho * avg_sq._value + (1 - self._rho) * jnp.square(g32)
        update = jnp.sqrt(avg_upd._value + self._eps) / jnp.sqrt(new_avg_sq + self._eps) * g32
        new_avg_upd = self._rho * avg_upd._value + (1 - self._rho) * jnp.square(update)
        avg_sq._bind(new_avg_sq)
        avg_upd._bind(new_avg_upd)
        return p._value.astype(jnp.float32) - lr * update


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._eps, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _single_update(self, p, g, lr):
        g32 = g.astype(jnp.float32)
        ms = self._acc("mean_square", p, dtype=jnp.float32)
        new_ms = self._rho * ms._value + (1 - self._rho) * jnp.square(g32)
        ms._bind(new_ms)
        if self._centered:
            mg = self._acc("mean_grad", p, dtype=jnp.float32)
            new_mg = self._rho * mg._value + (1 - self._rho) * g32
            mg._bind(new_mg)
            denom = jnp.sqrt(new_ms - jnp.square(new_mg) + self._eps)
        else:
            denom = jnp.sqrt(new_ms + self._eps)
        update = lr * g32 / denom
        if self._momentum > 0:
            mom = self._acc("momentum", p, dtype=jnp.float32)
            new_mom = self._momentum * mom._value + update
            mom._bind(new_mom)
            update = new_mom
        return p._value.astype(jnp.float32) - update


class Lamb(Optimizer):
    """Layer-wise adaptive moments (reference python/paddle/optimizer/lamb.py;
    the fused DistributedFusedLamb CUDA path is unnecessary here — XLA fuses)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _single_update(self, p, g, lr):
        g32 = g.astype(jnp.float32)
        m = self._acc("moment1", p, dtype=jnp.float32)
        v = self._acc("moment2", p, dtype=jnp.float32)
        b1p = self._acc("beta1_pow", p, init=lambda: jnp.asarray(1.0, jnp.float32))
        b2p = self._acc("beta2_pow", p, init=lambda: jnp.asarray(1.0, jnp.float32))
        new_m = self._beta1 * m._value + (1 - self._beta1) * g32
        new_v = self._beta2 * v._value + (1 - self._beta2) * jnp.square(g32)
        new_b1p, new_b2p = b1p._value * self._beta1, b2p._value * self._beta2
        m._bind(new_m), v._bind(new_v), b1p._bind(new_b1p), b2p._bind(new_b2p)
        m_hat = new_m / (1 - new_b1p)
        v_hat = new_v / (1 - new_b2p)
        master = p._value.astype(jnp.float32)
        r = m_hat / (jnp.sqrt(v_hat) + self._eps)
        wd = 0.0 if (self._exclude_fn is not None and self._exclude_fn(p)) else self._wd
        update = r + wd * master
        w_norm = jnp.linalg.norm(master)
        u_norm = jnp.linalg.norm(update)
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return master - lr * trust * update


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _single_update(self, p, g, lr):
        g32 = g.astype(jnp.float32)
        m = self._acc("moment", p, dtype=jnp.float32)
        u = self._acc("inf_norm", p, dtype=jnp.float32)
        b1p = self._acc("beta1_pow", p, init=lambda: jnp.asarray(1.0, jnp.float32))
        new_m = self._beta1 * m._value + (1 - self._beta1) * g32
        new_u = jnp.maximum(self._beta2 * u._value, jnp.abs(g32))
        new_b1p = b1p._value * self._beta1
        m._bind(new_m), u._bind(new_u), b1p._bind(new_b1p)
        return p._value.astype(jnp.float32) - lr / (1 - new_b1p) * new_m / (new_u + self._eps)


class NAdam(Adam):
    def _single_update(self, p, g, lr):
        m_hat, v_hat = self._update_moments(p, g)
        g32 = g.astype(jnp.float32)
        nesterov_m = self._beta1 * m_hat + (1 - self._beta1) * g32
        return p._value.astype(jnp.float32) - lr * nesterov_m / (jnp.sqrt(v_hat) + self._eps)


class RAdam(Adam):
    def _single_update(self, p, g, lr):
        # Rectified Adam: variance rectification term
        m_hat, v_hat = self._update_moments(p, g)
        t = self._step_count + 1
        rho_inf = 2.0 / (1 - self._beta2) - 1
        beta2_t = self._beta2**t
        rho_t = rho_inf - 2 * t * beta2_t / (1 - beta2_t)
        master = p._value.astype(jnp.float32)
        if rho_t > 4:
            r = ((rho_t - 4) * (rho_t - 2) * rho_inf / ((rho_inf - 4) * (rho_inf - 2) * rho_t)) ** 0.5
            return master - lr * r * m_hat / (jnp.sqrt(v_hat) + self._eps)
        return master - lr * m_hat


class ASGD(Optimizer):
    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _single_update(self, p, g, lr):
        return p._value.astype(jnp.float32) - lr * g.astype(jnp.float32)


class Rprop(Optimizer):
    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50), parameters=None, etas=(0.5, 1.2), grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_minus, self._eta_plus = etas

    def _single_update(self, p, g, lr):
        g32 = g.astype(jnp.float32)
        prev_g = self._acc("prev_grad", p, dtype=jnp.float32)
        step_size = self._acc("step_size", p, init=lambda: jnp.full(p._value.shape, float(lr), jnp.float32))
        sign = jnp.sign(g32 * prev_g._value)
        factor = jnp.where(sign > 0, self._eta_plus, jnp.where(sign < 0, self._eta_minus, 1.0))
        new_step = jnp.clip(step_size._value * factor, self._lr_min, self._lr_max)
        g_eff = jnp.where(sign < 0, 0.0, g32)
        step_size._bind(new_step)
        prev_g._bind(g_eff)
        return p._value.astype(jnp.float32) - jnp.sign(g_eff) * new_step
