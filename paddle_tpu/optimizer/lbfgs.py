"""LBFGS optimizer (reference capability: python/paddle/optimizer/lbfgs.py:309).

TPU-native design: LBFGS is a host-control-flow optimizer — the closure is
re-evaluated a data-dependent number of times per step, so the driver loop
stays in Python (as in the reference) while all vector math (two-loop
recursion, dot products, axpys) runs as jnp ops on the flattened parameter
vector, which XLA fuses per call.  The strong-Wolfe line search is the
standard bracket + cubic-interpolation zoom of Nocedal & Wright (Alg. 3.5/3.6),
implemented from the math.
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu._core.tensor import Tensor
from .optimizer import Optimizer

__all__ = ["LBFGS"]


def _cubic_min(a, fa, ga, b, fb, gb):
    """Minimizer of the cubic through (a, fa, ga), (b, fb, gb); falls back to
    bisection when the interpolation is ill-conditioned."""
    d1 = ga + gb - 3.0 * (fa - fb) / (a - b)
    rad = d1 * d1 - ga * gb
    if rad < 0.0:
        return (a + b) / 2.0
    d2 = rad**0.5
    if a <= b:
        x = b - (b - a) * ((gb + d2 - d1) / (gb - ga + 2.0 * d2))
    else:
        x = a - (a - b) * ((ga + d2 - d1) / (ga - gb + 2.0 * d2))
    lo, hi = min(a, b), max(a, b)
    if not (lo < x < hi):
        return (a + b) / 2.0
    return x


class LBFGS(Optimizer):
    def __init__(
        self,
        learning_rate=1.0,
        max_iter=20,
        max_eval=None,
        tolerance_grad=1e-7,
        tolerance_change=1e-9,
        history_size=100,
        line_search_fn=None,
        parameters=None,
        weight_decay=None,
        grad_clip=None,
        name=None,
    ):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self.max_iter = int(max_iter)
        self.max_eval = int(max_eval) if max_eval is not None else self.max_iter * 5 // 4
        self.tolerance_grad = float(tolerance_grad)
        self.tolerance_change = float(tolerance_change)
        self.history_size = int(history_size)
        if line_search_fn not in (None, "strong_wolfe"):
            raise RuntimeError("only 'strong_wolfe' is supported")
        self.line_search_fn = line_search_fn
        self._s_hist: list = []
        self._y_hist: list = []
        self._rho_hist: list = []
        self._prev_flat_grad = None
        self._H_diag = 1.0

    # ------------------------------------------------------------- flat view
    def _params(self):
        return [p for p in self._parameter_list if p.trainable]

    def _flat_params(self):
        return jnp.concatenate([jnp.ravel(p._value.astype(jnp.float32)) for p in self._params()])

    def _flat_grad(self):
        parts = []
        for p in self._params():
            g = p.grad._value if p.grad is not None else jnp.zeros_like(p._value)
            parts.append(jnp.ravel(g.astype(jnp.float32)))
        return jnp.concatenate(parts)

    def _assign_flat(self, flat):
        off = 0
        for p in self._params():
            n = int(p._value.size)
            p._bind(jnp.reshape(flat[off : off + n], p._value.shape).astype(p._value.dtype))
            off += n

    # ----------------------------------------------------------- direction
    def _direction(self, flat_grad):
        q = -flat_grad
        alphas = []
        for s, y, rho in zip(reversed(self._s_hist), reversed(self._y_hist), reversed(self._rho_hist)):
            a = rho * jnp.dot(s, q)
            q = q - a * y
            alphas.append(a)
        r = q * self._H_diag
        for (s, y, rho), a in zip(
            zip(self._s_hist, self._y_hist, self._rho_hist), reversed(alphas)
        ):
            b = rho * jnp.dot(y, r)
            r = r + s * (a - b)
        return r

    def _push_history(self, s, y):
        ys = float(jnp.dot(y, s))
        if ys > 1e-10:
            if len(self._s_hist) >= self.history_size:
                self._s_hist.pop(0)
                self._y_hist.pop(0)
                self._rho_hist.pop(0)
            self._s_hist.append(s)
            self._y_hist.append(y)
            self._rho_hist.append(1.0 / ys)
            self._H_diag = ys / float(jnp.dot(y, y))

    # ---------------------------------------------------------- line search
    def _clear(self):
        for p in self._params():
            p.grad = None

    def _eval(self, closure, x):
        self._assign_flat(x)
        self._clear()  # closure need not zero grads (accumulation breaks the math)
        loss = closure()
        return float(loss), self._flat_grad()

    def _strong_wolfe(self, closure, x, t, d, f0, g0, c1=1e-4, c2=0.9, max_ls=25):
        gtd0 = float(jnp.dot(g0, d))
        f_prev, t_prev, g_prev = f0, 0.0, g0
        fe = 0
        bracket = None
        for _ in range(max_ls):
            f_new, g_new = self._eval(closure, x + t * d)
            fe += 1
            gtd_new = float(jnp.dot(g_new, d))
            if f_new > f0 + c1 * t * gtd0 or (fe > 1 and f_new >= f_prev):
                bracket = (t_prev, f_prev, g_prev, t, f_new, g_new)
                break
            if abs(gtd_new) <= -c2 * gtd0:
                return t, f_new, g_new, fe
            if gtd_new >= 0:
                bracket = (t, f_new, g_new, t_prev, f_prev, g_prev)
                break
            t_prev, f_prev, g_prev = t, f_new, g_new
            t = min(t * 2.0, 1e8)
        if bracket is None:
            return t, f_new, g_new, fe
        lo_t, lo_f, lo_g, hi_t, hi_f, hi_g = bracket
        for _ in range(max_ls):
            if abs(hi_t - lo_t) * max(abs(float(jnp.max(jnp.abs(d)))), 1e-20) < self.tolerance_change:
                break
            t = _cubic_min(
                lo_t, lo_f, float(jnp.dot(lo_g, d)), hi_t, hi_f, float(jnp.dot(hi_g, d))
            )
            f_new, g_new = self._eval(closure, x + t * d)
            fe += 1
            gtd_new = float(jnp.dot(g_new, d))
            if f_new > f0 + c1 * t * gtd0 or f_new >= lo_f:
                hi_t, hi_f, hi_g = t, f_new, g_new
            else:
                if abs(gtd_new) <= -c2 * gtd0:
                    return t, f_new, g_new, fe
                if gtd_new * (hi_t - lo_t) >= 0:
                    hi_t, hi_f, hi_g = lo_t, lo_f, lo_g
                lo_t, lo_f, lo_g = t, f_new, g_new
        return lo_t, lo_f, lo_g, fe

    # ----------------------------------------------------------------- step
    def step(self, closure):
        """One LBFGS optimization step: re-evaluates `closure` (compute loss
        + backward; grads are cleared here before each eval) up to
        max_iter x line-search evals times.  Returns the final loss Tensor."""
        self._clear()
        loss = closure()
        f = float(loss)
        flat_grad = self._flat_grad()
        evals = 1
        lr = float(self._lr_t._value)
        for _ in range(self.max_iter):
            if float(jnp.max(jnp.abs(flat_grad))) <= self.tolerance_grad:
                break
            d = self._direction(flat_grad)
            x = self._flat_params()
            gtd = float(jnp.dot(flat_grad, d))
            if gtd > -1e-32:  # not a descent direction; reset history
                self._s_hist.clear(); self._y_hist.clear(); self._rho_hist.clear()
                d = -flat_grad
            t = min(1.0, 1.0 / max(float(jnp.sum(jnp.abs(flat_grad))), 1e-20)) * lr if not self._s_hist else lr
            if self.line_search_fn == "strong_wolfe":
                t, f_new, g_new, fe = self._strong_wolfe(closure, x, t, d, f, flat_grad)
                evals += fe
            else:
                f_new, g_new = self._eval(closure, x + t * d)
                evals += 1
            s = t * d
            y = g_new - flat_grad
            self._push_history(s, y)
            self._assign_flat(x + s)
            if abs(f_new - f) < self.tolerance_change or float(jnp.max(jnp.abs(s))) < self.tolerance_change:
                f, flat_grad = f_new, g_new
                break
            f, flat_grad = f_new, g_new
            if evals >= self.max_eval:
                break
        self._step_count += 1
        if self._lr_scheduler is not None:
            self._sync_lr()
        return Tensor(jnp.asarray(f, jnp.float32))

    def state_dict(self):
        sd = super().state_dict() if hasattr(Optimizer, "state_dict") else {}
        sd["lbfgs"] = {
            "s": [np_array(s) for s in self._s_hist],
            "y": [np_array(y) for y in self._y_hist],
            "rho": list(self._rho_hist),
            "H_diag": self._H_diag,
        }
        return sd

    def set_state_dict(self, state):
        super().set_state_dict(state)
        hist = state.get("lbfgs")
        if hist is None:
            return
        self._s_hist = [jnp.asarray(s) for s in hist["s"]]
        self._y_hist = [jnp.asarray(y) for y in hist["y"]]
        self._rho_hist = [float(r) for r in hist["rho"]]
        self._H_diag = hist["H_diag"]


def np_array(x):
    import numpy as np

    return np.asarray(x)
