"""LR schedulers (reference: python/paddle/optimizer/lr.py — 20+ schedules)."""

from __future__ import annotations

import math

__all__ = [
    "LRScheduler",
    "NoamDecay",
    "ExponentialDecay",
    "NaturalExpDecay",
    "InverseTimeDecay",
    "PolynomialDecay",
    "LinearWarmup",
    "PiecewiseDecay",
    "CosineAnnealingDecay",
    "CosineAnnealingWarmRestarts",
    "StepDecay",
    "MultiStepDecay",
    "LambdaDecay",
    "ReduceOnPlateau",
    "MultiplicativeDecay",
    "OneCycleLR",
    "CyclicLR",
    "LinearLR",
    "ConstantLR",
]


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.last_lr = self.base_lr
        self.step()

    def get_lr(self) -> float:
        return self.last_lr

    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self._compute()
        if self.verbose:
            print(f"Epoch {self.last_epoch}: lr set to {self.last_lr}")

    def _compute(self) -> float:
        raise NotImplementedError

    def state_dict(self):
        return {"last_epoch": self.last_epoch, "last_lr": self.last_lr}

    def set_state_dict(self, state):
        self.last_epoch = state.get("last_epoch", self.last_epoch)
        self.last_lr = state.get("last_lr", self.last_lr)

    set_dict = set_state_dict


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1, verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self):
        step = max(self.last_epoch, 1)
        return self.base_lr * (self.d_model**-0.5) * min(step**-0.5, step * self.warmup_steps**-1.5)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self):
        return self.base_lr * self.gamma**self.last_epoch


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0, cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self):
        step = self.last_epoch
        if self.cycle:
            div = math.ceil(step / self.decay_steps) if step > 0 else 1
            decay_steps = self.decay_steps * div
        else:
            decay_steps = self.decay_steps
            step = min(step, decay_steps)
        return (self.base_lr - self.end_lr) * (1 - step / decay_steps) ** self.power + self.end_lr


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr, last_epoch=-1, verbose=False):
        self.lr_after = learning_rate  # float or LRScheduler
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(start_lr, last_epoch, verbose)

    def _compute(self):
        if self.last_epoch < self.warmup_steps:
            return self.start_lr + (self.end_lr - self.start_lr) * self.last_epoch / self.warmup_steps
        if isinstance(self.lr_after, LRScheduler):
            self.lr_after.step(self.last_epoch - self.warmup_steps)
            return self.lr_after.get_lr()
        return float(self.lr_after)

    def state_dict(self):
        state = super().state_dict()
        if isinstance(self.lr_after, LRScheduler):
            state["lr_after"] = self.lr_after.state_dict()
        return state

    def set_state_dict(self, state):
        super().set_state_dict(state)
        if "lr_after" in state and isinstance(self.lr_after, LRScheduler):
            self.lr_after.set_state_dict(state["lr_after"])


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries = boundaries
        self.values = values
        super().__init__(values[0], last_epoch, verbose)

    def _compute(self):
        for b, v in zip(self.boundaries, self.values):
            if self.last_epoch < b:
                return v
        return self.values[len(self.boundaries)]


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1, verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self):
        return (
            self.eta_min
            + (self.base_lr - self.eta_min) * (1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2
        )


class CosineAnnealingWarmRestarts(LRScheduler):
    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0, last_epoch=-1, verbose=False):
        self.T_0, self.T_mult, self.eta_min = T_0, T_mult, eta_min
        self.T_cur = last_epoch
        self.T_i = T_0
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self):
        t = self.last_epoch
        T_i, T_0 = self.T_0, self.T_0
        while t >= T_i:
            t -= T_i
            T_i *= self.T_mult
        return self.eta_min + (self.base_lr - self.eta_min) * (1 + math.cos(math.pi * t / T_i)) / 2


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1, verbose=False):
        self.step_size, self.gamma = step_size, gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self):
        return self.base_lr * self.gamma ** (self.last_epoch // self.step_size)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1, verbose=False):
        self.milestones, self.gamma = list(milestones), gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self):
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * self.gamma**n


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)


class MultiplicativeDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        self._cur = float(learning_rate)
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self):
        if self.last_epoch > 0:
            self._cur = self._cur * self.lr_lambda(self.last_epoch)
        return self._cur

    def state_dict(self):
        # _cur is RUNNING multiplicative state, not derivable from
        # last_epoch alone — without it a restored scheduler restarts the
        # product from base_lr
        state = super().state_dict()
        state["_cur"] = self._cur
        return state

    def set_state_dict(self, state):
        super().set_state_dict(state)
        self._cur = state.get("_cur", self._cur)


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10, threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0, epsilon=1e-8, verbose=False):
        self.mode, self.factor, self.patience = mode, factor, patience
        self.threshold, self.threshold_mode = threshold, threshold_mode
        self.cooldown, self.min_lr, self.epsilon = cooldown, min_lr, epsilon
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self._lr = float(learning_rate)
        super().__init__(learning_rate, -1, verbose)

    def _compute(self):
        return self._lr

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            self.last_epoch += 1
            self.last_lr = self._lr
            return
        cur = float(metrics) if not hasattr(metrics, "item") else float(metrics.item())
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.num_bad = 0
        else:
            self.num_bad += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad = 0
        if self.num_bad > self.patience:
            new_lr = max(self._lr * self.factor, self.min_lr)
            if self._lr - new_lr > self.epsilon:
                self._lr = new_lr
            self.cooldown_counter = self.cooldown
            self.num_bad = 0
        self.last_epoch += 1
        self.last_lr = self._lr

    def _better(self, a, b):
        if self.mode == "min":
            th = b * (1 - self.threshold) if self.threshold_mode == "rel" else b - self.threshold
            return a < th
        th = b * (1 + self.threshold) if self.threshold_mode == "rel" else b + self.threshold
        return a > th

    def state_dict(self):
        # the plateau detector is all mutable state: the running best
        # metric, bad-epoch and cooldown counters, and the decayed lr itself
        state = super().state_dict()
        state.update(best=self.best, num_bad=self.num_bad,
                     cooldown_counter=self.cooldown_counter, _lr=self._lr)
        return state

    def set_state_dict(self, state):
        super().set_state_dict(state)
        self.best = state.get("best", self.best)
        self.num_bad = state.get("num_bad", self.num_bad)
        self.cooldown_counter = state.get("cooldown_counter", self.cooldown_counter)
        self._lr = state.get("_lr", self._lr)


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0, end_learning_rate=0.0001, phase_pct=0.3, anneal_strategy="cos", three_phase=False, last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        self.anneal = anneal_strategy
        self.three_phase = three_phase
        super().__init__(self.initial_lr, last_epoch, verbose)

    def _anneal_fn(self, start, end, pct):
        if self.anneal == "cos":
            return end + (start - end) / 2.0 * (math.cos(math.pi * pct) + 1)
        return (end - start) * pct + start

    def _compute(self):
        step = min(self.last_epoch, self.total_steps)
        up_steps = int(self.phase_pct * self.total_steps)
        if step <= up_steps:
            return self._anneal_fn(self.initial_lr, self.max_lr, step / max(up_steps, 1))
        return self._anneal_fn(self.max_lr, self.end_lr, (step - up_steps) / max(self.total_steps - up_steps, 1))


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate, max_learning_rate, step_size_up, step_size_down=None, mode="triangular", exp_gamma=1.0, scale_fn=None, scale_mode="cycle", last_epoch=-1, verbose=False):
        self.base_lr_ = base_learning_rate
        self.max_lr = max_learning_rate
        self.up = step_size_up
        self.down = step_size_down or step_size_up
        self.mode = mode
        self.gamma = exp_gamma
        self.scale_fn = scale_fn
        self.scale_mode = scale_mode
        super().__init__(base_learning_rate, last_epoch, verbose)

    def _compute(self):
        total = self.up + self.down
        cycle = math.floor(1 + self.last_epoch / total)
        x = self.last_epoch - (cycle - 1) * total
        scale = x / self.up if x <= self.up else (total - x) / self.down
        amp = (self.max_lr - self.base_lr_) * scale
        if self.scale_fn is not None:
            factor = self.scale_fn(cycle if self.scale_mode == "cycle" else self.last_epoch)
        elif self.mode == "triangular2":
            factor = 1 / (2 ** (cycle - 1))
        elif self.mode == "exp_range":
            factor = self.gamma**self.last_epoch
        else:
            factor = 1.0
        return self.base_lr_ + amp * factor


class LinearLR(LRScheduler):
    def __init__(self, learning_rate, total_steps, start_factor=1.0 / 3, end_factor=1.0, last_epoch=-1, verbose=False):
        self.total_steps = total_steps
        self.start_factor = start_factor
        self.end_factor = end_factor
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self):
        if self.last_epoch >= self.total_steps:
            return self.base_lr * self.end_factor
        pct = self.last_epoch / self.total_steps
        factor = self.start_factor + (self.end_factor - self.start_factor) * pct
        return self.base_lr * factor


class ConstantLR(LRScheduler):
    def __init__(self, learning_rate, factor=1.0 / 3, total_steps=5, last_epoch=-1, verbose=False):
        self.factor = factor
        self.total_steps = total_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self):
        if self.last_epoch < self.total_steps:
            return self.base_lr * self.factor
        return self.base_lr
