"""Optimizer base (reference: python/paddle/optimizer/optimizer.py:99).

Design notes (TPU-first):
- Per-parameter state ("accumulators", reference naming) are Tensors; the
  update math is pure jnp, so a whole train step (forward+backward+step) can
  be traced by jax.jit and the python loop unrolls into one fused XLA program
  — the reference needs fused multi-tensor CUDA kernels
  (DistributedFusedLamb etc.) to get this; XLA fusion gives it for free.
- The learning rate lives in a scalar Tensor so LR schedules don't retrigger
  compilation under jit (the scalar is a traced input, not a Python constant).
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu._core.autograd import no_grad
from paddle_tpu._core.tensor import Tensor
from paddle_tpu.nn.clip import ClipGradBase

__all__ = ["Optimizer"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, name=None):
        from . import lr as lr_mod

        if parameters is None:
            raise ValueError("parameters must be provided (dygraph mode)")
        self._parameter_list = list(parameters)
        # parameter groups support (list of dicts, reference optimizer.py:197)
        self._param_groups = []
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            groups = self._parameter_list
            self._parameter_list = []
            for g in groups:
                ps = list(g["params"])
                self._parameter_list.extend(ps)
                self._param_groups.append({**g, "params": ps})
        else:
            self._param_groups.append({"params": self._parameter_list})

        self._lr_scheduler = None
        if isinstance(learning_rate, lr_mod.LRScheduler):
            self._lr_scheduler = learning_rate
            base_lr = float(learning_rate.get_lr())
        else:
            base_lr = float(learning_rate)
        self._lr_t = Tensor(jnp.asarray(base_lr, jnp.float32))

        if isinstance(weight_decay, (int, float)):
            self._weight_decay = float(weight_decay)
            self._wd_is_l2 = True  # plain L2 into grads (reference L2Decay)
        else:
            self._weight_decay = 0.0
            self._wd_is_l2 = True
        self._grad_clip = grad_clip
        self._accumulators: dict = {}
        self._step_count = 0
        self.helper = None

    # ------------------------------------------------------------------- lr
    def get_lr(self) -> float:
        if self._lr_scheduler is not None:
            return float(self._lr_scheduler.get_lr())
        return float(self._lr_t._value) if not _is_tracer(self._lr_t._value) else self._lr_t

    def set_lr(self, value: float):
        if self._lr_scheduler is not None:
            raise RuntimeError("can't set_lr when using an LRScheduler")
        self._lr_t._bind(jnp.asarray(float(value), jnp.float32))

    def _sync_lr(self):
        if self._lr_scheduler is not None:
            self._lr_t._bind(jnp.asarray(float(self._lr_scheduler.get_lr()), jnp.float32))

    # ---------------------------------------------------------- accumulators
    def _acc(self, name: str, p: Tensor, init=None, dtype=None):
        key = (name, id(p))
        if key not in self._accumulators:
            if init is None:
                v = jnp.zeros(p._value.shape, dtype or p._value.dtype)
            else:
                v = init
            self._accumulators[key] = Tensor(v)
        return self._accumulators[key]

    # ---------------------------------------------------------------- update
    def _single_update(self, p: Tensor, grad, lr):
        raise NotImplementedError

    def step(self):
        if not _is_tracer(self._lr_t._value):
            self._sync_lr()
        lr = self._lr_t._value
        params_grads = [
            (p, p.grad) for p in self._parameter_list if not p.stop_gradient and p.grad is not None
        ]
        if self._grad_clip is not None and isinstance(self._grad_clip, ClipGradBase):
            params_grads = self._grad_clip(params_grads)
        with no_grad():
            for p, g in params_grads:
                if g is None:
                    continue
                gv = g._value.astype(jnp.float32) if g._value.dtype == jnp.float16 else g._value
                if self._weight_decay and self._wd_is_l2 and not self._decoupled_wd():
                    gv = gv + self._weight_decay * p._value.astype(gv.dtype)
                new_val = self._single_update(p, gv, lr)
                p._bind(new_val.astype(p._value.dtype) if new_val.dtype != p._value.dtype else new_val)
        self._step_count += 1

    def _decoupled_wd(self) -> bool:
        return False

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    # ------------------------------------------------------------ state dict
    def state_dict(self) -> dict:
        out = {}
        for (name, pid), t in self._accumulators.items():
            # stable naming: param-index based
            idx = next((i for i, p in enumerate(self._parameter_list) if id(p) == pid), None)
            out[f"{name}_{idx}"] = t
        out["LR_Scheduler"] = (
            self._lr_scheduler.state_dict() if self._lr_scheduler is not None else {"lr": float(self._lr_t._value)}
        )
        out["step_count"] = self._step_count
        return out

    def set_state_dict(self, state: dict):
        for (name, pid), t in self._accumulators.items():
            idx = next((i for i, p in enumerate(self._parameter_list) if id(p) == pid), None)
            key = f"{name}_{idx}"
            if key in state:
                src = state[key]
                t.set_value(src._value if isinstance(src, Tensor) else src)
        if "LR_Scheduler" in state and self._lr_scheduler is not None:
            self._lr_scheduler.set_state_dict(state["LR_Scheduler"])
        self._step_count = state.get("step_count", self._step_count)

    # -------------------------------------------------- functionalization API
    def opt_state_tensors(self) -> list:
        """All mutable optimizer-state tensors (for jit functionalization)."""
        return list(self._accumulators.values()) + [self._lr_t]


def _is_tracer(x):
    import jax

    return isinstance(x, jax.core.Tracer)
