"""Optimizer base (reference: python/paddle/optimizer/optimizer.py:99).

Design notes (TPU-first):
- Per-parameter state ("accumulators", reference naming) are Tensors; the
  update math is pure jnp, so a whole train step (forward+backward+step) can
  be traced by jax.jit and the python loop unrolls into one fused XLA program
  — the reference needs fused multi-tensor CUDA kernels
  (DistributedFusedLamb etc.) to get this; XLA fusion gives it for free.
- The learning rate lives in a scalar Tensor so LR schedules don't retrigger
  compilation under jit (the scalar is a traced input, not a Python constant).
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu._core.autograd import no_grad
from paddle_tpu._core.tensor import Tensor
from paddle_tpu.nn.clip import ClipGradBase

__all__ = ["Optimizer"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, name=None):
        from . import lr as lr_mod

        if parameters is None:
            raise ValueError("parameters must be provided (dygraph mode)")
        self._parameter_list = list(parameters)
        # parameter groups support (list of dicts, reference optimizer.py:197)
        self._param_groups = []
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            groups = self._parameter_list
            self._parameter_list = []
            for g in groups:
                ps = list(g["params"])
                self._parameter_list.extend(ps)
                self._param_groups.append({**g, "params": ps})
        else:
            self._param_groups.append({"params": self._parameter_list})

        self._lr_scheduler = None
        if isinstance(learning_rate, lr_mod.LRScheduler):
            self._lr_scheduler = learning_rate
            base_lr = float(learning_rate.get_lr())
        else:
            base_lr = float(learning_rate)
        self._lr_t = Tensor(jnp.asarray(base_lr, jnp.float32))

        from paddle_tpu.regularizer import L2Decay, WeightDecayRegularizer

        # one source of truth: the optimizer-level decay is ALWAYS a
        # regularizer instance (a float is reference L2Decay semantics);
        # _weight_decay mirrors the coeff for cheap truthiness checks
        if isinstance(weight_decay, WeightDecayRegularizer):
            self._regularizer = weight_decay
        elif isinstance(weight_decay, (int, float)) and float(weight_decay):
            self._regularizer = L2Decay(float(weight_decay))
        else:
            self._regularizer = None
        self._weight_decay = float(self._regularizer.coeff) if self._regularizer else 0.0
        self._wd_is_l2 = True  # legacy flag (L2-into-grads convention)
        self._grad_clip = grad_clip
        self._accumulators: dict = {}
        self._step_count = 0
        self.helper = None

    # ------------------------------------------------------------------- lr
    def get_lr(self) -> float:
        if self._lr_scheduler is not None:
            return float(self._lr_scheduler.get_lr())
        return float(self._lr_t._value) if not _is_tracer(self._lr_t._value) else self._lr_t

    def set_lr(self, value: float):
        if self._lr_scheduler is not None:
            raise RuntimeError("can't set_lr when using an LRScheduler")
        self._lr_t._bind(jnp.asarray(float(value), jnp.float32))

    def _sync_lr(self):
        if self._lr_scheduler is not None:
            self._lr_t._bind(jnp.asarray(float(self._lr_scheduler.get_lr()), jnp.float32))

    # ---------------------------------------------------------- accumulators
    def _acc(self, name: str, p: Tensor, init=None, dtype=None):
        key = (name, id(p))
        if key not in self._accumulators:
            if init is None:
                v = jnp.zeros(p._value.shape, dtype or p._value.dtype)
            else:
                v = init() if callable(init) else init
            self._accumulators[key] = Tensor(v)
        return self._accumulators[key]

    # ---------------------------------------------------------------- update
    def _single_update(self, p: Tensor, grad, lr):
        raise NotImplementedError

    def step(self):
        if not _is_tracer(self._lr_t._value):
            self._sync_lr()
        lr = self._lr_t._value
        from paddle_tpu.framework.selected_rows import SelectedRows

        params_grads = [
            (p, p.grad) for p in self._parameter_list if not p.stop_gradient and p.grad is not None
        ]
        if self._grad_clip is not None and isinstance(self._grad_clip, ClipGradBase):
            # grad clip computes dense norms: densify any SelectedRows first
            params_grads = [
                (p, Tensor(g.to_dense()) if isinstance(g, SelectedRows) else g)
                for p, g in params_grads
            ]
            params_grads = self._grad_clip(params_grads)
        with no_grad():
            for p, g in params_grads:
                if g is None:
                    continue
                if isinstance(g, SelectedRows):
                    # lazy row update (reference adam_functors.h lazy_mode):
                    # only the looked-up rows are touched; master-weight and
                    # L2 interplay stay dense-path-only by design — surface
                    # that divergence once instead of silently skipping it
                    if (self._weight_decay
                            or getattr(p, "regularizer", None) is not None
                            or p._value.dtype in (jnp.bfloat16, jnp.float16)) \
                            and not getattr(self, "_warned_sparse_path", False):
                        import warnings

                        self._warned_sparse_path = True
                        warnings.warn(
                            f"SelectedRows sparse update for {p.name!r}: "
                            "weight_decay and fp32 master weights apply only "
                            "on the dense path; the sparse rows are updated "
                            "without regularization/master-weight handling",
                            stacklevel=2,
                        )
                    new_val = self._sparse_update(p, g.coalesce(), lr)
                    p._bind(new_val.astype(p._value.dtype))
                    continue
                gv = g._value.astype(jnp.float32) if g._value.dtype == jnp.float16 else g._value
                if p._value.dtype in (jnp.bfloat16, jnp.float16):
                    # Persistent fp32 master weights (reference multi_precision,
                    # python/paddle/optimizer/adamw.py + fleet/utils/
                    # mix_precision_utils.py): update the fp32 master, cast down
                    # for the model copy.  Without this, updates smaller than
                    # the bf16 ulp are lost — always on for low-precision
                    # params (the reference's opt-in flag is kept in optimizer
                    # signatures for API parity only).
                    low_dtype = p._value.dtype
                    mw = self._acc("master_weight", p, init=lambda p=p: p._value.astype(jnp.float32))
                    reg = self._reg_grad_term(p, mw._value)
                    if reg is not None:
                        # decay term from the fp32 master, not the quantized copy
                        gv = gv.astype(jnp.float32) + reg
                    orig_val = p._value
                    try:
                        p._bind(mw._value)  # _single_update reads the master
                        new32 = self._single_update(p, gv, lr).astype(jnp.float32)
                    except Exception:
                        p._bind(orig_val)
                        raise
                    mw._bind(new32)
                    p._bind(new32.astype(low_dtype))
                else:
                    reg = self._reg_grad_term(p, p._value.astype(gv.dtype))
                    if reg is not None:
                        gv = gv + reg
                    new_val = self._single_update(p, gv, lr)
                    p._bind(new_val.astype(p._value.dtype) if new_val.dtype != p._value.dtype else new_val)
        self._step_count += 1

    def _decoupled_wd(self) -> bool:
        return False

    def _reg_grad_term(self, p, value):
        """Penalty gradient for `p`, or None.  A per-parameter regularizer
        (the ParamAttr path: `param.regularizer = L1Decay(...)`) takes
        priority over the optimizer-level weight_decay, matching the
        reference's append_regularization_ops resolution order; the
        optimizer-level term is skipped for decoupled-decay optimizers
        (AdamW applies its own decay outside the gradient)."""
        reg = getattr(p, "regularizer", None)
        if reg is None:
            if self._decoupled_wd():
                return None  # AdamW applies its own decay to the weight
            reg = self._regularizer
        if reg is None or not reg.coeff:
            return None  # zero-coeff = the "disable for this param" idiom
        return reg._grad_term(value)

    def _sparse_update(self, p, sr, lr):
        """Row-sparse update for a coalesced SelectedRows grad.  Base class:
        densify (correct for every optimizer); SGD/Momentum/Adam override
        with true touched-rows-only updates."""
        return self._single_update(p, sr.to_dense(), lr)

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from paddle_tpu.static.program import in_static_capture

        if in_static_capture():
            return self._static_minimize(loss, parameters)
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def _static_minimize(self, loss, parameters=None):
        """Static-graph path: append backward + one fused update super-op.

        The op's body replays self.step() on traced values — the same Python
        update math serves eager and static (the reference gets this from its
        YAML codegen emitting both dygraph ad_func and static op).  Parameter
        and accumulator mutations during tracing are journaled and rolled
        back, so the live objects are untouched; the program records
        param/state write-backs instead.
        """
        from paddle_tpu.static.autodiff import append_backward
        from paddle_tpu.static.program import current_main_program, suspend_capture

        prog = current_main_program()
        params = [p for p in (parameters or self._parameter_list) if not p.stop_gradient]
        p_g = append_backward(loss, parameter_list=params)
        grad_vars = [g for _, g in p_g]
        param_vars = [prog.var_for_parameter(p) for p in params]

        # discover accumulators with a rolled-back dry trace
        journal = self._journaled_step(params)
        acc_items = sorted(self._accumulators.items(), key=lambda kv: (kv[0][0], self._pidx(kv[0][1], params)))
        acc_tensors = [t for _, t in acc_items]
        acc_vars = [
            prog.var_for_state(t, name=f"opt_{name}_{self._pidx(pid, params)}")
            for (name, pid), t in acc_items
        ]

        n_p = len(params)
        n_a = len(acc_tensors)

        def update_fn(*vals):
            pvals = vals[:n_p]
            gvals = vals[n_p : 2 * n_p]
            avals = vals[2 * n_p : 2 * n_p + n_a]
            with suspend_capture():
                saved = [(p, p._value, p.grad) for p in params]
                saved_acc = [(t, t._value) for t in acc_tensors]
                saved_count = self._step_count
                try:
                    for p, pv, gv in zip(params, pvals, gvals):
                        p._bind(pv)
                        p.grad = Tensor(gv)
                    for t, av in zip(acc_tensors, avals):
                        t._bind(av)
                    # NOTE: python-level step-count math (e.g. RAdam's
                    # rectification branch) freezes at trace time in static
                    # mode, like the reference's non-var step attrs; stateful
                    # accumulators (beta pows) advance correctly.
                    self.step()
                    new_p = tuple(p._value for p in params)
                    new_a = tuple(t._value for t in acc_tensors)
                finally:
                    for (p, pv, g) in saved:
                        p._bind(pv)
                        p.grad = g
                    for (t, av) in saved_acc:
                        t._bind(av)
                    self._step_count = saved_count
            return new_p + new_a

        outs = prog.record(
            "optimizer_update", update_fn, tuple(param_vars) + tuple(grad_vars) + tuple(acc_vars), {}
        )
        outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
        for var, out in zip(list(param_vars) + list(acc_vars), outs):
            prog.add_write(var, out)
        return None, p_g

    @staticmethod
    def _pidx(pid, params):
        for i, p in enumerate(params):
            if id(p) == pid:
                return i
        return -1

    def _journaled_step(self, params):
        """Run one step() against zero grads purely to CREATE accumulators,
        then roll back every mutation: params/grads/step count restored from
        snapshots, pre-existing accumulators restored, newly-created ones
        reset to the exact fresh init their creation produced (captured by a
        spy on _acc at creation time, before step() mutates them)."""
        import jax.numpy as _jnp

        pre_acc_vals = {k: t._value for k, t in self._accumulators.items()}
        fresh_inits = {}
        orig_acc = self._acc

        def acc_spy(name, p, init=None, dtype=None):
            key = (name, id(p))
            existed = key in self._accumulators
            t = orig_acc(name, p, init=init, dtype=dtype)
            if not existed and key not in pre_acc_vals:
                fresh_inits[key] = t._value
            return t

        saved = [(p, p._value, p.grad) for p in params]
        saved_count = self._step_count
        self._acc = acc_spy
        try:
            for p in params:
                p.grad = Tensor(_jnp.zeros_like(p._value))
            with no_grad():
                self.step()
        finally:
            del self._acc  # restore the bound method
            for p, v, g in saved:
                p._bind(v)
                p.grad = g
            self._step_count = saved_count
            for k, t in self._accumulators.items():
                if k in pre_acc_vals:
                    t._bind(pre_acc_vals[k])
                elif k in fresh_inits:
                    t._bind(fresh_inits[k])

    # ------------------------------------------------------------ state dict
    def state_dict(self) -> dict:
        out = {}
        for (name, pid), t in self._accumulators.items():
            # stable naming: param-index based
            idx = next((i for i, p in enumerate(self._parameter_list) if id(p) == pid), None)
            out[f"{name}_{idx}"] = t
        out["LR_Scheduler"] = (
            self._lr_scheduler.state_dict() if self._lr_scheduler is not None else {"lr": float(self._lr_t._value)}
        )
        out["step_count"] = self._step_count
        return out

    def set_state_dict(self, state: dict):
        for (name, pid), t in self._accumulators.items():
            idx = next((i for i, p in enumerate(self._parameter_list) if id(p) == pid), None)
            key = f"{name}_{idx}"
            if key in state:
                src = state[key]
                t.set_value(src._value if isinstance(src, Tensor) else src)
        if "LR_Scheduler" in state and self._lr_scheduler is not None:
            self._lr_scheduler.set_state_dict(state["LR_Scheduler"])
        self._step_count = state.get("step_count", self._step_count)

    # -------------------------------------------------- functionalization API
    def opt_state_tensors(self) -> list:
        """All mutable optimizer-state tensors (for jit functionalization)."""
        return list(self._accumulators.values()) + [self._lr_t]


def _is_tracer(x):
    import jax

    return isinstance(x, jax.core.Tracer)
