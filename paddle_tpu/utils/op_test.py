"""OpTest utilities — numeric-vs-analytic gradient checking.

Reference: test/legacy_test/op_test.py:417 (OpTest.check_output /
check_grad :2944 — central finite differences against the registered grad
kernel, per-place/dtype tolerances).

TPU-native: the analytic side is the tape (autograd.apply -> jax.vjp); the
numeric side is central differences on the same callable.  `check_grad`
works on any Tensor->Tensor callable, so model code and custom ops get the
same gradient audit the reference gives its kernels.
"""

from __future__ import annotations

import numpy as np

__all__ = ["check_grad", "check_grad_dir", "check_output"]


def check_output(fn, oracle, *arrays, rtol=1e-5, atol=1e-6):
    """fn(Tensor...) vs oracle(ndarray...) — OpTest.check_output analog."""
    from paddle_tpu._core.tensor import Tensor

    out = fn(*[Tensor(np.asarray(a)) for a in arrays])
    np.testing.assert_allclose(
        np.asarray(out._value), oracle(*[np.asarray(a) for a in arrays]),
        rtol=rtol, atol=atol,
    )


def check_grad_dir(fn, *arrays, eps=1e-3, rtol=5e-3, atol=5e-4, argnums=None,
                   n_dirs=2, seed=0):
    """Directional finite-difference gradient check (OpTest.check_grad's
    role at sweep scale): for random directions v,
    dot(analytic_grad, v) ~= (f(x + eps*v) - f(x - eps*v)) / (2*eps).

    One FD pair per direction regardless of input size — the per-element
    version (`check_grad`) stays for the deep per-op audits; this one makes
    a 300-op registry sweep affordable (reference runs per-element checks
    across 1,340 test files; we trade that for directional projections at
    full registry breadth)."""
    from paddle_tpu._core.tensor import Tensor

    arrays = [np.asarray(a, np.float32) for a in arrays]
    argnums = list(range(len(arrays))) if argnums is None else list(argnums)

    def eval_loss(arrs, want_grads=False):
        ts = [Tensor(np.asarray(a, np.float32)) for a in arrs]
        for i in argnums:
            ts[i].stop_gradient = False
        out = fn(*ts)
        loss = out if out.size == 1 else (out.astype("float32") ** 2).sum()
        if not want_grads:
            return float(np.asarray(loss._value, np.float64)), None
        loss.backward()
        grads = []
        for i in argnums:
            g = ts[i].grad
            grads.append(
                np.zeros_like(arrays[i], np.float64)
                if g is None else np.asarray(g._value, np.float64)
            )
        return float(np.asarray(loss._value, np.float64)), grads

    _, analytic = eval_loss(arrays, want_grads=True)
    rng = np.random.default_rng(seed)
    for d in range(n_dirs):
        # one direction per CHECKED input (indexing by argnums position)
        dirs = [rng.normal(size=arrays[i].shape).astype(np.float32) for i in argnums]
        plus = list(arrays)
        minus = list(arrays)
        for k, i in enumerate(argnums):
            plus[i] = arrays[i] + eps * dirs[k]
            minus[i] = arrays[i] - eps * dirs[k]
        fp, _ = eval_loss(plus)
        fm, _ = eval_loss(minus)
        fd = (fp - fm) / (2 * eps)
        an = sum(float(np.sum(analytic[k] * dirs[k])) for k in range(len(argnums)))
        np.testing.assert_allclose(
            an, fd, rtol=rtol, atol=atol,
            err_msg=f"directional gradient mismatch (direction {d})",
        )


def check_grad(fn, *arrays, eps=1e-3, rtol=5e-3, atol=5e-4, argnums=None):
    """Central finite differences vs the tape's analytic gradients.

    fn: Tensor callable returning a Tensor (reduced to scalar via sum).
    arrays: float64-able numpy inputs.  argnums: which inputs to check
    (default: all).
    """
    from paddle_tpu._core.tensor import Tensor

    arrays = [np.asarray(a, np.float32) for a in arrays]
    argnums = list(range(len(arrays))) if argnums is None else list(argnums)

    def scalar_fn(arrs):
        ts = [Tensor(a) for a in arrs]
        for i in argnums:
            ts[i].stop_gradient = False
        out = fn(*ts)
        return out, ts

    # analytic
    out, ts = scalar_fn(arrays)
    loss = out if out.size == 1 else out.sum()
    loss.backward()
    analytic = [np.asarray(ts[i].grad._value, np.float64) for i in argnums]

    # numeric: central differences on the scalarized fn
    def eval_scalar(arrs):
        o, _ = scalar_fn(arrs)
        o = o if o.size == 1 else o.sum()
        return float(np.asarray(o._value, np.float64))

    for k, i in enumerate(argnums):
        a = arrays[i]
        num = np.zeros_like(a, np.float64)
        flat = a.reshape(-1)
        num_flat = num.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = eval_scalar(arrays)
            flat[j] = orig - eps
            fm = eval_scalar(arrays)
            flat[j] = orig
            num_flat[j] = (fp - fm) / (2 * eps)
        np.testing.assert_allclose(
            analytic[k], num, rtol=rtol, atol=atol,
            err_msg=f"gradient mismatch for input {i}",
        )
