"""OpTest utilities — numeric-vs-analytic gradient checking.

Reference: test/legacy_test/op_test.py:417 (OpTest.check_output /
check_grad :2944 — central finite differences against the registered grad
kernel, per-place/dtype tolerances).

TPU-native: the analytic side is the tape (autograd.apply -> jax.vjp); the
numeric side is central differences on the same callable.  `check_grad`
works on any Tensor->Tensor callable, so model code and custom ops get the
same gradient audit the reference gives its kernels.
"""

from __future__ import annotations

import numpy as np

__all__ = ["check_grad", "check_output"]


def check_output(fn, oracle, *arrays, rtol=1e-5, atol=1e-6):
    """fn(Tensor...) vs oracle(ndarray...) — OpTest.check_output analog."""
    from paddle_tpu._core.tensor import Tensor

    out = fn(*[Tensor(np.asarray(a)) for a in arrays])
    np.testing.assert_allclose(
        np.asarray(out._value), oracle(*[np.asarray(a) for a in arrays]),
        rtol=rtol, atol=atol,
    )


def check_grad(fn, *arrays, eps=1e-3, rtol=5e-3, atol=5e-4, argnums=None):
    """Central finite differences vs the tape's analytic gradients.

    fn: Tensor callable returning a Tensor (reduced to scalar via sum).
    arrays: float64-able numpy inputs.  argnums: which inputs to check
    (default: all).
    """
    from paddle_tpu._core.tensor import Tensor

    arrays = [np.asarray(a, np.float32) for a in arrays]
    argnums = list(range(len(arrays))) if argnums is None else list(argnums)

    def scalar_fn(arrs):
        ts = [Tensor(a) for a in arrs]
        for i in argnums:
            ts[i].stop_gradient = False
        out = fn(*ts)
        return out, ts

    # analytic
    out, ts = scalar_fn(arrays)
    loss = out if out.size == 1 else out.sum()
    loss.backward()
    analytic = [np.asarray(ts[i].grad._value, np.float64) for i in argnums]

    # numeric: central differences on the scalarized fn
    def eval_scalar(arrs):
        o, _ = scalar_fn(arrs)
        o = o if o.size == 1 else o.sum()
        return float(np.asarray(o._value, np.float64))

    for k, i in enumerate(argnums):
        a = arrays[i]
        num = np.zeros_like(a, np.float64)
        flat = a.reshape(-1)
        num_flat = num.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = eval_scalar(arrays)
            flat[j] = orig - eps
            fm = eval_scalar(arrays)
            flat[j] = orig
            num_flat[j] = (fp - fm) / (2 * eps)
        np.testing.assert_allclose(
            analytic[k], num, rtol=rtol, atol=atol,
            err_msg=f"gradient mismatch for input {i}",
        )
