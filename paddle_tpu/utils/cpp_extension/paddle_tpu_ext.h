// Custom-op C ABI for paddle_tpu (reference: paddle/phi/api/ext/op_meta_info.h
// PD_BUILD_OP + paddle/phi/capi/ C ABI — redesigned for the TPU runtime:
// custom C++ ops execute as host callbacks alongside the XLA program, so the
// ABI is a plain tensor-view struct, no device context).
//
// A custom op source defines:
//   extern "C" int my_op(const PTExtTensor* inputs, int n_inputs,
//                        PTExtTensor* outputs, int n_outputs);
// returning 0 on success.  Outputs are pre-allocated by the framework using
// the shape inference declared at load() time.
#pragma once
#include <cstdint>

extern "C" {

typedef enum {
  PT_FLOAT32 = 0,
  PT_FLOAT64 = 1,
  PT_INT32 = 2,
  PT_INT64 = 3,
  PT_BOOL = 4,
} PTExtDtype;

typedef struct {
  void* data;           // contiguous row-major buffer
  const int64_t* shape; // dims
  int32_t ndim;
  int32_t dtype;        // PTExtDtype
} PTExtTensor;

static inline int64_t pt_numel(const PTExtTensor* t) {
  int64_t n = 1;
  for (int i = 0; i < t->ndim; ++i) n *= t->shape[i];
  return n;
}

}  // extern "C"
