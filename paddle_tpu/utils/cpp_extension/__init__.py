"""paddle.utils.cpp_extension equivalent (reference:
python/paddle/utils/cpp_extension/cpp_extension.py — setup/CppExtension/
CUDAExtension/BuildExtension/load building custom C++ ops).

TPU-native design: a custom C++ op cannot be a device kernel (TPU kernels
are Pallas/XLA), so loaded ops run as **host callbacks** — the C++ fn is
compiled to a .so with g++, bound via ctypes, and wrapped in
jax.pure_callback so it composes with jit/vmap; a paired `<name>_grad`
symbol (reference PD_BUILD_GRAD_OP) becomes the op's custom_vjp.  This is
the honest mapping of the reference's CPU custom-op path; performance-
critical custom TPU ops should be written as Pallas kernels instead
(paddle_tpu/ops/)."""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu._core.tensor import Tensor

__all__ = ["load", "setup", "CppExtension", "CUDAExtension", "get_build_directory", "CustomOpModule"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_DTYPES = {
    np.dtype("float32"): 0,
    np.dtype("float64"): 1,
    np.dtype("int32"): 2,
    np.dtype("int64"): 3,
    np.dtype("bool"): 4,
}


def get_build_directory():
    d = os.environ.get("PADDLE_EXTENSION_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu", "extensions"
    )
    os.makedirs(d, exist_ok=True)
    return d


class _PTExtTensor(ctypes.Structure):
    _fields_ = [
        ("data", ctypes.c_void_p),
        ("shape", ctypes.POINTER(ctypes.c_int64)),
        ("ndim", ctypes.c_int32),
        ("dtype", ctypes.c_int32),
    ]


def _build(name, sources, extra_cxx_flags=()):
    h = hashlib.sha256()
    for s in sources:
        h.update(open(s, "rb").read())
    # the injected ABI header is part of the binary contract
    h.update(open(os.path.join(_HERE, "paddle_tpu_ext.h"), "rb").read())
    h.update(" ".join(extra_cxx_flags).encode())
    out = os.path.join(get_build_directory(), f"{name}-{h.hexdigest()[:16]}.so")
    if not os.path.exists(out):
        tmp = f"{out}.{os.getpid()}.tmp"
        cmd = [
            "g++", "-O2", "-fPIC", "-shared", "-std=c++17",
            f"-I{_HERE}", *extra_cxx_flags, *sources, "-o", tmp,
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"custom op build failed:\n{e.stderr.decode(errors='replace')}"
            ) from None
        os.replace(tmp, out)
    return out


def _make_tensor_array(arrays, keepalive):
    arr_t = (_PTExtTensor * len(arrays))()
    for i, a in enumerate(arrays):
        a = np.ascontiguousarray(a)
        keepalive.append(a)
        shape = (ctypes.c_int64 * a.ndim)(*a.shape)
        keepalive.append(shape)
        arr_t[i].data = a.ctypes.data_as(ctypes.c_void_p)
        arr_t[i].shape = shape
        arr_t[i].ndim = a.ndim
        arr_t[i].dtype = _DTYPES[a.dtype]
    return arr_t


class _LoadedOp:
    """One custom op: host callback + optional custom vjp."""

    def __init__(self, lib, name, infer_shape, infer_dtype, n_outputs, grad_sym):
        self._fn = getattr(lib, name)
        self._fn.restype = ctypes.c_int
        self._grad = grad_sym
        if self._grad is not None:
            self._grad.restype = ctypes.c_int
        self.name = name
        self.infer_shape = infer_shape or (lambda *shapes: [shapes[0]] * n_outputs)
        self.infer_dtype = infer_dtype or (lambda *dts: [dts[0]] * n_outputs)
        self.n_outputs = n_outputs
        self._callable = self._build_callable()

    def _host_call(self, fn, inputs, out_shapes, out_dtypes):
        keep = []
        ins = _make_tensor_array(inputs, keep)
        # np.zeros buffers are already contiguous, so _make_tensor_array
        # passes them through and the C op writes them in place
        outs_np = [np.zeros(s, d) for s, d in zip(out_shapes, out_dtypes)]
        outs = _make_tensor_array(outs_np, keep)
        rc = fn(ins, len(inputs), outs, len(outs_np))
        if rc != 0:
            raise RuntimeError(f"custom op {self.name} returned {rc}")
        return outs_np

    def _build_callable(self):
        def forward_host(*inputs):
            shapes = self.infer_shape(*[tuple(i.shape) for i in inputs])
            dtypes = self.infer_dtype(*[i.dtype for i in inputs])
            return tuple(self._host_call(self._fn, list(inputs), shapes, dtypes))

        def apply(*inputs):
            arrs = [i._value if isinstance(i, Tensor) else jnp.asarray(i) for i in inputs]
            out = _callback(*arrs)
            res = [Tensor(o) for o in out]
            return res[0] if self.n_outputs == 1 else res

        def _cb_fwd(*arrs):
            shapes = self.infer_shape(*[tuple(a.shape) for a in arrs])
            dtypes = self.infer_dtype(*[np.dtype(a.dtype) for a in arrs])
            out_spec = tuple(jax.ShapeDtypeStruct(s, d) for s, d in zip(shapes, dtypes))
            return jax.pure_callback(forward_host, out_spec, *arrs, vmap_method="sequential")

        if self._grad is None:
            _callback = _cb_fwd
        else:
            grad_c = self._grad

            @jax.custom_vjp
            def _callback(*arrs):
                return _cb_fwd(*arrs)

            def fwd(*arrs):
                outs = _cb_fwd(*arrs)
                return outs, (arrs, outs)

            def bwd(res, cts):
                arrs, outs = res

                def grad_host(*all_ins):
                    n_x = len(arrs)
                    xs = all_ins[:n_x]
                    rest = all_ins[n_x:]
                    shapes = [tuple(x.shape) for x in xs]
                    dts = [x.dtype for x in xs]
                    return tuple(
                        self._host_call(grad_c, list(all_ins), shapes, dts)
                    )

                spec = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype) for a in arrs)
                grads = jax.pure_callback(
                    grad_host, spec, *arrs, *outs, *cts, vmap_method="sequential"
                )
                return tuple(grads)

            _callback.defvjp(fwd, bwd)

        return apply

    def __call__(self, *inputs):
        return self._callable(*inputs)


class CustomOpModule:
    """Namespace of loaded ops (mirror of the reference's generated python
    module from load(), extension_utils.py _generate_python_module)."""

    def __init__(self):
        self._ops = {}

    def _add(self, op):
        self._ops[op.name] = op
        setattr(self, op.name, op)


def load(name, sources, extra_cxx_flags=None, extra_cuda_cflags=None,
         extra_ldflags=None, extra_include_paths=None, build_directory=None,
         verbose=False, op_names=None, infer_shape=None, infer_dtype=None,
         n_outputs=1):
    """Build + load custom ops (reference cpp_extension.py:797).

    op_names: list of exported op symbols; defaults to [name].  For each op,
    a `<op>_grad` symbol (if present) becomes its vjp:
        grad(ins..., outs..., out_grads...) -> input grads.
    """
    flags = list(extra_cxx_flags or [])
    for p in extra_include_paths or []:
        flags.append(f"-I{p}")
    path = _build(name, sources, flags)
    lib = ctypes.CDLL(path)
    module = CustomOpModule()
    for op_name in op_names or [name]:
        grad_sym = None
        try:
            grad_sym = getattr(lib, f"{op_name}_grad")
        except AttributeError:
            pass
        module._add(
            _LoadedOp(lib, op_name, infer_shape, infer_dtype, n_outputs, grad_sym)
        )
    return module


def CppExtension(sources, *args, **kwargs):
    """reference cpp_extension.py:239 — returns a setuptools Extension."""
    from setuptools import Extension

    kwargs.setdefault("include_dirs", []).append(_HERE)
    kwargs.setdefault("language", "c++")
    name = kwargs.pop("name", "paddle_tpu_custom_ops")
    return Extension(name, sources, *args, **kwargs)


def CUDAExtension(sources, *args, **kwargs):
    """CUDA has no meaning on TPU; accepted for API compat and built as a
    plain C++ extension with .cu files rejected (reference :289)."""
    cu = [s for s in sources if s.endswith(".cu")]
    if cu:
        raise ValueError(
            f"CUDA sources {cu} cannot target TPU — port device code to a "
            "Pallas kernel (paddle_tpu/ops) and keep host code in .cc files"
        )
    return CppExtension(sources, *args, **kwargs)


def setup(**attr):
    """reference cpp_extension.py:79 — delegates to setuptools.setup with
    the C++ build configured."""
    from setuptools import setup as _setup

    attr.setdefault("ext_modules", [])
    return _setup(**attr)
