"""paddle.utils equivalent (reference: python/paddle/utils/) — currently
cpp_extension (custom C++ op build/load) plus small helpers."""

from . import cpp_extension  # noqa: F401

__all__ = ["cpp_extension"]


def run_check():
    """paddle.utils.run_check equivalent: verify the device stack works."""
    import jax
    import jax.numpy as jnp

    n = len(jax.devices())
    out = jnp.ones((8, 8)) @ jnp.ones((8, 8))
    assert float(out[0, 0]) == 8.0
    print(f"paddle_tpu is installed successfully! {n} device(s) available.")
