"""paddle.utils equivalent (reference: python/paddle/utils/) — currently
cpp_extension (custom C++ op build/load) plus small helpers."""

from . import cpp_extension  # noqa: F401

__all__ = ["cpp_extension", "try_import", "require_version", "deprecated"]


def run_check():
    """paddle.utils.run_check equivalent: verify the device stack works."""
    import jax
    import jax.numpy as jnp

    n = len(jax.devices())
    out = jnp.ones((8, 8)) @ jnp.ones((8, 8))
    assert float(out[0, 0]) == 8.0
    print(f"paddle_tpu is installed successfully! {n} device(s) available.")


def try_import(module_name, err_msg=None):
    """reference: python/paddle/utils/lazy_import.py try_import."""
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or f"{module_name} is required: pip install {module_name}") from e


def require_version(min_version, max_version=None):
    """reference: python/paddle/utils/__init__.py require_version — check the
    installed framework version against [min, max]."""
    from paddle_tpu import __version__

    def parse(v):
        t = tuple(int(p) for p in str(v).split(".")[:3] if p.isdigit())
        return t + (0,) * (3 - len(t))  # '0.1' == '0.1.0'

    cur = parse(__version__)
    if parse(min_version) > cur:
        raise Exception(f"paddle_tpu>={min_version} required, found {__version__}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(f"paddle_tpu<={max_version} required, found {__version__}")
    return True


def deprecated(update_to="", since="", reason="", level=0):
    """reference: python/paddle/utils/deprecated.py — decorator emitting a
    DeprecationWarning on call."""
    import functools
    import warnings

    def wrap(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f"; use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            if level >= 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return inner

    return wrap
