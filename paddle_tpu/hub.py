"""Model hub (reference: python/paddle/hub.py — torch.hub-style list /
help / load over a repo's hubconf.py).

Local sources (`source='local'`, a directory containing hubconf.py) are
fully supported.  GitHub/gitee sources require network access; in
hermetic environments the download step raises a clear error instead of
hanging — pass a pre-downloaded checkout as a local source instead.
"""

from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    return mod


def _resolve(repo_dir: str, source: str) -> str:
    source = source.lower()
    if source == "local":
        if not os.path.isdir(repo_dir):
            raise FileNotFoundError(f"local hub repo {repo_dir!r} not found")
        return repo_dir
    if source in ("github", "gitee"):
        # hermetic environment: no network egress. A pre-fetched checkout
        # in the hub cache dir is honored; otherwise fail loudly.
        cache = os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                             "hub", repo_dir.replace("/", "_").replace(":", "_"))
        if os.path.isdir(cache):
            return cache
        raise RuntimeError(
            f"hub source {source!r} needs network access to fetch "
            f"{repo_dir!r}; this environment has none. Clone the repo "
            f"yourself and pass source='local' (or place it at {cache})")
    raise ValueError(f"unknown hub source {source!r} "
                     "(expected 'github', 'gitee' or 'local')")


def _entrypoints(mod):
    return {n: f for n, f in vars(mod).items()
            if callable(f) and not n.startswith("_")}


def list(repo_dir: str, source: str = "github", force_reload: bool = False):  # noqa: A001 — reference name
    """Names of the callable entrypoints in the repo's hubconf.py."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    return sorted(_entrypoints(mod))


def help(repo_dir: str, model: str, source: str = "github",  # noqa: A001
         force_reload: bool = False):
    """The docstring of one entrypoint."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    eps = _entrypoints(mod)
    if model not in eps:
        raise RuntimeError(f"entrypoint {model!r} not found; available: "
                           f"{sorted(eps)}")
    return eps[model].__doc__


def load(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False, **kwargs):
    """Call the named entrypoint and return its result (usually a Layer)."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    eps = _entrypoints(mod)
    if model not in eps:
        raise RuntimeError(f"entrypoint {model!r} not found; available: "
                           f"{sorted(eps)}")
    return eps[model](**kwargs)
