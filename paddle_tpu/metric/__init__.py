"""Metrics (reference: python/paddle/metric/metrics.py)."""

from __future__ import annotations

import numpy as np

from paddle_tpu._core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Top-k accuracy (reference python/paddle/metric/metrics.py accuracy)."""
    import jax.numpy as jnp

    from paddle_tpu.tensor._ops_common import apply, ensure_tensor

    input, label = ensure_tensor(input), ensure_tensor(label)

    def _acc(logits, lbl):
        topk_idx = jnp.argsort(-logits, axis=-1)[..., :k]
        l = lbl.reshape(-1, 1) if lbl.ndim == 1 else lbl
        hit = jnp.any(topk_idx == l, axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return apply("accuracy", _acc, input, label)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = np.asarray(pred._value if isinstance(pred, Tensor) else pred)
        label_np = np.asarray(label._value if isinstance(label, Tensor) else label)
        idx = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        correct = idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = np.asarray(correct._value if isinstance(correct, Tensor) else correct)
        num = c.shape[0] if c.ndim > 0 else 1
        for i, k in enumerate(self.topk):
            hits = c[..., :k].any(axis=-1).sum()
            self.total[i] += float(hits)
            self.count[i] += int(num)
        accs = [t / max(cn, 1) for t, cn in zip(self.total, self.count)]
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        accs = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return accs[0] if len(accs) == 1 else accs

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds).round()
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds).round()
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self.num_thresholds = num_thresholds
        self._name = name or "auc"
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels).reshape(-1)
        pos_prob = p[:, 1] if p.ndim == 2 and p.shape[1] == 2 else p.reshape(-1)
        bins = np.minimum((pos_prob * self.num_thresholds).astype(int), self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate over thresholds from high to low
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        tpr = pos_cum / tot_pos
        fpr = neg_cum / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name
