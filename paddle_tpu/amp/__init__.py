"""AMP: auto_cast / decorate / GradScaler.

Reference: python/paddle/amp/auto_cast.py:703, grad_scaler.py:578, per-op
white/black lists in C++ (paddle/fluid/eager/amp_utils.h).  TPU-native notes:
the natural mixed-precision dtype on TPU is bfloat16, which needs NO loss
scaling (same exponent range as fp32) — GradScaler degenerates to a pass-
through there, but retains full dynamic-scaling semantics for float16.
The cast hook lives in the op dispatcher (autograd.apply consults
`amp_state()`), mirroring the reference's AMP auto-cast insertion in
generated ad_funcs (eager_gen.py AMP logic).
"""

from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from paddle_tpu._core.dtype import to_jax_dtype
from paddle_tpu._core.tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler", "amp_state", "white_list", "black_list"]

# Ops that benefit from low precision (MXU ops) — reference white list.
WHITE_LIST = {
    "matmul", "linear", "conv", "conv_transpose", "mm", "bmm", "einsum", "addmm",
    "scaled_dot_product_attention", "flash_attention",
    "fused_dot_product_attention", "flash_attn_unpadded",
    "fused_gate_attention",
}
# Numerically sensitive ops stay fp32 — reference black list.
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "logsumexp", "softmax_with_cross_entropy",
    "cross_entropy", "layer_norm", "rms_norm", "batch_norm", "group_norm",
    "instance_norm", "softmax", "log_softmax", "mean", "sum", "cumsum", "norm",
    "pow", "sqrt", "rsqrt", "square", "reciprocal",
}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def amp_state():
    return _state


def white_list():
    return (WHITE_LIST | _state.custom_white) - _state.custom_black


def black_list():
    return (BLACK_LIST | _state.custom_black) - _state.custom_white


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1", dtype="bfloat16", use_promote=True):
    """paddle.amp.auto_cast equivalent.  Default dtype is bfloat16 (TPU MXU
    native); 'float16' also supported."""
    prev = (_state.enabled, _state.dtype, _state.level, _state.custom_white, _state.custom_black)
    _state.enabled = enable
    _state.dtype = to_jax_dtype(dtype)
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level, _state.custom_white, _state.custom_black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16", master_weight=None, save_dtype=None):
    """O2: cast model params to low precision; the Optimizer base then keeps
    a persistent fp32 master weight per low-precision param (updates apply to
    the master, the model copy is the cast-down view — see
    optimizer/optimizer.py step()).
    """
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        dt = to_jax_dtype(dtype)
        for m in model_list:
            for p in m.parameters():
                if jnp.issubdtype(p._value.dtype, jnp.floating):
                    p._bind(p._value.astype(dt))
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers


class GradScaler:
    """Dynamic loss scaling (reference grad_scaler.py).

    TPU-native redesign: the scaler STATE (loss scale, good/bad step
    counters) lives in device scalar Tensors and every decision is a traced
    select — so the scaler works identically eagerly and inside a compiled
    TrainStep (the reference's python-bool bookkeeping would freeze at trace
    time).  A skipped step (inf/nan grads) is expressed as
    where(found_inf, old, updated) over params and accumulators, matching
    the reference's found_inf kernel path.  On bfloat16 runs, construct with
    enable=False (scaling unnecessary).
    """

    def __init__(
        self,
        enable=True,
        init_loss_scaling=2.0**16,
        incr_ratio=2.0,
        decr_ratio=0.5,
        incr_every_n_steps=2000,
        decr_every_n_nan_or_inf=1,
        use_dynamic_loss_scaling=True,
    ):
        self._enable = enable
        self._incr_ratio = float(incr_ratio)
        self._decr_ratio = float(decr_ratio)
        self._incr_every = int(incr_every_n_steps)
        self._decr_every = int(decr_every_n_nan_or_inf)
        self._dynamic = use_dynamic_loss_scaling
        self._scale_t = Tensor(jnp.asarray(float(init_loss_scaling), jnp.float32))
        self._good_t = Tensor(jnp.asarray(0, jnp.int32))
        self._bad_t = Tensor(jnp.asarray(0, jnp.int32))
        self._found_t = Tensor(jnp.asarray(False))

    def state_tensors(self):
        """Device-state exposed to compiled train steps (TrainStep donates
        and threads these alongside params/accumulators)."""
        return [self._scale_t, self._good_t, self._bad_t]

    def scale(self, var):
        if not self._enable:
            return var
        from paddle_tpu.tensor._ops_common import apply

        return apply(
            "amp_scale", lambda v, s: v * s.astype(v.dtype), var, self._scale_t
        )

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale_t._value
        found = jnp.asarray(False)
        for p in optimizer._parameter_list:
            if p.grad is not None:
                g = p.grad._value.astype(jnp.float32) * inv
                found = jnp.logical_or(found, jnp.any(~jnp.isfinite(g)))
                p.grad = Tensor(g)
        self._found_t = Tensor(found)

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        found = self._found_t._value
        if not _is_tracer(found):
            if not bool(found):
                optimizer.step()
            self.update()
            return
        # traced: run the update unconditionally, then select old state back
        # where inf was found (the skip, expressed functionally)
        params = [p for p in optimizer._parameter_list if not p.stop_gradient]
        snap_p = [(p, p._value) for p in params]
        snap_a = {k: t._value for k, t in optimizer._accumulators.items()}
        optimizer.step()
        for p, old in snap_p:
            p._bind(jnp.where(found, old, p._value))
        for k, t in optimizer._accumulators.items():
            old = snap_a.get(k)
            if old is None:
                old = jnp.zeros_like(t._value)  # created this step
            p_val = t._value
            if old.shape == p_val.shape:
                t._bind(jnp.where(found, old, p_val))
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def update(self):
        if not self._enable or not self._dynamic:
            return
        found = self._found_t._value
        good = self._good_t._value
        bad = self._bad_t._value
        scale = self._scale_t._value
        bad2 = jnp.where(found, bad + 1, 0)
        good2 = jnp.where(found, 0, good + 1)
        dec = bad2 >= self._decr_every
        inc = jnp.logical_and(~found, good2 >= self._incr_every)
        new_scale = jnp.where(dec, jnp.maximum(scale * self._decr_ratio, 1.0), scale)
        new_scale = jnp.where(inc, new_scale * self._incr_ratio, new_scale)
        self._scale_t._bind(new_scale)
        self._bad_t._bind(jnp.where(dec, 0, bad2).astype(jnp.int32))
        self._good_t._bind(jnp.where(inc, 0, good2).astype(jnp.int32))

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        v = self._scale_t._value
        return v if _is_tracer(v) else float(v)

    def set_init_loss_scaling(self, v):
        self._scale_t._bind(jnp.asarray(float(v), jnp.float32))

    def state_dict(self):
        return {
            "scale": self.get_loss_scaling(),
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_count": int(self._good_t._value) if not _is_tracer(self._good_t._value) else 0,
            "decr_count": int(self._bad_t._value) if not _is_tracer(self._bad_t._value) else 0,
        }

    def load_state_dict(self, state):
        self._scale_t._bind(jnp.asarray(float(state.get("scale", self.get_loss_scaling())), jnp.float32))
        self._good_t._bind(jnp.asarray(int(state.get("incr_count", 0)), jnp.int32))
        self._bad_t._bind(jnp.asarray(int(state.get("decr_count", 0)), jnp.int32))


def _is_tracer(x):
    import jax

    return isinstance(x, jax.core.Tracer)


def is_bfloat16_supported(device=None):
    """bf16 is the TPU-native compute dtype (MXU); always true, and true on
    the XLA CPU backend too (reference: paddle.amp.is_bfloat16_supported)."""
    return True


def is_float16_supported(device=None):
    """fp16 storage/compute is supported by XLA on TPU, though bf16 is
    preferred (no loss scaling needed)."""
    return True


__all__ += ["is_bfloat16_supported", "is_float16_supported"]
