"""Static control-flow ops: cond / while_loop / case / switch_case.

Reference: paddle.static.nn.cond / while_loop
(python/paddle/static/nn/control_flow.py) executed by IfInstruction /
WhileInstruction sub-interpreters
(paddle/fluid/framework/new_executor/instruction/if_instruction.cc:1,
while_instruction.cc).

TPU-native redesign: there is no sub-interpreter — data-dependent branches
lower to `lax.cond` / `lax.while_loop` inside the traced program, the only
control flow XLA can compile.  Semantics:

- Concrete (eager) predicates take the plain Python branch: full tape
  autograd, zero overhead — paddle dygraph parity.
- Traced predicates (inside jit / to_static / TrainStep):
  * `cond` discovers the Tensors each branch closes over by running a
    recording pass (paddle's static mode likewise builds both branch
    programs), then registers the whole lax.cond as ONE tape op via the
    apply() funnel — gradients flow into both branches' captures
    (jax.vjp of lax.cond backpropagates the taken branch and produces
    zeros for the other, matching the reference's select-grad semantics).
  * `while_loop` lowers to lax.while_loop.  XLA cannot reverse-differentiate
    a dynamic-trip-count loop (the reference's while_grad replays a stack of
    per-iteration states — unbounded memory the TPU path deliberately
    avoids); outputs are stop_gradient and training loops should use
    fixed-length scans (lax.scan via paddle ops) or bounded unrolling.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu._core.autograd import apply, no_grad, record_touched_tensors
from paddle_tpu._core.tensor import Tensor

__all__ = ["cond", "while_loop", "case", "switch_case", "Print"]


def _is_tracer(v):
    return isinstance(v, jax.core.Tracer)


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _pred_value(pred):
    v = pred._value if isinstance(pred, Tensor) else pred
    if hasattr(v, "reshape") and getattr(v, "ndim", 0) > 0:
        v = v.reshape(())
    return v


def _run_branch(fn, out_template=None):
    out = fn() if fn is not None else None
    flat, tree = jax.tree_util.tree_flatten(
        out, is_leaf=lambda x: isinstance(x, Tensor)
    )
    return [_unwrap(v) for v in flat], tree


def _discover_captures(fns, prog):
    """Find the Variables of `prog` that the closures reference, by replaying
    them into a sacrificial Program (the reference's static mode likewise
    builds both branch sub-programs — if_instruction.cc runs them in sub-
    interpreters; here the discovery program is discarded and the real op
    replays the closures under lax control flow)."""
    from paddle_tpu._core.autograd import TouchRecorder, record_touched_tensors
    from paddle_tpu.static.program import Program, program_guard

    temp = Program()
    # sacrificial: records ops against the OUTER program's vids and is then
    # discarded — verifier sweeps (static.verify.track_programs) skip it
    temp._discovery = True
    rec = TouchRecorder()
    with record_touched_tensors(rec), program_guard(temp):
        for fn in fns:
            if fn is not None:
                try:
                    fn()
                except Exception:
                    pass  # discovery only; the real trace surfaces errors
    from paddle_tpu._core.tensor import Parameter

    seen, out = set(), []
    for t in rec.inputs:
        is_prog_var = getattr(t, "_program", None) is prog
        # Parameters are captured too: Program.record registers them as
        # state vars (var_for_parameter) so optimizer updates reach the
        # branches — dropping them would bake weights in as constants
        if (is_prog_var or isinstance(t, Parameter)) and id(t) not in seen:
            seen.add(id(t))
            out.append(t)
    return out


def _static_cond(pred, true_fn, false_fn):
    from paddle_tpu._core.autograd import apply
    from paddle_tpu.static.program import current_main_program

    prog = current_main_program()
    captured = _discover_captures([true_fn, false_fn], prog)

    def cond_replay(pred_v, *cap_vals):
        originals = [t._value for t in captured]
        try:
            for t, v in zip(captured, cap_vals):
                t._bind(v)
            # suspend_capture is active inside Operator replay, so this runs
            # the eager/traced cond (lax.cond on tracers); the branch's
            # ORIGINAL pytree structure (dict/nested) is preserved
            out = cond(Tensor(pred_v, stop_gradient=True), true_fn, false_fn)
            return jax.tree_util.tree_map(
                _unwrap, out, is_leaf=lambda x: isinstance(x, Tensor)
            )
        finally:
            for t, v in zip(captured, originals):
                t._bind(v)

    return apply("cond", cond_replay, pred, *captured)


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Run true_fn or false_fn depending on pred (scalar bool Tensor)."""
    from paddle_tpu.static.program import in_static_capture

    if in_static_capture():
        return _static_cond(pred, true_fn, false_fn)
    pv = _pred_value(pred)
    if not _is_tracer(pv):
        # eager: plain python dispatch, tape records the taken branch
        if bool(pv):
            return true_fn() if true_fn is not None else None
        return false_fn() if false_fn is not None else None

    # Traced predicate: discover each branch's Tensor captures by running it
    # once under a recorder (outputs discarded), then trace both branches
    # inside lax.cond over the explicit capture list.  Branch-local
    # intermediates are filtered out (recorder tracks creations).
    from paddle_tpu._core.autograd import TouchRecorder

    recorder = TouchRecorder()
    with record_touched_tensors(recorder), no_grad():
        t_out, t_tree = _run_branch(true_fn)
        f_out, f_tree = _run_branch(false_fn)
    if t_tree != f_tree:
        raise ValueError(
            f"cond branches must return the same structure: {t_tree} vs {f_tree}"
        )
    for tv, fv in zip(t_out, f_out):
        if jnp.shape(tv) != jnp.shape(fv) or jnp.result_type(tv) != jnp.result_type(fv):
            raise ValueError(
                "cond branches must return matching shapes/dtypes: "
                f"{jnp.shape(tv)}/{jnp.result_type(tv)} vs {jnp.shape(fv)}/{jnp.result_type(fv)}"
            )
    captured = recorder.external_inputs()

    tree = t_tree

    def cond_val(pv_, *cap_vals):
        def run(fn):
            originals = [t._value for t in captured]
            try:
                for t, v in zip(captured, cap_vals):
                    t._bind(v)
                with no_grad():
                    flat, _ = _run_branch(fn)
                return tuple(flat)
            finally:
                for t, v in zip(captured, originals):
                    t._bind(v)

        return lax.cond(pv_ != 0, lambda _: run(true_fn), lambda _: run(false_fn), None)

    out_flat = apply("cond", cond_val, Tensor(pv, stop_gradient=True), *captured)
    if not isinstance(out_flat, (tuple, list)):
        out_flat = (out_flat,)
    return jax.tree_util.tree_unflatten(tree, list(out_flat))


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """Repeat body_fn while cond_fn(*vars) holds (reference while_loop).

    Differentiable when fully eager; under tracing it lowers to
    lax.while_loop, whose outputs are stop_gradient (see module docstring).
    """
    from paddle_tpu.static.program import current_main_program, in_static_capture

    if in_static_capture():
        from paddle_tpu._core.autograd import apply

        prog = current_main_program()
        loop_vars = list(loop_vars)
        n_loop = len(loop_vars)
        captured = [
            t for t in _discover_captures(
                [lambda: cond_fn(*loop_vars), lambda: body_fn(*loop_vars)], prog
            )
            if all(t is not lv for lv in loop_vars)
        ]

        def wl_replay(*vals):
            lvs = [Tensor(v) for v in vals[:n_loop]]
            originals = [t._value for t in captured]
            try:
                for t, v in zip(captured, vals[n_loop:]):
                    t._bind(v)
                res = while_loop(cond_fn, body_fn, lvs)
                return tuple(_unwrap(v) for v in res)
            finally:
                for t, v in zip(captured, originals):
                    t._bind(v)

        out = apply("while_loop", wl_replay, *loop_vars, *captured)
        return list(out) if isinstance(out, (tuple, list)) else [out]

    loop_vars = list(loop_vars)
    vals = [_unwrap(v) for v in loop_vars]

    traced = any(_is_tracer(v) for v in vals)
    if not traced:
        # probe the condition once; if concrete, run the pure-python loop
        c0 = cond_fn(*loop_vars)
        c0v = _pred_value(c0)
        if not _is_tracer(c0v):
            cur = loop_vars
            cont = bool(c0v)
            while cont:
                out = body_fn(*cur)
                cur = list(out) if isinstance(out, (tuple, list)) else [out]
                cont = bool(_pred_value(cond_fn(*cur)))
            return cur
        traced = True

    def to_val_tuple(vars_):
        return tuple(_unwrap(v) for v in vars_)

    def wrap_all(vals_):
        return [Tensor(v) for v in vals_]

    def c(vs):
        with no_grad():
            r = cond_fn(*wrap_all(vs))
        rv = _pred_value(r)
        return rv != 0 if rv.dtype != jnp.bool_ else rv

    def b(vs):
        with no_grad():
            out = body_fn(*wrap_all(vs))
        out = list(out) if isinstance(out, (tuple, list)) else [out]
        return tuple(_unwrap(v) for v in out)

    with no_grad():
        res = lax.while_loop(c, b, to_val_tuple(loop_vars))
    return [Tensor(v, stop_gradient=True) for v in res]


def case(pred_fn_pairs, default=None, name=None):
    """First pair whose pred holds wins (reference static/nn/control_flow.py
    case) — built as a nested cond chain."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")

    def build(pairs):
        (pred, fn), rest = pairs[0], pairs[1:]
        if not rest:
            if default is None:
                return cond(pred, fn, fn)
            return cond(pred, fn, default)
        return cond(pred, fn, lambda: build(rest))

    return build(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Dispatch on an integer index (reference switch_case)."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    iv = branch_index if isinstance(branch_index, Tensor) else Tensor(jnp.asarray(branch_index))

    def build(pairs):
        (idx, fn), rest = pairs[0], pairs[1:]
        pred = iv.equal(Tensor(jnp.asarray(idx, iv._value.dtype)))
        if not rest:
            if default is None:
                return cond(pred, fn, fn)
            return cond(pred, fn, default)
        return cond(pred, fn, lambda: build(rest))

    return build(items)


def Print(input, first_n=-1, message=None, **kwargs):  # noqa: N802
    """reference static Print op — host callback debug print."""
    msg = message or ""
    jax.debug.print(msg + "{x}", x=_unwrap(input))
    return input
