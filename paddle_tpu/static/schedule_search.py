"""Cost-model-driven Pallas schedule search over discovered subgraphs.

Reference: the CINN auto-scheduler role (paddle/cinn/auto_schedule/
auto_tuner.h — measured-cost search over schedule configs) rebuilt in the
TVM/Ansor shape (PAPERS.md: TVM, arXiv 1802.04799): instead of hand-picked
tile sizes per named kernel, DISCOVERED reduction- and matmul-rooted
subgraphs (static/rewrite.py ScheduleSearchPattern) get a searched Pallas
schedule.  The fusion-miss classes hunted here are the ones XLA is known to
leave on the table (PAPERS.md: "Operator Fusion in XLA", arXiv 2301.13062):
matmul→bias→act→reduce tails and softmax-adjacent reduction chains that no
named pattern matches.

Pipeline per subgraph (pruning order is part of the contract, see
docs/SCHEDULE_SEARCH.md):

1. **enumerate** candidate tilings — block shapes (block_rows × block_cols),
   grid layouts and dimension orders (rows-inner vs cols-inner sweep);
2. **roofline prune** (cost_model.device_peaks / flops_time): per-candidate
   HBM traffic is modeled from the grid geometry (a weight tile re-fetched
   per row-block vs an activation tile re-fetched per col-block depends on
   the dimension order), candidates worse than `roofline_margin` × the best
   analytic candidate are dropped;
3. **VMEM prune** (ops.autotune.validate_tile): candidates whose working
   set exceeds the per-core VMEM budget are dropped;
4. **measure** the top-K survivors (K = FLAGS_schedule_search_budget)
   on-device via cost_model.OpCostModel.measure;
5. **measured-win gate**: the best candidate races an XLA-only twin of the
   same subgraph; only a win by ≥ FLAGS_schedule_search_min_win is accepted.
   Winners AND losers persist through the per-device AutotuneCache
   (`schedule/*` kernel namespace in ops/tuned/<slug>.json) — a losing
   subgraph is recorded as *disabled* and never measured again on that
   device kind.

Semantics are guarded independently of the gate: under
FLAGS_verify_programs every accepted substitution is differentially
replayed against the unrewritten program (static/verify.py).

CPU/CI caveat: with the TPU tunnel down, kernels run in Pallas interpret
mode where XLA-only almost always wins — the gate then (correctly) disables
fusions.  Tests and the bench's --smoke twin inject a deterministic
`measure` callback instead (see `measure_override`), keeping the decision
logic falsifiable offline while the real measure path stays ready for the
tunnel's return.
"""

from __future__ import annotations

import contextlib
import hashlib
from dataclasses import dataclass, field

import numpy as np

__all__ = [
    "ExtInput",
    "SubgraphSpec",
    "match_subgraph",
    "enumerate_candidates",
    "candidate_vmem_bytes",
    "candidate_roofline_ms",
    "build_kernel",
    "build_reference",
    "Decision",
    "ScheduleSearcher",
    "measure_override",
    "schedule_search_stats",
    "reset_schedule_search_stats",
]

from ..framework.op_registry import base_op_type as _base_type

# ---------------------------------------------------------------------------
# counters (module-owned, surfaced via profiler.schedule_search_stats())

_COUNTERS = {
    "subgraphs_found": 0,     # fresh searches only (cache service counted
                              # separately in cache_hits / disabled_hits)
    "candidates": 0,          # tilings enumerated across all searches
    "pruned_roofline": 0,     # dropped by the analytic roofline ranking
    "pruned_vmem": 0,         # dropped by the VMEM working-set budget
    "measured": 0,            # candidates actually timed on device
    "pruned_parity": 0,       # candidates whose numerics failed the spec's
                              # parity gate vs the XLA twin (never measured)
    "accepted": 0,            # subgraphs whose best schedule beat XLA
    "disabled": 0,            # subgraphs recorded as losing (or unbuildable)
    "cache_hits": 0,          # accepted schedules served from the cache
    "disabled_hits": 0,       # disabled subgraphs skipped via the cache
}


def schedule_search_stats(reset: bool = False) -> dict:
    out = dict(_COUNTERS)
    if reset:
        reset_schedule_search_stats()
    return out


def reset_schedule_search_stats():
    for k in _COUNTERS:
        _COUNTERS[k] = 0


# ---------------------------------------------------------------------------
# op-class sets for the discovery tier

_REDUCE_OPS = {
    "sum", "nansum", "mean", "nanmean", "prod", "max", "min", "amax",
    "amin", "logsumexp",
}
# shape-preserving but last-axis-coupled (internal reduction): fusible as a
# row op, forbids tiling the reduced axis
_ROWWISE_OPS = {"softmax", "log_softmax"}
_MATMUL_OPS = {"matmul", "linear"}


@dataclass
class ExtInput:
    """One external input of a discovered subgraph.

    role: 'row'    — leading dims match the row shape; 2-D view (rows, cols)
          'xrow'   — a matmul's activation input: row-shaped leading dims
                     but its last dim is the CONTRACTION dim, so it is
                     never col-tiled (on square K == N shapes it is
                     indistinguishable from 'row' by cols alone)
          'bcast'  — all-leading-1 broadcast (e.g. a bias); view (1, cols)
          'weight' — a matmul's 2-D weight, resident per grid step
    """

    vid: int
    shape: tuple
    dtype: object
    cols: int
    role: str


@dataclass
class SubgraphSpec:
    """A discovered reduction-/matmul-rooted subgraph, ready to schedule."""

    kind: str               # 'matmul' | 'reduce'
    root: object            # downstream-end Operator (keeps its out vid)
    ops: list               # chain Operators in execution order
    ext: list               # ExtInput per external input, in first-use order
    out_vid: int
    out_shape: tuple
    out_cols: int           # last dim of the kernel's 2-D output (cols or 1)
    out_dtype: object
    rows: int
    cols: int
    k_dims: tuple           # matmul inner dims, in chain order
    has_reduce: bool
    col_tilable: bool       # the reduced axis may be tiled (no reduce/rowwise)
    k_tilable: bool = False  # the contraction dim may be tiled (single
                             # matmul whose x/w feed no other chain op)
    sig: str = ""

    def __post_init__(self):
        if not self.sig:
            parts = [
                ",".join(_base_type(op.type) for op in self.ops),
                ";".join(f"{e.role}{e.cols}" for e in self.ext),
                repr(self.out_shape),
            ]
            self.sig = hashlib.sha1("|".join(parts).encode()).hexdigest()[:10]

    def kernel_name(self) -> str:
        return f"schedule/{self.kind}"

    def key(self) -> dict:
        return {
            "rows": self.rows,
            "cols": self.cols,
            "k": "x".join(str(k) for k in self.k_dims) or "0",
            "sig": self.sig,
            "dtype": np.dtype(self.out_dtype).name,
        }

    def label(self) -> str:
        from paddle_tpu.ops.autotune import _key_str

        return f"{self.kernel_name()}|{_key_str(self.key())}"

    # ---- searcher protocol (shared with ops.decode_chain.DecodeChainSpec:
    # the ScheduleSearcher drives any spec through these six hooks) -------
    check_parity = False  # Program subgraphs rely on differential_check

    def enumerate_configs(self):
        return enumerate_candidates(self)

    def roofline_ms(self, config, cost_model=None):
        return candidate_roofline_ms(self, config, cost_model)

    def vmem_bytes(self, config):
        return candidate_vmem_bytes(self, config)

    def build(self, config):
        return build_kernel(self, config)

    def reference(self):
        return build_reference(self)

    def synthetic_args(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        return tuple(
            jnp.asarray(rng.standard_normal(e.shape), e.dtype)
            for e in self.ext)

    def parity_ok(self, fn, args, reference_out):  # noqa: ARG002
        return True

    def config_label(self, config):
        lbl = (f"#{config['block_rows']}x{config['block_cols']}"
               f"@{config['grid_order']}")
        bk = config.get("block_k")
        if bk and self.k_dims and bk < self.k_dims[0]:
            lbl += f"k{bk}"
        return lbl


# ---------------------------------------------------------------------------
# discovery


def _entry_shape(graph, entry):
    if entry[0] == "var":
        return graph.shape(entry[1])
    try:
        return tuple(np.shape(entry[1]))
    except Exception:
        return None


def _const_ok(value, cols):
    """Consts are baked inside recorded op fns: only scalars and rank<=2
    last-dim broadcasts replay correctly on 2-D row blocks."""
    try:
        arr = np.asarray(value)
    except Exception:
        return False
    if arr.size == 1:
        return True
    if arr.ndim > 2:
        return False
    return all(d == 1 for d in arr.shape[:-1]) and arr.shape[-1] in (1, cols)


def _wide_const(value, cols):
    try:
        arr = np.asarray(value)
    except Exception:
        return False
    return arr.size > 1 and arr.ndim >= 1 and arr.shape[-1] == cols


def _reduces_last_axis(op, row_shape, keepdim_only):
    """True iff the op's BAKED reduction axis is the last one.  Shapes alone
    cannot tell: on square dims (S == C) an axis=1 reduction's output shape
    coincides with a last-axis reduction's — fusing it would replay the
    baked axis on the collapsed 2-D block and reduce the wrong dimension.
    Recorded reduce ops take exactly one tensor and close over no other
    shaped values, so probing the fn at an all-distinct-dims aval is safe:
    only a last-axis reduction maps probe -> probe[:-1] (+ keepdim 1)."""
    import jax

    probe = tuple(range(2, 2 + len(row_shape) - 1)) + (2 + len(row_shape),)
    try:
        out = jax.eval_shape(
            op.fn, jax.ShapeDtypeStruct(probe, np.float32))
        flat = jax.tree_util.tree_leaves(out)
    except Exception:
        return False
    if len(flat) != 1:
        return False
    shape = tuple(flat[0].shape)
    if shape == probe[:-1] + (1,):
        return True
    return not keepdim_only and shape == probe[:-1]


def _classify(op, graph, row_shape, root=None):
    """-> 'elem' | 'rowwise' | 'reduce' | 'matmul' | None (not fusible)."""
    from ..framework.op_registry import side_effect_op_types

    from .rewrite import _ELEMENTWISE

    b = _base_type(op.type)
    if b in side_effect_op_types():
        return None  # dropout/RNG/print/collectives: never cross
    if not op.out_vids or len(op.out_vids) != 1:
        return None
    o = graph.shape(op.out_vids[0])
    if o is None:
        return None
    reduced = row_shape[:-1] + (1,)
    cols = row_shape[-1]
    if b in _MATMUL_OPS:
        if op.kwargs.get("transpose_x") or op.kwargs.get("transpose_y"):
            return None
        if o != row_shape or len(op.arg_spec) not in (2, 3):
            return None
        x_e, w_e = op.arg_spec[0], op.arg_spec[1]
        if x_e[0] != "var":
            return None
        xs = graph.shape(x_e[1])
        if xs is None or len(xs) < 2 or xs[:-1] != row_shape[:-1]:
            return None
        ws = _entry_shape(graph, w_e)
        if not ws or len(ws) != 2 or ws != (xs[-1], cols):
            return None
        if len(op.arg_spec) == 3 and _entry_shape(graph, op.arg_spec[2]) != (cols,):
            return None
        return "matmul"
    if b in _REDUCE_OPS:
        ins = [s for s in op.arg_spec if s[0] == "var"]
        if len(ins) != 1 or len(op.arg_spec) != 1:
            return None
        if graph.shape(ins[0][1]) != row_shape:
            return None
        if o != reduced and not (op is root and o == row_shape[:-1]):
            return None  # non-keepdim only at the root (reshaped at the end)
        if not _reduces_last_axis(op, row_shape, keepdim_only=(o == reduced)):
            return None  # baked axis is not the last one (square-dims trap)
        return "reduce"
    if b in _ROWWISE_OPS:
        ax = op.kwargs.get("axis", -1)
        if ax not in (-1, len(row_shape) - 1):
            return None
        ins = [s for s in op.arg_spec if s[0] == "var"]
        if len(ins) != 1 or graph.shape(ins[0][1]) != row_shape or o != row_shape:
            return None
        return "rowwise"
    if b in _ELEMENTWISE:
        if o not in (row_shape, reduced):
            return None
        oc = o[-1]
        for s in op.arg_spec:
            if s[0] == "var":
                vs = graph.shape(s[1])
                if vs is None:
                    return None
                bcast = (len(vs) >= 1 and all(d == 1 for d in vs[:-1])
                         and vs[-1] in (1, oc))
                if vs not in (row_shape, reduced) and not bcast:
                    return None
            elif not _const_ok(s[1], cols):
                return None
        return "elem"
    return None


def _extends(consumer, graph, row_shape):
    """Would `consumer` continue this chain?  Used to anchor discovery at
    the downstream END only — interior ops stand down so the maximal
    subgraph is searched once, not every suffix of it."""
    from .rewrite import _ELEMENTWISE

    b = _base_type(consumer.type)
    if not consumer.out_vids or len(consumer.out_vids) != 1:
        return False
    o = graph.shape(consumer.out_vids[0])
    if b in _ELEMENTWISE or b in _ROWWISE_OPS:
        return o == row_shape
    if b in _REDUCE_OPS:
        return o in (row_shape[:-1] + (1,), row_shape[:-1])
    return False


def match_subgraph(root, graph, min_ops=2):
    """Anchor at `root` (downstream end) and collect the maximal fusible
    reduction-/matmul-rooted subgraph feeding it; None when `root` is not a
    viable anchor.

    Interior links require every consumer of a value to sit inside the
    chain (DAG discovery — manual softmax's exp feeds both the sum and the
    divide).  Fetch-frontier/write-visible interior values are deliberately
    NOT checked here: the PatternRewritePass use-def rollback (PR 4) is the
    authoritative refusal path and counts them in `.refused`."""
    import jax.numpy as jnp

    from .rewrite import _ELEMENTWISE

    base = _base_type(root.type)
    if not root.out_vids or len(root.out_vids) != 1:
        return None
    out_shape = graph.shape(root.out_vids[0])
    if out_shape is None:
        return None

    if base in _REDUCE_OPS:
        ins = [s for s in root.arg_spec if s[0] == "var"]
        if len(ins) != 1:
            return None
        row_shape = graph.shape(ins[0][1])
        if row_shape is None or len(row_shape) < 2:
            return None
        if out_shape not in (row_shape[:-1], row_shape[:-1] + (1,)):
            return None
    elif base in _ELEMENTWISE or base in _ROWWISE_OPS:
        row_shape = out_shape
        if len(row_shape) < 2 or row_shape[-1] < 2:
            return None
    else:
        return None

    root_kind = _classify(root, graph, row_shape, root=root)
    if root_kind is None:
        return None
    # downstream-END anchor: if every consumer would extend the chain, some
    # later op is the true root — stand down here
    cons = graph.consumers.get(root.out_vids[0], [])
    if cons and all(_extends(c, graph, row_shape) for c in cons):
        return None

    chain = {id(root): root}
    kinds = {id(root): root_kind}
    changed = True
    while changed:
        changed = False
        for op in list(chain.values()):
            if kinds[id(op)] == "matmul":
                continue  # matmul is an origin: its x input stays external
            for s in op.arg_spec:
                if s[0] != "var":
                    continue
                vid = s[1]
                prod = graph.producer.get(vid)
                if prod is None or id(prod) in chain:
                    continue
                vcons = graph.consumers.get(vid, [])
                if not all(id(c) in chain for c in vcons):
                    continue
                k = _classify(prod, graph, row_shape, root=root)
                if k is None:
                    continue
                chain[id(prod)] = prod
                kinds[id(prod)] = k
                changed = True

    ordered = [op for op in graph.block.ops if id(op) in chain]
    if len(ordered) < min_ops:
        return None
    n_mm = sum(1 for op in ordered if kinds[id(op)] == "matmul")
    n_red = sum(1 for op in ordered if kinds[id(op)] == "reduce")
    n_row = sum(1 for op in ordered if kinds[id(op)] == "rowwise")
    if n_mm + n_red + n_row == 0:
        return None  # plain elementwise chain: GenericElementwiseFusionPass's job
    if n_mm and len(ordered) == n_mm:
        return None  # a bare matmul is XLA's bread and butter

    rows = int(np.prod(row_shape[:-1]))
    cols = int(row_shape[-1])
    out_var = graph.program._var_by_vid.get(root.out_vids[0])
    if out_var is None or not jnp.issubdtype(out_var._value.dtype, jnp.inexact):
        return None

    produced = {vid for op in ordered for vid in op.out_vids}
    mm_slots = {}  # vid -> role hint from matmul operand positions
    for op in ordered:
        if kinds[id(op)] == "matmul":
            specs = op.arg_spec
            mm_slots[specs[0][1]] = "xrow"
            if specs[1][0] == "var":
                mm_slots[specs[1][1]] = "weight"
            if len(specs) == 3 and specs[2][0] == "var":
                mm_slots[specs[2][1]] = "bcast"
    reduced_shape = row_shape[:-1] + (1,)
    ext, seen = [], set()
    k_dims = []
    for op in ordered:
        if kinds[id(op)] == "matmul":
            k_dims.append(int(graph.shape(op.arg_spec[0][1])[-1]))
        for s in op.arg_spec:
            if s[0] != "var" or s[1] in produced or s[1] in seen:
                continue
            vid = s[1]
            vs = graph.shape(vid)
            var = graph.program._var_by_vid.get(vid)
            if var is None or vs is None:
                return None
            dt = var._value.dtype
            if not jnp.issubdtype(dt, jnp.inexact):
                return None
            role = mm_slots.get(vid)
            if role is None:
                if vs in (row_shape, reduced_shape):
                    role = "row"
                elif all(d == 1 for d in vs[:-1]):
                    role = "bcast"
                else:
                    return None
            ext.append(ExtInput(vid, vs, dt, int(vs[-1]), role))
            seen.add(vid)
    if not ext:
        return None

    wide_consts = any(
        s[0] == "const" and _wide_const(s[1], cols)
        for op in ordered for s in op.arg_spec)
    # an xrow consumed by a NON-matmul chain op (possible only on square
    # K == N shapes) would mix an untiled (br, K) block with tiled (br, bc)
    # blocks inside the kernel — forbid col tiling then
    xrow_vids = {e.vid for e in ext if mm_slots.get(e.vid) == "xrow"}
    xrow_in_elem = any(
        s[0] == "var" and s[1] in xrow_vids
        for op in ordered if kinds[id(op)] != "matmul"
        for s in op.arg_spec)
    col_tilable = (n_mm > 0 and n_red == 0 and n_row == 0 and not wide_consts
                   and not xrow_in_elem
                   and all(e.role != "weight" or e.cols == cols for e in ext))
    # K-loop tiling (phase 2): a SINGLE matmul whose x AND w are chain-
    # external vars feeding nothing but the matmul itself — the kernel
    # then carries an f32 accumulator across contraction grid steps and
    # replays the epilogue on the last one.  On K == M / K == N aliasing
    # shapes a weight or activation consumed by an elementwise op would
    # mix k-sliced blocks with row blocks, so those chains stay untiled.
    mm_vids = xrow_vids | {e.vid for e in ext
                           if mm_slots.get(e.vid) == "weight"}
    mm_ext_in_elem = any(
        s[0] == "var" and s[1] in mm_vids
        for op in ordered if kinds[id(op)] != "matmul"
        for s in op.arg_spec)
    k_tilable = (n_mm == 1 and not mm_ext_in_elem
                 and any(e.role == "weight" for e in ext))

    out_cols = cols if out_shape == row_shape else 1
    return SubgraphSpec(
        kind="matmul" if n_mm else "reduce",
        root=root,
        ops=ordered,
        ext=ext,
        out_vid=root.out_vids[0],
        out_shape=tuple(out_shape),
        out_cols=out_cols,
        out_dtype=out_var._value.dtype,
        rows=rows,
        cols=cols,
        k_dims=tuple(k_dims),
        has_reduce=n_red > 0 or n_row > 0,
        col_tilable=col_tilable,
        k_tilable=k_tilable,
    )


# ---------------------------------------------------------------------------
# schedule space


def enumerate_candidates(spec: SubgraphSpec):
    """Candidate tilings: block shapes, grid layouts, dimension orders —
    and, for K-tilable matmul chains, contraction-dim splits.

    Row blocks are multiples of 8 (f32 sublane).  The reduced axis is tiled
    only for reduction-free matmul chains (a per-block partial reduction
    would be wrong; a rowwise op needs its whole row).  Dimension order
    (which grid axis sweeps innermost) matters whenever the grid is 2-D:
    it decides whether weight tiles or activation tiles get re-fetched.
    K-tiled candidates carry ``block_k`` and always place the contraction
    axis INNERMOST (the f32 accumulator block then stays VMEM-resident
    across its revisits; with K innermost both operands re-stream the same
    under either outer order, so only one order is enumerated)."""
    rows, cols = spec.rows, spec.cols
    brs = [b for b in (8, 16, 32, 64, 128, 256, 512)
           if b <= rows and rows % b == 0] or [rows]
    if spec.col_tilable:
        bcs = [b for b in (128, 256, 512) if b < cols and cols % b == 0]
        bcs.append(cols)
    else:
        bcs = [cols]
    K = spec.k_dims[0] if spec.k_dims else 0
    if spec.k_tilable and K:
        bks = [b for b in (128, 256, 512) if b < K and K % b == 0]
        bks.append(K)
    else:
        bks = [None]
    out = []
    for br in brs:
        for bc in bcs:
            for bk in bks:
                orders = ["rows_first"]
                split = bk is not None and bk < K  # K innermost: no 2nd order
                if not split and bc != cols and rows // br > 1:
                    orders.append("cols_first")
                for od in orders:
                    cfg = {"block_rows": br, "block_cols": bc,
                           "grid_order": od}
                    if bk is not None:
                        cfg["block_k"] = bk
                    out.append(cfg)
    return out


def _grid_dims(spec, config):
    br, bc = int(config["block_rows"]), int(config["block_cols"])
    return br, bc, spec.rows // br, spec.cols // bc


def _k_split(spec, config):
    """(block_k, grid_k) — (K, 1) when the candidate keeps the contraction
    resident (incl. legacy cached configs with no block_k entry)."""
    K = spec.k_dims[0] if spec.k_dims else 0
    bk = int(config.get("block_k") or 0)
    if spec.k_tilable and K and bk and bk < K:
        return bk, K // bk
    return K, 1


def candidate_vmem_bytes(spec: SubgraphSpec, config: dict) -> int:
    """f32 working-set estimate for one grid step (double-buffered): all
    input blocks + the output block + one block-sized temp per chain op.
    A K-tiled candidate holds (br, bk) activation and (bk, bc) weight
    slices plus the f32 accumulator block instead of whole-K operands —
    the split that lets large-K matmul chains fit the budget at all."""
    br, bc, _, _ = _grid_dims(spec, config)
    bk, gk = _k_split(spec, config)
    tiled = bc != spec.cols
    elems = br * (bc if (tiled and spec.out_cols == spec.cols) else spec.out_cols)
    widest = spec.out_cols
    for e in spec.ext:
        ec = bc if (tiled and e.cols == spec.cols
                    and e.role != "xrow") else e.cols
        if e.role == "xrow":
            elems += br * (bk if gk > 1 else ec)
        elif e.role == "row":
            elems += br * ec
        elif e.role == "bcast":
            elems += ec
        else:  # weight: whole-K resident per step unless K is tiled
            elems += (bk if gk > 1 else e.shape[0]) * ec
        widest = max(widest, ec)
    if gk > 1:
        elems += br * (bc if tiled else spec.cols)  # f32 accumulator block
    elems += len(spec.ops) * br * max(widest, bc if tiled else spec.cols)
    return int(elems) * 4 * 2


# Per-grid-step pipeline/dispatch overhead for the analytic ranking
# (~100ns: the scale of one Mosaic grid-step turnaround).  Matters for
# 1-D grids, where traffic and flops are block_rows-independent and would
# otherwise tie every candidate — the stable sort would then measure only
# the smallest blocks and the budget cutoff could skip the large-block
# schedules that actually feed the MXU/VPU well.
_GRID_STEP_OVERHEAD_S = 1e-7


def candidate_roofline_ms(spec: SubgraphSpec, config: dict,
                          cost_model=None) -> float:
    """Roofline estimate (cost_model.flops_time over device_peaks) with
    per-candidate HBM traffic from the grid geometry: a block whose index
    map is constant across the INNER grid axis is fetched once per outer
    step; one that changes every inner step is re-fetched each time.
    A small per-grid-step overhead term breaks ties between candidates
    whose traffic is identical (1-D grids)."""
    if cost_model is None:
        from paddle_tpu.cost_model import OpCostModel

        cost_model = OpCostModel()
    br, bc, gm, gn = _grid_dims(spec, config)
    bk, gk = _k_split(spec, config)
    rows, cols = spec.rows, spec.cols
    order = config.get("grid_order", "rows_first")
    tiled = gn > 1

    flops = 0.0
    for k in spec.k_dims:
        flops += 2.0 * rows * k * cols
    flops += (len(spec.ops) - len(spec.k_dims)) * rows * cols

    traffic = float(np.prod(spec.out_shape)) * np.dtype(spec.out_dtype).itemsize
    if gk > 1:
        # the f32 accumulator rides an extra HBM-backed output (written
        # once per (i, j) tile) — K-tiling is not free and must rank so
        traffic += float(rows * cols) * 4
    for e in spec.ext:
        sz = float(np.prod(e.shape)) * np.dtype(e.dtype).itemsize
        if gk > 1 and e.role == "xrow":
            # with K innermost the activation's (i, k) slices re-stream
            # once per column block — the whole-K residency that made x
            # fetch-once is exactly what the split gives up
            traffic += sz * gn
            continue
        if gk > 1 and e.role == "weight":
            traffic += sz * gm  # (k, j) slices re-stream per row block
            continue
        j_indexed = tiled and e.cols == cols and e.role in ("bcast", "weight")
        i_only = (e.role == "xrow"
                  or (e.role == "row" and not (tiled and e.cols == cols)))
        if j_indexed:
            traffic += sz * (gm if order == "rows_first" else 1)
        elif i_only:
            traffic += sz * (gn if order == "cols_first" else 1)
        else:
            traffic += sz  # each block visited exactly once
    return (cost_model.flops_time(flops, traffic)
            + gm * gn * gk * _GRID_STEP_OVERHEAD_S) * 1e3


# ---------------------------------------------------------------------------
# codegen


def build_reference(spec: SubgraphSpec):
    """Replay the recorded op fns on the given external inputs — ONE
    definition of the subgraph's semantics, shared by the XLA-only twin
    (the measured-win gate's baseline and numerics oracle, fed
    ORIGINAL-shaped inputs) and the kernel's block-level trace
    (_chain_body, fed block-shaped inputs)."""
    ext_vids = [e.vid for e in spec.ext]

    def ref(*vals):
        import jax

        env = dict(zip(ext_vids, vals))
        for op in spec.ops:
            var_vals = [env[s[1]] for s in op.arg_spec if s[0] == "var"]
            out = op.fn(*var_vals)
            for vid, v in zip(op.out_vids, jax.tree_util.tree_leaves(out)):
                env[vid] = v
        return env[spec.out_vid]

    return ref


def _chain_body(spec):
    """build_reference's replay at block shape, plus the block-level
    normalization of a non-keepdim root reduction to 2-D."""
    ref = build_reference(spec)

    def body(*vals):
        r = ref(*vals)
        if r.ndim == 1:
            r = r.reshape(r.shape[0], 1)
        return r

    return body


def _epilogue_body(spec, mm_op, mm_dtype):
    """The chain replay with the matmul's output SUBSTITUTED: the K-tiled
    kernel accumulates x@w across contraction grid steps and feeds the
    finished accumulator here on the last one.  A 3-arg matmul/linear adds
    its bias now (the partial products must sum before the epilogue)."""
    import jax
    import jax.numpy as jnp

    ext_vids = [e.vid for e in spec.ext]

    def body(mm_out, *vals):
        env = dict(zip(ext_vids, vals))
        for op in spec.ops:
            if op is mm_op:
                r = mm_out
                if len(op.arg_spec) == 3:
                    s = op.arg_spec[2]
                    bv = env[s[1]] if s[0] == "var" else jnp.asarray(s[1])
                    r = r + bv
                env[op.out_vids[0]] = r.astype(mm_dtype)
            else:
                var_vals = [env[s[1]] for s in op.arg_spec if s[0] == "var"]
                out = op.fn(*var_vals)
                for vid, v in zip(op.out_vids,
                                  jax.tree_util.tree_leaves(out)):
                    env[vid] = v
        r = env[spec.out_vid]
        if r.ndim == 1:
            r = r.reshape(r.shape[0], 1)
        return r

    return body


def _build_kernel_ktiled(spec: SubgraphSpec, config: dict):
    """K-tiled variant of build_kernel: grid (gm, gn, gk) with the
    contraction axis INNERMOST, an f32 accumulator carried across the k
    revisits as an extra (i, j)-indexed output, and the epilogue (every
    chain op beyond the matmul) replayed once on the final k step.  Only
    (br, bk) activation and (bk, bc) weight slices are VMEM-resident per
    step — large-K matmul chains fit the budget instead of being
    auto-disabled."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from paddle_tpu.ops._pl_utils import imap

    br, bc, gm, gn = _grid_dims(spec, config)
    bk, gk = _k_split(spec, config)
    rows, cols = spec.rows, spec.cols
    tiled = gn > 1
    mm_op = next(op for op in spec.ops
                 if _base_type(op.type) in _MATMUL_OPS)
    xrow_i = next(i for i, e in enumerate(spec.ext) if e.role == "xrow")
    w_i = next(i for i, e in enumerate(spec.ext) if e.role == "weight")
    mm_dtype = jnp.result_type(spec.ext[xrow_i].dtype, spec.ext[w_i].dtype)

    def view2d(e, v):
        if e.role in ("row", "xrow"):
            return v.reshape(rows, e.cols)
        if e.role == "bcast":
            return v.reshape(1, e.cols)
        return v  # weight: already 2-D

    def block_shape(e):
        if e.role == "xrow":
            return (br, bk)
        if e.role == "row":
            return (br, bc) if (tiled and e.cols == cols) else (br, e.cols)
        if e.role == "bcast":
            return (1, bc) if (tiled and e.cols == cols) else (1, e.cols)
        return (bk, bc) if tiled else (bk, e.cols)  # weight

    def index_fn(e):
        if e.role == "xrow":
            return lambda i, j, k: (i, k)
        if e.role == "weight":
            return lambda i, j, k: (k, j)  # j fixed 0 when untiled cols
        if e.role == "row":
            if tiled and e.cols == cols:
                return lambda i, j, k: (i, j)
            return lambda i, j, k: (i, 0)
        if tiled and e.cols == cols:  # bcast sliced along cols
            return lambda i, j, k: (0, j)
        return lambda i, j, k: (0, 0)

    acc_block = (br, bc if tiled else cols)
    out_block = (br, bc if (tiled and spec.out_cols == cols)
                 else spec.out_cols)
    ij = imap(lambda i, j, k: (i, j))

    block_avals = [jax.ShapeDtypeStruct(block_shape(e), e.dtype)
                   for e in spec.ext]
    acc_aval = jax.ShapeDtypeStruct(acc_block, mm_dtype)
    closed = jax.make_jaxpr(_epilogue_body(spec, mm_op, mm_dtype))(
        acc_aval, *block_avals)
    np_consts = [np.asarray(c) for c in closed.consts]
    n_in = len(spec.ext)

    def kernel(*refs):
        ins, o_ref, acc_ref = refs[:n_in], refs[n_in], refs[n_in + 1]
        k = pl.program_id(2)

        @pl.when(k == 0)
        def _zero():
            acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)

        acc_ref[...] += jnp.dot(
            ins[xrow_i][...].astype(jnp.float32),
            ins[w_i][...].astype(jnp.float32),
            preferred_element_type=jnp.float32)

        @pl.when(k == gk - 1)
        def _epilogue():
            out = jax.core.eval_jaxpr(
                closed.jaxpr, np_consts,
                acc_ref[...].astype(mm_dtype),
                *(r[...] for r in ins))[0]
            o_ref[...] = out.astype(o_ref.dtype)

    in_specs = [pl.BlockSpec(block_shape(e), imap(index_fn(e)))
                for e in spec.ext]
    out_specs = [pl.BlockSpec(out_block, ij), pl.BlockSpec(acc_block, ij)]
    out_shape = [
        jax.ShapeDtypeStruct((rows, spec.out_cols), spec.out_dtype),
        jax.ShapeDtypeStruct((rows, cols), jnp.float32),
    ]

    def fused(*vals):
        flat = [view2d(e, v) for e, v in zip(spec.ext, vals)]
        out, _acc = pl.pallas_call(
            kernel,
            grid=(gm, gn, gk),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=jax.default_backend() != "tpu",
        )(*flat)
        return out.reshape(spec.out_shape)

    return fused


def build_kernel(spec: SubgraphSpec, config: dict):
    """One Pallas kernel for the whole subgraph at `config`'s tiling: the
    recorded op fns are pre-traced at block shape (jax.make_jaxpr, closure
    constants baked as numpy — Pallas kernels may not capture traced
    arrays) and replayed over VMEM blocks, so an N-op chain makes one HBM
    round trip.  Returns a callable over ORIGINAL-shaped external inputs.
    Candidates carrying a genuine ``block_k`` split route to the K-tiled
    accumulator variant (_build_kernel_ktiled)."""
    import jax
    from jax.experimental import pallas as pl

    from paddle_tpu.ops._pl_utils import imap

    if _k_split(spec, config)[1] > 1:
        return _build_kernel_ktiled(spec, config)

    br, bc, gm, gn = _grid_dims(spec, config)
    rows, cols = spec.rows, spec.cols
    order = config.get("grid_order", "rows_first")
    tiled = gn > 1

    def view2d(e, v):
        if e.role in ("row", "xrow"):
            return v.reshape(rows, e.cols)
        if e.role == "bcast":
            return v.reshape(1, e.cols)
        return v  # weight: already 2-D

    def block_shape(e):
        if e.role == "xrow":  # contraction dim: never col-sliced
            return (br, e.cols)
        if e.role == "row":
            return (br, bc) if (tiled and e.cols == cols) else (br, e.cols)
        if e.role == "bcast":
            return (1, bc) if (tiled and e.cols == cols) else (1, e.cols)
        return (e.shape[0], bc) if (tiled and e.cols == cols) else tuple(e.shape)

    def index_fn(e):
        if e.role == "xrow":
            return lambda i, j: (i, 0)
        if e.role == "row":
            if tiled and e.cols == cols:
                return lambda i, j: (i, j)
            return lambda i, j: (i, 0)
        if tiled and e.cols == cols:  # bcast/weight sliced along cols
            return lambda i, j: (0, j)
        return lambda i, j: (0, 0)

    out_tiled = tiled and spec.out_cols == cols
    out_block = (br, bc if out_tiled else spec.out_cols)
    out_index = (lambda i, j: (i, j)) if out_tiled else (lambda i, j: (i, 0))

    # grid layout + dimension order: the kernel's index maps receive grid
    # coordinates in grid order; `wrap` restores (row_block, col_block)
    if gn > 1 and order == "cols_first":
        grid = (gn, gm)
        def wrap(f):
            return imap(lambda a, b: f(b, a))
    elif gn > 1:
        grid = (gm, gn)
        def wrap(f):
            return imap(lambda a, b: f(a, b))
    else:
        grid = (gm,)
        def wrap(f):
            return imap(lambda a: f(a, 0))

    block_avals = [jax.ShapeDtypeStruct(block_shape(e), e.dtype)
                   for e in spec.ext]
    closed = jax.make_jaxpr(_chain_body(spec))(*block_avals)
    np_consts = [np.asarray(c) for c in closed.consts]
    n_in = len(spec.ext)

    def kernel(*refs):
        ins, o_ref = refs[:n_in], refs[n_in]
        out = jax.core.eval_jaxpr(
            closed.jaxpr, np_consts, *(r[:] for r in ins))[0]
        o_ref[:] = out.astype(o_ref.dtype)

    in_specs = [pl.BlockSpec(block_shape(e), wrap(index_fn(e)))
                for e in spec.ext]
    out_specs = pl.BlockSpec(out_block, wrap(out_index))

    def fused(*vals):
        flat = [view2d(e, v) for e, v in zip(spec.ext, vals)]
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=jax.ShapeDtypeStruct((rows, spec.out_cols),
                                           spec.out_dtype),
            interpret=jax.default_backend() != "tpu",
        )(*flat)
        return out.reshape(spec.out_shape)

    return fused


# ---------------------------------------------------------------------------
# the searcher + measured-win gate

_MEASURE_OVERRIDE = None


@contextlib.contextmanager
def measure_override(fn):
    """Route every schedule measurement through `fn(run, args, *, label,
    config)` -> ms.  config is None for the XLA-only twin.  Tests and the
    bench --smoke twin use this for deterministic CPU decisions."""
    global _MEASURE_OVERRIDE
    prev, _MEASURE_OVERRIDE = _MEASURE_OVERRIDE, fn
    try:
        yield
    finally:
        _MEASURE_OVERRIDE = prev


@dataclass
class Decision:
    """Outcome of one subgraph search."""

    status: str             # accepted | disabled | cache | cache_disabled
    config: dict | None = None
    pallas_ms: float = 0.0
    xla_ms: float = 0.0
    win: float = 0.0

    @property
    def accepted(self) -> bool:
        return self.status in ("accepted", "cache")


class ScheduleSearcher:
    """Enumerate → roofline-prune → VMEM-prune → measure → gate → persist.

    measure(fn, args, *, label, config) -> ms overrides the default
    OpCostModel.measure timing (deterministic tests / bench smoke)."""

    def __init__(self, cost_model=None, measure=None, budget=None,
                 min_win=None, roofline_margin=1.5, iters=3, warmup=1):
        from paddle_tpu._core import flags

        if cost_model is None:
            from paddle_tpu.cost_model import OpCostModel

            cost_model = OpCostModel()
        self.cost_model = cost_model
        self._measure = measure
        self.budget = (int(flags.flag("FLAGS_schedule_search_budget"))
                       if budget is None else int(budget))
        self.min_win = (float(flags.flag("FLAGS_schedule_search_min_win"))
                        if min_win is None else float(min_win))
        self.roofline_margin = float(roofline_margin)
        self.iters = int(iters)
        self.warmup = int(warmup)

    # ----------------------------------------------------------- plumbing
    def _measure_ms(self, label, fn, args, config):
        cb = _MEASURE_OVERRIDE or self._measure
        if cb is not None:
            return float(cb(fn, args, label=label, config=config))
        return self.cost_model.measure(
            label, fn, *args, iters=self.iters, warmup=self.warmup) * 1e3

    @staticmethod
    def _synthetic_args(spec):
        return spec.synthetic_args()

    @staticmethod
    def _cached(spec):
        from paddle_tpu.ops import autotune as at

        return at.lookup(spec.kernel_name(), spec.key())

    def _persist(self, spec, config, ms, meta):
        from paddle_tpu._core import flags
        from paddle_tpu.ops import autotune as at

        if not flags.flag("FLAGS_use_autotune_cache"):
            return  # cache disabled: decisions stay process-local
        c = at.cache()
        c.put(spec.kernel_name(), spec.key(), config, ms, meta=meta)
        c.save()

    # -------------------------------------------------------------- search
    def search(self, spec) -> Decision:
        """Drive any spec implementing the searcher protocol — a Program
        SubgraphSpec or an ops.decode_chain.DecodeChainSpec — through
        enumerate → roofline → VMEM → (parity) → measure → gate →
        persist.  Specs with ``check_parity`` have every candidate's
        numerics compared against the XLA twin BEFORE it may be measured:
        a candidate that fails parity can never be accepted, however fast
        (Program specs instead rely on the differential replay under
        FLAGS_verify_programs)."""
        cached = self._cached(spec)
        if cached is not None:
            if cached.get("disabled"):
                _COUNTERS["disabled_hits"] += 1
                return Decision("cache_disabled")
            _COUNTERS["cache_hits"] += 1
            return Decision("cache", cached)

        import jax

        _COUNTERS["subgraphs_found"] += 1
        args = spec.synthetic_args()
        candidates = spec.enumerate_configs()
        _COUNTERS["candidates"] += len(candidates)

        ranked = [(spec.roofline_ms(c, self.cost_model), c)
                  for c in candidates]
        best_roof = min(r for r, _ in ranked)
        kept = [(r, c) for r, c in ranked
                if r <= best_roof * self.roofline_margin]
        _COUNTERS["pruned_roofline"] += len(ranked) - len(kept)

        from paddle_tpu.ops.autotune import validate_tile

        fit = [(r, c) for r, c in kept
               if validate_tile(spec.vmem_bytes(c)) is None]
        _COUNTERS["pruned_vmem"] += len(kept) - len(fit)

        fit.sort(key=lambda rc: rc[0])

        ref_fn = jax.jit(spec.reference())
        ref_out = None
        best_cfg, best_ms = None, float("inf")
        budget_left = max(1, self.budget)
        for _, cfg in fit:
            if budget_left <= 0:
                break
            try:
                fn = jax.jit(spec.build(cfg))
                if spec.check_parity:
                    if ref_out is None:
                        ref_out = ref_fn(*args)
                    if not spec.parity_ok(fn, args, ref_out):
                        # wrong numerics beat nothing: rejected before any
                        # timing, without burning a measure-budget slot
                        _COUNTERS["pruned_parity"] += 1
                        continue
                ms = self._measure_ms(
                    spec.label() + spec.config_label(cfg), fn, args, cfg)
            except Exception:
                # unbuildable/unrunnable on this backend: does NOT burn a
                # budget slot — a later buildable candidate still gets
                # measured instead of the subgraph being disabled outright
                continue
            _COUNTERS["measured"] += 1
            budget_left -= 1
            if ms < best_ms:
                best_cfg, best_ms = dict(cfg), float(ms)

        if best_cfg is None:
            # nothing built/ran on this backend: a code-level or transient
            # failure, NOT a measured loss — do not persist, so a later
            # version whose builder handles this subgraph gets to retry
            _COUNTERS["disabled"] += 1
            return Decision("disabled")

        xla_ms = float(self._measure_ms(
            f"{spec.label()}#xla", ref_fn, args, None))
        win = xla_ms / best_ms if best_ms > 0 else 0.0
        meta = {"win": round(win, 4), "xla_ms": round(xla_ms, 6)}
        if win >= self.min_win:
            self._persist(spec, best_cfg, best_ms, meta)
            _COUNTERS["accepted"] += 1
            return Decision("accepted", best_cfg, best_ms, xla_ms, win)
        self._persist(spec, {"disabled": True}, best_ms, meta)
        _COUNTERS["disabled"] += 1
        return Decision("disabled", None, best_ms, xla_ms, win)
