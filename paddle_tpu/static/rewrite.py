"""Pattern rewriting over captured Programs — the DRR/pattern_match role.

Reference: paddle/pir/pattern_rewrite/pattern_match.h (RewritePattern /
PatternRewriter / greedy driver) + paddle/fluid/pir/drr/ (declarative
source->result patterns), and the fusion-extraction role of
paddle/fluid/pir/transforms/build_cinn_pass.cc + sub_graph_detector.cc.

TPU-native role: XLA already fuses elementwise chains, so the profitable
Program-level rewrites are the ones XLA can NOT do — substituting an
algebraic subgraph with a hand-written Pallas kernel that changes the
algorithm (flash attention's online softmax, fused-norm's single pass).
The pass family here (`PallasFusionPass`) is the SURVEY §7 "Pallas codegen
pass for flagged subgraphs": a captured vanilla-jnp attention block gets
flash-attention substituted before lowering; rms-norm and swiglu chains get
their fused kernels.  Replaced final ops keep their output vids, so
downstream consumers / fetches are untouched and orphaned intermediates die
in the executor's dead-code-elimination pass.
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np

__all__ = [
    "ProgramGraph",
    "RewritePattern",
    "PatternRewritePass",
    "PallasFusionPass",
    "FlashAttentionPattern",
    "RMSNormPattern",
    "SwiGLUPattern",
    "MatmulEpiloguePattern",
    "AddNormPattern",
    "GenericElementwiseFusionPass",
    "ScheduleSearchPattern",
    "ScheduleSearchPass",
]


# Strip pass-inserted namespaces ('fp16::matmul' -> 'matmul') so patterns
# still anchor after the fp16 program rewrite has run — the rewrite order
# (user-applied fp16 pass, then the Executor's default fusion pass) would
# otherwise silently defeat every substitution.
from ..framework.op_registry import base_op_type as _base_type


def _const_scalar(spec):
    """('const', v) -> python float if v is a scalar, else None."""
    if spec[0] != "const":
        return None
    v = spec[1]
    try:
        arr = np.asarray(v)
    except Exception:
        return None
    if arr.ndim == 0 or arr.size == 1:
        try:
            return float(arr.reshape(()))
        except (TypeError, ValueError):
            return None
    return None


def _is_causal_mask_const(spec, S):
    """('const', v) holding an additive causal mask over an [.., S, S]
    score matrix: 0 on/below the diagonal, <= -1e9 (or -inf) strictly
    above.  Leading broadcast dims of size 1 are allowed."""
    if spec[0] != "const":
        return False
    try:
        arr = np.asarray(spec[1], np.float32)
    except Exception:
        return False
    if arr.ndim < 2 or arr.shape[-1] != S or arr.shape[-2] != S:
        return False
    if any(d != 1 for d in arr.shape[:-2]):
        return False
    m = arr.reshape(S, S)
    lower = np.tril(np.ones((S, S), bool))
    if not np.all(m[lower] == 0):
        return False
    upper_vals = m[~lower]
    if upper_vals.size == 0:
        return True
    return bool(np.all(np.isneginf(upper_vals) | (upper_vals <= -1e9)))


class ProgramGraph:
    """Def-use view of a Program's global block (the pattern matcher's
    working set; reference pattern_match.h works over Operation/Value
    use-def chains the same way)."""

    def __init__(self, program, fetch_vids=()):
        self.program = program
        self.block = program.global_block()
        self.producer = {}
        self.consumers = defaultdict(list)
        for op in self.block.ops:
            for vid in op.out_vids:
                self.producer[vid] = op
            for vid in op.input_vids():
                self.consumers[vid].append(op)
        # vids visible outside the op list: fetches and state writes
        self.external = set(fetch_vids)
        self.external.update(program.writes.keys())
        self.external.update(program.writes.values())

    def single_use(self, vid) -> bool:
        return len(self.consumers[vid]) == 1 and vid not in self.external

    def shape(self, vid):
        var = self.program._var_by_vid.get(vid)
        return tuple(var._value.shape) if var is not None else None

    def def_op(self, vid, type_=None):
        op = self.producer.get(vid)
        if op is None:
            return None
        if type_ is not None and _base_type(op.type) != type_:
            return None
        return op

    def replace_op(self, old_op, new_op):
        """Swap old_op for new_op at the same position (same out vids →
        consumers unchanged; orphaned producers go to DCE)."""
        idx = self.block.ops.index(old_op)
        self.block.ops[idx] = new_op
        self.program.version += 1


class RewritePattern:
    """One source->result rule; anchored at a root op type (reference
    RewritePattern::match_and_rewrite)."""

    name = "base"
    root_type = None  # op.type this pattern anchors at

    def match_and_rewrite(self, op, graph: ProgramGraph) -> bool:
        raise NotImplementedError


class PatternRewritePass:
    """Greedy driver: apply patterns to fixpoint (bounded), reference
    ApplyPatternsGreedily.

    Every successful rewrite is use-def verified against the program's
    fetch frontier before it is accepted: a pattern that consumes an
    interior var whose producer other ops (or the fetch list) still need is
    ROLLED BACK and counted in `self.refused` — patterns cannot break
    def-before-use no matter what they match.  Under FLAGS_verify_programs
    the whole pass additionally runs between full verifier invocations."""

    name = "pattern_rewrite"

    def __init__(self, patterns, fetch_vids=(), max_iterations=8):
        self._patterns = list(patterns)
        self._fetch_vids = tuple(fetch_vids)
        self._max_iterations = max_iterations
        self.refused = 0

    def _rewrite_ok(self, program) -> bool:
        """Structural use-def + live-producer check of the post-rewrite
        program (registry/abstract tiers skipped: a rewrite cannot
        introduce those violation classes cheaply checkable here)."""
        from .verify import ProgramVerifier

        v = ProgramVerifier(check_registry=False, check_kwargs=False,
                            abstract_eval=False)
        bad = v._check_structure(program, self._fetch_vids)
        bad += v._check_live_producers(program, self._fetch_vids)
        return not bad

    def apply(self, program) -> int:
        from paddle_tpu._core import flags

        verify = flags.flag("FLAGS_verify_programs")
        if verify:
            from .verify import verify_program

            verify_program(program, self._fetch_vids)
        total = 0
        refused_sites: set = set()  # (pattern, op) identities already rolled back
        for _ in range(self._max_iterations):
            graph = ProgramGraph(program, self._fetch_vids)
            changed = 0
            for op in list(graph.block.ops):
                for pat in self._patterns:
                    if pat.root_type is not None and _base_type(op.type) != pat.root_type:
                        continue
                    if op not in graph.block.ops:
                        break  # already replaced this round
                    if (id(pat), id(op)) in refused_sites:
                        continue  # rolled back while the program was in
                        # this state; re-attempted only after another
                        # rewrite changes it (the op object survives
                        # rollbacks verbatim, so the identity is stable)
                    ops_before = list(graph.block.ops)
                    version_before = program.version
                    if pat.match_and_rewrite(op, graph):
                        if not self._rewrite_ok(program):
                            # refuse to fuse: restore the pre-rewrite op
                            # list; an interior matched var had consumers
                            # outside the matched set or sat in the fetch
                            # list
                            graph.block.ops[:] = ops_before
                            program.version = version_before
                            refused_sites.add((id(pat), id(op)))
                            self.refused += 1
                            from .verify import _COUNTERS

                            _COUNTERS["rewrites_refused"] += 1
                            graph = ProgramGraph(program, self._fetch_vids)
                            continue
                        changed += 1
                        graph = ProgramGraph(program, self._fetch_vids)
                        break
            total += changed
            if not changed:
                break
            # progress made: a refused site's outside consumers may have
            # been fused away, so it gets one fresh attempt per change round
            refused_sites.clear()
        if verify:
            from .verify import verify_program

            verify_program(program, self._fetch_vids)
        return total


def _make_op(type_, fn, var_vids, template_op, kwargs=None):
    """New Operator producing template_op's outputs from var inputs.
    kwargs are METADATA for later passes (the fn has them baked in)."""
    from paddle_tpu.static.program import Operator

    return Operator(
        type=type_,
        fn=fn,
        arg_spec=[("var", vid) for vid in var_vids],
        kwargs=dict(kwargs or {}),
        out_vids=list(template_op.out_vids),
        out_tree=template_op.out_tree,
    )


class FlashAttentionPattern(RewritePattern):
    """matmul(q,kᵀ) [→ scale] [→ +causal mask] → softmax → matmul(·,v)
    ⇒ Pallas flash attention (ops/flash_attention.py — online softmax,
    O(S) memory).

    Anchored at the second matmul.  Conservative: 4-D [B, N, S, D] layouts
    only; an additive CONST mask fuses only when it is recognizably the
    causal triangle (maps to the kernel's causal flag) — arbitrary masks
    have no kernel parameter and block fusion; unique consumers for every
    interior value; and S != D so the kᵀ layout is unambiguous."""

    name = "flash_attention_fuse"
    root_type = "matmul"

    def match_and_rewrite(self, op, graph):
        import jax.numpy as jnp

        # root: out = matmul(probs, v)
        if len(op.arg_spec) != 2 or any(s[0] != "var" for s in op.arg_spec):
            return False
        if op.kwargs.get("transpose_x") or op.kwargs.get("transpose_y"):
            # probs is square [B,N,S,S], so a transposed root is shape-
            # indistinguishable from the attention form but computes
            # probs^T @ v — never fusable
            return False
        probs_vid, v_vid = op.arg_spec[0][1], op.arg_spec[1][1]
        out_shape = graph.shape(op.out_vids[0]) if op.out_vids else None
        v_shape = graph.shape(v_vid)
        p_shape = graph.shape(probs_vid)
        if not (out_shape and v_shape and p_shape):
            return False
        if len(out_shape) != 4 or len(v_shape) != 4 or len(p_shape) != 4:
            return False
        B, N, S, D = out_shape
        if p_shape != (B, N, S, S) or v_shape != (B, N, S, D) or S == D:
            return False

        sm = graph.def_op(probs_vid, "softmax")
        if sm is None or not graph.single_use(probs_vid):
            return False
        if len(sm.arg_spec) != 1 or sm.arg_spec[0][0] != "var":
            return False
        # flash attention's online softmax is last-axis only
        sm_axis = sm.kwargs.get("axis", -1)
        if sm_axis not in (-1, 3):
            return False

        # optional scale / causal-mask-add chain between qk-matmul and
        # softmax (vanilla LLaMA writes scores/sqrt(d) + causal_mask)
        scale = None
        causal = False
        cur_vid = sm.arg_spec[0][1]
        if not graph.single_use(cur_vid):
            return False
        cur = graph.def_op(cur_vid)
        for _ in range(2):  # at most one scale + one mask-add, any order
            if cur is None:
                return False
            var_ins = [s for s in cur.arg_spec if s[0] == "var"]
            consts = [s for s in cur.arg_spec if s[0] == "const"]
            if (
                _base_type(cur.type) in ("divide", "multiply")
                and len(var_ins) == 1
                and len(consts) == 1
                and _const_scalar(consts[0]) is not None
                and scale is None
            ):
                c = _const_scalar(consts[0])
                scale = (1.0 / c) if _base_type(cur.type) == "divide" else c
            elif (
                _base_type(cur.type) == "add"
                and len(var_ins) == 1
                and len(consts) == 1
                and not causal
                and _is_causal_mask_const(consts[0], S)
            ):
                causal = True
            else:
                break
            cur_vid = var_ins[0][1]
            if not graph.single_use(cur_vid):
                return False
            cur = graph.def_op(cur_vid)
        qk = cur
        if qk is None or _base_type(qk.type) != "matmul":
            return False
        if len(qk.arg_spec) != 2 or any(s[0] != "var" for s in qk.arg_spec):
            return False
        if qk.kwargs.get("transpose_x"):
            return False  # q^T @ k is not the attention form
        q_vid, k_vid = qk.arg_spec[0][1], qk.arg_spec[1][1]
        q_shape, k_shape = graph.shape(q_vid), graph.shape(k_vid)
        if q_shape != (B, N, S, D):
            return False
        if k_shape == (B, N, S, D):
            k_transposed = True  # user wrote matmul(q, k, transpose_y=True)
        elif k_shape == (B, N, D, S):
            k_transposed = False
        else:
            return False
        # the recorded transpose_y must agree with the shape-inferred layout
        # (with S != D they can only disagree on malformed programs — keep
        # the cross-check so the kernel can never silently flip k)
        if bool(qk.kwargs.get("transpose_y")) != k_transposed:
            return False

        if scale is None:
            scale = 1.0  # plain matmul softmax: no 1/sqrt(d) in source

        # matched through fp16::-wrapped matmuls (fp16 pass ran first):
        # keep the low-dtype compute the user asked for — downcast fp32
        # inputs, run the kernel there, upcast the result back, exactly
        # Fp16ProgramRewrite's contract
        low = getattr(op, "fp16_low", None) or getattr(qk, "fp16_low", None)

        def fused(q, k, v):
            from paddle_tpu.ops import flash_attention

            downcast = False
            if low is not None:
                ins = []
                for t in (q, k, v):
                    if t.dtype == jnp.float32:
                        ins.append(t.astype(low))
                        downcast = True
                    else:
                        ins.append(t)
                q, k, v = ins
            if not k_transposed:
                k = jnp.swapaxes(k, -1, -2)
            qt = jnp.swapaxes(q, 1, 2)  # [B,N,S,D] -> kernel's [B,S,N,D]
            kt = jnp.swapaxes(k, 1, 2)
            vt = jnp.swapaxes(v, 1, 2)
            o = flash_attention(qt, kt, vt, scale=scale, causal=causal)
            if downcast and o.dtype == low:
                o = o.astype(jnp.float32)
            return jnp.swapaxes(o, 1, 2)

        new_type = "flash_attention" if low is None else "fp16::flash_attention"
        graph.replace_op(op, _make_op(new_type, fused, [q_vid, k_vid, v_vid], op))
        return True


class RMSNormPattern(RewritePattern):
    """x·rsqrt(mean(x²)+ε)·w  ⇒  Pallas fused_rms_norm (ops/fused_norm.py).

    Anchored at the final weight multiply; accepts square(x) or
    multiply(x, x) for the square."""

    name = "rms_norm_fuse"
    root_type = "multiply"

    def _match_square_mean(self, vid, graph, x_vid):
        mean = graph.def_op(vid, "mean")
        if mean is None or not graph.single_use(vid):
            return False
        if len(mean.arg_spec) != 1 or mean.arg_spec[0][0] != "var":
            return False
        sq_vid = mean.arg_spec[0][1]
        if not graph.single_use(sq_vid):
            return False
        sq = graph.def_op(sq_vid)
        if sq is None:
            return False
        if _base_type(sq.type) == "square":
            return sq.arg_spec[0] == ("var", x_vid)
        if _base_type(sq.type) in ("multiply", "pow"):
            vids = [s[1] for s in sq.arg_spec if s[0] == "var"]
            if _base_type(sq.type) == "multiply":
                return vids == [x_vid, x_vid]
            c = next((_const_scalar(s) for s in sq.arg_spec if s[0] == "const"), None)
            return vids == [x_vid] and c == 2.0
        return False

    def match_and_rewrite(self, op, graph):
        # root: out = multiply(normed, w)   (w: 1-D over last axis)
        if len(op.arg_spec) != 2 or any(s[0] != "var" for s in op.arg_spec):
            return False
        normed_vid, w_vid = op.arg_spec[0][1], op.arg_spec[1][1]
        w_shape = graph.shape(w_vid)
        out_shape = graph.shape(op.out_vids[0]) if op.out_vids else None
        if not w_shape or not out_shape or len(w_shape) != 1 or w_shape[0] != out_shape[-1]:
            return False
        if not graph.single_use(normed_vid):
            return False
        # normed = multiply(x, rsqrt(mean(x*x) + eps))
        mul = graph.def_op(normed_vid, "multiply")
        if mul is None or len(mul.arg_spec) != 2 or any(s[0] != "var" for s in mul.arg_spec):
            return False
        x_vid, r_vid = mul.arg_spec[0][1], mul.arg_spec[1][1]
        if graph.shape(x_vid) != out_shape:
            x_vid, r_vid = r_vid, x_vid
        if graph.shape(x_vid) != out_shape:
            return False
        if not graph.single_use(r_vid):
            return False
        rs = graph.def_op(r_vid, "rsqrt")
        if rs is None or len(rs.arg_spec) != 1 or rs.arg_spec[0][0] != "var":
            return False
        add_vid = rs.arg_spec[0][1]
        if not graph.single_use(add_vid):
            return False
        add = graph.def_op(add_vid, "add")
        if add is None:
            return False
        eps = next((_const_scalar(s) for s in add.arg_spec if s[0] == "const"), None)
        var_ins = [s[1] for s in add.arg_spec if s[0] == "var"]
        if eps is None or len(var_ins) != 1:
            return False
        if not self._match_square_mean(var_ins[0], graph, x_vid):
            return False
        # mean must reduce the last axis with keepdim
        mean_shape = graph.shape(var_ins[0])
        if mean_shape is None or mean_shape != out_shape[:-1] + (1,):
            return False

        def fused(x, w):
            from paddle_tpu.ops import fused_rms_norm

            return fused_rms_norm(x, w, epsilon=eps)

        graph.replace_op(op, _make_op("fused_rms_norm", fused, [x_vid, w_vid],
                                      op, kwargs={"epsilon": eps}))
        return True


class SwiGLUPattern(RewritePattern):
    """silu(g)·u  ⇒  Pallas swiglu (ops/swiglu.py)."""

    name = "swiglu_fuse"
    root_type = "multiply"

    def match_and_rewrite(self, op, graph):
        if len(op.arg_spec) != 2 or any(s[0] != "var" for s in op.arg_spec):
            return False
        a_vid, b_vid = op.arg_spec[0][1], op.arg_spec[1][1]
        for gate_vid, up_vid in ((a_vid, b_vid), (b_vid, a_vid)):
            silu = graph.def_op(gate_vid, "silu")
            if silu is None or not graph.single_use(gate_vid):
                continue
            if len(silu.arg_spec) != 1 or silu.arg_spec[0][0] != "var":
                continue
            g_vid = silu.arg_spec[0][1]
            if graph.shape(g_vid) != graph.shape(up_vid):
                continue

            def fused(g, u):
                from paddle_tpu.ops import swiglu

                return swiglu(g, u)

            graph.replace_op(op, _make_op("swiglu", fused, [g_vid, up_vid], op))
            return True
        return False


def _entry_shape(graph, entry):
    if entry[0] == "var":
        return graph.shape(entry[1])
    import numpy as _np

    try:
        return tuple(_np.shape(entry[1]))
    except Exception:
        return None


def _mixed(entries):
    """(var_vids, rebuild): rebuild(var_vals) -> full positional values with
    const entries baked in (weights captured as concrete tensors record as
    consts, not vars)."""
    var_vids = [e[1] for e in entries if e[0] == "var"]

    def rebuild(var_vals):
        it = iter(var_vals)
        return [next(it) if e[0] == "var" else e[1] for e in entries]

    return var_vids, rebuild


class MatmulEpiloguePattern(RewritePattern):
    """act(linear(x, w[, b]))  ⇒  Pallas matmul_bias_act
    (ops/matmul_epilogue.py — the epilogue runs on the f32 accumulator in
    VMEM; the pre-activation never round-trips HBM).

    Anchored at the activation (gelu/silu/relu) whose single input is the
    single-use output of a linear/matmul op."""

    name = "matmul_epilogue_fuse"
    root_type = None  # three root types; filtered in match
    _ROOTS = {"gelu", "silu", "relu"}

    def match_and_rewrite(self, op, graph):
        base = _base_type(op.type)
        if base not in self._ROOTS:
            return False
        if len(op.arg_spec) != 1 or op.arg_spec[0][0] != "var":
            return False
        if base == "silu" and op.out_vids:
            # silu feeding a multiply is SwiGLUPattern's subgraph (the
            # LLaMA-canonical kernel with analytic backward): stand down
            cons = graph.consumers.get(op.out_vids[0], [])
            if any(_base_type(c.type) == "multiply" for c in cons):
                return False
        pre_vid = op.arg_spec[0][1]
        if not graph.single_use(pre_vid):
            return False
        mm = graph.def_op(pre_vid)
        if mm is None or _base_type(mm.type) not in ("linear", "matmul"):
            return False
        if mm.type.startswith("wq::"):
            # weight-only-quantized op: different arg contract (int8 q +
            # scale appended) — fusing would add the scale as a bias
            return False
        if mm.kwargs.get("transpose_x") or mm.kwargs.get("transpose_y"):
            # paddle.matmul(..., transpose_y=True) computes x @ w.T; the
            # fused kernel has no transpose contract — for square weights
            # the shape check below cannot catch it, so bail out
            return False
        if len(mm.arg_spec) not in (2, 3):
            return False
        x_entry, w_entry = mm.arg_spec[0], mm.arg_spec[1]
        b_entry = mm.arg_spec[2] if len(mm.arg_spec) == 3 else None
        if x_entry[0] != "var":  # activations are always program values
            return False
        w_shape = _entry_shape(graph, w_entry)
        x_shape = graph.shape(x_entry[1])
        if not w_shape or not x_shape or len(w_shape) != 2 or x_shape[-1] != w_shape[0]:
            return False
        # defense in depth: the weight must be a FLOAT tensor (an int8
        # quantized weight means a dequant contract this kernel lacks)
        if w_entry[0] == "var":
            wvar = graph.program._var_by_vid.get(w_entry[1])
            import jax.numpy as _jnp

            if wvar is None or not _jnp.issubdtype(wvar._value.dtype, _jnp.inexact):
                return False
        if b_entry is not None and _entry_shape(graph, b_entry) != (w_shape[1],):
            return False
        act = base
        if base == "gelu" and op.kwargs.get("approximate"):
            act = "gelu_tanh"

        entries = [x_entry, w_entry] + ([b_entry] if b_entry is not None else [])
        var_vids, rebuild = _mixed(entries)
        has_bias = b_entry is not None
        # keep the fp16 rewrite's low-dtype compute (see FlashAttentionPattern:
        # replacing an fp16:: op with an fp32 kernel would silently revert
        # the precision choice)
        low = getattr(mm, "fp16_low", None)

        def fused(*var_vals, act=act, has_bias=has_bias, rebuild=rebuild, low=low):
            import jax.numpy as _jnp

            from paddle_tpu.ops import matmul_bias_act

            full = rebuild(var_vals)
            x, w = full[0], full[1]
            b = full[2] if has_bias else None
            downcast = False
            if low is not None and x.dtype == _jnp.float32:
                x, downcast = x.astype(low), True
                w = w.astype(low) if w.dtype == _jnp.float32 else w
                if b is not None and b.dtype == _jnp.float32:
                    b = b.astype(low)
            out = matmul_bias_act(x, w, b, act)
            return out.astype(_jnp.float32) if downcast else out

        new_type = ("fp16::" if low is not None else "") + "matmul_epilogue"
        new_op = _make_op(new_type, fused, var_vids, op)
        if low is not None:
            new_op.fp16_low = low
        graph.replace_op(op, new_op)
        return True


class AddNormPattern(RewritePattern):
    """norm(x + residual)  ⇒  fused residual-add norm (ops/fused_norm.py
    residual= contract) — the transformer residual-stream chain.

    Anchors on fused_rms_norm (produced by RMSNormPattern, so this fires on
    the same pass's fixpoint iteration), raw rms_norm, or layer_norm, whose
    input comes from an add of two same-shape tensors.  The fused op emits
    BOTH the normed output and the sum (the residual stream usually feeds
    the next block too), replacing the add at its own position so every
    consumer of the sum still reads a defined value."""

    name = "add_norm_fuse"
    root_type = None
    _ROOTS = {"fused_rms_norm", "rms_norm", "layer_norm"}

    def match_and_rewrite(self, op, graph):
        import jax as _jax

        base = _base_type(op.type)
        if base not in self._ROOTS:
            return False
        if op.arg_spec[0][0] != "var":
            return False
        if base == "layer_norm":
            if len(op.arg_spec) != 3:  # x, weight, bias (elementwise affine)
                return False
            x_vid = op.arg_spec[0][1]
            w_entry, b_entry = op.arg_spec[1], op.arg_spec[2]
        else:
            if len(op.arg_spec) != 2:  # x, weight
                return False
            x_vid = op.arg_spec[0][1]
            w_entry, b_entry = op.arg_spec[1], None
        add = graph.def_op(x_vid, "add")
        if add is None:
            return False
        if len(add.arg_spec) != 2 or any(s[0] != "var" for s in add.arg_spec):
            return False
        a_vid, r_vid = add.arg_spec[0][1], add.arg_spec[1][1]
        if graph.shape(a_vid) != graph.shape(r_vid):
            return False
        eps = op.kwargs.get("epsilon", op.kwargs.get("eps"))
        if eps is None:
            return False  # can't recover the recorded epsilon: don't fuse

        # the fused op replaces the ADD at its position: every other VAR
        # input (norm weight/bias) must already be defined there
        block = graph.block
        add_idx = block.ops.index(add)

        def _defined_before(entry):
            if entry is None or entry[0] != "var":
                return True
            prod = graph.producer.get(entry[1])
            return prod is None or block.ops.index(prod) < add_idx

        if not (_defined_before(w_entry) and _defined_before(b_entry)):
            return False

        from paddle_tpu.static.program import Operator

        entries = [("var", a_vid), ("var", r_vid), w_entry] + (
            [b_entry] if b_entry is not None else [])
        var_vids, rebuild = _mixed(entries)
        is_ln = base == "layer_norm"

        def fused(*var_vals, eps=eps, is_ln=is_ln, rebuild=rebuild):
            from paddle_tpu.ops import fused_layer_norm, fused_rms_norm

            full = rebuild(var_vals)
            if is_ln:
                out, s = fused_layer_norm(full[0], full[2], full[3],
                                          residual=full[1], epsilon=eps)
            else:
                out, s = fused_rms_norm(full[0], full[2], residual=full[1],
                                        epsilon=eps)
            return s, out

        new_op = Operator(
            "add_" + ("layer_norm" if is_ln else "rms_norm"),
            fused,
            [("var", v) for v in var_vids],
            {"epsilon": eps},
            [add.out_vids[0], op.out_vids[0]],
            _jax.tree_util.tree_structure((0, 0)),
        )
        block.ops[add_idx] = new_op
        block.ops.remove(op)
        graph.program.version += 1
        return True


class PallasFusionPass(PatternRewritePass):
    """The default Pallas-substitution pipeline (SURVEY §7's CINN analog)."""

    name = "pallas_fusion"

    def __init__(self, fetch_vids=()):
        super().__init__(
            [FlashAttentionPattern(), RMSNormPattern(), SwiGLUPattern(),
             MatmulEpiloguePattern(), AddNormPattern()],
            fetch_vids=fetch_vids,
        )


# ---------------------------------------------------------------------------
# generic elementwise-chain fusion (the CINN auto-discovery role)


_ELEMENTWISE = {
    "add", "subtract", "multiply", "divide", "maximum", "minimum", "pow",
    "exp", "log", "tanh", "sigmoid", "relu", "gelu", "silu", "abs", "neg",
    "sqrt", "rsqrt", "square", "floor", "ceil", "round", "clip", "cast",
    "scale", "leaky_relu", "elu", "hardtanh", "softplus", "mish",
    "hardswish", "hardsigmoid", "erf", "sin", "cos", "amp_cast",
    "fake_quant",
}


class GenericElementwiseFusionPass:
    """Discover maximal chains of same-shape elementwise ops and generate
    ONE Pallas VPU kernel per chain (reference: CINN's fusible-subgraph
    discovery + codegen, paddle/cinn/hlir/framework/op_lowering_impl.cc —
    the mechanism, not a fixed pattern set).

    A chain is a maximal straight line of whitelisted ops where every link
    is single-use and every participating tensor has the output's shape
    (scalar/python constants are already baked inside the recorded op fns).
    The generated kernel replays the recorded op fns over VMEM blocks, so
    an N-op bandwidth-bound chain makes one HBM round trip instead of N.
    Opt-in (`apply_pass(prog, "generic_elementwise_fusion")` or the
    save_inference_model passes= list): XLA fuses most of these itself —
    this pass exists for tile control and for chains fusion boundaries
    would otherwise split.
    """

    name = "generic_elementwise_fusion"

    def __init__(self, fetch_vids=(), min_chain=3):
        self._fetch_vids = tuple(fetch_vids)
        self._min_chain = int(min_chain)

    # ------------------------------------------------------------ discovery
    def _eligible(self, op, graph, shape):
        if _base_type(op.type) not in _ELEMENTWISE:
            return False
        if not op.out_vids or len(op.out_vids) != 1:
            return False
        if graph.shape(op.out_vids[0]) != shape:
            return False
        for s in op.arg_spec:
            if s[0] == "var" and graph.shape(s[1]) not in (shape, None):
                return False
            if s[0] == "var" and graph.shape(s[1]) is None:
                return False
        return True

    def _collect_chain(self, root, graph):
        """Walk producers from `root` collecting the fusible upstream set
        (a tree of single-use elementwise producers), returned in
        execution order."""
        shape = graph.shape(root.out_vids[0])
        block_ops = graph.block.ops
        chain = {id(root): root}
        frontier = [root]
        while frontier:
            op = frontier.pop()
            for s in op.arg_spec:
                if s[0] != "var":
                    continue
                prod = graph.def_op(s[1])
                if (prod is None or id(prod) in chain
                        or not graph.single_use(s[1])
                        or not self._eligible(prod, graph, shape)):
                    continue
                chain[id(prod)] = prod
                frontier.append(prod)
        ordered = [op for op in block_ops if id(op) in chain]
        return ordered

    # -------------------------------------------------------------- codegen
    def _build_kernel(self, ordered, ext_vids, final_vid, shape, dtype):
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        from paddle_tpu.ops._pl_utils import imap

        def chain_body(*vals):
            env = dict(zip(ext_vids, vals))
            for op in ordered:
                var_vals = [env[s[1]] for s in op.arg_spec if s[0] == "var"]
                out = op.fn(*var_vals)
                flat = jax.tree_util.tree_leaves(out)
                for vid, v in zip(op.out_vids, flat):
                    env[vid] = v
            return env[final_vid]

        n_in = len(ext_vids)

        def fused(*vals):
            flat = [v.reshape(-1, shape[-1]) if len(shape) > 1 else
                    v.reshape(1, -1) for v in vals]
            rows, cols = flat[0].shape
            # tile like the swiglu kernel: bounded VMEM, 128-multiple lanes
            from paddle_tpu.ops import autotune as _at

            tuned = _at.lookup("vpu_chain", {
                "rows": rows, "cols": cols, "n_ops": len(ordered),
                "dtype": jnp.dtype(dtype).name})
            br = int(tuned["rows_block"]) if tuned else min(256, rows)
            bc = int(tuned["cols_block"]) if tuned else cols
            if rows % br:
                br = rows
            if cols % bc:
                bc = cols
                for cand in (2048, 1024, 512, 256, 128):
                    if cols % cand == 0:
                        bc = cand
                        break
            # Pre-trace the chain at BLOCK shape and bake closure constants
            # as numpy literals — Pallas kernels may not capture traced
            # jax arrays (scalar consts recorded inside op fns are such).
            block_avals = [jax.ShapeDtypeStruct((br, bc), f.dtype)
                           for f in flat]
            closed = jax.make_jaxpr(chain_body)(*block_avals)
            np_consts = [np.asarray(c) for c in closed.consts]

            def kernel(*refs):
                ins, o_ref = refs[:n_in], refs[n_in]
                out = jax.core.eval_jaxpr(
                    closed.jaxpr, np_consts, *(r[:] for r in ins))[0]
                o_ref[:] = out.astype(o_ref.dtype)

            out = pl.pallas_call(
                kernel,
                grid=(rows // br, cols // bc),
                in_specs=[pl.BlockSpec((br, bc), imap(lambda i, j: (i, j)))
                          for _ in flat],
                out_specs=pl.BlockSpec((br, bc), imap(lambda i, j: (i, j))),
                out_shape=jax.ShapeDtypeStruct((rows, cols), dtype),
                interpret=jax.default_backend() != "tpu",
            )(*flat)
            return out.reshape(shape)

        return fused

    # ----------------------------------------------------------------- apply
    def apply(self, program) -> int:
        import jax

        n = 0
        while True:
            graph = ProgramGraph(program, self._fetch_vids)
            block = graph.block
            done = False
            for root in reversed(list(block.ops)):
                shape = graph.shape(root.out_vids[0]) if root.out_vids else None
                if shape is None or len(shape) < 1:
                    continue
                if not self._eligible(root, graph, shape):
                    continue
                # root must be the DOWNSTREAM end: its single output is not
                # consumed by another fusible op (that op would be the root)
                out_vid = root.out_vids[0]
                cons = graph.consumers.get(out_vid, [])
                if (len(cons) == 1 and graph.single_use(out_vid)
                        and self._eligible(cons[0], graph, shape)):
                    continue
                ordered = self._collect_chain(root, graph)
                if len(ordered) < self._min_chain:
                    continue
                in_chain_out = {vid for op in ordered for vid in op.out_vids}
                ext_vids = []
                for op in ordered:
                    for s in op.arg_spec:
                        if s[0] == "var" and s[1] not in in_chain_out and s[1] not in ext_vids:
                            ext_vids.append(s[1])
                var = program._var_by_vid[out_vid]
                dtype = var._value.dtype
                fused = self._build_kernel(
                    ordered, list(ext_vids), out_vid, shape, dtype)
                new_op = _make_op(
                    f"vpu_chain_{len(ordered)}", fused, ext_vids, root)
                idx = block.ops.index(root)
                block.ops[idx] = new_op
                for op in ordered:
                    if op is not root and op in block.ops:
                        block.ops.remove(op)
                program.version += 1
                n += 1
                done = True
                break
            if not done:
                return n


# ---------------------------------------------------------------------------
# schedule-searched fusion (the discovery tier beyond elementwise chains)


class ScheduleSearchPattern(RewritePattern):
    """Discover a reduction-/matmul-rooted subgraph anchored at `op` (the
    downstream end), hand it to the ScheduleSearcher (static/
    schedule_search.py: enumerate tilings → roofline prune → VMEM prune →
    measure → measured-win gate → per-device cache), and substitute ONE
    generated Pallas kernel when the searched schedule beat XLA.

    The classes hunted are the fusion misses named patterns skip: matmul→
    bias→act→reduce tails, softmax-adjacent reduction chains (discovery is
    DAG-shaped — manual softmax's exp feeding both sum and divide fuses as
    one subgraph).  Fetch-frontier/write-visible interior values are
    refused by the PatternRewritePass use-def rollback (PR 4's machinery,
    counted in `.refused`); side-effect ops and collectives are never
    crossed (op_registry.side_effect_op_types)."""

    name = "schedule_search"
    root_type = None

    def __init__(self, searcher=None):
        self._searcher = searcher
        self._seen: set = set()  # (sig, root identity) already searched

    def match_and_rewrite(self, op, graph):
        from . import schedule_search as ss

        spec = ss.match_subgraph(op, graph)
        if spec is None:
            return False
        tag = (spec.sig, id(spec.root))
        if tag in self._seen:
            return False  # searched this site already (disabled/rolled back)
        self._seen.add(tag)
        searcher = self._searcher
        if searcher is None:
            searcher = self._searcher = ss.ScheduleSearcher()
        decision = searcher.search(spec)
        if not decision.accepted:
            return False
        try:
            fused = ss.build_kernel(spec, decision.config)
        except Exception:
            return False  # cached config no longer buildable here
        new_op = _make_op(
            f"sched_chain_{len(spec.ops)}", fused,
            [e.vid for e in spec.ext], spec.root,
            kwargs={"kind": spec.kind, "schedule": dict(decision.config)})
        graph.replace_op(spec.root, new_op)
        block = graph.block
        for o in spec.ops:
            if o is not spec.root and o in block.ops:
                block.ops.remove(o)
        return True


class ScheduleSearchPass(PatternRewritePass):
    """Schedule-searched Pallas substitution over discovered subgraphs
    (ROADMAP item 2; docs/SCHEDULE_SEARCH.md).  Runs after PallasFusionPass
    in the Executor pipeline (FLAGS_schedule_search) so the named patterns
    take their subgraphs first and fused ops act as chain breakers here."""

    name = "schedule_search"

    def __init__(self, fetch_vids=(), searcher=None):
        super().__init__([ScheduleSearchPattern(searcher)],
                         fetch_vids=fetch_vids)
