"""Pattern rewriting over captured Programs — the DRR/pattern_match role.

Reference: paddle/pir/pattern_rewrite/pattern_match.h (RewritePattern /
PatternRewriter / greedy driver) + paddle/fluid/pir/drr/ (declarative
source->result patterns), and the fusion-extraction role of
paddle/fluid/pir/transforms/build_cinn_pass.cc + sub_graph_detector.cc.

TPU-native role: XLA already fuses elementwise chains, so the profitable
Program-level rewrites are the ones XLA can NOT do — substituting an
algebraic subgraph with a hand-written Pallas kernel that changes the
algorithm (flash attention's online softmax, fused-norm's single pass).
The pass family here (`PallasFusionPass`) is the SURVEY §7 "Pallas codegen
pass for flagged subgraphs": a captured vanilla-jnp attention block gets
flash-attention substituted before lowering; rms-norm and swiglu chains get
their fused kernels.  Replaced final ops keep their output vids, so
downstream consumers / fetches are untouched and orphaned intermediates die
in the executor's dead-code-elimination pass.
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np

__all__ = [
    "ProgramGraph",
    "RewritePattern",
    "PatternRewritePass",
    "PallasFusionPass",
    "FlashAttentionPattern",
    "RMSNormPattern",
    "SwiGLUPattern",
]


def _base_type(t):
    """Strip pass-inserted namespaces ('fp16::matmul' -> 'matmul') so
    patterns still anchor after the fp16 program rewrite has run — the
    rewrite order (user-applied fp16 pass, then the Executor's default
    fusion pass) would otherwise silently defeat every substitution."""
    return t.rsplit("::", 1)[-1]


def _const_scalar(spec):
    """('const', v) -> python float if v is a scalar, else None."""
    if spec[0] != "const":
        return None
    v = spec[1]
    try:
        arr = np.asarray(v)
    except Exception:
        return None
    if arr.ndim == 0 or arr.size == 1:
        try:
            return float(arr.reshape(()))
        except (TypeError, ValueError):
            return None
    return None


def _is_causal_mask_const(spec, S):
    """('const', v) holding an additive causal mask over an [.., S, S]
    score matrix: 0 on/below the diagonal, <= -1e9 (or -inf) strictly
    above.  Leading broadcast dims of size 1 are allowed."""
    if spec[0] != "const":
        return False
    try:
        arr = np.asarray(spec[1], np.float32)
    except Exception:
        return False
    if arr.ndim < 2 or arr.shape[-1] != S or arr.shape[-2] != S:
        return False
    if any(d != 1 for d in arr.shape[:-2]):
        return False
    m = arr.reshape(S, S)
    lower = np.tril(np.ones((S, S), bool))
    if not np.all(m[lower] == 0):
        return False
    upper_vals = m[~lower]
    if upper_vals.size == 0:
        return True
    return bool(np.all(np.isneginf(upper_vals) | (upper_vals <= -1e9)))


class ProgramGraph:
    """Def-use view of a Program's global block (the pattern matcher's
    working set; reference pattern_match.h works over Operation/Value
    use-def chains the same way)."""

    def __init__(self, program, fetch_vids=()):
        self.program = program
        self.block = program.global_block()
        self.producer = {}
        self.consumers = defaultdict(list)
        for op in self.block.ops:
            for vid in op.out_vids:
                self.producer[vid] = op
            for vid in op.input_vids():
                self.consumers[vid].append(op)
        # vids visible outside the op list: fetches and state writes
        self.external = set(fetch_vids)
        self.external.update(program.writes.keys())
        self.external.update(program.writes.values())

    def single_use(self, vid) -> bool:
        return len(self.consumers[vid]) == 1 and vid not in self.external

    def shape(self, vid):
        var = self.program._var_by_vid.get(vid)
        return tuple(var._value.shape) if var is not None else None

    def def_op(self, vid, type_=None):
        op = self.producer.get(vid)
        if op is None:
            return None
        if type_ is not None and _base_type(op.type) != type_:
            return None
        return op

    def replace_op(self, old_op, new_op):
        """Swap old_op for new_op at the same position (same out vids →
        consumers unchanged; orphaned producers go to DCE)."""
        idx = self.block.ops.index(old_op)
        self.block.ops[idx] = new_op
        self.program.version += 1


class RewritePattern:
    """One source->result rule; anchored at a root op type (reference
    RewritePattern::match_and_rewrite)."""

    name = "base"
    root_type = None  # op.type this pattern anchors at

    def match_and_rewrite(self, op, graph: ProgramGraph) -> bool:
        raise NotImplementedError


class PatternRewritePass:
    """Greedy driver: apply patterns to fixpoint (bounded), reference
    ApplyPatternsGreedily."""

    name = "pattern_rewrite"

    def __init__(self, patterns, fetch_vids=(), max_iterations=8):
        self._patterns = list(patterns)
        self._fetch_vids = tuple(fetch_vids)
        self._max_iterations = max_iterations

    def apply(self, program) -> int:
        total = 0
        for _ in range(self._max_iterations):
            graph = ProgramGraph(program, self._fetch_vids)
            changed = 0
            for op in list(graph.block.ops):
                for pat in self._patterns:
                    if pat.root_type is not None and _base_type(op.type) != pat.root_type:
                        continue
                    if op not in graph.block.ops:
                        break  # already replaced this round
                    if pat.match_and_rewrite(op, graph):
                        changed += 1
                        graph = ProgramGraph(program, self._fetch_vids)
                        break
            total += changed
            if not changed:
                break
        return total


def _make_op(type_, fn, var_vids, template_op):
    """New Operator producing template_op's outputs from var inputs."""
    from paddle_tpu.static.program import Operator

    return Operator(
        type=type_,
        fn=fn,
        arg_spec=[("var", vid) for vid in var_vids],
        kwargs={},
        out_vids=list(template_op.out_vids),
        out_tree=template_op.out_tree,
    )


class FlashAttentionPattern(RewritePattern):
    """matmul(q,kᵀ) [→ scale] [→ +causal mask] → softmax → matmul(·,v)
    ⇒ Pallas flash attention (ops/flash_attention.py — online softmax,
    O(S) memory).

    Anchored at the second matmul.  Conservative: 4-D [B, N, S, D] layouts
    only; an additive CONST mask fuses only when it is recognizably the
    causal triangle (maps to the kernel's causal flag) — arbitrary masks
    have no kernel parameter and block fusion; unique consumers for every
    interior value; and S != D so the kᵀ layout is unambiguous."""

    name = "flash_attention_fuse"
    root_type = "matmul"

    def match_and_rewrite(self, op, graph):
        import jax.numpy as jnp

        # root: out = matmul(probs, v)
        if len(op.arg_spec) != 2 or any(s[0] != "var" for s in op.arg_spec):
            return False
        probs_vid, v_vid = op.arg_spec[0][1], op.arg_spec[1][1]
        out_shape = graph.shape(op.out_vids[0]) if op.out_vids else None
        v_shape = graph.shape(v_vid)
        p_shape = graph.shape(probs_vid)
        if not (out_shape and v_shape and p_shape):
            return False
        if len(out_shape) != 4 or len(v_shape) != 4 or len(p_shape) != 4:
            return False
        B, N, S, D = out_shape
        if p_shape != (B, N, S, S) or v_shape != (B, N, S, D) or S == D:
            return False

        sm = graph.def_op(probs_vid, "softmax")
        if sm is None or not graph.single_use(probs_vid):
            return False
        if len(sm.arg_spec) != 1 or sm.arg_spec[0][0] != "var":
            return False
        # flash attention's online softmax is last-axis only
        sm_axis = sm.kwargs.get("axis", -1)
        if sm_axis not in (-1, 3):
            return False

        # optional scale / causal-mask-add chain between qk-matmul and
        # softmax (vanilla LLaMA writes scores/sqrt(d) + causal_mask)
        scale = None
        causal = False
        cur_vid = sm.arg_spec[0][1]
        if not graph.single_use(cur_vid):
            return False
        cur = graph.def_op(cur_vid)
        for _ in range(2):  # at most one scale + one mask-add, any order
            if cur is None:
                return False
            var_ins = [s for s in cur.arg_spec if s[0] == "var"]
            consts = [s for s in cur.arg_spec if s[0] == "const"]
            if (
                _base_type(cur.type) in ("divide", "multiply")
                and len(var_ins) == 1
                and len(consts) == 1
                and _const_scalar(consts[0]) is not None
                and scale is None
            ):
                c = _const_scalar(consts[0])
                scale = (1.0 / c) if _base_type(cur.type) == "divide" else c
            elif (
                _base_type(cur.type) == "add"
                and len(var_ins) == 1
                and len(consts) == 1
                and not causal
                and _is_causal_mask_const(consts[0], S)
            ):
                causal = True
            else:
                break
            cur_vid = var_ins[0][1]
            if not graph.single_use(cur_vid):
                return False
            cur = graph.def_op(cur_vid)
        qk = cur
        if qk is None or _base_type(qk.type) != "matmul":
            return False
        if len(qk.arg_spec) != 2 or any(s[0] != "var" for s in qk.arg_spec):
            return False
        q_vid, k_vid = qk.arg_spec[0][1], qk.arg_spec[1][1]
        q_shape, k_shape = graph.shape(q_vid), graph.shape(k_vid)
        if q_shape != (B, N, S, D):
            return False
        if k_shape == (B, N, S, D):
            k_transposed = True  # user wrote matmul(q, k, transpose_y=True)
        elif k_shape == (B, N, D, S):
            k_transposed = False
        else:
            return False

        if scale is None:
            scale = 1.0  # plain matmul softmax: no 1/sqrt(d) in source

        # matched through fp16::-wrapped matmuls (fp16 pass ran first):
        # keep the low-dtype compute the user asked for — downcast fp32
        # inputs, run the kernel there, upcast the result back, exactly
        # Fp16ProgramRewrite's contract
        low = getattr(op, "fp16_low", None) or getattr(qk, "fp16_low", None)

        def fused(q, k, v):
            from paddle_tpu.ops import flash_attention

            downcast = False
            if low is not None:
                ins = []
                for t in (q, k, v):
                    if t.dtype == jnp.float32:
                        ins.append(t.astype(low))
                        downcast = True
                    else:
                        ins.append(t)
                q, k, v = ins
            if not k_transposed:
                k = jnp.swapaxes(k, -1, -2)
            qt = jnp.swapaxes(q, 1, 2)  # [B,N,S,D] -> kernel's [B,S,N,D]
            kt = jnp.swapaxes(k, 1, 2)
            vt = jnp.swapaxes(v, 1, 2)
            o = flash_attention(qt, kt, vt, scale=scale, causal=causal)
            if downcast and o.dtype == low:
                o = o.astype(jnp.float32)
            return jnp.swapaxes(o, 1, 2)

        new_type = "flash_attention" if low is None else "fp16::flash_attention"
        graph.replace_op(op, _make_op(new_type, fused, [q_vid, k_vid, v_vid], op))
        return True


class RMSNormPattern(RewritePattern):
    """x·rsqrt(mean(x²)+ε)·w  ⇒  Pallas fused_rms_norm (ops/fused_norm.py).

    Anchored at the final weight multiply; accepts square(x) or
    multiply(x, x) for the square."""

    name = "rms_norm_fuse"
    root_type = "multiply"

    def _match_square_mean(self, vid, graph, x_vid):
        mean = graph.def_op(vid, "mean")
        if mean is None or not graph.single_use(vid):
            return False
        if len(mean.arg_spec) != 1 or mean.arg_spec[0][0] != "var":
            return False
        sq_vid = mean.arg_spec[0][1]
        if not graph.single_use(sq_vid):
            return False
        sq = graph.def_op(sq_vid)
        if sq is None:
            return False
        if _base_type(sq.type) == "square":
            return sq.arg_spec[0] == ("var", x_vid)
        if _base_type(sq.type) in ("multiply", "pow"):
            vids = [s[1] for s in sq.arg_spec if s[0] == "var"]
            if _base_type(sq.type) == "multiply":
                return vids == [x_vid, x_vid]
            c = next((_const_scalar(s) for s in sq.arg_spec if s[0] == "const"), None)
            return vids == [x_vid] and c == 2.0
        return False

    def match_and_rewrite(self, op, graph):
        # root: out = multiply(normed, w)   (w: 1-D over last axis)
        if len(op.arg_spec) != 2 or any(s[0] != "var" for s in op.arg_spec):
            return False
        normed_vid, w_vid = op.arg_spec[0][1], op.arg_spec[1][1]
        w_shape = graph.shape(w_vid)
        out_shape = graph.shape(op.out_vids[0]) if op.out_vids else None
        if not w_shape or not out_shape or len(w_shape) != 1 or w_shape[0] != out_shape[-1]:
            return False
        if not graph.single_use(normed_vid):
            return False
        # normed = multiply(x, rsqrt(mean(x*x) + eps))
        mul = graph.def_op(normed_vid, "multiply")
        if mul is None or len(mul.arg_spec) != 2 or any(s[0] != "var" for s in mul.arg_spec):
            return False
        x_vid, r_vid = mul.arg_spec[0][1], mul.arg_spec[1][1]
        if graph.shape(x_vid) != out_shape:
            x_vid, r_vid = r_vid, x_vid
        if graph.shape(x_vid) != out_shape:
            return False
        if not graph.single_use(r_vid):
            return False
        rs = graph.def_op(r_vid, "rsqrt")
        if rs is None or len(rs.arg_spec) != 1 or rs.arg_spec[0][0] != "var":
            return False
        add_vid = rs.arg_spec[0][1]
        if not graph.single_use(add_vid):
            return False
        add = graph.def_op(add_vid, "add")
        if add is None:
            return False
        eps = next((_const_scalar(s) for s in add.arg_spec if s[0] == "const"), None)
        var_ins = [s[1] for s in add.arg_spec if s[0] == "var"]
        if eps is None or len(var_ins) != 1:
            return False
        if not self._match_square_mean(var_ins[0], graph, x_vid):
            return False
        # mean must reduce the last axis with keepdim
        mean_shape = graph.shape(var_ins[0])
        if mean_shape is None or mean_shape != out_shape[:-1] + (1,):
            return False

        def fused(x, w):
            from paddle_tpu.ops import fused_rms_norm

            return fused_rms_norm(x, w, epsilon=eps)

        graph.replace_op(op, _make_op("fused_rms_norm", fused, [x_vid, w_vid], op))
        return True


class SwiGLUPattern(RewritePattern):
    """silu(g)·u  ⇒  Pallas swiglu (ops/swiglu.py)."""

    name = "swiglu_fuse"
    root_type = "multiply"

    def match_and_rewrite(self, op, graph):
        if len(op.arg_spec) != 2 or any(s[0] != "var" for s in op.arg_spec):
            return False
        a_vid, b_vid = op.arg_spec[0][1], op.arg_spec[1][1]
        for gate_vid, up_vid in ((a_vid, b_vid), (b_vid, a_vid)):
            silu = graph.def_op(gate_vid, "silu")
            if silu is None or not graph.single_use(gate_vid):
                continue
            if len(silu.arg_spec) != 1 or silu.arg_spec[0][0] != "var":
                continue
            g_vid = silu.arg_spec[0][1]
            if graph.shape(g_vid) != graph.shape(up_vid):
                continue

            def fused(g, u):
                from paddle_tpu.ops import swiglu

                return swiglu(g, u)

            graph.replace_op(op, _make_op("swiglu", fused, [g_vid, up_vid], op))
            return True
        return False


class PallasFusionPass(PatternRewritePass):
    """The default Pallas-substitution pipeline (SURVEY §7's CINN analog)."""

    name = "pallas_fusion"

    def __init__(self, fetch_vids=()):
        super().__init__(
            [FlashAttentionPattern(), RMSNormPattern(), SwiGLUPattern()],
            fetch_vids=fetch_vids,
        )
