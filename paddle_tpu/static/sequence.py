"""Sequence (LoD) op tier for paddle.static.nn.

Reference: python/paddle/static/nn/sequence_lod.py — ops over LoDTensors,
variable-length sequences stored as one flat tensor plus level-0 row
offsets.  TPUs want static shapes, so the TPU-native representation makes
the offsets EXPLICIT: every op takes `x` (the flat [total, ...] data) and
`lod` (the [n+1] int offsets vector, exactly the reference's level-0 LoD),
and computes with XLA segment ops / gathers instead of per-sequence host
loops.  Ops that return sequences return (flat, lod) pairs; ops that
reduce return dense [n, ...] tensors.

A missing `lod` raises immediately — the reference reads it off the
LoDTensor; here it must be passed, and silently assuming one-big-sequence
would be a wrong-results class.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.tensor._ops_common import apply, ensure_tensor

__all__ = [
    "sequence_softmax", "sequence_pool", "sequence_concat",
    "sequence_first_step", "sequence_last_step", "sequence_slice",
    "sequence_expand", "sequence_expand_as", "sequence_pad",
    "sequence_unpad", "sequence_reshape", "sequence_scatter",
    "sequence_enumerate", "sequence_reverse",
]


def _lod_np(lod, name):
    if lod is None:
        raise ValueError(
            f"{name}: `lod` (the [n+1] sequence offsets vector) is required "
            "— the TPU-native sequence tier stores offsets explicitly "
            "(reference LoDTensors carry them implicitly)")
    arr = np.asarray(lod._value if hasattr(lod, "_value") else lod,
                     dtype=np.int64)
    if arr.ndim != 1 or arr.size < 2 or arr[0] != 0 or np.any(np.diff(arr) < 0):
        raise ValueError(f"{name}: malformed lod {arr!r} (want monotonic "
                         "offsets starting at 0)")
    return arr


def _segment_ids(lod, total):
    """[total] int vector mapping each row to its sequence index."""
    total = int(total)
    ids = np.zeros(total, dtype=np.int32)
    # offsets == total belong to trailing EMPTY sequences — no rows carry
    # them (indexing with them would be out of bounds)
    starts = lod[1:-1].astype(np.int64)
    np.add.at(ids, starts[starts < total], 1)
    return np.cumsum(ids, dtype=np.int32)


def sequence_softmax(input, use_cudnn=False, name=None, lod=None):
    """Softmax over each sequence (input [total, 1] or [total])."""
    x = ensure_tensor(input)
    lod_np = _lod_np(lod, "sequence_softmax")
    seg = _segment_ids(lod_np, x.shape[0])
    n = len(lod_np) - 1

    def _fn(v):
        flat = v.reshape(v.shape[0], -1)
        m = jax.ops.segment_max(flat, seg, num_segments=n)[seg]
        e = jnp.exp(flat - m)
        z = jax.ops.segment_sum(e, seg, num_segments=n)[seg]
        return (e / z).reshape(v.shape)

    return apply("sequence_softmax", _fn, x)


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0, lod=None):
    """sum/average/sqrt/max/min/first/last pooling per sequence -> [n, ...].
    Empty sequences yield `pad_value` (reference semantics)."""
    x = ensure_tensor(input)
    pool_type = pool_type.lower()
    lod_np = _lod_np(lod, "sequence_pool")
    seg = _segment_ids(lod_np, x.shape[0])
    n = len(lod_np) - 1
    lens = np.diff(lod_np)
    empty = lens == 0

    def _fn(v):
        flat = v.reshape(v.shape[0], -1)
        if pool_type == "sum":
            out = jax.ops.segment_sum(flat, seg, num_segments=n)
        elif pool_type in ("average", "mean"):
            s = jax.ops.segment_sum(flat, seg, num_segments=n)
            out = s / jnp.maximum(jnp.asarray(lens)[:, None], 1)
        elif pool_type == "sqrt":
            s = jax.ops.segment_sum(flat, seg, num_segments=n)
            out = s / jnp.sqrt(jnp.maximum(jnp.asarray(lens)[:, None], 1))
        elif pool_type == "max":
            out = jax.ops.segment_max(flat, seg, num_segments=n)
        elif pool_type == "min":
            out = jax.ops.segment_min(flat, seg, num_segments=n)
        elif pool_type == "first":
            idx = np.clip(lod_np[:-1], 0, max(v.shape[0] - 1, 0))
            out = flat[jnp.asarray(idx)]
        elif pool_type == "last":
            idx = np.clip(lod_np[1:] - 1, 0, max(v.shape[0] - 1, 0))
            out = flat[jnp.asarray(idx)]
        else:
            raise ValueError(f"unknown pool_type {pool_type}")
        if empty.any():
            out = jnp.where(jnp.asarray(empty)[:, None], pad_value, out)
        return out.reshape((n,) + v.shape[1:])

    return apply("sequence_pool", _fn, x)


def sequence_first_step(input, lod=None):
    return sequence_pool(input, "first", lod=lod)


def sequence_last_step(input, lod=None):
    return sequence_pool(input, "last", lod=lod)


def sequence_concat(input, name=None, lod=None):
    """Concat the i-th sequences of every input -> (flat, lod).
    `input`/`lod` are same-length lists."""
    if lod is None or len(input) != len(lod):
        raise ValueError("sequence_concat needs one lod per input")
    xs = [ensure_tensor(x) for x in input]
    lods = [_lod_np(l, "sequence_concat") for l in lod]
    n = len(lods[0]) - 1
    if any(len(l) - 1 != n for l in lods):
        raise ValueError("sequence_concat: inputs disagree on sequence count")
    order = []  # (input_idx, start, stop) in output order
    out_lens = []
    for i in range(n):
        tot = 0
        for j, l in enumerate(lods):
            order.append((j, int(l[i]), int(l[i + 1])))
            tot += int(l[i + 1] - l[i])
        out_lens.append(tot)
    gather_src = np.concatenate(
        [np.arange(s, e) + sum(x.shape[0] for x in xs[:j])
         for j, s, e in order]) if order else np.zeros(0, np.int64)
    new_lod = np.concatenate([[0], np.cumsum(out_lens)])

    def _fn(*vs):
        allv = jnp.concatenate([v.reshape(v.shape[0], -1) for v in vs], 0)
        out = allv[jnp.asarray(gather_src)]
        return out.reshape((out.shape[0],) + vs[0].shape[1:])

    from paddle_tpu._core.tensor import Tensor

    flat = apply("sequence_concat", _fn, *xs)
    return flat, Tensor(jnp.asarray(new_lod))


def sequence_slice(input, offset, length, name=None, lod=None):
    """Per-sequence slice: sequence i keeps rows [offset[i], offset[i]+length[i])."""
    x = ensure_tensor(input)
    lod_np = _lod_np(lod, "sequence_slice")
    off = np.asarray(offset._value if hasattr(offset, "_value") else offset,
                     np.int64).reshape(-1)
    ln = np.asarray(length._value if hasattr(length, "_value") else length,
                    np.int64).reshape(-1)
    n = len(lod_np) - 1
    if off.size != n or ln.size != n:
        raise ValueError("sequence_slice: offset/length must have one entry "
                         "per sequence")
    idx, new_lens = [], []
    for i in range(n):
        s = lod_np[i] + off[i]
        e = s + ln[i]
        if off[i] < 0 or e > lod_np[i + 1]:
            raise ValueError(f"sequence_slice: slice [{off[i]}, {off[i]+ln[i]}) "
                             f"out of bounds for sequence {i} of length "
                             f"{lod_np[i+1]-lod_np[i]}")
        idx.append(np.arange(s, e))
        new_lens.append(int(ln[i]))
    gather = np.concatenate(idx) if idx else np.zeros(0, np.int64)
    new_lod = np.concatenate([[0], np.cumsum(new_lens)])

    from paddle_tpu._core.tensor import Tensor

    flat = apply("sequence_slice", lambda v: v[jnp.asarray(gather)], x)
    return flat, Tensor(jnp.asarray(new_lod))


def sequence_expand(x, y, ref_level=-1, name=None, x_lod=None, y_lod=None):
    """Repeat sequence i of x once per entry of y's sequence i
    (level-0 semantics of the reference op)."""
    xt = ensure_tensor(x)
    ylod = _lod_np(y_lod, "sequence_expand")
    xlod = _lod_np(x_lod, "sequence_expand") if x_lod is not None else None
    n = len(ylod) - 1
    reps = np.diff(ylod)
    if xlod is None:  # x dense [n, ...]: repeat rows
        if int(xt.shape[0]) != n:
            raise ValueError("sequence_expand: dense x rows must equal y's "
                             "sequence count")
        gather = np.repeat(np.arange(n), reps)
        new_lod = np.concatenate([[0], np.cumsum(reps)])
    else:
        if len(xlod) - 1 != n:
            raise ValueError("sequence_expand: x and y sequence counts differ")
        idx, lens = [], []
        for i in range(n):
            seq = np.arange(xlod[i], xlod[i + 1])
            for _ in range(int(reps[i])):
                idx.append(seq)
                lens.append(seq.size)
        gather = np.concatenate(idx) if idx else np.zeros(0, np.int64)
        new_lod = np.concatenate([[0], np.cumsum(lens)]) if lens else np.array([0, 0])

    from paddle_tpu._core.tensor import Tensor

    flat = apply("sequence_expand", lambda v: v[jnp.asarray(gather)], xt)
    return flat, Tensor(jnp.asarray(new_lod))


def sequence_expand_as(x, y, name=None, y_lod=None):
    """Expand row i of x to the length of y's sequence i."""
    xt = ensure_tensor(x)
    ylod = _lod_np(y_lod, "sequence_expand_as")
    n = len(ylod) - 1
    if int(xt.shape[0]) != n:
        raise ValueError("sequence_expand_as: x rows must equal y's "
                         "sequence count")
    reps = np.diff(ylod)
    gather = np.repeat(np.arange(n), reps)

    from paddle_tpu._core.tensor import Tensor

    flat = apply("sequence_expand_as", lambda v: v[jnp.asarray(gather)], xt)
    return flat, Tensor(jnp.asarray(ylod))


def sequence_pad(x, pad_value, maxlen=None, name=None, lod=None):
    """(flat, lod) -> ([n, maxlen, ...] padded, [n] lengths)."""
    xt = ensure_tensor(x)
    pv = ensure_tensor(pad_value)
    lod_np = _lod_np(lod, "sequence_pad")
    lens = np.diff(lod_np)
    n = len(lens)
    m = int(maxlen) if maxlen is not None else int(lens.max() if n else 0)
    if n and lens.max() > m:
        raise ValueError(f"sequence_pad: maxlen={m} < longest sequence "
                         f"({int(lens.max())})")
    # gather index per (seq, pos): row in flat, or a sentinel for padding
    gather = np.zeros((n, m), np.int64)
    is_pad = np.ones((n, m), bool)
    for i in range(n):
        gather[i, : lens[i]] = np.arange(lod_np[i], lod_np[i + 1])
        is_pad[i, : lens[i]] = False

    def _fn(v, p):
        flat = v.reshape(v.shape[0], -1)
        out = flat[jnp.asarray(gather.reshape(-1))]
        out = jnp.where(jnp.asarray(is_pad.reshape(-1))[:, None],
                        p.reshape(-1), out)
        return out.reshape((n, m) + v.shape[1:])

    from paddle_tpu._core.tensor import Tensor

    padded = apply("sequence_pad", _fn, xt, pv)
    return padded, Tensor(jnp.asarray(lens))


def sequence_unpad(x, length, name=None):
    """([n, maxlen, ...], [n] lengths) -> (flat, lod)."""
    xt = ensure_tensor(x)
    lens = np.asarray(length._value if hasattr(length, "_value") else length,
                      np.int64).reshape(-1)
    n, m = int(xt.shape[0]), int(xt.shape[1])
    if lens.size != n or (lens > m).any():
        raise ValueError("sequence_unpad: bad lengths")
    pairs = np.concatenate([np.stack([np.full(l, i), np.arange(l)], 1)
                            for i, l in enumerate(lens) if l],
                           0) if lens.sum() else np.zeros((0, 2), np.int64)
    lod_np = np.concatenate([[0], np.cumsum(lens)])

    def _fn(v):
        flat = v.reshape(n * m, -1)
        out = flat[jnp.asarray(pairs[:, 0] * m + pairs[:, 1])]
        return out.reshape((out.shape[0],) + v.shape[2:])

    from paddle_tpu._core.tensor import Tensor

    return apply("sequence_unpad", _fn, xt), Tensor(jnp.asarray(lod_np))


def sequence_reshape(input, new_dim, lod=None):
    """Re-chunk each sequence's flattened features into rows of new_dim."""
    x = ensure_tensor(input)
    lod_np = _lod_np(lod, "sequence_reshape")
    d = int(x.shape[-1])
    lens = np.diff(lod_np) * d
    if (lens % new_dim).any():
        raise ValueError("sequence_reshape: each sequence's total elements "
                         "must divide new_dim")
    new_lens = lens // new_dim
    new_lod = np.concatenate([[0], np.cumsum(new_lens)])

    from paddle_tpu._core.tensor import Tensor

    flat = apply("sequence_reshape",
                 lambda v: v.reshape(-1, new_dim), x)
    return flat, Tensor(jnp.asarray(new_lod))


def sequence_scatter(input, index, updates, name=None, index_lod=None):
    """Scatter-add per-sequence updates into rows of a dense input:
    sequence i's (index, update) pairs modify input row i."""
    x = ensure_tensor(input)
    upd = ensure_tensor(updates)
    lod_np = _lod_np(index_lod, "sequence_scatter")
    idx = np.asarray(index._value if hasattr(index, "_value") else index,
                     np.int64).reshape(-1)
    seg = _segment_ids(lod_np, idx.size)

    def _fn(v, u):
        rows = jnp.asarray(seg)
        cols = jnp.asarray(idx)
        return v.at[rows, cols].add(u.reshape(-1).astype(v.dtype))

    return apply("sequence_scatter", _fn, x, upd)


def sequence_enumerate(input, win_size, pad_value=0, name=None, lod=None):
    """Sliding windows of ids per sequence -> [total, win_size]."""
    x = ensure_tensor(input)
    lod_np = _lod_np(lod, "sequence_enumerate")
    total = int(x.shape[0])
    gather = np.zeros((total, win_size), np.int64)
    pad = np.zeros((total, win_size), bool)
    n = len(lod_np) - 1
    for i in range(n):
        for t in range(lod_np[i], lod_np[i + 1]):
            for w in range(win_size):
                src = t + w
                if src < lod_np[i + 1]:
                    gather[t, w] = src
                else:
                    pad[t, w] = True

    def _fn(v):
        flat = v.reshape(-1)
        out = flat[jnp.asarray(gather.reshape(-1))]
        out = jnp.where(jnp.asarray(pad.reshape(-1)), pad_value, out)
        return out.reshape(total, win_size)

    return apply("sequence_enumerate", _fn, x)


def sequence_reverse(x, name=None, lod=None):
    """Reverse rows within each sequence."""
    xt = ensure_tensor(x)
    lod_np = _lod_np(lod, "sequence_reverse")
    gather = np.concatenate(
        [np.arange(lod_np[i + 1] - 1, lod_np[i] - 1, -1)
         for i in range(len(lod_np) - 1)]
    ) if lod_np[-1] else np.zeros(0, np.int64)
    return apply("sequence_reverse", lambda v: v[jnp.asarray(gather)], xt)
