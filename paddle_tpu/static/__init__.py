"""paddle.static parity (reference python/paddle/static/) on the TPU-native
Program IR: capture via the apply() funnel, execution via one jitted XLA
program per (program, signature) — see program.py / executor.py."""

from __future__ import annotations

import jax

from paddle_tpu._core import dtype as _dtype_mod

from .program import (  # noqa: F401
    Program,
    Variable,
    Operator,
    program_guard,
    default_main_program,
    default_startup_program,
    enable_static,
    disable_static,
    in_dynamic_mode,
    in_static_capture,
    current_main_program,
    name_scope,
    suspend_capture,
)
from .executor import Executor, Scope, global_scope, scope_guard  # noqa: F401
from .autodiff import append_backward, gradients  # noqa: F401
from .io import (  # noqa: F401
    save,
    load,
    save_inference_model,
    load_inference_model,
    serialize_program,
    deserialize_program,
)
from .verify import (  # noqa: F401
    ProgramVerifier,
    VerificationError,
    differential_check,
    verify_program,
)
from .mesh_lint import (  # noqa: F401
    MeshLinter,
    MeshLintError,
    lint_engine,
    lint_program,
    lint_train_step,
)
from .protocol_lint import (  # noqa: F401
    ProtocolLintError,
    ProtocolViolation,
    check_model,
    lint_blocking_calls,
    lint_cluster_protocol,
)
from . import nn  # noqa: F401
from .compat import *  # noqa: F401,F403
from .compat import __all__ as _compat_all

__all__ = _compat_all + [
    "Program",
    "Variable",
    "program_guard",
    "default_main_program",
    "default_startup_program",
    "data",
    "InputSpec",
    "Executor",
    "global_scope",
    "scope_guard",
    "append_backward",
    "gradients",
    "save",
    "load",
    "save_inference_model",
    "load_inference_model",
    "nn",
    "cpu_places",
    "device_guard",
    "ProgramVerifier",
    "VerificationError",
    "verify_program",
    "differential_check",
    "MeshLinter",
    "MeshLintError",
    "lint_program",
    "lint_train_step",
    "lint_engine",
    "ProtocolLintError",
    "ProtocolViolation",
    "check_model",
    "lint_cluster_protocol",
    "lint_blocking_calls",
]


def data(name, shape, dtype="float32", lod_level=0):
    """Feed placeholder (reference paddle.static.data).  -1 dims are captured
    as 1 for shape inference; execution re-traces with actual feed shapes."""
    prog = current_main_program()
    if prog is None:
        prog = default_main_program()
    jdt = _dtype_mod.to_jax_dtype(dtype)
    dyn = tuple(i for i, d in enumerate(shape) if d is None or d < 0)
    shape = [1 if (d is None or d < 0) else int(d) for d in shape]
    v = prog.new_var(jax.ShapeDtypeStruct(tuple(shape), jdt), name=name)
    v.dynamic_dims = dyn  # export serializes these as symbolic dims
    prog.add_feed(v)
    return v


class InputSpec:
    """paddle.static.InputSpec parity (used by jit.save signatures)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def cpu_places(device_count=None):
    from paddle_tpu._core.place import CPUPlace

    return [CPUPlace()]


class device_guard:
    def __init__(self, device=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
