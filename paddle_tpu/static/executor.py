"""Static-graph executor.

Reference: StandaloneExecutor + PirInterpreter
(paddle/fluid/framework/new_executor/pir_interpreter.h:56 Run,
standalone_executor.cc; python/paddle/base/executor.py:1151 Executor, :2017
_run_pir_impl).

TPU-native: the "interpreter" executes the Program's op list once under
jax.jit tracing, producing ONE fused XLA executable per (program version,
feed signature, fetch set) — dependency analysis, stream assignment, memory
planning and GC are XLA's job.  Persistent state (parameters + optimizer
accumulators) lives in a Scope keyed by variable id; state buffers are
donated to the executable so updates are in-place in HBM.
"""

from __future__ import annotations

import numpy as np
import jax

from paddle_tpu._core.tensor import Tensor

from .program import Program, Variable, default_main_program, _st

__all__ = ["Executor", "Scope", "global_scope", "scope_guard"]


class Scope:
    def __init__(self):
        self._vals: dict[int, jax.Array] = {}

    def find_var(self, vid):
        return self._vals.get(vid)

    def set_var(self, vid, val):
        self._vals[vid] = val


_global_scope = Scope()


def global_scope():
    return _global_scope


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        global _global_scope
        self._prev = _global_scope
        _global_scope = self.scope
        return self.scope

    def __exit__(self, *exc):
        global _global_scope
        _global_scope = self._prev


class Executor:
    """Executor(place).run(program, feed, fetch_list) -> list of np arrays."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def _ensure_state(self, program: Program, scope: Scope):
        import jax.numpy as jnp

        for vid, init in program.param_inits.items():
            if scope.find_var(vid) is None:
                # own copy: state buffers are donated to the executable, and
                # the init value may still back a live dygraph Parameter
                scope.set_var(vid, jnp.array(init, copy=True))

    @staticmethod
    def _rewrite_stage(program, fetch_vids, feed_vals, verify_mode,
                       stamp_attr, pass_cls):
        """One memoized fusion stage.  Memoized per (version, fetch set) —
        a SET, so alternating fetch lists don't ping-pong the stamp and
        re-pay the scan on the per-step hot path.  Verify mode keeps the
        unrewritten program so any fusion this stamp performs can be
        differentially replayed on the LIVE feed (static/verify.py;
        docs/VERIFIER.md)."""
        seen = getattr(program, stamp_attr, None)
        if seen is None:
            seen = set()
            setattr(program, stamp_attr, seen)
        stamp = (program.version, fetch_vids)
        if stamp in seen:
            return
        reference = program.clone() if verify_mode else None
        fused = pass_cls(fetch_vids).apply(program)
        if verify_mode and fused:
            from .verify import DifferentialError, differential_check

            try:
                differential_check(reference, program, fetch_vids,
                                   feeds=feed_vals)
            except DifferentialError:
                # sticky failure: un-fuse and don't stamp, so a caller that
                # catches and retries re-runs the pass and the check
                # instead of silently serving the mis-fused program
                program.global_block().ops[:] = \
                    reference.global_block().ops
                program.version = reference.version
                raise
        seen.add((program.version, fetch_vids))

    def run(self, program=None, feed=None, fetch_list=None, scope=None, return_numpy=True):
        program = program or default_main_program()
        scope = scope or global_scope()
        feed = feed or {}
        fetch_list = fetch_list or []

        if not program.global_block().ops and not program.param_inits and not fetch_list:
            return []  # startup program: state materializes lazily below
            # (op-less programs WITH fetches still run: feeds flow straight
            # to fetches — pass-through segments from jit/sot.py need this)

        self._ensure_state(program, scope)

        fetch_vars = []
        for f in fetch_list:
            if isinstance(f, Variable):
                fetch_vars.append(f)
            elif isinstance(f, str):
                fetch_vars.append(program.global_block().var(f))
            else:
                raise TypeError(f"bad fetch entry {f!r}")
        fetch_vids = tuple(v._vid for v in fetch_vars)

        feed_vals = []
        for v in program.feed_vars:
            if v.name not in feed:
                raise KeyError(f"missing feed '{v.name}'")
            feed_vals.append(jax.numpy.asarray(feed[v.name], v._value.dtype))

        from paddle_tpu._core import flags

        verify_mode = flags.flag("FLAGS_verify_programs")
        if flags.flag("FLAGS_use_pallas_fusion"):
            # default pass pipeline: substitute Pallas kernels for the
            # attention/rms-norm/swiglu subgraphs XLA cannot re-derive
            # (SURVEY §7's CINN role).  Idempotent — fused ops don't match
            # again; a change bumps program.version → fresh cache entry.
            from .rewrite import PallasFusionPass

            self._rewrite_stage(program, fetch_vids, feed_vals, verify_mode,
                                "_pallas_fused_at", PallasFusionPass)
        if flags.flag("FLAGS_schedule_search"):
            # schedule-searched fusion over discovered reduction-/matmul-
            # rooted subgraphs (docs/SCHEDULE_SEARCH.md).  Runs AFTER the
            # named patterns so those keep their hand-written kernels;
            # accepted schedules come from the per-device autotune cache,
            # so steady-state runs pay a lookup, not a search.
            from .rewrite import ScheduleSearchPass

            self._rewrite_stage(program, fetch_vids, feed_vals, verify_mode,
                                "_sched_searched_at", ScheduleSearchPass)

        sig = tuple((tuple(v.shape), str(v.dtype)) for v in feed_vals)
        key = (id(program), program.version, sig, fetch_vids)
        if key not in self._cache:
            if verify_mode:
                # compile path, verify mode: the program about to be traced
                # must be structurally valid for THIS fetch set
                from .verify import verify_program

                verify_program(program, fetch_vids)
            if flags.flag("FLAGS_verify_sharding"):
                # mesh lint on the compile path: collective congruence of
                # every recorded op + use-after-donation on the fetch set,
                # abstractly — before XLA (or a dead-axis rendezvous) can
                # turn a placement bug into a hang (docs/MESH_LINT.md)
                from .mesh_lint import lint_program as _mesh_lint

                _mesh_lint(program, fetch_vids, raise_on_error=True)
            # Prune to the fetch/write frontier (non-mutating): ops whose
            # outputs no fetch or state write needs don't execute.  Beyond
            # wasted compute, a dead duplicate of a collective-carrying
            # chain (value_and_grad's forward vs the recorded forward ops)
            # can deadlock XLA:CPU's in-process communicator.
            live = set(fetch_vids) | set(program.writes) | set(program.writes.values())
            pruned = []
            for op in reversed(program.global_block().ops):
                if any(v in live for v in op.out_vids):
                    pruned.append(op)
                    # last-writer-wins: this op now defines its outputs, so
                    # earlier (superseded) producers of the same vids are
                    # dead — without this, append_backward's share_loss
                    # re-bind keeps the original forward chain alive and the
                    # compiled step traces the forward twice
                    live.difference_update(op.out_vids)
                    live.update(op.input_vids())
            pruned.reverse()
            run_fn, feed_vids, state_vids = program.as_function(
                list(fetch_vids), ops=pruned)

            prev = _st.main_program
            _st.main_program = None  # never capture while executing
            try:
                compiled = jax.jit(run_fn, donate_argnums=(1,) if program.writes else ())
            finally:
                _st.main_program = prev
            self._cache[key] = (compiled, state_vids)
        compiled, state_vids = self._cache[key]

        state_vals = [scope.find_var(vid) for vid in state_vids]
        prev = _st.main_program
        _st.main_program = None
        try:
            fetches, new_state = compiled(feed_vals, state_vals)
        finally:
            _st.main_program = prev

        if program.writes:
            for vid, val in zip(state_vids, new_state):
                scope.set_var(vid, val)

        if return_numpy:
            return [np.asarray(v) for v in fetches]
        return [Tensor(v) for v in fetches]

    def close(self):
        self._cache.clear()

    def state_dict(self, program: Program, scope=None):
        """Name-keyed trained values (parameters + optimizer state)."""
        scope = scope or global_scope()
        return {
            var.name: scope.find_var(var._vid)
            for var in program.all_parameters() + list(program.state_vars.values())
            if scope.find_var(var._vid) is not None
        }
