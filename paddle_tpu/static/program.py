"""Static graph IR: Program / Block / Variable / Operator.

Reference: the PIR program stack (paddle/pir/core/*.h Operation/Value/Block/
Program; python surface python/paddle/base/framework.py:5655 Program, :1401
Variable, program_guard :7733).

TPU-native redesign (SURVEY.md §7): the IR's only lowering target is XLA, so
an op is simply (traceable jax fn, input vars/constants, static attrs) and
InferMeta is jax.eval_shape.  Capture rides the SAME funnel as eager — every
framework op goes through `_core.autograd.apply`, which, inside a
program_guard, appends an Operator instead of executing (so the whole tensor/
nn surface is static-capturable with no per-op work, like the reference's
single YAML registry feeding both dygraph and PIR codegen).  Parameters
(dygraph `Parameter` objects touched during capture) auto-register as program
inputs with their init value recorded for the startup program.  Programs
compile to a single XLA executable per (feed signature, fetch set) in the
Executor.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp

from paddle_tpu._core.tensor import Parameter, Tensor

__all__ = [
    "Variable",
    "Operator",
    "Block",
    "Program",
    "program_guard",
    "default_main_program",
    "default_startup_program",
    "in_static_capture",
    "current_main_program",
    "enable_static",
    "disable_static",
    "in_dynamic_mode",
    "name_scope",
]

_vid_counter = itertools.count()

# Observers called with every new Program (construction AND clone) — the
# verifier's track_programs() sweep hook (static/verify.py, tools/lint_ir.py).
_creation_hooks: list = []


class Variable(Tensor):
    """Symbolic tensor in a Program: `_value` is a jax.ShapeDtypeStruct.

    Subclasses Tensor so the whole op surface (which reads ._value
    shape/dtype and routes compute through apply) treats it uniformly.
    """

    __slots__ = ("_vid", "_program", "is_parameter", "dynamic_dims")

    def __init__(self, aval, name="", program=None, persistable=False, is_parameter=False):
        # bypass Tensor.__init__ value coercion
        self._value = aval
        self.stop_gradient = True
        self.name = name or f"var_{next(_vid_counter)}"
        self.grad = None
        self._grad_node = None
        self._out_index = None
        self._hooks = []
        self._vid = next(_vid_counter)
        self._program = program
        self.persistable = persistable
        self.is_parameter = is_parameter
        self.dynamic_dims = ()  # axis positions declared as -1/None in data()

    def numpy(self):
        raise RuntimeError(
            f"Variable '{self.name}' has no value inside a static Program; "
            "fetch it through Executor.run"
        )

    def __repr__(self):
        return f"Variable(name={self.name}, shape={list(self._value.shape)}, dtype={self._value.dtype})"


@dataclass
class Operator:
    """One recorded op: jax fn + where its inputs come from.

    arg_spec entries: ('var', vid) for Variable inputs, ('const', value) for
    captured concrete values / python args.
    """

    type: str
    fn: Any
    arg_spec: list
    kwargs: dict
    out_vids: list
    out_tree: Any

    def input_vids(self):
        return [s[1] for s in self.arg_spec if s[0] == "var"]


class Block:
    def __init__(self, program, idx=0):
        self.program = program
        self.idx = idx
        self.ops: list[Operator] = []
        self.vars: dict[str, Variable] = {}

    def var(self, name):
        return self.vars[name]

    def all_parameters(self):
        return [v for v in self.vars.values() if v.is_parameter]


class Program:
    """A captured computation: feed vars -> ops -> any var fetchable.

    `param_inits` maps parameter vid -> concrete init value (the startup
    program's content); `writes` maps vid -> vid (state updates applied to the
    scope after each run — optimizer param/accumulator updates).
    """

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.feed_vars: list[Variable] = []
        self.param_vars: dict[int, Variable] = {}  # id(Parameter) -> Variable
        self.param_inits: dict[int, Any] = {}  # vid -> concrete init value
        self.state_vars: dict[int, Variable] = {}  # id(Tensor) -> Variable (opt state)
        self.writes: dict[int, int] = {}  # target vid -> source vid
        self.version = 0
        self._var_by_vid: dict[int, Variable] = {}
        self.random_seed = None
        for cb in _creation_hooks:
            cb(self)

    # ------------------------------------------------------------- structure
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[-1]

    def list_vars(self):
        return list(self.global_block().vars.values())

    def all_parameters(self):
        return [self._var_by_vid[vid] for vid in self.param_inits if self._var_by_vid[vid].is_parameter]

    # -------------------------------------------------------------- capture
    def _register_var(self, var: Variable):
        var._program = self
        self.global_block().vars[var.name] = var
        self._var_by_vid[var._vid] = var
        self.version += 1
        return var

    def new_var(self, aval, name="", persistable=False, is_parameter=False):
        return self._register_var(
            Variable(aval, name=name, program=self, persistable=persistable, is_parameter=is_parameter)
        )

    def add_feed(self, var: Variable):
        self.feed_vars.append(var)
        return var

    def var_for_parameter(self, p: Parameter) -> Variable:
        key = id(p)
        if key not in self.param_vars:
            aval = jax.ShapeDtypeStruct(p._value.shape, p._value.dtype)
            v = self.new_var(aval, name=p.name or f"param_{len(self.param_vars)}", persistable=True, is_parameter=True)
            self.param_vars[key] = v
            self.param_inits[v._vid] = p._value
        return self.param_vars[key]

    def var_for_state(self, t: Tensor, name="") -> Variable:
        """Non-parameter persistent state (optimizer accumulators)."""
        key = id(t)
        if key not in self.state_vars:
            aval = jax.ShapeDtypeStruct(t._value.shape, t._value.dtype)
            v = self.new_var(aval, name=name or f"state_{len(self.state_vars)}", persistable=True)
            self.state_vars[key] = v
            self.param_inits[v._vid] = t._value
        return self.state_vars[key]

    def state_tensors(self):
        """Name -> persistable payload (params + optimizer state) of this
        program — the save/load unit of static.io serialize_persistables."""
        out = {}
        for vid, val in self.param_inits.items():
            var = self._var_by_vid.get(vid)
            if var is not None:
                out[var.name] = Tensor(val)
        return out

    def set_state_tensor(self, name, value):
        for vid in list(self.param_inits):
            var = self._var_by_vid.get(vid)
            if var is not None and var.name == name:
                self.param_inits[vid] = value
                return True
        return False

    def record(self, type_, fn, args, kwargs):
        """Append an Operator; returns output Variable(s).  Called by
        _core.autograd.apply when this program is being captured."""
        from paddle_tpu._core import autograd as _ag

        if _ag._state.touch_recorders:
            # control-flow capture discovery (static.control_flow): log the
            # Variable inputs so branch closures' dependencies are found
            _ag._state.touch_recorders[-1].inputs.extend(
                a for a in args if isinstance(a, Tensor)
            )
        arg_spec = []
        in_avals = []
        var_slots = []
        for i, a in enumerate(args):
            if isinstance(a, Variable):
                arg_spec.append(("var", a._vid))
                in_avals.append(jax.ShapeDtypeStruct(a._value.shape, a._value.dtype))
                var_slots.append(i)
            elif isinstance(a, Parameter):
                v = self.var_for_parameter(a)
                arg_spec.append(("var", v._vid))
                in_avals.append(jax.ShapeDtypeStruct(a._value.shape, a._value.dtype))
                var_slots.append(i)
            elif isinstance(a, Tensor):
                arg_spec.append(("const", a._value))
            else:
                arg_spec.append(("const", a))

        n_args = len(args)
        slot_set = set(var_slots)

        def g(*var_vals):
            it = iter(var_vals)
            full = [next(it) if i in slot_set else arg_spec[i][1] for i in range(n_args)]
            with suspend_capture():
                return fn(*full, **kwargs)

        out_shape = jax.eval_shape(g, *in_avals)
        flat, tree = jax.tree_util.tree_flatten(out_shape)
        outs = [self.new_var(jax.ShapeDtypeStruct(o.shape, o.dtype)) for o in flat]
        op = Operator(type_, g, arg_spec, dict(kwargs), [o._vid for o in outs], tree)
        self.current_block().ops.append(op)
        self.version += 1
        return jax.tree_util.tree_unflatten(tree, outs)

    def add_write(self, target: Variable, source: Variable):
        self.writes[target._vid] = source._vid
        self.version += 1

    # ------------------------------------------------------------ execution
    def as_function(self, fetch_vids, feed_vids=None, state_vids=None, ops=None):
        """Build fn(feed_vals, state_vals) -> (fetches, write_values).

        `ops` overrides the executed op list (passes re-derive a grad
        super-op over a transformed forward prefix this way)."""
        feed_vids = feed_vids if feed_vids is not None else [v._vid for v in self.feed_vars]
        state_vids = state_vids if state_vids is not None else list(self.param_inits.keys())
        ops = list(self.global_block().ops) if ops is None else list(ops)
        writes = dict(self.writes)

        def run(feed_vals, state_vals):
            env = {}
            for vid, val in zip(feed_vids, feed_vals):
                env[vid] = val
            for vid, val in zip(state_vids, state_vals):
                env[vid] = val
            for op in ops:
                var_vals = [env[s[1]] for s in op.arg_spec if s[0] == "var"]
                out = op.fn(*var_vals)
                flat = jax.tree_util.tree_leaves(out)
                for vid, v in zip(op.out_vids, flat):
                    env[vid] = v
            fetches = [env[vid] for vid in fetch_vids]
            new_state = [env.get(writes.get(vid, -1), env[vid]) for vid in state_vids]
            return fetches, new_state

        return run, feed_vids, state_vids

    # --------------------------------------------------------------- extras
    def clone(self, for_test=False):
        p = Program.__new__(Program)
        p.blocks = [Block(p, 0)]
        p.blocks[0].ops = list(self.global_block().ops)
        p.blocks[0].vars = dict(self.global_block().vars)
        p.feed_vars = list(self.feed_vars)
        p.param_vars = dict(self.param_vars)
        p.param_inits = dict(self.param_inits)
        p.state_vars = dict(self.state_vars)
        p.writes = {} if for_test else dict(self.writes)
        p.version = self.version
        p._var_by_vid = dict(self._var_by_vid)
        p.random_seed = self.random_seed
        for cb in _creation_hooks:
            cb(p)
        return p

    def to_string(self, throw_on_error=False, with_details=False):
        lines = [f"Program(version={self.version})"]
        for v in self.feed_vars:
            lines.append(f"  feed {v!r}")
        for op in self.global_block().ops:
            ins = ", ".join(str(s[1]) if s[0] == "var" else "<const>" for s in op.arg_spec)
            lines.append(f"  {op.type}({ins}) -> {op.out_vids}")
        for t, s in self.writes.items():
            lines.append(f"  write var{t} <- var{s}")
        return "\n".join(lines)

    __str__ = to_string


# ------------------------------------------------------------------ context

class _StaticState(threading.local):
    def __init__(self):
        self.main_program = None
        self.startup_program = None
        self.static_mode = False
        self.suspended = 0
        self.default_main = Program()
        self.default_startup = Program()


_st = _StaticState()


@contextlib.contextmanager
def suspend_capture():
    """Run eagerly (on values or tracers) while a program_guard is active —
    used while tracing a recorded op's body (e.g. the optimizer-update
    super-op replays Optimizer.step through the eager path)."""
    _st.suspended += 1
    try:
        yield
    finally:
        _st.suspended -= 1


def in_static_capture():
    return _st.main_program is not None and not _st.suspended


def current_main_program():
    return _st.main_program


def default_main_program():
    return _st.default_main


def default_startup_program():
    return _st.default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    prev = (_st.main_program, _st.startup_program)
    _st.main_program = main_program
    _st.startup_program = startup_program
    try:
        yield
    finally:
        _st.main_program, _st.startup_program = prev


def enable_static():
    """Reference paddle.enable_static: subsequent API calls build the default
    main program until disable_static()."""
    _st.static_mode = True
    _st.main_program = _st.default_main
    _st.startup_program = _st.default_startup


def disable_static():
    _st.static_mode = False
    _st.main_program = None
    _st.startup_program = None


def in_dynamic_mode():
    return _st.main_program is None


@contextlib.contextmanager
def name_scope(prefix):
    yield
