"""Static-graph compat surface (reference: python/paddle/static/__init__.py).

Strategy/places/persistable utilities the reference exports at
paddle.static.*.  On XLA these are thin by design: BuildStrategy's fusion
passes and ExecutionStrategy's thread pools configure machinery XLA replaces
(whole-program compilation + its own scheduler), so the knob objects are
kept (scripts set them freely) and the Executor honors what still has
meaning.  IPU members are n/a on this backend (SURVEY.md excludes IPU) and
raise if actually used.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "BuildStrategy",
    "CompiledProgram",
    "ExecutionStrategy",
    "ExponentialMovingAverage",
    "Print",
    "WeightNormParamAttr",
    "accuracy",
    "auc",
    "create_global_var",
    "create_parameter",
    "ctr_metric_bundle",
    "cuda_places",
    "xpu_places",
    "deserialize_persistables",
    "serialize_persistables",
    "load_from_file",
    "save_to_file",
    "load_program_state",
    "set_program_state",
    "normalize_program",
    "py_func",
    "ipu_shard_guard",
    "set_ipu_shard",
    "IpuStrategy",
    "IpuCompiledProgram",
]


class BuildStrategy:
    """Graph-build knobs (reference: paddle/fluid/framework/build_strategy.h).
    XLA performs fusion/memory-planning itself; the attributes are accepted
    so reference training scripts run unchanged."""

    def __init__(self):
        self.enable_inplace = True
        self.memory_optimize = True
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.fuse_bn_add_act_ops = False
        self.fuse_gemm_epilogue = False
        self.fuse_all_reduce_ops = False
        self.enable_addto = False
        self.reduce_strategy = 0
        self.gradient_scale_strategy = 0
        self.build_cinn_pass = False
        self.sync_batch_norm = False


class ExecutionStrategy:
    """Executor knobs (reference ExecutionStrategy): thread counts map to
    nothing on a compiled-executable runtime, kept for script compat."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        self.num_iteration_per_run = 1


class CompiledProgram:
    """reference: python/paddle/static/compiler.py CompiledProgram — the
    with-data-parallel wrapper.  Here a Program already compiles to one XLA
    executable per feed signature, so this forwards to the wrapped program
    and keeps the strategy objects."""

    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()

    def __getattr__(self, item):
        return getattr(self._program, item)


def cuda_places(device_ids=None):
    """Accelerator places (reference static.cuda_places maps to GPUs; here
    the default backend's devices)."""
    import jax

    from paddle_tpu._core.place import CUDAPlace

    n = len(jax.devices())
    ids = range(n) if device_ids is None else device_ids
    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False, name=None):
    """A mutable global tensor registered on the current static Program's
    scope (reference: python/paddle/static/__init__.py create_global_var)."""
    import jax.numpy as jnp

    from paddle_tpu._core.dtype import to_jax_dtype
    from paddle_tpu._core.tensor import Tensor

    v = Tensor(jnp.full(tuple(int(s) for s in shape), value, to_jax_dtype(dtype)))
    v.persistable = persistable
    v.name = name or ""
    return v


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False, default_initializer=None):
    from paddle_tpu.framework.defaults import create_parameter as _cp

    return _cp(shape, dtype, name, attr, is_bias, default_initializer)


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True, print_tensor_type=True, print_tensor_shape=True, print_tensor_layout=True, print_tensor_lod=True, print_phase="both"):
    """Debug-print op (reference: paddle/fluid/operators/print_op.cc) —
    lowered to jax.debug.print so it fires inside compiled programs too."""
    import jax

    from paddle_tpu.tensor._ops_common import apply, ensure_tensor

    input = ensure_tensor(input)
    msg = message or ""

    def _fn(v):
        jax.debug.print(msg + " {v}", v=v)
        return v

    return apply("print", _fn, input)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-callback op (reference: python/paddle/static/nn/common.py py_func
    over the C++ py_func op): runs a numpy function inside the graph via
    jax.pure_callback.  When backward_func is given the callback is wrapped
    in jax.custom_vjp and the cotangents route through a second
    pure_callback, mirroring the reference's paired forward/backward py_func
    ops (backward input = x + out + out_grads, minus
    skip_vars_in_backward_input; backward output = one grad per x)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.tensor._ops_common import Tensor, apply, ensure_tensor

    xs = x if isinstance(x, (list, tuple)) else [x]
    xs = [ensure_tensor(v) for v in xs]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = [jax.ShapeDtypeStruct(tuple(o.shape), o._value.dtype) for o in outs]

    def _call(*vals):
        def host(*hv):
            res = func(*[np.asarray(h) for h in hv])
            res = res if isinstance(res, (list, tuple)) else [res]
            return [np.asarray(r, sh.dtype) for r, sh in zip(res, shapes)]

        return tuple(jax.pure_callback(host, shapes, *vals))

    if backward_func is None:
        def _fn(*vals):
            res = _call(*vals)
            return res if len(res) > 1 else res[0]
    else:
        skip = skip_vars_in_backward_input or []
        skip = skip if isinstance(skip, (list, tuple)) else [skip]
        skip_ids = {id(t) for t in skip}
        keep_x = [i for i, t in enumerate(xs) if id(t) not in skip_ids]
        keep_o = [i for i, t in enumerate(outs) if id(t) not in skip_ids]
        x_shapes = [jax.ShapeDtypeStruct(tuple(v.shape), v._value.dtype) for v in xs]

        @jax.custom_vjp
        def _cb(*vals):
            res = _call(*vals)
            return res if len(res) > 1 else res[0]

        def _cb_fwd(*vals):
            res = _call(*vals)
            return (res if len(res) > 1 else res[0]), (vals, res)

        def _cb_bwd(saved, cot):
            vals, res = saved
            cots = cot if isinstance(cot, tuple) else (cot,)
            b_in = [vals[i] for i in keep_x] + [res[i] for i in keep_o] + list(cots)

            def host_bwd(*hv):
                g = backward_func(*[np.asarray(h) for h in hv])
                g = g if isinstance(g, (list, tuple)) else [g]
                if len(g) != len(x_shapes):
                    raise ValueError(
                        f"py_func backward_func returned {len(g)} grads for "
                        f"{len(x_shapes)} inputs"
                    )
                return [np.asarray(gv, sh.dtype).reshape(sh.shape) for gv, sh in zip(g, x_shapes)]

            return tuple(jax.pure_callback(host_bwd, x_shapes, *b_in))

        _cb.defvjp(_cb_fwd, _cb_bwd)
        _fn = _cb

    return apply("py_func", _fn, *xs, n_outputs=len(shapes) if len(shapes) > 1 else None)


def accuracy(input, label, k=1, correct=None, total=None):
    """Top-k accuracy (reference: python/paddle/static/nn/metric.py accuracy)."""
    from paddle_tpu.metric import accuracy as _acc

    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=200, topk=1, slide_steps=1):
    """Batch AUC (reference: python/paddle/static/nn/metric.py auc) — exact
    rank-statistic AUC of this batch's scores."""
    import jax.numpy as jnp

    from paddle_tpu.tensor._ops_common import Tensor, ensure_tensor

    s = ensure_tensor(input)._value
    y = ensure_tensor(label)._value.reshape(-1)
    score = s[:, 1] if s.ndim == 2 and s.shape[1] == 2 else s.reshape(-1)
    order = jnp.argsort(score)
    ranks = jnp.empty_like(order).at[order].set(jnp.arange(1, score.shape[0] + 1))
    pos = (y > 0).astype(jnp.float32)
    n_pos = jnp.sum(pos)
    n_neg = jnp.sum(1.0 - pos)
    auc_v = (jnp.sum(ranks.astype(jnp.float32) * pos) - n_pos * (n_pos + 1) / 2.0) / jnp.maximum(n_pos * n_neg, 1.0)
    t = Tensor(auc_v)
    return t, [t, t]


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """CTR metrics (reference static.ctr_metric_bundle): returns (auc,
    predicted-ctr mae, rmse, actual-ctr) of the batch."""
    import jax.numpy as jnp

    from paddle_tpu.tensor._ops_common import Tensor, ensure_tensor

    s = ensure_tensor(input)._value.reshape(-1)
    y = ensure_tensor(label)._value.reshape(-1).astype(jnp.float32)
    auc_t, _ = auc(input, label)
    mae = Tensor(jnp.mean(jnp.abs(s - y)))
    rmse = Tensor(jnp.sqrt(jnp.mean((s - y) ** 2)))
    actual = Tensor(jnp.mean(y))
    return auc_t, mae, rmse, actual


class ExponentialMovingAverage:
    """EMA of trainable parameters (reference:
    python/paddle/static/__init__.py ExponentialMovingAverage): update()
    after each step; apply()/restore() swap EMA weights in and out for eval."""

    def __init__(self, decay=0.999, thres_steps=None, name=None, parameter_list=None):
        self._decay = float(decay)
        self._step = 0
        self._ema = {}
        self._backup = {}
        self._parameter_list = list(parameter_list) if parameter_list else None

    def update(self, parameters=None):
        import jax.numpy as jnp

        params = parameters or self._parameter_list or self._discover()
        if not params:
            # the reference discovers params from the startup program; in
            # dygraph there is no global registry — a silent no-op would make
            # apply() a lie, so demand the list once
            raise RuntimeError(
                "ExponentialMovingAverage.update(): pass `parameters=` (or "
                "`parameter_list=` at construction) — there is no global "
                "program to discover trainable parameters from in dygraph"
            )
        self._step += 1
        # reference uses min(decay, (1+steps)/(10+steps)) when thres_steps set
        d = self._decay
        for p in params:
            k = id(p)
            v = p._value.astype(jnp.float32)
            if k not in self._ema:
                self._ema[k] = (p, v)
            else:
                _, old = self._ema[k]
                self._ema[k] = (p, d * old + (1.0 - d) * v)

    def _discover(self):
        return [p for (p, _) in self._ema.values()]

    def apply(self, executor=None, need_restore=True):
        for k, (p, ema) in self._ema.items():
            self._backup[k] = p._value
            p._bind(ema.astype(p._value.dtype))
        return _EmaGuard(self) if need_restore else None

    def restore(self, executor=None):
        for k, (p, _) in self._ema.items():
            if k in self._backup:
                p._bind(self._backup.pop(k))


class _EmaGuard:
    def __init__(self, ema):
        self._ema = ema

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self._ema.restore()


def WeightNormParamAttr(dim=None, name=None, initializer=None, learning_rate=1.0, regularizer=None, trainable=True, do_model_average=False, need_clip=True):
    """reference: python/paddle/static/__init__.py WeightNormParamAttr —
    ParamAttr that requests weight normalization; the nn utils
    weight_norm hook is the dygraph mechanism here."""
    from paddle_tpu.nn.layer.layers import ParamAttr

    attr = ParamAttr(name=name, initializer=initializer, learning_rate=learning_rate, regularizer=regularizer, trainable=trainable, do_model_average=do_model_average, need_clip=need_clip)
    attr.weight_norm_dim = dim
    return attr


# ------------------------------------------------------- program state io
def save_to_file(path, content: bytes):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def serialize_persistables(feed_vars, fetch_vars, executor=None, program=None):
    """Serialize a Program's parameter payload (reference static.io)."""
    import pickle

    from .program import current_main_program, default_main_program

    prog = program or current_main_program() or default_main_program()
    state = {k: np.asarray(t._value) for k, t in prog.state_tensors().items()}
    return pickle.dumps(state)


def deserialize_persistables(program, blob: bytes, executor=None):
    import pickle

    import jax.numpy as jnp

    state = pickle.loads(blob)
    for k, v in state.items():
        program.set_state_tensor(k, jnp.asarray(v))
    return state


def load_program_state(model_path, var_list=None):
    """reference: python/paddle/static/io.py load_program_state — returns a
    name->ndarray dict from a saved model dir/prefix."""
    import os
    import pickle

    for cand in (model_path, model_path + ".pdparams"):
        if os.path.isfile(cand):
            with open(cand, "rb") as f:
                payload = pickle.load(f)
            return {k: np.asarray(v) for k, v in (payload.items() if isinstance(payload, dict) else [])}
    raise FileNotFoundError(model_path)


def set_program_state(program, state_dict):
    import jax.numpy as jnp

    for k, v in state_dict.items():
        program.set_state_tensor(k, jnp.asarray(v))


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """reference: python/paddle/static/io.py normalize_program — prune to the
    feed->fetch subgraph.  Programs here capture exactly the traced ops; the
    dead-code-elimination pass is the pruning step."""
    from .passes import apply_pass

    try:
        return apply_pass(program, "dead_code_elimination")
    except Exception:
        return program


# ----------------------------------------------------------------- IPU n/a
def _ipu_na(*a, **k):
    raise RuntimeError("IPU support is not applicable on the TPU backend (SURVEY.md: IPU excluded)")


ipu_shard_guard = _ipu_na
set_ipu_shard = _ipu_na


class IpuStrategy:
    def __init__(self, *a, **k):
        _ipu_na()


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        _ipu_na()
