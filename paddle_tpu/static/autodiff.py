"""Program-level autodiff: append_backward / gradients.

Reference: python/paddle/autograd/ir_backward.py:885 (PIR autodiff appending
grad ops per forward op via VJP interfaces).

TPU-native: the whole recorded prefix is one traceable function, so backward
is jax.value_and_grad of that function — one grad "super-op" appended to the
program whose outputs are the per-parameter grad Variables PLUS the loss
value, and the program's loss variable is re-bound (via an alias op) to that
loss output.  With the executor pruning to the fetch frontier, the compiled
step then contains exactly ONE traced forward — important beyond perf:
a duplicated forward chain that carries collectives (pipeline ppermute,
TP psum) deadlocks XLA:CPU's in-process communicator when the two chains'
collectives interleave, and relying on CSE to dedupe them is not sound.
"""

from __future__ import annotations

import jax

from paddle_tpu._core.tensor import Parameter, Tensor

from .program import Program, Variable, current_main_program

__all__ = ["append_backward", "gradients"]


def build_grad_fn(prog: Program, target_vid, wrt_vids, in_vids, ops=None):
    """d(target)/d(wrt) over the program's CURRENT op list (or an explicit
    `ops` prefix) — factored out so program-rewriting passes (recompute) can
    REBUILD the grad super-op after transforming the forward ops."""
    run_fn, _, _ = prog.as_function([target_vid], feed_vids=[], state_vids=in_vids,
                                    ops=ops)
    wrt_pos = [in_vids.index(vid) for vid in wrt_vids]

    def fn(*vals):
        def scalar(*wrt_vals):
            full = list(vals)
            for pos, wv in zip(wrt_pos, wrt_vals):
                full[pos] = wv
            (out,), _ = run_fn([], full)
            return out.sum() if out.ndim else out

        loss, grads = jax.value_and_grad(
            scalar, argnums=tuple(range(len(wrt_pos)))
        )(*[vals[p] for p in wrt_pos])
        # output layout: (grads..., loss) — consumers that need only the
        # grads slice by grad_meta["wrt_vids"] length
        return tuple(grads) + (loss,)

    return fn


def _grad_superop(prog: Program, target: Variable, wrt_vars, name):
    """Record one op computing d(target)/d(wrt_vars); returns grad Variables.

    The op carries `grad_meta` (target/wrt/input vids) so passes can rebuild
    it after rewriting the forward — its fn closes over a SNAPSHOT of the op
    list, so forward rewrites alone would not reach the backward."""
    inputs = list(prog.feed_vars) + [prog._var_by_vid[vid] for vid in prog.param_inits]
    in_vids = [v._vid for v in inputs]
    wrt_vids = [v._vid for v in wrt_vars]
    fn = build_grad_fn(prog, target._vid, wrt_vids, in_vids)
    out = prog.record(name, fn, tuple(inputs), {})
    outs = list(out)
    grads, loss_out = outs[:-1], outs[-1]
    prog.global_block().ops[-1].grad_meta = {
        "target_vid": target._vid, "wrt_vids": wrt_vids, "in_vids": in_vids,
    }
    # Re-bind the loss variable to the grad op's loss output: later reads
    # (fetches) take the value_and_grad forward, so a fetch-frontier prune
    # can drop the original forward chain entirely.
    import jax as _jax

    single = _jax.tree_util.tree_structure(0)
    alias = type(prog.global_block().ops[-1])(
        "share_loss", lambda v: v, [("var", loss_out._vid)], {},
        [target._vid], single)
    prog.global_block().ops.append(alias)
    prog.version += 1
    return grads


def append_backward(loss, parameter_list=None, no_grad_set=None):
    """Returns [(parameter Variable, grad Variable)].  parameter_list may hold
    dygraph Parameters (auto-registered) or Variables."""
    prog = current_main_program()
    if prog is None:
        raise RuntimeError("append_backward requires an active program_guard")

    if parameter_list:
        wrt = []
        for p in parameter_list:
            if isinstance(p, Variable):
                wrt.append(p)
            elif isinstance(p, Parameter):
                wrt.append(prog.var_for_parameter(p))
            else:
                raise TypeError(f"bad parameter {p!r}")
    else:
        wrt = prog.all_parameters()

    grads = _grad_superop(prog, loss, wrt, "grad")
    if not isinstance(grads, (tuple, list)):
        grads = (grads,)
    return list(zip(wrt, grads))


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """paddle.static.gradients parity: grads of sum(targets) wrt inputs."""
    prog = current_main_program()
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if len(targets) != 1:
        raise NotImplementedError("multiple targets: sum them first")
    pairs = append_backward(targets[0], parameter_list=list(inputs))
    return [g for _, g in pairs]
