"""Protocol lint: the cluster wire protocol checked BEFORE any fork.

The serving cluster's headline contract — bit-exact fail-over across
router / decode replicas / prefill workers / warm standbys — was until
PR 19 proven only dynamically, by SIGKILLing real processes (the
tests/test_serving_cluster_crash.py matrix) and reading the wreckage.
This module is the static half: the same philosophy as the PR-4 Program
verifier and the SPMD mesh lint (docs/VERIFIER.md, docs/MESH_LINT.md)
applied to the wire protocol of docs/SERVING_CLUSTER.md, so the ROADMAP
item-1 TCP data plane can be built against a machine-checked spec.

Three check families (docs/PROTOCOL_LINT.md):

1. **Exhaustive interleaving model check** — a breadth-first search with
   state hashing over a small abstract cluster (1 router, 2 decode
   replicas, 1 prefill worker, 1 warm standby, bounded message queues, a
   crash transition armed at every state).  Every reachable state is
   visited exactly once; every named invariant of
   ``serving.protocol.INVARIANTS`` is checked in every state; a
   quiescent non-terminal state is reported as a deadlock.  BFS order
   makes the first counterexample found a MINIMAL one: the trace handed
   back is the shortest interleaving that reaches the violation.
   Transport semantics are a parameter: ``ShmRingSemantics`` models
   the shared-memory rings, ``TcpStubSemantics`` keeps the worst-case
   drop-as-death stub (a dropped connection is a ``BrokenPipeError`` to
   the worker, i.e. death), and ``TcpRingSemantics`` models the REAL
   ``serving/transport.py`` TcpRing — a drop is silence + background
   redial with the in-flight frame re-sent whole, so the armed fault is
   a DUPLICATED frame and the checker proves the protocol
   re-emission-safe (plus the loud KeyError death on a spec-foreign
   duplicate).  Reconnect-after-drop and death are distinct transitions.

2. **Seeded-violation scenarios** — deliberately broken protocol
   variants (skip the intake fsync; treat ring ``TimeoutError`` as a
   death verdict; let a second router replay the same journal) that must
   each produce a readable counterexample trace naming the violated
   invariant.  They are to the model checker what the verifier's seeded
   IR fixtures are to ``verify_program``: proof the checker can actually
   see the bug class it claims to guard.

3. **Blocking-call lint** — an AST pass over ``serving/`` and
   ``distributed/collective/`` that classifies blocking call sites
   (ring ``push``/``pop``, store ``wait``/``get``, process ``join``,
   lock ``acquire``) and flags: waits that neither carry a timeout nor
   ride ``retry_backoff``'s shared deadline (``unbounded-blocking``),
   blocking calls made while lexically holding a lock the heartbeat
   thread may need (``lock-held-blocking``), and a frame that can block
   in BOTH directions of a channel without deadlines — the two-party
   circular-wait shape (``circular-wait``).  Receiver-name heuristics
   (``ring``/``store``/``proc``/``lock``) keep dict ``.pop``/``.get``
   and ``str.join`` out of scope.

Counters ride ``paddle_tpu.profiler.protocol_lint_stats()`` with a
``Protocol lint:`` summary footer; ``tools/lint_protocol.py`` sweeps the
battery (clean spec clean on both transports, seeded scenarios flagged
with traces, real tree lints clean) and a ``--pytest`` mode.
"""

from __future__ import annotations

import ast
import os
import re
from collections import deque, namedtuple
from dataclasses import dataclass, field

from paddle_tpu.serving import protocol

__all__ = [
    "ProtocolViolation",
    "ProtocolLintError",
    "ShmRingSemantics",
    "TcpStubSemantics",
    "TcpRingSemantics",
    "Scenario",
    "SCENARIOS",
    "ModelCheckResult",
    "check_model",
    "lint_cluster_protocol",
    "lint_blocking_calls",
    "lint_source",
    "render_trace",
    "protocol_lint_stats",
    "reset_protocol_lint_stats",
]


_COUNTERS = {
    "scenarios_checked": 0,     # check_model calls
    "model_states": 0,          # distinct states visited
    "model_transitions": 0,     # successor edges generated
    "invariant_checks": 0,      # per-state named-invariant evaluations
    "violations": 0,            # model violations + blocking-lint flags
    "deadlocks": 0,             # quiescent non-terminal states reported
    "files_linted": 0,          # sources through the blocking-call pass
    "functions_scanned": 0,
    "blocking_calls_checked": 0,
}


def protocol_lint_stats(reset: bool = False) -> dict:
    out = dict(_COUNTERS)
    if reset:
        reset_protocol_lint_stats()
    return out


def reset_protocol_lint_stats():
    for k in _COUNTERS:
        _COUNTERS[k] = 0


@dataclass
class ProtocolViolation:
    code: str        # an INVARIANTS key | unbounded-blocking |
                     # lock-held-blocking | circular-wait
    message: str
    site: str = ""   # model:<scenario> or file:line the flag anchors to
    trace: tuple = ()  # model counterexample: step labels, root first

    def __str__(self):
        loc = f" [{self.site}]" if self.site else ""
        return f"{self.code}{loc}: {self.message}"


class ProtocolLintError(RuntimeError):
    def __init__(self, violations, header="Protocol lint failed"):
        self.violations = list(violations)
        lines = [f"{header} ({len(self.violations)} violation(s)):"]
        lines += [f"  - {v}" for v in self.violations]
        super().__init__("\n".join(lines))


# =====================================================================
# transport semantics (the model's swappable data plane)
# =====================================================================
class ShmRingSemantics:
    """Today's data plane: bounded shared-memory rings.  A full ring is
    backpressure (the send transition is simply not enabled until the
    consumer drains); only a destroyed ring (worker death) breaks it."""

    name = "shmring"
    queue_cap = 2      # bounded rings: small cap keeps the model finite
    drop_budget = 0    # shm rings cannot drop a connection
    reconnect = False  # a drop (if any) is final


class TcpStubSemantics(ShmRingSemantics):
    """The pre-transport stub: a TCP ring behaves like a shm ring plus
    one extra environment transition — the connection can drop.  The
    worker sees that as BrokenPipeError and exits, so a drop IS a death
    with a different cause label; the checker proves the recovery
    machinery absorbs it like a SIGKILL.  Kept as the WORST-CASE model:
    a protocol that survives drop-as-death also survives any softer
    semantics."""

    name = "tcp-stub"
    drop_budget = 1


class TcpRingSemantics(ShmRingSemantics):
    """The REAL serving/transport.py TcpRing: a connection drop is
    SILENCE, not death.  The transport redials in the background and
    re-sends the in-flight frame whole on reconnect (at-least-once
    delivery); push sees backpressure, pop sees timeouts, and the
    heartbeat tier keeps sole death authority.  The armed environment
    transition is therefore a DUPLICATED frame — the checker proves the
    protocol is re-emission-safe under redelivery (idempotent submit,
    bit-mergeable token runs, claims consumed exactly once) and that a
    spec-foreign duplicate (a stale ``promote`` re-sent to an
    already-promoted standby) dies loudly through the KeyError path
    instead of corrupting state."""

    name = "tcp-ring"
    drop_budget = 1
    reconnect = True


@dataclass(frozen=True)
class Scenario:
    """One model-checking configuration: a transport plus optional
    seeded protocol bugs.  ``expect`` names the invariants a seeded bug
    must trip — empty for the real spec, which must explore clean."""

    name: str
    transport: type = ShmRingSemantics
    drop_fsync: bool = False      # accept without journaling (seeded bug)
    lethal_timeout: bool = False  # ring TimeoutError => death (seeded bug)
    rogue_router: bool = False    # a 2nd router replays the journal
    drop_as_backpressure: bool = False  # worker shrugs off a DESTROYED
                                  # peer ring as backpressure (seeded bug)
    n_requests: int = 2
    crash_budget: int = 1
    queue_cap: int = 0            # 0 = the transport's own cap
    expect: tuple = ()
    description: str = ""


SCENARIOS = {
    "clean-shmring": Scenario(
        "clean-shmring", ShmRingSemantics,
        description="the real protocol over shared-memory rings — must "
                    "explore clean"),
    "clean-tcp": Scenario(
        "clean-tcp", TcpStubSemantics, crash_budget=0,
        description="the real protocol over the TCP stub transport — "
                    "the connection-drop transition is the armed fault "
                    "(SIGKILL interleavings are clean-shmring's job); "
                    "must explore clean"),
    "clean-tcp-ring": Scenario(
        "clean-tcp-ring", TcpRingSemantics, crash_budget=0,
        description="the real protocol over the REAL TcpRing transport "
                    "(serving/transport.py): a drop is redial + "
                    "at-least-once re-send, so the armed fault is a "
                    "DUPLICATED in-flight frame, not a death — must "
                    "explore clean (the protocol is re-emission-safe)"),
    "drop-intake-fsync": Scenario(
        "drop-intake-fsync", ShmRingSemantics, drop_fsync=True,
        expect=("journal-before-dispatch", "nonce-before-first-token"),
        description="accept skips the intake-journal fsync: dispatch "
                    "precedes durability, a router crash loses requests"),
    "lethal-ring-timeout": Scenario(
        "lethal-ring-timeout", ShmRingSemantics, lethal_timeout=True,
        queue_cap=1,  # a 1-deep ring actually fills under 2 requests
        expect=("backpressure-not-death",),
        description="a full ring's TimeoutError is treated as a death "
                    "verdict instead of backpressure"),
    "two-routers": Scenario(
        "two-routers", ShmRingSemantics, rogue_router=True,
        expect=("no-double-serve",),
        description="a second router replays the same intake journal "
                    "and re-dispatches an owned rid"),
    "drop-as-backpressure": Scenario(
        "drop-as-backpressure", TcpRingSemantics,
        drop_as_backpressure=True, n_requests=1, crash_budget=0,
        expect=("no-double-serve",),
        description="a TcpRing worker treats its genuinely-destroyed "
                    "peer ring (BrokenPipeError / CLOSE) as mere "
                    "backpressure and keeps serving while the heartbeat "
                    "tier declares it dead — its streams re-dispatch and "
                    "are served twice (silence is for TRANSIENT drops; "
                    "ring teardown must stay lethal)"),
}


# =====================================================================
# the abstract cluster model
# =====================================================================
# Workers, in fixed index order.  A promoted standby enters the decode
# machine at "serving" (serving/protocol.py ROLE_STATES).
_WORKERS = ("D0", "D1", "P0", "S0")
_WROLE = ("decode", "decode", "prefill", "standby")

# The model state: one flat immutable record, hashable by construction.
# Queues hold (message, payload) pairs; payload is a rid, a claim tuple,
# or None.  BFS identity = structural equality of this tuple.
_S = namedtuple("_S", [
    "phase",      # per-worker lifecycle phase ("dead" once crashed)
    "inq",        # per-worker router->worker queue (tuple of entries)
    "outq",       # per-worker worker->router queue
    "journaled",  # rids fsynced to the intake journal
    "accepted",   # rids accepted from clients
    "owner",      # sorted (rid, wi): router's canonical owner map
    "active",     # per-worker frozenset of rids it is serving
    "toked",      # per-worker frozenset of rids with tokens emitted
    "delivered",  # rids whose tokens reached the router (the client)
    "done",       # rids completed
    "shipping",   # sorted (rid, target_wi): prefill shipments in flight
    "pclaims",    # sorted rids awaiting a promoted standby's claim
    "claims",     # sorted (rid, n): how often each rid was claimed
    "grace",      # worker indices still inside boot grace
    "warmed",     # worker indices whose warmed report was processed
    "sb_ready",   # standby announced ready (promotion-eligible)
    "crashes",    # remaining crash budget
    "drops",      # remaining connection-drop budget (TCP stub)
    "cause",      # sorted (wi, cause) for dead workers
    "restore",    # per-worker claim payload while restoring (standby)
    "to_accept",  # requests not yet accepted
    "detected",   # dead workers whose death the router has handled
    "rogue",      # the two-routers seeded dispatch already fired
])


def _initial(sc: Scenario) -> _S:
    return _S(
        phase=("booting",) * 4,
        inq=((),) * 4, outq=((),) * 4,
        journaled=frozenset(), accepted=frozenset(),
        owner=(), active=(frozenset(),) * 4, toked=(frozenset(),) * 4,
        delivered=frozenset(), done=frozenset(),
        shipping=(), pclaims=(), claims=(),
        grace=frozenset(range(4)), warmed=frozenset(),
        sb_ready=False,
        crashes=sc.crash_budget, drops=sc.transport.drop_budget,
        cause=(), restore=((),) * 4, to_accept=sc.n_requests,
        detected=frozenset(), rogue=False)


def _tset(t, i, v):
    return t[:i] + (v,) + t[i + 1:]


def _decode_capable(s):
    """Workers the router may route decode traffic to: the replicas,
    plus the standby once promoted into the decode machine."""
    out = []
    for wi in range(4):
        if s.phase[wi] == "dead":
            continue
        if _WROLE[wi] == "decode" or (_WROLE[wi] == "standby"
                                      and s.phase[wi] == "serving"):
            out.append(wi)
    return out


def _kill(s, wi, cause, *, crashes=None, drops=None):
    """Worker death: rings destroyed (queues vanish), in-flight worker
    state gone.  The router's view (owner/shipping) is untouched until
    a `detect` transition fires — that delay is the interesting part."""
    return s._replace(
        phase=_tset(s.phase, wi, "dead"),
        inq=_tset(s.inq, wi, ()), outq=_tset(s.outq, wi, ()),
        active=_tset(s.active, wi, frozenset()),
        toked=_tset(s.toked, wi, frozenset()),
        cause=tuple(sorted(set(s.cause) | {(wi, cause)})),
        crashes=s.crashes if crashes is None else crashes,
        drops=s.drops if drops is None else drops)


def _bump_claims(claims, rids):
    d = dict(claims)
    for rid in rids:
        d[rid] = d.get(rid, 0) + 1
    return tuple(sorted(d.items()))


def _successors(s: _S, sc: Scenario):
    """Yield (label, next_state) for every transition enabled in `s`.
    Exhaustive nondeterminism: the scheduler, the crash fault, and (on
    TCP) the network are all adversarial."""
    cap = sc.queue_cap or sc.transport.queue_cap
    owned = {rid for rid, _ in s.owner}
    shipping = {rid for rid, _ in s.shipping}
    targets = _decode_capable(s)

    # ---- router: accept a client request -----------------------------
    if s.to_accept:
        rid = f"r{sc.n_requests - s.to_accept + 1}"
        if sc.drop_fsync:
            label = f"router: accept {rid} (intake-journal fsync DROPPED)"
            j = s.journaled
        else:
            label = f"router: accept {rid} (journaled + nonce fsynced)"
            j = s.journaled | {rid}
        yield (label, s._replace(journaled=j, accepted=s.accepted | {rid},
                                 to_accept=s.to_accept - 1))

    # ---- router: dispatch un-owned accepted rids ---------------------
    pool = [rid for rid in sorted(s.accepted)
            if rid not in s.done and rid not in owned
            and rid not in shipping and rid not in s.pclaims]
    for rid in pool:
        for wi in targets:
            if len(s.inq[wi]) < cap:
                yield (f"router: dispatch submit({rid}) -> {_WORKERS[wi]}",
                       s._replace(
                           inq=_tset(s.inq, wi,
                                     s.inq[wi] + (("submit", rid),)),
                           owner=tuple(sorted(set(s.owner) | {(rid, wi)}))))
            elif sc.lethal_timeout:
                yield (f"router: push submit({rid}) -> {_WORKERS[wi]} hits "
                       "a full ring (TimeoutError); BUG: backpressure "
                       "treated as a death verdict",
                       _kill(s, wi, "timeout"))
        # via the prefill worker (KV pages shipped to a chosen target)
        if s.phase[2] != "dead":
            for tgt in targets:
                if len(s.inq[2]) < cap:
                    yield (f"router: dispatch {rid} via P0 (prefill, ship "
                           f"to {_WORKERS[tgt]})",
                           s._replace(
                               inq=_tset(s.inq, 2,
                                         s.inq[2] + (("prefill", rid),)),
                               shipping=tuple(sorted(set(s.shipping)
                                                     | {(rid, tgt)}))))
                elif sc.lethal_timeout:
                    yield (f"router: push prefill({rid}) -> P0 hits a full "
                           "ring (TimeoutError); BUG: backpressure treated "
                           "as a death verdict",
                           _kill(s, 2, "timeout"))
                break  # ship target re-chosen on `shipped`; one row here

    # ---- the two-routers seeded bug ----------------------------------
    if sc.rogue_router and not s.rogue:
        for rid, wi in s.owner:
            if s.phase[wi] == "dead" or rid not in s.active[wi]:
                continue
            for wj in targets:
                if wj != wi and len(s.inq[wj]) < cap:
                    yield (f"SECOND router (same journal replay): "
                           f"dispatch submit({rid}) -> {_WORKERS[wj]} "
                           f"while {_WORKERS[wi]} still serves it",
                           s._replace(
                               inq=_tset(s.inq, wj,
                                         s.inq[wj] + (("submit", rid),)),
                               rogue=True))

    # ---- router: consume one worker report ---------------------------
    for wi in range(4):
        if not s.outq[wi]:
            continue
        msg, pay = s.outq[wi][0]
        base = s._replace(outq=_tset(s.outq, wi, s.outq[wi][1:]))
        name = _WORKERS[wi]
        if msg == "resume":
            if wi in s.warmed:
                # at-least-once redelivery (TcpRing re-send): the real
                # router's _pending_claims.pop already ran — claims are
                # consumed exactly once, re-assign is a set no-op
                yield (f"router: recv duplicate resume from {name} — "
                       "claims already consumed, idempotent redelivery",
                       base)
            elif _WROLE[wi] == "standby" and pay:
                # the promoted standby's ONE claim of the victim's streams
                yield (f"router: recv resume from {name} — claims "
                       f"{list(pay)} (mark_warmed)",
                       base._replace(
                           owner=tuple(sorted(set(s.owner)
                                              | {(r, wi) for r in pay})),
                           pclaims=tuple(r for r in s.pclaims
                                         if r not in pay),
                           claims=_bump_claims(s.claims, pay),
                           warmed=s.warmed | {wi},
                           grace=s.grace - {wi}))
            else:
                yield (f"router: recv resume from {name} (mark_warmed — "
                       "boot grace ends)",
                       base._replace(warmed=s.warmed | {wi},
                                     grace=s.grace - {wi}))
        elif msg == "ready":
            yield (f"router: recv ready from {name} — standby is "
                   "promotion-eligible (mark_warmed)",
                   base._replace(sb_ready=True, warmed=s.warmed | {wi},
                                 grace=s.grace - {wi}))
        elif msg == "tokens":
            yield (f"router: recv tokens({pay}) from {name} — first "
                   "tokens reach the client stream",
                   base._replace(delivered=s.delivered | {pay}))
        elif msg == "done":
            yield (f"router: recv done({pay}) from {name}",
                   base._replace(
                       done=s.done | {pay},
                       owner=tuple(e for e in s.owner
                                   if e != (pay, wi))))
        elif msg == "shipped":
            entry = next((e for e in s.shipping if e[0] == pay), None)
            if entry is None:     # target died; shipment already released
                yield (f"router: recv shipped({pay}) from {name} — "
                       "shipment already released (target died)", base)
                continue
            tgt = entry[1]
            ship2 = tuple(e for e in s.shipping if e != entry)
            if s.phase[tgt] == "dead":
                yield (f"router: recv shipped({pay}) from {name} — target "
                       f"{_WORKERS[tgt]} is dead, release for re-dispatch",
                       base._replace(shipping=ship2))
            elif len(s.inq[tgt]) < cap:
                yield (f"router: recv shipped({pay}) from {name} — submit "
                       f"{pay} to {_WORKERS[tgt]}",
                       base._replace(
                           shipping=ship2,
                           inq=_tset(base.inq, tgt,
                                     base.inq[tgt] + (("submit", pay),)),
                           owner=tuple(sorted(set(s.owner)
                                              | {(pay, tgt)}))))
            elif sc.lethal_timeout:
                yield (f"router: post-ship submit({pay}) -> "
                       f"{_WORKERS[tgt]} hits a full ring (TimeoutError); "
                       "BUG: backpressure treated as a death verdict",
                       _kill(base._replace(shipping=ship2), tgt, "timeout"))
            # else: target ring full — the router retries later
            #       (backpressure: this consume is simply not enabled)

    # ---- router: notice a death (detection is delayed — that's the
    # race the invariants must survive) --------------------------------
    for wi in range(4):
        if s.phase[wi] != "dead" or wi in s.detected:
            continue
        name = _WORKERS[wi]
        orphans = tuple(sorted(rid for rid, w in s.owner if w == wi))
        nxt = s._replace(
            detected=s.detected | {wi},
            owner=tuple(e for e in s.owner if e[1] != wi))
        if wi == 2:  # prefill: release in-flight shipments
            yield (f"router: heartbeat misses exceed budget — {name} "
                   "declared dead; in-flight shipments released",
                   nxt._replace(shipping=()))
        elif wi == 3:  # standby died (parked/restoring/serving)
            yield (f"router: heartbeat misses exceed budget — {name} "
                   "declared dead; pending claims released for "
                   "re-dispatch",
                   nxt._replace(sb_ready=False, pclaims=()))
        else:          # a decode replica
            if (s.sb_ready and s.phase[3] == "parked"
                    and len(s.inq[3]) < cap):
                yield (f"router: heartbeat misses exceed budget — {name} "
                       f"declared dead; promote S0 to claim "
                       f"{list(orphans)}",
                       nxt._replace(
                           sb_ready=False,
                           pclaims=tuple(sorted(set(s.pclaims)
                                                | set(orphans))),
                           inq=_tset(nxt.inq, 3,
                                     nxt.inq[3]
                                     + (("promote", orphans),))))
            else:
                yield (f"router: heartbeat misses exceed budget — {name} "
                       "declared dead; orphans released for re-dispatch",
                       nxt)

    # ---- router: respawn a handled-dead decode replica ---------------
    # The real cluster respawns a dead replica into the same slot (a new
    # generation, fresh rings, boot grace restarted).  Without this the
    # model deadlocks when every replica dies before the standby's ready
    # report lands — the exact liveness hole respawn exists to close.
    for wi in range(4):
        if (s.phase[wi] == "dead" and wi in s.detected
                and _WROLE[wi] == "decode"):
            yield (f"router: respawn {_WORKERS[wi]} (new generation, "
                   "fresh rings, boot grace restarted)",
                   s._replace(phase=_tset(s.phase, wi, "booting"),
                              grace=s.grace | {wi},
                              warmed=s.warmed - {wi},
                              detected=s.detected - {wi}))

    # ---- workers ------------------------------------------------------
    for wi in range(4):
        ph = s.phase[wi]
        if ph == "dead":
            continue
        name = _WORKERS[wi]
        role = _WROLE[wi]
        # boot
        if ph == "booting":
            if role == "decode" and len(s.outq[wi]) < cap:
                yield (f"{name}: engine warm — send resume report",
                       s._replace(phase=_tset(s.phase, wi, "serving"),
                                  outq=_tset(s.outq, wi,
                                             s.outq[wi]
                                             + (("resume", ()),))))
            elif role == "prefill":
                yield (f"{name}: model built — serving",
                       s._replace(phase=_tset(s.phase, wi, "serving")))
            elif role == "standby" and len(s.outq[wi]) < cap:
                yield (f"{name}: AOT warmup done — send ready "
                       "(warmed=True)",
                       s._replace(phase=_tset(s.phase, wi, "parked"),
                                  outq=_tset(s.outq, wi,
                                             s.outq[wi]
                                             + (("ready", None),))))
            continue
        # standby lifecycle
        if role == "standby" and ph == "parked":
            if s.inq[wi]:
                msg, pay = s.inq[wi][0]
                if msg == "promote":
                    yield (f"{name}: recv promote — restore victim "
                           "snapshot",
                           s._replace(
                               phase=_tset(s.phase, wi, "restoring"),
                               inq=_tset(s.inq, wi, s.inq[wi][1:]),
                               restore=_tset(s.restore, wi, pay)))
            continue
        if role == "standby" and ph == "restoring":
            if len(s.outq[wi]) < cap:
                pay = s.restore[wi]
                yield (f"{name}: snapshot restored — send resume claiming "
                       f"{list(pay)}; serving as a decode replica",
                       s._replace(
                           phase=_tset(s.phase, wi, "serving"),
                           outq=_tset(s.outq, wi,
                                      s.outq[wi] + (("resume", pay),)),
                           active=_tset(s.active, wi, frozenset(pay)),
                           restore=_tset(s.restore, wi, ())))
            continue
        # prefill serving
        if role == "prefill":
            if s.inq[wi] and len(s.outq[wi]) < cap:
                msg, rid = s.inq[wi][0]
                yield (f"{name}: prefill {rid} — compute K/V, ship pages, "
                       "report shipped",
                       s._replace(
                           inq=_tset(s.inq, wi, s.inq[wi][1:]),
                           outq=_tset(s.outq, wi,
                                      s.outq[wi] + (("shipped", rid),))))
            continue
        # decode-capable serving (replicas + promoted standby)
        if s.inq[wi]:
            msg, rid = s.inq[wi][0]
            if msg == "submit":
                yield (f"{name}: recv submit({rid}) — request admitted",
                       s._replace(
                           inq=_tset(s.inq, wi, s.inq[wi][1:]),
                           active=_tset(s.active, wi,
                                        s.active[wi] | {rid})))
            else:
                # a frame outside the decode alphabet (e.g. a stale
                # `promote` re-sent to a PROMOTED standby) is the
                # KeyError fatal path in cluster_worker: die loudly,
                # never drop silently — recovery absorbs it like a crash
                yield (f"{name}: spec-foreign `{msg}` frame in the "
                       "decode serve loop — KeyError fatal path, worker "
                       "exits loudly",
                       _kill(s, wi, "protocol"))
        for rid in sorted(s.active[wi] - s.toked[wi]):
            if len(s.outq[wi]) < cap:
                yield (f"{name}: emit first tokens for {rid}",
                       s._replace(
                           outq=_tset(s.outq, wi,
                                      s.outq[wi] + (("tokens", rid),)),
                           toked=_tset(s.toked, wi, s.toked[wi] | {rid})))
        for rid in sorted(s.toked[wi]):
            if len(s.outq[wi]) < cap:
                yield (f"{name}: {rid} complete — report done",
                       s._replace(
                           outq=_tset(s.outq, wi,
                                      s.outq[wi] + (("done", rid),)),
                           active=_tset(s.active, wi,
                                        s.active[wi] - {rid}),
                           toked=_tset(s.toked, wi,
                                       s.toked[wi] - {rid})))

    # ---- the environment: crash / connection-drop, armed everywhere --
    if s.crashes:
        for wi in range(4):
            if s.phase[wi] != "dead":
                yield (f"SIGKILL {_WORKERS[wi]}",
                       _kill(s, wi, "crash", crashes=s.crashes - 1))
    if s.drops:
        for wi in range(4):
            if s.phase[wi] == "dead":
                continue
            name = _WORKERS[wi]
            if not sc.transport.reconnect:
                # TcpStubSemantics: drop-as-death, the worst case
                yield (f"TCP connection to {name} drops — worker "
                       "sees BrokenPipeError and exits",
                       _kill(s, wi, "conn-drop", drops=s.drops - 1))
                continue
            if sc.drop_as_backpressure:
                # seeded bug: the peer ring was genuinely torn down
                # (the heartbeat tier already counted this worker out)
                # but the worker shrugs the BrokenPipeError off as
                # backpressure and keeps serving its residents while
                # the router re-homes them
                if _WROLE[wi] != "decode" or not s.active[wi]:
                    continue
                orphans = tuple(sorted(
                    rid for rid, w in s.owner if w == wi))
                yield (f"{name}'s rings torn down after heartbeat "
                       "death verdict; BUG: worker treats the "
                       "BrokenPipeError as backpressure and keeps "
                       f"serving {list(orphans)} while the router "
                       "re-homes them",
                       s._replace(
                           detected=s.detected | {wi},
                           owner=tuple(e for e in s.owner
                                       if e[1] != wi),
                           drops=s.drops - 1))
                continue
            # the REAL TcpRing: drop = silence + redial; the in-flight
            # frame is re-sent whole, so the observable fault is a
            # duplicated head-of-queue frame (at-least-once delivery) —
            # silence itself is already every scheduling interleaving
            # where this worker simply isn't picked
            if s.inq[wi] and len(s.inq[wi]) < cap:
                m0 = s.inq[wi][0][0]
                yield (f"TCP conn for {name}.ring_in drops mid-frame — "
                       f"redial re-sends the in-flight `{m0}` whole: "
                       "frame delivered twice (at-least-once)",
                       s._replace(
                           inq=_tset(s.inq, wi,
                                     (s.inq[wi][0],) + s.inq[wi]),
                           drops=s.drops - 1))
            if s.outq[wi] and len(s.outq[wi]) < cap:
                m0 = s.outq[wi][0][0]
                yield (f"TCP conn for {name}.ring_out drops mid-frame — "
                       f"redial re-sends the in-flight `{m0}` whole: "
                       "frame delivered twice (at-least-once)",
                       s._replace(
                           outq=_tset(s.outq, wi,
                                      (s.outq[wi][0],) + s.outq[wi]),
                           drops=s.drops - 1))


def _check_invariants(s: _S, sc: Scenario):
    """Evaluate every named invariant in state `s`; return violations as
    (code, message) pairs.  One entry per INVARIANTS key — the checker
    proves each name, not a vibe."""
    out = []
    _COUNTERS["invariant_checks"] += len(protocol.INVARIANTS)
    # journal-before-dispatch: anything the router pushed toward a ring
    # (or routed through prefill) must already be journaled
    dispatched = {rid for rid, _ in s.owner} | {r for r, _ in s.shipping}
    for q in s.inq:
        dispatched |= {pay for m, pay in q
                       if m in ("submit", "prefill")}
    for rid in sorted(dispatched - s.journaled):
        out.append(("journal-before-dispatch",
                    f"rid {rid} was dispatched toward a worker ring "
                    "without a fsynced intake-journal record — a router "
                    "crash here silently loses an accepted request"))
    # no-double-serve: a rid active on two LIVE workers at once
    for rid in sorted(s.accepted):
        servers = [wi for wi in range(4)
                   if s.phase[wi] != "dead" and rid in s.active[wi]]
        if len(servers) > 1:
            names = "/".join(_WORKERS[w] for w in servers)
            out.append(("no-double-serve",
                        f"rid {rid} is actively served by {names} "
                        "simultaneously — two live token streams for one "
                        "request"))
    # nonce-before-first-token: delivery implies a journaled nonce
    for rid in sorted(s.delivered - s.journaled):
        out.append(("nonce-before-first-token",
                    f"tokens for rid {rid} reached the client before its "
                    "nonce was journaled — the stream has no durable "
                    "identity"))
    # backpressure-not-death: only BrokenPipeError/SIGKILL may kill
    for wi, cause in s.cause:
        if cause == "timeout":
            out.append(("backpressure-not-death",
                        f"{_WORKERS[wi]} was declared dead on a ring "
                        "TimeoutError — backpressure must never be a "
                        "death verdict"))
    # promotion-claims-once
    for rid, n in s.claims:
        if n > 1:
            out.append(("promotion-claims-once",
                        f"rid {rid} was claimed by a standby promotion "
                        f"{n} times — exactly one resume claim allowed"))
    # warmed-ends-boot-grace
    for wi in sorted(s.warmed & s.grace):
        out.append(("warmed-ends-boot-grace",
                    f"{_WORKERS[wi]} reported warmed=True but is still "
                    "inside boot grace — mark_warmed must end it"))
    return out


def _terminal(s: _S) -> bool:
    """Quiescence is legal only once every accepted request completed."""
    return s.to_accept == 0 and s.accepted <= s.done


@dataclass
class ModelCheckResult:
    scenario: str
    transport: str
    states: int = 0
    transitions: int = 0
    violations: list = field(default_factory=list)
    deadlocks: int = 0
    complete: bool = False   # frontier exhausted (no early stop)

    def ok(self) -> bool:
        return not self.violations

    def report(self) -> str:
        head = (f"model check [{self.scenario} / {self.transport}]: "
                f"{self.states} states, {self.transitions} transitions"
                f"{'' if self.complete else ' (stopped at first hits)'}")
        if self.ok():
            return head + " — clean (all invariants hold, no deadlock)"
        parts = [head + f" — {len(self.violations)} violation(s):"]
        parts += [render_trace(v) for v in self.violations]
        return "\n".join(parts)


def render_trace(v: ProtocolViolation) -> str:
    """A counterexample as a numbered interleaving ending in the named
    invariant — the readable artifact a protocol bug report starts
    from."""
    lines = [f"counterexample ({len(v.trace)} steps) -> {v.code}:"]
    lines += [f"  {i + 1:2d}. {step}" for i, step in enumerate(v.trace)]
    lines.append(f"  VIOLATED {v.code}: {v.message}")
    return "\n".join(lines)


def check_model(scenario="clean-shmring", *, max_states=2_000_000,
                stop_on_expected=True) -> ModelCheckResult:
    """Exhaustive BFS over every reachable state of the abstract
    cluster under `scenario` (a SCENARIOS name or a Scenario).

    Breadth-first order means the first state exhibiting a violation is
    at minimal depth, so its parent-pointer walk IS a minimal
    counterexample.  For seeded scenarios (``scenario.expect``
    non-empty) the search stops once every expected invariant produced
    a trace — the point is the counterexample, not the full graph; the
    real spec always runs to frontier exhaustion and must be clean."""
    sc = SCENARIOS[scenario] if isinstance(scenario, str) else scenario
    _COUNTERS["scenarios_checked"] += 1
    res = ModelCheckResult(scenario=sc.name, transport=sc.transport.name)
    init = _initial(sc)
    parents = {init: (None, None)}
    frontier = deque([init])
    found = {}
    while frontier:
        s = frontier.popleft()
        res.states += 1
        for code, msg in _check_invariants(s, sc):
            if code not in found:
                found[code] = ProtocolViolation(
                    code=code, message=msg,
                    site=f"model:{sc.name}", trace=_walk_trace(parents, s))
        if (stop_on_expected and sc.expect
                and set(sc.expect) <= set(found)):
            break
        succ = list(_successors(s, sc))
        if not succ and not _terminal(s):
            res.deadlocks += 1
            _COUNTERS["deadlocks"] += 1
            undone = sorted(s.accepted - s.done)
            if "no-lost-request" not in found:
                found["no-lost-request"] = ProtocolViolation(
                    code="no-lost-request",
                    message=("deadlock: quiescent state with accepted "
                             f"request(s) {undone} never completed — "
                             "no transition is enabled"),
                    site=f"model:{sc.name}",
                    trace=_walk_trace(parents, s))
        for label, s2 in succ:
            res.transitions += 1
            if s2 not in parents:
                parents[s2] = (s, label)
                frontier.append(s2)
        if len(parents) > max_states:
            raise RuntimeError(
                f"protocol model check [{sc.name}] exceeded max_states="
                f"{max_states} — the abstract model must stay finite")
    else:
        res.complete = True
    res.violations = [found[c] for c in sorted(found)]
    _COUNTERS["model_states"] += res.states
    _COUNTERS["model_transitions"] += res.transitions
    _COUNTERS["violations"] += len(res.violations)
    return res


def _walk_trace(parents, s):
    steps = []
    while True:
        parent, label = parents[s]
        if parent is None:
            break
        steps.append(label)
        s = parent
    return tuple(reversed(steps))


def lint_cluster_protocol(transport="shmring",
                          *, max_states=2_000_000) -> ModelCheckResult:
    """Model-check the REAL protocol spec over `transport` ("shmring" |
    "tcp" | "tcp-ring") and raise ProtocolLintError unless it explores
    clean.  "tcp" is the worst-case drop-as-death stub; "tcp-ring" is
    serving/transport.py's real redial + at-least-once semantics."""
    name = {"shmring": "clean-shmring", "tcp": "clean-tcp",
            "tcp-ring": "clean-tcp-ring"}[transport]
    res = check_model(name, max_states=max_states)
    if not res.ok():
        raise ProtocolLintError(
            res.violations,
            header=f"Protocol model check failed [{name}]")
    return res


# =====================================================================
# blocking-call lint (AST pass over the real code)
# =====================================================================
# Receiver-name heuristics: the op classes the deadline discipline
# covers.  A dict's .pop/.get and str.join never match these.
_RING_RE = re.compile(r"ring")
_STORE_RE = re.compile(r"store")
_PROC_RE = re.compile(r"proc|process|child|thread")
_LOCK_RE = re.compile(r"lock|sem|cond")
_TIMEOUT_KW = re.compile(r"^timeout")


def _dotted(node):
    """`a.b.c` as lowered text, '' for non-trivial receivers."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts)).lower()
    return ""


def _classify(call):
    """(kind, direction) for a blocking op call node, else None."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    recv = _dotted(f.value)
    if not recv:
        return None
    meth = f.attr
    if meth in ("push", "pop") and _RING_RE.search(recv):
        return ("ring", "send" if meth == "push" else "recv")
    if meth in ("get", "wait") and _STORE_RE.search(recv):
        return ("store", "recv")
    if meth == "join" and _PROC_RE.search(recv):
        return ("process-join", "recv")
    if meth == "acquire" and _LOCK_RE.search(recv):
        return ("lock-acquire", "recv")
    return None


def _timed(call, kind):
    """Does the call site carry an explicit deadline?  timeout*= kwargs
    always count; a positional is a timeout only where the stdlib
    signature says so (proc.join(5), lock.acquire(True, 5)) — a store's
    positional is its KEY, a ring push's is its payload."""
    for kw in call.keywords:
        if kw.arg and _TIMEOUT_KW.match(kw.arg):
            return True
    if kind == "process-join" and call.args:
        return True            # proc.join(5)
    if kind == "lock-acquire" and len(call.args) >= 2:
        return True            # lock.acquire(True, 5)
    return False


class _BlockingVisitor(ast.NodeVisitor):
    def __init__(self, filename, retry_names):
        self.filename = filename
        self.violations = []
        self._retry_names = retry_names  # defs passed to retry_backoff
        self._retry_depth = 0
        self._locks = []                 # with-held lock expressions
        self._frames = []                # per-function untimed ring dirs

    # -- scope tracking ------------------------------------------------
    def _enter_fn(self, node, name):
        riding = name in self._retry_names
        if riding:
            self._retry_depth += 1
        self._frames.append({"name": name, "line": node.lineno,
                             "untimed": {}})
        self.generic_visit(node)
        frame = self._frames.pop()
        if riding:
            self._retry_depth -= 1
        dirs = frame["untimed"]
        if "send" in dirs and "recv" in dirs:
            self.violations.append(ProtocolViolation(
                code="circular-wait",
                message=(f"function {frame['name']!r} can block WITHOUT a "
                         "deadline in both directions of a channel "
                         f"(untimed send at line {dirs['send']}, untimed "
                         f"recv at line {dirs['recv']}) — the two-party "
                         "circular-wait shape; ride retry_backoff's "
                         "shared deadline"),
                site=f"{self.filename}:{frame['line']}"))

    def visit_FunctionDef(self, node):
        _COUNTERS["functions_scanned"] += 1
        self._enter_fn(node, node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._enter_fn(node, "<lambda>")

    def visit_With(self, node):
        lockish = [it for it in node.items
                   if _LOCK_RE.search(_dotted(it.context_expr)
                                      or (_dotted(it.context_expr.func)
                                          if isinstance(it.context_expr,
                                                        ast.Call)
                                          and isinstance(
                                              it.context_expr.func,
                                              (ast.Attribute, ast.Name))
                                          else ""))]
        self._locks.extend(lockish)
        self.generic_visit(node)
        if lockish:
            del self._locks[-len(lockish):]

    # -- the ops -------------------------------------------------------
    def visit_Call(self, node):
        fname = _dotted(node.func) if not isinstance(node.func, ast.Name) \
            else node.func.id.lower()
        if fname.endswith("retry_backoff"):
            # thunks handed to retry_backoff ride its shared deadline
            for arg in node.args:
                if isinstance(arg, ast.Lambda):
                    self._retry_names.add("<lambda>")
        cls = _classify(node)
        if cls is not None:
            kind, direction = cls
            _COUNTERS["blocking_calls_checked"] += 1
            timed = _timed(node, kind)
            riding = self._retry_depth > 0
            site = f"{self.filename}:{node.lineno}"
            src = f"{_dotted(node.func.value)}.{node.func.attr}"
            if not timed and not riding:
                self.violations.append(ProtocolViolation(
                    code="unbounded-blocking",
                    message=(f"{kind} wait `{src}(...)` has no timeout "
                             "and does not ride retry_backoff's shared "
                             "deadline — an unreachable peer parks this "
                             "frame forever"),
                    site=site))
                if self._frames and kind == "ring":
                    self._frames[-1]["untimed"].setdefault(
                        direction, node.lineno)
            if self._locks and kind in ("ring", "store", "process-join"):
                held = _dotted(self._locks[-1].context_expr) or "a lock"
                self.violations.append(ProtocolViolation(
                    code="lock-held-blocking",
                    message=(f"{kind} wait `{src}(...)` is made while "
                             f"holding `{held}` — a heartbeat thread "
                             "needing that lock misses its beat and the "
                             "router declares this worker dead"),
                    site=site))
        self.generic_visit(node)


def _retry_sanctioned_names(tree):
    """Names of local functions passed (by name) to retry_backoff — the
    blocking op inside them rides the shared deadline by construction."""
    names = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        fname = (f.id if isinstance(f, ast.Name)
                 else f.attr if isinstance(f, ast.Attribute) else "")
        if fname == "retry_backoff":
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
                elif isinstance(arg, ast.Lambda):
                    names.add("<lambda>")
    return names


def lint_source(src, filename="<src>"):
    """Blocking-call lint over one source text; returns violations.
    The battery's seeded fixtures come through here."""
    tree = ast.parse(src, filename)
    _COUNTERS["files_linted"] += 1
    visitor = _BlockingVisitor(filename, _retry_sanctioned_names(tree))
    visitor.visit(tree)
    _COUNTERS["violations"] += len(visitor.violations)
    return visitor.violations


def _default_lint_paths():
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    roots = [os.path.join(pkg, "serving"),
             os.path.join(pkg, "distributed", "collective")]
    out = []
    for root in roots:
        for dirpath, _, files in os.walk(root):
            out += [os.path.join(dirpath, f)
                    for f in sorted(files) if f.endswith(".py")]
    return out


def lint_blocking_calls(paths=None):
    """Blocking-call lint over the real serving/ + collective/ trees
    (or explicit `paths`); returns all violations."""
    violations = []
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    for path in (paths or _default_lint_paths()):
        with open(path, encoding="utf-8") as f:
            src = f.read()
        rel = os.path.relpath(path, pkg_root)
        violations += lint_source(src, rel)
    return violations
