"""Static-Program pass infrastructure.

Reference: the PIR pass manager + pattern rewriter
(paddle/pir/pass/pass.h, paddle/pir/pattern_rewrite/pattern_match.h) and
transform passes like DCE/constant-fold
(paddle/fluid/pir/transforms/*.cc).

TPU-native role: XLA performs the heavy optimization (fusion, CSE, layout),
so Program-level passes exist for what must happen BEFORE lowering —
pruning ops whose outputs are unreachable from the fetch/write frontier
(smaller traced graphs, faster compiles) and folding operators whose inputs
are all compile-time constants.  Pass objects follow the reference's
PassManager shape so strategy-driven pipelines compose.
"""

from __future__ import annotations

__all__ = ["ProgramPass", "ProgramPassManager", "dead_code_elimination", "apply_pass"]


class ProgramPass:
    name = "base"

    def apply(self, program) -> int:
        """Mutate the program; return the number of changes."""
        raise NotImplementedError


class DeadCodeEliminationPass(ProgramPass):
    """Remove ops whose outputs no fetch/write/op-input can reach
    (reference paddle/fluid/pir/transforms/dead_code_elimination_pass.cc).

    Ops with side effects beyond their data outputs — the in-place tier,
    RNG/seed ops (eliminating one shifts every later op's key sequence),
    print/py_func, collectives (a dropped rank deadlocks its peers) — are
    never eliminated, fetch-reachable or not
    (framework.op_registry.side_effect_op_types)."""

    name = "dead_code_elimination"

    def __init__(self, fetch_vids=None):
        self._fetch_vids = set(fetch_vids or ())

    def apply(self, program) -> int:
        from paddle_tpu.framework.op_registry import (
            base_op_type, side_effect_op_types)

        block = program.global_block()
        live = set(self._fetch_vids)
        live.update(program.writes.keys())
        live.update(program.writes.values())
        if not self._fetch_vids:
            # no fetch frontier given: every named var is fetchable → only
            # ops feeding writes are provably removable; keep all. (The
            # executor applies this pass with the actual fetch list.)
            return 0
        effectful = side_effect_op_types()
        removed = 0
        # reverse liveness walk over the op list
        keep = []
        for op in reversed(block.ops):
            if (any(v in live for v in op.out_vids)
                    or base_op_type(op.type) in effectful):
                keep.append(op)
                live.update(op.input_vids())
            else:
                removed += 1
        block.ops = list(reversed(keep))
        if removed:
            program.version += 1
        return removed


def dead_code_elimination(program, fetch_vars=()):
    """Prune a COPY of the op list down to what `fetch_vars` need; returns
    the number of removed ops (executor integration point)."""
    vids = [v._vid for v in fetch_vars]
    return DeadCodeEliminationPass(vids).apply(program)


class ProgramPassManager:
    """Runs passes in order; under FLAGS_verify_programs every pass runs
    between verifier invocations (the reference PassManager's
    EnableIRPrinting/verify hooks) so the pass that breaks an invariant is
    named in the error, not discovered downstream."""

    def __init__(self, passes, fetch_vids=()):
        self._passes = list(passes)
        self._fetch_vids = tuple(fetch_vids)

    def run(self, program):
        from paddle_tpu._core import flags

        verify = flags.flag("FLAGS_verify_programs")
        mesh_lint = flags.flag("FLAGS_verify_sharding")
        if verify:
            from .verify import VerificationError, verify_program

            try:
                verify_program(program, self._fetch_vids)
            except VerificationError as e:
                raise VerificationError(
                    e.violations,
                    header="Program invalid BEFORE pass pipeline") from None
        if mesh_lint:
            self._mesh_lint(program, "BEFORE pass pipeline")
        total = 0
        for p in self._passes:
            total += p.apply(program)
            name = getattr(p, "name", type(p).__name__)
            if verify:
                try:
                    verify_program(program, self._fetch_vids)
                except VerificationError as e:
                    raise VerificationError(
                        e.violations,
                        header=f"Program invalid after pass {name!r}",
                    ) from None
            if mesh_lint:
                self._mesh_lint(program, f"after pass {name!r}")
        return total

    def _mesh_lint(self, program, where):
        """Pass-boundary mesh lint (FLAGS_verify_sharding): the pass that
        introduces a mis-axised collective or a stale-donation fetch is
        named in the error, not discovered at dispatch."""
        from .mesh_lint import MeshLintError, lint_program

        try:
            lint_program(program, self._fetch_vids, raise_on_error=True)
        except MeshLintError as e:
            raise MeshLintError(
                e.violations,
                header=f"Mesh lint failed {where}") from None


def _pallas_fusion_factory(**kwargs):
    from .rewrite import PallasFusionPass

    return PallasFusionPass(**kwargs)


def _generic_elementwise_factory(**kwargs):
    from .rewrite import GenericElementwiseFusionPass

    return GenericElementwiseFusionPass(**kwargs)


def _schedule_search_factory(**kwargs):
    from .rewrite import ScheduleSearchPass

    return ScheduleSearchPass(**kwargs)


def _fp16_rewrite_factory(**kwargs):
    from paddle_tpu.distributed.passes import Fp16ProgramRewrite

    return Fp16ProgramRewrite(**kwargs)


def _dist_rewrite_factory(name):
    def factory(**kwargs):
        from paddle_tpu.distributed.passes import program_rewrites as pr

        return getattr(pr, name)(**kwargs)

    return factory


class WeightOnlyQuantPass(ProgramPass):
    """Bake weight-only int8/int4 parameters into a serving program.

    Reference capability: weight-only quantized deployment
    (paddle/fluid/inference analysis passes + nn.quant weight_only_linear).
    Every matmul/linear-family op whose weight operand is a 2-D program
    parameter gets its weight replaced by (int8-or-packed-int4 q, per-output
    -channel scale) parameters; the op body dequantizes then calls the
    original fn, so XLA fuses the dequant into the matmul and the exported
    artifact carries 4x/8x smaller weights.  fp32 weights that no other op
    uses are retired from param_inits (they would otherwise still be baked
    into the .pdmodel).
    """

    name = "weight_only_quant"
    TARGETS = {"matmul", "linear", "mm", "addmm"}

    def __init__(self, algo="weight_only_int8"):
        if algo == "int8":
            algo = "weight_only_int8"
        if algo not in ("weight_only_int8", "weight_only_int4"):
            raise ValueError(f"unsupported weight-only algo {algo!r}")
        self.algo = algo

    def apply(self, program) -> int:
        import numpy as np
        import jax
        import jax.numpy as jnp

        from .program import Operator

        from .executor import global_scope

        scope = global_scope()
        block = program.global_block()
        cache = {}  # weight vid -> (q_vid, s_vid)
        rewritten = []
        n = 0
        for i, op in enumerate(list(block.ops)):
            if op.type.startswith("wq::"):
                continue  # idempotent: never re-quantize a rewritten op
            if op.type.split("::")[-1] not in self.TARGETS:
                continue
            var_positions = [j for j, s in enumerate(op.arg_spec) if s[0] == "var"]
            cand = None
            for pos_in_vars, j in enumerate(var_positions):
                vid = op.arg_spec[j][1]
                init = program.param_inits.get(vid)
                if init is not None and getattr(init, "ndim", None) == 2:
                    # trained value lives in the scope; param_inits only has
                    # the INIT (executor persists updates to the scope —
                    # quantizing inits would bake untrained weights)
                    trained = scope.find_var(vid)
                    cand = (pos_in_vars, j, vid,
                            trained if trained is not None else init)
            if cand is None:
                continue
            widx, spec_idx, wvid, W = cand
            if wvid not in cache:
                W32 = np.asarray(W, np.float32)
                amax = np.abs(W32).max(axis=0)
                if self.algo == "weight_only_int4":
                    if W32.shape[0] % 2:
                        raise ValueError(
                            "weight_only_int4 needs an even input dim, got "
                            f"{W32.shape}")
                    scale = np.where(amax > 0, amax / 7.0, 1.0).astype(np.float32)
                    q = np.clip(np.round(W32 / scale), -8, 7).astype(np.int8)
                    q = ((q[0::2] & 0x0F) | ((q[1::2] & 0x0F) << 4)).astype(np.int8)
                else:
                    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
                    q = np.clip(np.round(W32 / scale), -127, 127).astype(np.int8)
                qv = program.new_var(
                    jax.ShapeDtypeStruct(q.shape, jnp.int8),
                    name=f"wq_{wvid}", persistable=True, is_parameter=True)
                sv = program.new_var(
                    jax.ShapeDtypeStruct(scale.shape, jnp.float32),
                    name=f"wq_scale_{wvid}", persistable=True, is_parameter=True)
                program.param_inits[qv._vid] = jnp.asarray(q)
                program.param_inits[sv._vid] = jnp.asarray(scale)
                cache[wvid] = (qv._vid, sv._vid)
            q_vid, s_vid = cache[wvid]
            orig_dtype = W.dtype

            def make(fn, widx=widx, odt=orig_dtype, algo=self.algo):
                def wrapped(*vals):
                    vals = list(vals)
                    scale_v = vals.pop()  # appended last by the rewrite
                    qw = vals[widx]
                    if algo == "weight_only_int4":
                        lo = (qw & 0x0F).astype(jnp.int8)
                        hi = ((qw >> 4) & 0x0F).astype(jnp.int8)
                        lo = jnp.where(lo > 7, lo - 16, lo)
                        hi = jnp.where(hi > 7, hi - 16, hi)
                        qw = jnp.stack([lo, hi], axis=1).reshape(
                            lo.shape[0] * 2, *lo.shape[1:])
                    wde = (qw.astype(jnp.float32) * scale_v).astype(odt)
                    vals[widx] = wde
                    return fn(*vals)

                return wrapped

            new_spec = list(op.arg_spec)
            new_spec[spec_idx] = ("var", q_vid)
            new_spec.append(("var", s_vid))
            block.ops[i] = Operator(
                "wq::" + op.type, make(op.fn), new_spec, op.kwargs,
                op.out_vids, op.out_tree)
            rewritten.append(wvid)
            n += 1
        if n:
            # retire fp32 weights nothing references anymore
            used = set()
            for op in block.ops:
                used.update(op.input_vids())
            used.update(program.writes)
            used.update(program.writes.values())
            for vid in set(rewritten):
                if vid not in used:
                    program.param_inits.pop(vid, None)
            program.version += 1
        return n


_REGISTRY = {
    "dead_code_elimination": DeadCodeEliminationPass,
    "weight_only_quant": WeightOnlyQuantPass,
    "pallas_fusion": _pallas_fusion_factory,
    "generic_elementwise_fusion": _generic_elementwise_factory,
    "schedule_search": _schedule_search_factory,
    "auto_parallel_fp16": _fp16_rewrite_factory,
    "auto_parallel_recompute": _dist_rewrite_factory("RecomputeProgramRewrite"),
    "auto_parallel_gradient_merge": _dist_rewrite_factory("GradientMergeProgramRewrite"),
    "auto_parallel_sharding": _dist_rewrite_factory("ShardingProgramRewrite"),
}


def apply_pass(program, name, **kwargs):
    if name not in _REGISTRY:
        raise ValueError(f"unknown program pass {name!r}; have {sorted(_REGISTRY)}")
    from paddle_tpu._core import flags

    pass_ = _REGISTRY[name](**kwargs)
    if (flags.flag("FLAGS_verify_programs")
            or flags.flag("FLAGS_verify_sharding")):
        fetch = kwargs.get("fetch_vids") or ()
        return ProgramPassManager([pass_], fetch_vids=fetch).run(program)
    return pass_.apply(program)
