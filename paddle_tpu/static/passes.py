"""Static-Program pass infrastructure.

Reference: the PIR pass manager + pattern rewriter
(paddle/pir/pass/pass.h, paddle/pir/pattern_rewrite/pattern_match.h) and
transform passes like DCE/constant-fold
(paddle/fluid/pir/transforms/*.cc).

TPU-native role: XLA performs the heavy optimization (fusion, CSE, layout),
so Program-level passes exist for what must happen BEFORE lowering —
pruning ops whose outputs are unreachable from the fetch/write frontier
(smaller traced graphs, faster compiles) and folding operators whose inputs
are all compile-time constants.  Pass objects follow the reference's
PassManager shape so strategy-driven pipelines compose.
"""

from __future__ import annotations

__all__ = ["ProgramPass", "ProgramPassManager", "dead_code_elimination", "apply_pass"]


class ProgramPass:
    name = "base"

    def apply(self, program) -> int:
        """Mutate the program; return the number of changes."""
        raise NotImplementedError


class DeadCodeEliminationPass(ProgramPass):
    """Remove ops whose outputs no fetch/write/op-input can reach
    (reference paddle/fluid/pir/transforms/dead_code_elimination_pass.cc)."""

    name = "dead_code_elimination"

    def __init__(self, fetch_vids=None):
        self._fetch_vids = set(fetch_vids or ())

    def apply(self, program) -> int:
        block = program.global_block()
        live = set(self._fetch_vids)
        live.update(program.writes.keys())
        live.update(program.writes.values())
        if not self._fetch_vids:
            # no fetch frontier given: every named var is fetchable → only
            # ops feeding writes are provably removable; keep all. (The
            # executor applies this pass with the actual fetch list.)
            return 0
        removed = 0
        # reverse liveness walk over the op list
        keep = []
        for op in reversed(block.ops):
            if any(v in live for v in op.out_vids):
                keep.append(op)
                live.update(op.input_vids())
            else:
                removed += 1
        block.ops = list(reversed(keep))
        if removed:
            program.version += 1
        return removed


def dead_code_elimination(program, fetch_vars=()):
    """Prune a COPY of the op list down to what `fetch_vars` need; returns
    the number of removed ops (executor integration point)."""
    vids = [v._vid for v in fetch_vars]
    return DeadCodeEliminationPass(vids).apply(program)


class ProgramPassManager:
    def __init__(self, passes):
        self._passes = list(passes)

    def run(self, program):
        total = 0
        for p in self._passes:
            total += p.apply(program)
        return total


def _pallas_fusion_factory(**kwargs):
    from .rewrite import PallasFusionPass

    return PallasFusionPass(**kwargs)


def _fp16_rewrite_factory(**kwargs):
    from paddle_tpu.distributed.passes import Fp16ProgramRewrite

    return Fp16ProgramRewrite(**kwargs)


def _dist_rewrite_factory(name):
    def factory(**kwargs):
        from paddle_tpu.distributed.passes import program_rewrites as pr

        return getattr(pr, name)(**kwargs)

    return factory


_REGISTRY = {
    "dead_code_elimination": DeadCodeEliminationPass,
    "pallas_fusion": _pallas_fusion_factory,
    "auto_parallel_fp16": _fp16_rewrite_factory,
    "auto_parallel_recompute": _dist_rewrite_factory("RecomputeProgramRewrite"),
    "auto_parallel_gradient_merge": _dist_rewrite_factory("GradientMergeProgramRewrite"),
    "auto_parallel_sharding": _dist_rewrite_factory("ShardingProgramRewrite"),
}


def apply_pass(program, name, **kwargs):
    if name not in _REGISTRY:
        raise ValueError(f"unknown program pass {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs).apply(program)
