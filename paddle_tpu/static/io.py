"""Static model serialization — the deploy format.

Reference: python/paddle/static/io.py save/load_inference_model writing
`.pdmodel` (protobuf program) + `.pdiparams` (params); loaded by the C++
AnalysisPredictor for serving.

TPU-native format (`.pdmodel` analog): the program is lowered AOT with
jax.jit(...).lower() and saved as **StableHLO (portable bytecode)** — the IR
XLA serves directly — plus a JSON manifest (feed/fetch names, shapes,
dtypes) and an `.npz` of parameters.  Loading deserializes into a callable
executable without the Python graph (paddle_tpu.inference.Predictor wraps
it); `load_inference_model` here returns (program-like callable, feed names,
fetch names) matching the reference's tuple shape.
"""

from __future__ import annotations

import json
import os

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu._core.tensor import Tensor

from .program import Program, Variable, _st
from .executor import Executor, global_scope

__all__ = [
    "save",
    "load",
    "save_inference_model",
    "load_inference_model",
    "serialize_program",
    "deserialize_program",
]


_PRECISION_ALIASES = {"int8": "weight_only_int8", "fp16": "float16",
                      "half": "float16", "bf16": "bfloat16"}
_PRECISIONS = ("float32", "float16", "bfloat16",
               "weight_only_int8", "weight_only_int4")


def canonicalize_precision(precision):
    """One canonical spelling for precision modes, shared by the export path
    and inference.Config so manifests and load-time requests always agree."""
    p = _PRECISION_ALIASES.get(str(precision).lower(), str(precision).lower())
    if p not in _PRECISIONS:
        raise ValueError(
            f"unknown precision {precision!r}; expected one of "
            f"{_PRECISIONS} (aliases {sorted(_PRECISION_ALIASES)})")
    return p


def save(program: Program, model_path: str):
    """paddle.static.save parity: persist parameters+state (pickled npz)."""
    scope = global_scope()
    state = {}
    for vid, init in program.param_inits.items():
        var = program._var_by_vid[vid]
        val = scope.find_var(vid)
        state[var.name] = np.asarray(val if val is not None else init)
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    np.savez(model_path + ".pdparams.npz", **state)


def load(program: Program, model_path: str, executor=None, var_list=None):
    data = np.load(model_path + ".pdparams.npz")
    scope = global_scope()
    by_name = {program._var_by_vid[vid].name: vid for vid in program.param_inits}
    for name in data.files:
        if name in by_name:
            scope.set_var(by_name[name], jnp.asarray(data[name]))


def _program_callable(program: Program, feed_vars, fetch_vars):
    run_fn, feed_vids, state_vids = program.as_function(
        [v._vid for v in fetch_vars], feed_vids=[v._vid for v in feed_vars]
    )
    scope = global_scope()
    state_vals = [
        scope.find_var(vid) if scope.find_var(vid) is not None else program.param_inits[vid]
        for vid in state_vids
    ]

    def fn(*feed_vals):
        fetches, _ = run_fn(list(feed_vals), state_vals)
        return tuple(fetches)

    return fn


def serialize_program(program: Program, feed_vars, fetch_vars):
    """Lower + export to StableHLO portable bytecode (the .pdmodel analog).
    Returns (serialized bytes, stablehlo text for inspection)."""
    fn = _program_callable(program, feed_vars, fetch_vars)
    scope = jax.export.SymbolicScope()
    avals = []
    for v in feed_vars:
        dyn = getattr(v, "dynamic_dims", ()) or ()
        if dyn:
            # shared symbol per axis position so e.g. batch dims unify
            dims = ",".join(
                f"d{i}" if i in dyn else str(d) for i, d in enumerate(v._value.shape)
            )
            shape = jax.export.symbolic_shape(dims, scope=scope)
        else:
            shape = v._value.shape
        avals.append(jax.ShapeDtypeStruct(shape, v._value.dtype))
    prev = _st.main_program
    _st.main_program = None
    try:
        exported = jax.export.export(jax.jit(fn), platforms=["cpu", "tpu"])(*avals)
    finally:
        _st.main_program = prev
    return exported.serialize(), str(exported.mlir_module())


def deserialize_program(blob: bytes):
    return jax.export.deserialize(blob)


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor=None,
                         program=None, passes=None, precision=None, **kwargs):
    """Writes <prefix>.pdmodel (StableHLO bytecode via jax.export),
    <prefix>.pdmodel.txt (HLO text), <prefix>.json (manifest),
    <prefix>.pdiparams.npz (parameters, already folded into the HLO as
    constants for serving; saved separately for inspection/re-export).

    `passes` / `precision` are the export-time analog of the reference's
    AnalysisConfig pass-pipeline + precision-mode controls
    (paddle/fluid/inference/api/paddle_analysis_config.h pass_builder /
    Precision): the named program passes from static.passes run over a
    CLONE of the program before export, and precision="bfloat16"/"float16"
    applies the fp16 cast-insertion rewrite — the optimized program is what
    the .pdmodel bakes, so every Predictor serves it."""
    program = program or (feed_vars[0]._program if isinstance(feed_vars[0], Variable) else None)
    if program is None:
        from .program import default_main_program

        program = default_main_program()
    extra_precisions = [canonicalize_precision(p)
                        for p in kwargs.pop("extra_precisions", ()) or ()]
    precision = canonicalize_precision(precision) if precision else None
    base_program = program
    applied = []

    def _apply_precision(prog, prec):
        from .passes import apply_pass

        if prec in ("bfloat16", "float16"):
            apply_pass(prog, "auto_parallel_fp16", dtype=prec)
            return f"auto_parallel_fp16:{prec}"
        apply_pass(prog, "weight_only_quant", algo=prec)
        return f"weight_only_quant:{prec}"

    def _apply_passes(prog, record):
        from .passes import apply_pass

        for name in passes or []:
            opts = dict(name) if isinstance(name, dict) else {}
            pname = opts.pop("name", name) if isinstance(name, dict) else name
            if pname in ("dead_code_elimination", "pallas_fusion",
                         "generic_elementwise_fusion"):
                # these passes compute use-def against the fetch frontier:
                # forward the export's fetch set so a fusion cannot swallow
                # a fetched intermediate (and DCE isn't a documented no-op)
                opts.setdefault("fetch_vids", [v._vid for v in fetch_vars])
            apply_pass(prog, pname, **opts)
            if record:
                applied.append(pname)

    if passes or precision:
        program = program.clone(for_test=True)
        _apply_passes(program, record=True)
        if precision:
            applied.append(_apply_precision(program, precision))

    from paddle_tpu._core import flags as _flags

    if _flags.flag("FLAGS_verify_programs"):
        # export verify mode: the artifact bakes the optimized program, so
        # check it structurally for the export's fetch frontier, and — when
        # only numerics-preserving passes ran — differentially against the
        # unrewritten program (precision rewrites change numerics by
        # design and are excluded; docs/VERIFIER.md)
        from .verify import differential_check, verify_program

        fetch_vids = [v._vid for v in fetch_vars]
        verify_program(program, fetch_vids)
        numerics_preserving = {"dead_code_elimination", "pallas_fusion",
                               "generic_elementwise_fusion"}
        if (program is not base_program and not precision
                and set(applied) <= numerics_preserving):
            differential_check(base_program, program, fetch_vids)
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)

    # Additional precision variants of the SAME program — each gets the SAME
    # pass pipeline as the main artifact plus its precision rewrite, exported
    # as <prefix>.<precision>.pdmodel and listed in the manifest: the
    # build-per-precision-engine analog of the reference's TensorRT flow
    # (paddle_analysis_config.h:676 Precision modes); the Predictor selects
    # a variant at load via Config.set_precision.
    variants = {}
    for prec in extra_precisions:
        vprog = base_program.clone(for_test=True)
        _apply_passes(vprog, record=False)
        _apply_precision(vprog, prec)
        vblob, _ = serialize_program(vprog, feed_vars, fetch_vars)
        vname = f"{os.path.basename(path_prefix)}.{prec}.pdmodel"
        with open(f"{path_prefix}.{prec}.pdmodel", "wb") as f:
            f.write(vblob)
        variants[prec] = vname

    blob, text = serialize_program(program, feed_vars, fetch_vars)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(blob)
    with open(path_prefix + ".pdmodel.txt", "w") as f:
        f.write(text)

    scope = global_scope()
    params = {}
    for vid, init in program.param_inits.items():
        val = scope.find_var(vid)
        params[program._var_by_vid[vid].name] = np.asarray(val if val is not None else init)
    np.savez(path_prefix + ".pdiparams.npz", **params)

    manifest = {
        "feed": [
            {"name": v.name, "shape": list(v._value.shape), "dtype": str(np.dtype(v._value.dtype))}
            for v in feed_vars
        ],
        "fetch": [
            {"name": v.name, "shape": list(v._value.shape), "dtype": str(np.dtype(v._value.dtype))}
            for v in fetch_vars
        ],
        "format": "stablehlo-text",
        "passes": applied,
        "precision": precision or "float32",
        "variants": variants,
    }
    with open(path_prefix + ".json", "w") as f:
        json.dump(manifest, f, indent=2)
    return path_prefix


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    """Returns (predictor_fn, feed_names, fetch_names): predictor_fn is a
    compiled callable over np arrays (serving path — no Python graph)."""
    from paddle_tpu.inference import Predictor

    pred = Predictor(path_prefix)
    feed_names = [s["name"] for s in pred.manifest["feed"]]
    fetch_names = [s["name"] for s in pred.manifest["fetch"]]
    return pred, feed_names, fetch_names
