"""Static model serialization — the deploy format.

Reference: python/paddle/static/io.py save/load_inference_model writing
`.pdmodel` (protobuf program) + `.pdiparams` (params); loaded by the C++
AnalysisPredictor for serving.

TPU-native format (`.pdmodel` analog): the program is lowered AOT with
jax.jit(...).lower() and saved as **StableHLO (portable bytecode)** — the IR
XLA serves directly — plus a JSON manifest (feed/fetch names, shapes,
dtypes) and an `.npz` of parameters.  Loading deserializes into a callable
executable without the Python graph (paddle_tpu.inference.Predictor wraps
it); `load_inference_model` here returns (program-like callable, feed names,
fetch names) matching the reference's tuple shape.
"""

from __future__ import annotations

import json
import os

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu._core.tensor import Tensor

from .program import Program, Variable, _st
from .executor import Executor, global_scope

__all__ = [
    "save",
    "load",
    "save_inference_model",
    "load_inference_model",
    "serialize_program",
    "deserialize_program",
]


def save(program: Program, model_path: str):
    """paddle.static.save parity: persist parameters+state (pickled npz)."""
    scope = global_scope()
    state = {}
    for vid, init in program.param_inits.items():
        var = program._var_by_vid[vid]
        val = scope.find_var(vid)
        state[var.name] = np.asarray(val if val is not None else init)
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    np.savez(model_path + ".pdparams.npz", **state)


def load(program: Program, model_path: str, executor=None, var_list=None):
    data = np.load(model_path + ".pdparams.npz")
    scope = global_scope()
    by_name = {program._var_by_vid[vid].name: vid for vid in program.param_inits}
    for name in data.files:
        if name in by_name:
            scope.set_var(by_name[name], jnp.asarray(data[name]))


def _program_callable(program: Program, feed_vars, fetch_vars):
    run_fn, feed_vids, state_vids = program.as_function(
        [v._vid for v in fetch_vars], feed_vids=[v._vid for v in feed_vars]
    )
    scope = global_scope()
    state_vals = [
        scope.find_var(vid) if scope.find_var(vid) is not None else program.param_inits[vid]
        for vid in state_vids
    ]

    def fn(*feed_vals):
        fetches, _ = run_fn(list(feed_vals), state_vals)
        return tuple(fetches)

    return fn


def serialize_program(program: Program, feed_vars, fetch_vars):
    """Lower + export to StableHLO portable bytecode (the .pdmodel analog).
    Returns (serialized bytes, stablehlo text for inspection)."""
    fn = _program_callable(program, feed_vars, fetch_vars)
    scope = jax.export.SymbolicScope()
    avals = []
    for v in feed_vars:
        dyn = getattr(v, "dynamic_dims", ()) or ()
        if dyn:
            # shared symbol per axis position so e.g. batch dims unify
            dims = ",".join(
                f"d{i}" if i in dyn else str(d) for i, d in enumerate(v._value.shape)
            )
            shape = jax.export.symbolic_shape(dims, scope=scope)
        else:
            shape = v._value.shape
        avals.append(jax.ShapeDtypeStruct(shape, v._value.dtype))
    prev = _st.main_program
    _st.main_program = None
    try:
        exported = jax.export.export(jax.jit(fn), platforms=["cpu", "tpu"])(*avals)
    finally:
        _st.main_program = prev
    return exported.serialize(), str(exported.mlir_module())


def deserialize_program(blob: bytes):
    return jax.export.deserialize(blob)


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor=None,
                         program=None, passes=None, precision=None, **kwargs):
    """Writes <prefix>.pdmodel (StableHLO bytecode via jax.export),
    <prefix>.pdmodel.txt (HLO text), <prefix>.json (manifest),
    <prefix>.pdiparams.npz (parameters, already folded into the HLO as
    constants for serving; saved separately for inspection/re-export).

    `passes` / `precision` are the export-time analog of the reference's
    AnalysisConfig pass-pipeline + precision-mode controls
    (paddle/fluid/inference/api/paddle_analysis_config.h pass_builder /
    Precision): the named program passes from static.passes run over a
    CLONE of the program before export, and precision="bfloat16"/"float16"
    applies the fp16 cast-insertion rewrite — the optimized program is what
    the .pdmodel bakes, so every Predictor serves it."""
    program = program or (feed_vars[0]._program if isinstance(feed_vars[0], Variable) else None)
    if program is None:
        from .program import default_main_program

        program = default_main_program()
    applied = []
    if passes or precision:
        from .passes import apply_pass

        program = program.clone(for_test=True)
        for name in passes or []:
            opts = dict(name) if isinstance(name, dict) else {}
            pname = opts.pop("name", name) if isinstance(name, dict) else name
            if pname == "dead_code_elimination":
                # DCE without a fetch frontier is a documented no-op:
                # forward the export's fetch set
                opts.setdefault("fetch_vids", [v._vid for v in fetch_vars])
            apply_pass(program, pname, **opts)
            applied.append(pname)
        if precision:
            if precision not in ("bfloat16", "float16"):
                raise ValueError(
                    f"precision must be bfloat16/float16, got {precision!r}")
            apply_pass(program, "auto_parallel_fp16", dtype=precision)
            applied.append(f"auto_parallel_fp16:{precision}")
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)

    blob, text = serialize_program(program, feed_vars, fetch_vars)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(blob)
    with open(path_prefix + ".pdmodel.txt", "w") as f:
        f.write(text)

    scope = global_scope()
    params = {}
    for vid, init in program.param_inits.items():
        val = scope.find_var(vid)
        params[program._var_by_vid[vid].name] = np.asarray(val if val is not None else init)
    np.savez(path_prefix + ".pdiparams.npz", **params)

    manifest = {
        "feed": [
            {"name": v.name, "shape": list(v._value.shape), "dtype": str(np.dtype(v._value.dtype))}
            for v in feed_vars
        ],
        "fetch": [
            {"name": v.name, "shape": list(v._value.shape), "dtype": str(np.dtype(v._value.dtype))}
            for v in fetch_vars
        ],
        "format": "stablehlo-text",
        "passes": applied,
    }
    with open(path_prefix + ".json", "w") as f:
        json.dump(manifest, f, indent=2)
    return path_prefix


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    """Returns (predictor_fn, feed_names, fetch_names): predictor_fn is a
    compiled callable over np arrays (serving path — no Python graph)."""
    from paddle_tpu.inference import Predictor

    pred = Predictor(path_prefix)
    feed_names = [s["name"] for s in pred.manifest["feed"]]
    fetch_names = [s["name"] for s in pred.manifest["fetch"]]
    return pred, feed_names, fetch_names
