"""paddle.static.nn parity: graph-building layer functions (reference
python/paddle/static/nn/common.py fc/embedding/...).

These are thin functional wrappers creating fresh Parameters per call —
inside a program_guard the parameters auto-register with the program.
"""

from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu._core.tensor import Parameter

from paddle_tpu.static.control_flow import (  # noqa: F401
    Print,
    case,
    cond,
    switch_case,
    while_loop,
)

__all__ = [
    "fc", "embedding", "batch_norm", "conv2d",
    "cond", "while_loop", "case", "switch_case", "Print",
]


def _make_param(shape, dtype, initializer):
    from paddle_tpu._core.dtype import to_jax_dtype

    val = initializer._init_value(tuple(shape), to_jax_dtype(dtype))
    return Parameter(val, stop_gradient=False)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None, activation=None, name=None):
    in_dim = 1
    for d in x.shape[num_flatten_dims:]:
        in_dim *= int(d)
    w = _make_param([in_dim, size], "float32", I.XavierNormal())
    b = _make_param([size], "float32", I.Constant(0.0))
    # -1 for the leading (batch) dim so dynamic feed shapes replay correctly
    x2 = paddle.reshape(x, [-1] + list(x.shape[1:num_flatten_dims]) + [in_dim])
    out = paddle.matmul(x2, w) + b
    if activation == "relu":
        out = F.relu(out)
    elif activation == "softmax":
        out = F.softmax(out)
    elif activation is not None:
        raise ValueError(f"unsupported activation {activation}")
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, param_attr=None, dtype="float32"):
    w = _make_param(list(size), dtype, I.XavierNormal())
    return F.embedding(input, w, padding_idx=padding_idx)


def batch_norm(input, momentum=0.9, epsilon=1e-5, data_layout="NCHW", **kwargs):
    import paddle_tpu.nn as nn

    bn = nn.BatchNorm2D(input.shape[1] if data_layout == "NCHW" else input.shape[-1], momentum, epsilon, data_format=data_layout)
    return bn(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1, groups=1, param_attr=None, bias_attr=None, data_format="NCHW"):
    import paddle_tpu.nn as nn

    conv = nn.Conv2D(input.shape[1], num_filters, filter_size, stride, padding, dilation, groups, data_format=data_format)
    return conv(input)
