"""paddle.static.nn parity: graph-building layer functions (reference
python/paddle/static/nn/common.py fc/embedding/...).

These are thin functional wrappers creating fresh Parameters per call —
inside a program_guard the parameters auto-register with the program.
"""

from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu._core.tensor import Parameter

from paddle_tpu.static.control_flow import (  # noqa: F401
    Print,
    case,
    cond,
    switch_case,
    while_loop,
)
from paddle_tpu.static.sequence import (  # noqa: F401
    sequence_concat,
    sequence_enumerate,
    sequence_expand,
    sequence_expand_as,
    sequence_first_step,
    sequence_last_step,
    sequence_pad,
    sequence_pool,
    sequence_reshape,
    sequence_reverse,
    sequence_scatter,
    sequence_slice,
    sequence_softmax,
    sequence_unpad,
)

__all__ = [
    "fc", "embedding", "batch_norm", "conv2d",
    "conv2d_transpose", "conv3d", "conv3d_transpose", "deform_conv2d",
    "group_norm", "instance_norm", "layer_norm", "data_norm",
    "spectral_norm", "prelu", "nce", "row_conv", "sparse_embedding",
    "bilinear_tensor_product", "py_func", "static_pylayer",
    "cond", "while_loop", "case", "switch_case", "Print",
    "sequence_softmax", "sequence_pool", "sequence_concat",
    "sequence_first_step", "sequence_last_step", "sequence_slice",
    "sequence_expand", "sequence_expand_as", "sequence_pad",
    "sequence_unpad", "sequence_reshape", "sequence_scatter",
    "sequence_enumerate", "sequence_reverse",
]


def _act(out, act):
    """Apply a reference activation string; unknown strings raise instead
    of silently returning un-activated output."""
    if act is None:
        return out
    fn = getattr(F, act, None)
    if fn is None:
        raise ValueError(f"unsupported act {act!r}")
    return fn(out)


def _transpose_filter_size(filter_size, output_size, in_spatial, stride,
                           padding, nd, name):
    """Reference conv*_transpose derives the kernel from output_size when
    filter_size is None: k = out + 2*p - (in - 1) * s (per dim)."""
    if filter_size is not None:
        return filter_size
    if output_size is None:
        raise ValueError(f"{name}: one of filter_size or output_size is "
                         "required")
    outs = [int(output_size)] * nd if isinstance(output_size, int) \
        else [int(o) for o in output_size]
    ss = [stride] * nd if isinstance(stride, int) else list(stride)
    ps = [padding] * nd if isinstance(padding, int) else list(padding)
    ks = [o + 2 * p - (i - 1) * s
          for o, p, i, s in zip(outs, ps, in_spatial, ss)]
    if any(k < 1 for k in ks):
        raise ValueError(f"{name}: output_size {outs} unreachable from "
                         f"input {list(in_spatial)} with stride {ss}")
    return ks


def _make_param(shape, dtype, initializer):
    from paddle_tpu._core.dtype import to_jax_dtype

    val = initializer._init_value(tuple(shape), to_jax_dtype(dtype))
    return Parameter(val)  # trainable=True -> stop_gradient False


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None, activation=None, name=None):
    in_dim = 1
    for d in x.shape[num_flatten_dims:]:
        in_dim *= int(d)
    w = _make_param([in_dim, size], "float32", I.XavierNormal())
    b = _make_param([size], "float32", I.Constant(0.0))
    # -1 for the leading (batch) dim so dynamic feed shapes replay correctly
    x2 = paddle.reshape(x, [-1] + list(x.shape[1:num_flatten_dims]) + [in_dim])
    out = paddle.matmul(x2, w) + b
    if activation == "relu":
        out = F.relu(out)
    elif activation == "softmax":
        out = F.softmax(out)
    elif activation is not None:
        raise ValueError(f"unsupported activation {activation}")
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, param_attr=None, dtype="float32"):
    w = _make_param(list(size), dtype, I.XavierNormal())
    return F.embedding(input, w, padding_idx=padding_idx)


def batch_norm(input, momentum=0.9, epsilon=1e-5, data_layout="NCHW", **kwargs):
    import paddle_tpu.nn as nn

    bn = nn.BatchNorm2D(input.shape[1] if data_layout == "NCHW" else input.shape[-1], momentum, epsilon, data_format=data_layout)
    return bn(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1, groups=1, param_attr=None, bias_attr=None, data_format="NCHW"):
    import paddle_tpu.nn as nn

    conv = nn.Conv2D(input.shape[1], num_filters, filter_size, stride, padding, dilation, groups, data_format=data_format)
    return conv(input)


def conv2d_transpose(input, num_filters, filter_size=None, output_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, data_format="NCHW"):
    import paddle_tpu.nn as nn

    in_ch = int(input.shape[1] if data_format == "NCHW" else input.shape[-1])
    spatial = [int(d) for d in (input.shape[2:] if data_format == "NCHW"
                                else input.shape[1:-1])]
    filter_size = _transpose_filter_size(filter_size, output_size, spatial,
                                         stride, padding, 2,
                                         "conv2d_transpose")
    conv = nn.Conv2DTranspose(in_ch, num_filters, filter_size, stride,
                              padding, dilation=dilation, groups=groups,
                              data_format=data_format)
    return conv(input, output_size=output_size)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, data_format="NCDHW"):
    import paddle_tpu.nn as nn

    in_ch = int(input.shape[1] if data_format == "NCDHW" else input.shape[-1])
    conv = nn.Conv3D(in_ch, num_filters, filter_size, stride, padding,
                     dilation, groups, data_format=data_format)
    return conv(input)


def conv3d_transpose(input, num_filters, filter_size=None, output_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, data_format="NCDHW"):
    import paddle_tpu.nn as nn

    in_ch = int(input.shape[1] if data_format == "NCDHW" else input.shape[-1])
    spatial = [int(d) for d in (input.shape[2:] if data_format == "NCDHW"
                                else input.shape[1:-1])]
    filter_size = _transpose_filter_size(filter_size, output_size, spatial,
                                         stride, padding, 3,
                                         "conv3d_transpose")
    conv = nn.Conv3DTranspose(in_ch, num_filters, filter_size, stride,
                              padding, dilation=dilation, groups=groups,
                              data_format=data_format)
    return conv(input, output_size=output_size)


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None):
    from paddle_tpu.vision.ops import deform_conv2d as _dc

    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    w = _make_param([num_filters, int(input.shape[1]) // groups, *ks],
                    "float32", I.XavierNormal())
    return _dc(input, offset, w, mask=mask, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW"):
    import paddle_tpu.nn as nn

    ch = int(input.shape[1] if data_layout == "NCHW" else input.shape[-1])
    gn = nn.GroupNorm(groups, ch, epsilon, data_format=data_layout)
    return _act(gn(input), act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None):
    import paddle_tpu.nn as nn

    inorm = nn.InstanceNorm2D(int(input.shape[1]), epsilon=epsilon)
    return inorm(input)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None):
    import paddle_tpu.nn as nn

    shape = [int(s) for s in input.shape[begin_norm_axis:]]
    ln = nn.LayerNorm(shape, epsilon=epsilon)
    return _act(ln(input), act)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """Global data normalization via accumulated batch statistics (reference
    data_norm op: batch_size/batch_sum/batch_square_sum accumulators).

    Normalizes with the running ratio, then ACCUMULATES the current batch
    into the buffers (the reference folds the accumulation into its
    optimizer step via synthetic gradients; here the buffers are mutated on
    forward like batch_norm's running stats — same `_bind` mechanism)."""
    from paddle_tpu.nn.functional.norm import with_no_grad_update

    ndim = len(input.shape)
    ch_ax = 1 if (data_layout == "NCHW" and ndim > 1) else ndim - 1
    d = int(input.shape[ch_ax])
    batch_size = _make_param([d], "float32", I.Constant(1e4))
    batch_sum = _make_param([d], "float32", I.Constant(0.0))
    batch_sq = _make_param([d], "float32", I.Constant(1e4))
    for buf in (batch_size, batch_sum, batch_sq):
        buf.stop_gradient = True
    mean = batch_sum / batch_size
    scale = (batch_size / batch_sq) ** 0.5
    bshape = [1] * ndim
    bshape[ch_ax] = d
    out = _act((input - mean.reshape(bshape)) * scale.reshape(bshape), act)
    # Per-channel accumulation of the current batch (momentum 0 == pure add).
    reduce_axes = tuple(i for i in range(ndim) if i != ch_ax)
    n_elems = 1.0
    for i in reduce_axes:
        n_elems *= float(input.shape[i])
    with_no_grad_update(batch_size, 0.0, batch_size + n_elems)
    with_no_grad_update(batch_sum, 0.0, batch_sum + input.sum(axis=reduce_axes))
    with_no_grad_update(batch_sq, 0.0, batch_sq + (input * input).sum(axis=reduce_axes))
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    import paddle_tpu.nn as nn

    sn = nn.SpectralNorm(weight.shape, dim=dim, power_iters=power_iters,
                         epsilon=eps)
    return sn(weight)


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    if mode == "all":
        shape = [1]
    elif mode == "channel":
        shape = [int(x.shape[1] if data_format == "NCHW" else x.shape[-1])]
    elif mode == "element":
        shape = [int(s) for s in x.shape[1:]]
    else:
        raise ValueError(f"prelu mode must be all/channel/element, got {mode}")
    alpha = _make_param(shape, "float32", I.Constant(0.25))
    if mode == "channel" and data_format == "NCHW":
        a = paddle.reshape(alpha, [1, -1] + [1] * (len(x.shape) - 2))
    else:
        a = alpha
    return paddle.maximum(x, paddle.zeros_like(x)) + a * paddle.minimum(
        x, paddle.zeros_like(x))


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """out[:, k] = x W_k y^T + b_k (reference bilinear_tensor_product)."""
    dx, dy = int(x.shape[-1]), int(y.shape[-1])
    w = _make_param([size, dx, dy], "float32", I.XavierNormal())
    b = _make_param([size], "float32", I.Constant(0.0))
    return _act(F.bilinear(x, y, w, b), act)


def row_conv(input, future_context_size, param_attr=None, act=None,
             lod=None):
    """Lookahead (row) convolution (reference row_conv op, the
    DeepSpeech2 streaming context layer): out[t] = sum_{i=0..C}
    x[t+i] * w[i], within each sequence.  Dense [B, T, D] input applies
    per batch row; flat input needs `lod`."""
    import numpy as np

    import jax.numpy as jnp

    from paddle_tpu.tensor._ops_common import apply as _apply

    x = paddle.to_tensor(input) if not hasattr(input, "_value") else input
    ctx = int(future_context_size) + 1
    d = int(x.shape[-1])
    w = _make_param([ctx, d], "float32", I.XavierNormal())
    if len(x.shape) == 3:  # dense batch [B, T, D]
        def _fn(v, wv):
            out = jnp.zeros_like(v)
            T = v.shape[1]
            for i in range(ctx):
                seg = v[:, i:, :] if i else v
                pad = jnp.zeros((v.shape[0], i, v.shape[2]), v.dtype)
                out = out + jnp.concatenate([seg, pad], 1)[:, :T] * wv[i]
            return out

        out = _apply("row_conv", _fn, x, w)
    else:
        from paddle_tpu.static.sequence import _lod_np

        lod_np = _lod_np(lod, "row_conv")

        def _fn(v, wv):
            out = jnp.zeros_like(v)
            for i in range(ctx):
                shifted = jnp.concatenate(
                    [v[i:], jnp.zeros((i, v.shape[1]), v.dtype)], 0) if i else v
                # zero the contributions that crossed a sequence boundary
                t = np.arange(v.shape[0])
                seq_end = np.zeros(v.shape[0], np.int64)
                for s in range(len(lod_np) - 1):
                    seq_end[lod_np[s]:lod_np[s + 1]] = lod_np[s + 1]
                valid = (t + i) < seq_end
                out = out + shifted * wv[i] * jnp.asarray(valid)[:, None]
            return out

        out = _apply("row_conv", _fn, x, w)
    return _act(out, act)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32"):
    """Embedding with row-sparse gradients (reference sparse_embedding —
    the large-scale PS path; here the SelectedRows sparse-grad tier)."""
    import paddle_tpu.nn as nn

    emb = nn.Embedding(int(size[0]), int(size[1]), padding_idx=padding_idx,
                       sparse=True)
    return emb(input)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference nce op): logistic
    loss over the true class plus `num_neg_samples` sampled noise
    classes.  Returns the per-example loss [B, 1]."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from paddle_tpu.tensor._ops_common import apply as _apply

    d = int(input.shape[-1])
    w = _make_param([num_total_classes, d], "float32", I.XavierNormal())
    b = _make_param([num_total_classes], "float32", I.Constant(0.0))
    rng = np.random.default_rng(seed or None)
    if sampler == "uniform":
        noise = rng.integers(0, num_total_classes, num_neg_samples)
        noise_p = np.full(num_neg_samples, 1.0 / num_total_classes)
    elif sampler == "log_uniform":
        u = rng.random(num_neg_samples)
        noise = np.minimum(
            (np.exp(u * np.log(num_total_classes + 1)) - 1).astype(np.int64),
            num_total_classes - 1)
        noise_p = (np.log((noise + 2.0) / (noise + 1.0))
                   / np.log(num_total_classes + 1.0))
    elif sampler == "custom_dist":
        p = np.asarray(custom_dist, np.float64)
        p = p / p.sum()
        noise = rng.choice(num_total_classes, num_neg_samples, p=p)
        noise_p = p[noise]
    else:
        raise ValueError(f"unknown sampler {sampler}")

    # noise probability OF THE LABEL, per the chosen sampler — using the
    # uniform value for every sampler biases the NCE objective
    if sampler == "uniform":
        label_p = None  # constant 1/num_total_classes
    elif sampler == "log_uniform":
        label_p = "log_uniform"
    else:
        label_p = np.asarray(custom_dist, np.float64)
        label_p = label_p / label_p.sum()

    def _fn(xv, yv, wv, bv):
        y = yv.reshape(-1).astype(jnp.int32)
        pos_logit = jnp.sum(xv * wv[y], -1) + bv[y]
        if label_p is None:
            pos_p = 1.0 / num_total_classes
        elif isinstance(label_p, str):
            pos_p = (jnp.log((y + 2.0) / (y + 1.0))
                     / jnp.log(num_total_classes + 1.0))
        else:
            pos_p = jnp.asarray(label_p)[y]
        pos = jax.nn.log_sigmoid(
            pos_logit - jnp.log(num_neg_samples * pos_p))
        neg_logit = xv @ wv[jnp.asarray(noise)].T + bv[jnp.asarray(noise)]
        neg = jax.nn.log_sigmoid(
            -(neg_logit - jnp.log(num_neg_samples * jnp.asarray(noise_p))))
        return -(pos + neg.sum(-1)).reshape(-1, 1)

    return _apply("nce", _fn, input, label, w, b)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    import paddle_tpu.static as st

    return st.py_func(func, x, out, backward_func=backward_func,
                      skip_vars_in_backward_input=skip_vars_in_backward_input)


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """Reference static_pylayer: a forward callable with a custom backward
    captured into the program.  Implemented over the py_func tier's
    custom-backward path when backward_fn is given; a plain call
    otherwise (autograd differentiates through it)."""
    if backward_fn is None:
        return forward_fn(*inputs)
    from paddle_tpu.autograd import PyLayer

    class _StaticPyLayer(PyLayer):
        @staticmethod
        def forward(ctx, *args):
            ctx.save_for_backward(*args)
            return forward_fn(*args)

        @staticmethod
        def backward(ctx, *grads):
            saved = ctx.saved_tensor  # property: the saved tuple
            return backward_fn(*saved, *grads)

    return _StaticPyLayer.apply(*inputs)
