"""Static-IR verifier + pass-differential checker.

Reference role: the IR verifier every serious compiler stack runs between
passes (TVM's structural verifier, arxiv 1802.04799; XLA's HloVerifier and
fusion-correctness analysis, arxiv 2301.13062).  The survey's PIR layer has
`paddle/pir/core/verify.h` for the same reason: a pattern rewrite that
mis-reads an attribute (the transpose-blind MatmulEpilogue fusion), retires
the wrong producer (the fetch-frontier prune keeping two producers of the
loss vid), or emits a malformed op must fail mechanically, not survive
until a reviewer spot-reads the graph.

Two layers:

- **ProgramVerifier** — structural checks over any Program: def-before-use
  (no dangling vids), every op type resolvable through the op registry
  (framework/op_registry.py resolve_op_type), kwargs completeness for the
  attributes rewrite patterns gate on (matmul transpose flags, gelu
  approximate, norm epsilon), at most one live producer per vid on the
  fetch frontier (the executor-prune invariant), and per-op shape/dtype
  consistency via abstract eval (`jax.eval_shape` over the recorded op fn)
  so a rewrite that changes an intermediate's shape or dtype is an error.

- **differential_check(reference, candidate, fetch_vids)** — replays both
  programs eagerly on the same feed (caller-supplied or synthetic) from
  identical RNG state and asserts the fetch set matches to tolerance; the
  mechanical answer to "did this pass change numerics".

Wiring (all gated on ``FLAGS_verify_programs``): ProgramPassManager and
PatternRewritePass verify pre/post, the Executor verifies on every compile
and differentially checks the fusion pass against the unrewritten program
on the live feed, save_inference_model checks its optimized clone, and
``tools/lint_ir.py`` sweeps every Program a test run builds.  Counters
surface through ``paddle_tpu.profiler.verify_stats()``.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp

__all__ = [
    "Violation",
    "VerificationError",
    "DifferentialError",
    "ProgramVerifier",
    "verify_program",
    "differential_check",
    "track_programs",
    "verify_stats",
    "reset_verify_stats",
]


_COUNTERS = {
    "programs_verified": 0,
    "programs_failed": 0,
    "violations": 0,
    "abstract_eval_skips": 0,
    "differential_checks": 0,
    "differential_failures": 0,
    "differential_skips": 0,  # reference program not eagerly replayable
    "rewrites_refused": 0,  # PatternRewritePass use-def rollbacks
}


def verify_stats(reset: bool = False) -> dict:
    out = dict(_COUNTERS)
    if reset:
        reset_verify_stats()
    return out


def reset_verify_stats():
    for k in _COUNTERS:
        _COUNTERS[k] = 0


@dataclass
class Violation:
    code: str       # dangling-vid | unknown-op-type | missing-kwargs | ...
    message: str
    op_index: int = -1
    op_type: str = ""

    def __str__(self):
        loc = f" [op#{self.op_index} {self.op_type}]" if self.op_index >= 0 else ""
        return f"{self.code}{loc}: {self.message}"


class VerificationError(RuntimeError):
    def __init__(self, violations, header="Program verification failed"):
        self.violations = list(violations)
        lines = [f"{header} ({len(self.violations)} violation(s)):"]
        lines += [f"  - {v}" for v in self.violations]
        super().__init__("\n".join(lines))


class DifferentialError(VerificationError):
    """Fetch-set numerics differ between the original and rewritten program."""


# Kwargs the rewrite patterns gate on (static/rewrite.py): a recording path
# that drops one of these makes the corresponding pattern blind — the exact
# shape of the transpose-blind MatmulEpilogue bug.
_REQUIRED_KWARGS = {
    "matmul": ("transpose_x", "transpose_y"),
    "gelu": ("approximate",),
    "softmax": ("axis",),
    "layer_norm": ("epsilon",),
    "rms_norm": ("epsilon",),
    "fused_rms_norm": ("epsilon",),
    "add_rms_norm": ("epsilon",),
    "add_layer_norm": ("epsilon",),
}


from ..framework.op_registry import base_op_type as _base_type


class ProgramVerifier:
    """Structural + abstract-eval checks over a Program.

    check_registry / check_kwargs / abstract_eval toggle the check tiers;
    ``strict_abstract`` escalates an op fn that cannot be abstractly
    evaluated (e.g. a collective outside its mesh) from a counted skip to a
    violation."""

    def __init__(self, check_registry=True, check_kwargs=True,
                 abstract_eval=True, strict_abstract=False):
        self.check_registry = check_registry
        self.check_kwargs = check_kwargs
        self.abstract_eval = abstract_eval
        self.strict_abstract = strict_abstract

    # ------------------------------------------------------------------ api
    def verify(self, program, fetch_vids=(), raise_on_error=False):
        violations = []
        violations += self._check_structure(program, fetch_vids)
        violations += self._check_live_producers(program, fetch_vids)
        _COUNTERS["programs_verified"] += 1
        if violations:
            _COUNTERS["programs_failed"] += 1
            _COUNTERS["violations"] += len(violations)
            if raise_on_error:
                raise VerificationError(violations)
        return violations

    # ------------------------------------------------------------ structure
    def _check_structure(self, program, fetch_vids):
        from paddle_tpu.framework.op_registry import resolve_op_type

        v = []
        ops = program.global_block().ops
        defined = set(program.param_inits)
        for var in program.feed_vars:
            if var._vid not in program._var_by_vid:
                v.append(Violation("unregistered-feed",
                                   f"feed '{var.name}' (vid {var._vid}) is not "
                                   "registered in the program"))
            defined.add(var._vid)

        for i, op in enumerate(ops):
            base = _base_type(op.type)
            if self.check_registry and not resolve_op_type(op.type):
                v.append(Violation(
                    "unknown-op-type",
                    f"op type {op.type!r} does not resolve in the op registry "
                    "(renamed op? unregistered extension? see "
                    "framework.op_registry.register_op_type)", i, op.type))
            if self.check_kwargs:
                for k in _REQUIRED_KWARGS.get(base, ()):
                    if k not in op.kwargs:
                        v.append(Violation(
                            "missing-kwargs",
                            f"op records no {k!r} kwarg; rewrite patterns gate "
                            "on it and would mis-match this op", i, op.type))

            in_avals, inputs_ok = [], True
            for spec in op.arg_spec:
                if spec[0] != "var":
                    continue
                vid = spec[1]
                var = program._var_by_vid.get(vid)
                if var is None:
                    v.append(Violation(
                        "unregistered-vid",
                        f"input vid {vid} has no Variable", i, op.type))
                    inputs_ok = False
                    continue
                if vid not in defined:
                    v.append(Violation(
                        "dangling-vid",
                        f"input vid {vid} ('{var.name}') is read before any "
                        "feed/state/op defines it", i, op.type))
                    inputs_ok = False
                in_avals.append(jax.ShapeDtypeStruct(var._value.shape,
                                                     var._value.dtype))

            out_vars = []
            for vid in op.out_vids:
                var = program._var_by_vid.get(vid)
                if var is None:
                    v.append(Violation(
                        "unregistered-vid",
                        f"output vid {vid} has no Variable", i, op.type))
                out_vars.append(var)

            if (self.abstract_eval and inputs_ok
                    and all(o is not None for o in out_vars)):
                v += self._abstract_eval_op(i, op, in_avals, out_vars)

            defined.update(op.out_vids)

        for tgt, src in program.writes.items():
            if tgt not in program._var_by_vid:
                v.append(Violation("bad-write",
                                   f"write target vid {tgt} has no Variable"))
            if src not in defined:
                v.append(Violation("bad-write",
                                   f"write source vid {src} is never defined"))
        for vid in fetch_vids:
            if vid not in defined:
                v.append(Violation(
                    "dangling-fetch",
                    f"fetch vid {vid} is never defined by a feed, state var "
                    "or op (a rewrite consumed its producer?)"))
        return v

    def _abstract_eval_op(self, i, op, in_avals, out_vars):
        try:
            out = jax.eval_shape(op.fn, *in_avals)
            flat = jax.tree_util.tree_leaves(out)
        except Exception as e:  # collective outside mesh, host-only fn, ...
            _COUNTERS["abstract_eval_skips"] += 1
            if self.strict_abstract:
                return [Violation("abstract-eval-error",
                                  f"op fn failed abstract eval: {e!r}",
                                  i, op.type)]
            return []
        if len(flat) != len(op.out_vids):
            return [Violation(
                "arity-mismatch",
                f"op fn produces {len(flat)} outputs but records "
                f"{len(op.out_vids)} out vids", i, op.type)]
        v = []
        for var, o in zip(out_vars, flat):
            want = (tuple(var._value.shape), jnp.dtype(var._value.dtype))
            got = (tuple(o.shape), jnp.dtype(o.dtype))
            if want[0] != got[0]:
                v.append(Violation(
                    "shape-mismatch",
                    f"'{var.name}' recorded as {want[0]} but op fn produces "
                    f"{got[0]}", i, op.type))
            elif want[1] != got[1]:
                v.append(Violation(
                    "dtype-mismatch",
                    f"'{var.name}' recorded as {want[1]} but op fn produces "
                    f"{got[1]}", i, op.type))
        return v

    # --------------------------------------------------- live-producer check
    def _check_live_producers(self, program, fetch_vids):
        """Replicate the executor's last-writer-wins fetch-frontier prune,
        then require that no vid in the kept set is redefined while its
        previous definition went unread — i.e. at most one live producer
        per vid reaches the frontier (the PR-2 invariant: share_loss
        re-binds the loss vid precisely so the original forward chain can
        drop; keeping both means the compiled step traces the forward
        twice, and a duplicated collective-carrying chain can deadlock)."""
        ops = program.global_block().ops
        live = set(fetch_vids) | set(program.writes) | set(program.writes.values())
        kept = []
        for op in reversed(ops):
            if any(vid in live for vid in op.out_vids):
                kept.append(op)
                live.difference_update(op.out_vids)
                live.update(op.input_vids())
        kept.reverse()

        v = []
        unread: dict[int, bool] = {}  # vid -> latest def not yet read
        for i, op in enumerate(kept):
            for vid in set(op.input_vids()):
                unread[vid] = False
            for vid in op.out_vids:
                if unread.get(vid, False):
                    var = program._var_by_vid.get(vid)
                    name = var.name if var is not None else vid
                    v.append(Violation(
                        "duplicate-producer",
                        f"two live producers of '{name}' (vid {vid}) reach "
                        "the fetch frontier: the earlier definition is never "
                        "read before this op redefines it (superseded chain "
                        "not retired)", i, op.type))
                unread[vid] = True
        return v


def verify_program(program, fetch_vids=(), raise_on_error=True, **kwargs):
    """One-shot convenience: ProgramVerifier(**kwargs).verify(...)."""
    return ProgramVerifier(**kwargs).verify(
        program, fetch_vids=fetch_vids, raise_on_error=raise_on_error)


# ---------------------------------------------------------------------------
# pass-differential checker


def _synthetic_feeds(feed_vars, seed):
    rng = np.random.default_rng(seed)
    feeds = []
    for var in feed_vars:
        shape = tuple(var._value.shape)
        dt = np.dtype(var._value.dtype)
        if np.issubdtype(dt, np.floating):
            feeds.append(jnp.asarray(rng.standard_normal(shape), dt))
        elif dt == np.bool_:
            feeds.append(jnp.asarray(rng.integers(0, 2, shape).astype(bool)))
        else:
            # small non-negative ints: valid for ids/indices in tiny vocab
            feeds.append(jnp.asarray(rng.integers(0, 2, shape), dt))
    return feeds


def _replay(program, fetch_vids, feed_vals):
    """Execute the program eagerly (no jit, no capture) on feed_vals with
    param_inits as state; restores the RNG state it consumed."""
    from paddle_tpu._core import random as _rnd

    from .program import _st as _static_state

    run_fn, feed_vids, state_vids = program.as_function(list(fetch_vids))
    state_vals = [program.param_inits[vid] for vid in state_vids]
    coerced = [jnp.asarray(v, program._var_by_vid[vid]._value.dtype)
               for vid, v in zip(feed_vids, feed_vals)]
    rng_state = _rnd.get_rng_state()
    prev = _static_state.main_program
    _static_state.main_program = None
    try:
        fetches, _ = run_fn(coerced, state_vals)
    finally:
        _static_state.main_program = prev
        _rnd.set_rng_state(rng_state)
    return [np.asarray(f) for f in fetches]


def differential_check(reference, candidate, fetch_vids, feeds=None,
                       rtol=2e-3, atol=2e-3, seed=0, raise_on_error=True):
    """Replay `reference` and `candidate` on the same feed from identical
    RNG state and compare the fetch set.  Returns the list of mismatch
    Violations (empty when the programs agree); raises DifferentialError
    when raise_on_error and they do not.

    feeds: positional feed values matching reference.feed_vars (the live
    executor feed, when available) — synthesized from the feed avals
    otherwise.  Default tolerance matches the Pallas-kernel parity bar of
    tests/test_pallas_fusion.py (interpret-mode kernels on CPU)."""
    _COUNTERS["differential_checks"] += 1
    fetch_vids = list(fetch_vids)
    if feeds is None:
        feeds = _synthetic_feeds(reference.feed_vars, seed)
    else:
        feeds = [v._value if hasattr(v, "_value") else v for v in feeds]

    violations = []
    try:
        ref_out = _replay(reference, fetch_vids, feeds)
    except Exception:
        # the REFERENCE cannot execute eagerly (collective outside its
        # mesh, host-only op, ...): there is no oracle to compare against —
        # counted skip, mirroring the verifier's abstract_eval_skips
        _COUNTERS["differential_skips"] += 1
        return []
    try:
        cand_out = _replay(candidate, fetch_vids, feeds)
    except Exception as e:
        violations.append(Violation(
            "differential-crash",
            f"rewritten program failed to execute on the differential "
            f"feed: {e!r}"))
        cand_out = None
    if cand_out is not None:
        for vid, a, b in zip(fetch_vids, ref_out, cand_out):
            var = reference._var_by_vid.get(vid)
            name = var.name if var is not None else vid
            if a.shape != b.shape or a.dtype != b.dtype:
                violations.append(Violation(
                    "differential-mismatch",
                    f"fetch '{name}': aval changed "
                    f"{a.shape}/{a.dtype} -> {b.shape}/{b.dtype}"))
                continue
            if not np.issubdtype(a.dtype, np.inexact):
                if not np.array_equal(a, b):
                    violations.append(Violation(
                        "differential-mismatch",
                        f"fetch '{name}': integer fetch values differ"))
                continue
            if not np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True):
                err = float(np.max(np.abs(a.astype(np.float64)
                                          - b.astype(np.float64))))
                violations.append(Violation(
                    "differential-mismatch",
                    f"fetch '{name}': numerics differ (max abs err "
                    f"{err:.3e} at rtol={rtol} atol={atol}) — the rewrite "
                    "changed the computation"))
    if violations:
        _COUNTERS["differential_failures"] += 1
        if raise_on_error:
            raise DifferentialError(
                violations, header="Pass-differential check failed")
    return violations


# ---------------------------------------------------------------------------
# program tracking (tools/lint_ir.py + the tier-1 property test)


@contextlib.contextmanager
def track_programs():
    """Collect every Program constructed while active (creation + clone),
    so a sweep can verify everything a test run traced."""
    from . import program as _prog_mod

    seen: list = []
    _prog_mod._creation_hooks.append(seen.append)
    try:
        yield seen
    finally:
        _prog_mod._creation_hooks.remove(seen.append)
        # drop sacrificial discovery programs (control_flow capture replay):
        # they record ops against the OUTER program's vids and are discarded
        seen[:] = [p for p in seen if not getattr(p, "_discovery", False)]
