"""Mesh lint: static SPMD sharding/collective/donation analyzer.

Reference role: the reference's auto_parallel layer validates SPMD rules
*before* execution (paddle/phi/infermeta/spmd_rules, the semi-auto
InferSpmd -> Reshard pipeline) — a mis-axised collective or an impossible
placement is a compile-time error there, never a hang.  Our distributed
tier had no equivalent: a psum over a dead mesh axis, a ppermute whose
permutation double-writes a rank, or a collective reachable only under a
data-dependent predicate surfaces at runtime — worst case as the
in-process 8-device XLA:CPU SIGSEGV class that keeps pushing real
coverage into `slow` (ROADMAP item 5).  This module is the PR-4
ProgramVerifier philosophy (docs/VERIFIER.md: mechanical checks + seeded
violation fixtures catch whole bug classes) extended from single-device
Program semantics to the mesh.

Everything here is ABSTRACT: computations are interpreted via
``jax.make_jaxpr`` / ``jax.eval_shape`` — no device collective is ever
launched, so the analysis itself cannot trip the crash class it hunts.

Four check families (docs/MESH_LINT.md):

1. **Sharding propagation** — every placement/PartitionSpec names a live
   mesh axis, shard dims exist on the tensor, no mesh axis shards two
   dims, sharded dims divide by the axis size; large tensors that end up
   fully replicated on a multi-device mesh are flagged with their
   per-device byte cost (the silent-replication blowup).
2. **Collective congruence** — every collective primitive reachable from
   an entry point (psum/ppermute/all_gather/all_to_all/..., including
   shard_map-internal forms) names axes that exist with consistent sizes,
   ppermute permutations are valid partial permutations (jax does NOT
   check this at trace time — a duplicate destination deadlocks or
   corrupts at run time), axis_index_groups partition the axis uniformly,
   and collectives reachable only under ``lax.cond`` branches or
   ``lax.while_loop`` bodies are flagged as the data-dependent
   deadlock/SIGSEGV class.
3. **Donation / aliasing** — fetching the stale value of a donated,
   in-place-updated state buffer (Program fetch of a `writes` target) and
   double-donating one buffer (the same jax.Array appearing twice in a
   donated state/pool list) are reported as use-after-donation.
4. **Per-device memory estimate** — sharding-divided HBM bytes per device
   for params + optimizer state + KV pools (+ QuantPool scales), linted
   against ``FLAGS_mesh_lint_hbm_budget_gb``.  Persistent state only:
   activation peaks are XLA's to schedule and are deliberately out of
   scope (an abstract liveness bound would be wrong under GSPMD
   repartitioning).

Entry points: ``lint_program`` (Program IR, wired into the Executor and
ProgramPassManager), ``lint_train_step`` (TrainStep / ShardedTrainStep),
``lint_engine`` (serving.GenerationEngine) — all gated in-tree on
``FLAGS_verify_sharding`` (same contract as ``FLAGS_verify_programs``:
pass-boundary checks, named failing site, counters via
``paddle_tpu.profiler.mesh_lint_stats()`` + a Profiler.summary footer).
``tools/lint_mesh.py`` sweeps both battery fixtures and pytest runs.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax

__all__ = [
    "MeshViolation",
    "MeshLintError",
    "MeshLinter",
    "lint_program",
    "lint_train_step",
    "lint_engine",
    "lint_decode_chain",
    "mesh_lint_stats",
    "reset_mesh_lint_stats",
]


_COUNTERS = {
    "entries_linted": 0,      # programs + train steps + engines linted
    "entries_failed": 0,
    "violations": 0,
    "collectives_checked": 0,
    "constraints_checked": 0,  # sharding_constraint placements validated
    "placements_checked": 0,   # named tensors through the placement tier
    "donation_checks": 0,
    "memory_estimates": 0,
    "trace_skips": 0,          # op fns that could not be abstractly traced
}


def mesh_lint_stats(reset: bool = False) -> dict:
    out = dict(_COUNTERS)
    if reset:
        reset_mesh_lint_stats()
    return out


def reset_mesh_lint_stats():
    for k in _COUNTERS:
        _COUNTERS[k] = 0


@dataclass
class MeshViolation:
    code: str        # unknown-axis | axis-size-mismatch | bad-permutation |
                     # bad-groups | conditional-collective | bad-shard-dim |
                     # duplicate-axis | indivisible-shard | replicated-giant |
                     # use-after-donation | over-budget
    message: str
    site: str = ""   # entry point / op / tensor the violation anchors to

    def __str__(self):
        loc = f" [{self.site}]" if self.site else ""
        return f"{self.code}{loc}: {self.message}"


class MeshLintError(RuntimeError):
    def __init__(self, violations, header="Mesh lint failed"):
        self.violations = list(violations)
        lines = [f"{header} ({len(self.violations)} violation(s)):"]
        lines += [f"  - {v}" for v in self.violations]
        super().__init__("\n".join(lines))


# Collective primitives whose participation must be congruent across the
# mesh.  shard_map rewrites psum->psum2 and inserts pbroadcast as a
# replication-rule marker — pbroadcast/axis_index are NOT collectives (no
# cross-device rendezvous), so they are deliberately absent: flagging them
# under a cond would false-positive every data-dependent branch.
_COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "pmax", "pmin", "pmean", "ppermute", "pshuffle",
    "all_gather", "all_gather_invariant", "all_to_all", "reduce_scatter",
    "psum_scatter", "pgather",
})

# Sub-jaxprs under these eqn param keys execute under a DATA-DEPENDENT
# predicate: a collective inside is only joined by devices whose predicate
# agrees — the deadlock/SIGSEGV class.  (lax.scan has a static trip count
# and pjit/remat are unconditional, so their bodies stay at the same
# conditional depth.)
_CONDITIONAL_PARAM_KEYS = {"branches", "cond_jaxpr", "body_jaxpr"}


def _axis_sizes(mesh) -> dict:
    """name -> size for a ProcessMesh / jax Mesh / {name: size} / None."""
    if mesh is None:
        return {}
    if isinstance(mesh, dict):
        return {str(k): int(v) for k, v in mesh.items()}
    shape = getattr(mesh, "shape", None)
    names = getattr(mesh, "dim_names", None)
    if names is not None and shape is not None:  # ProcessMesh
        return dict(zip(names, shape))
    jm = getattr(mesh, "axis_names", None)
    if jm is not None:  # jax.sharding.Mesh
        return {n: int(mesh.shape[n]) for n in mesh.axis_names}
    raise TypeError(f"cannot read mesh axes from {type(mesh)}")


def _default_mesh():
    from paddle_tpu.distributed.auto_parallel.process_mesh import get_mesh

    return get_mesh()


def _spec_entries(spec):
    """Flatten a PartitionSpec into per-dim tuples of axis names."""
    out = []
    for e in tuple(spec):
        if e is None:
            out.append(())
        elif isinstance(e, (tuple, list)):
            out.append(tuple(e))
        else:
            out.append((e,))
    return out


def _nbytes(aval) -> int:
    return int(np.prod(aval.shape, dtype=np.int64)) * np.dtype(aval.dtype).itemsize


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} GiB"


class MeshLinter:
    """Static analyzer over abstract sharded computations.

    mesh: ProcessMesh / jax Mesh / {axis: size} — defaults to the session
    mesh (paddle_tpu.distributed.get_mesh()).  Axis-existence checks are
    skipped when no mesh is known ANYWHERE (no session mesh, no shard_map
    binding in scope); shard_map-bound axes always validate their own
    interiors.  replicated_bytes / budget_bytes default to the
    FLAGS_mesh_lint_replicated_mb / FLAGS_mesh_lint_hbm_budget_gb knobs.
    """

    def __init__(self, mesh=None, replicated_bytes=None, budget_bytes=None):
        from paddle_tpu._core import flags

        self.mesh = mesh if mesh is not None else _default_mesh()
        self.axes = _axis_sizes(self.mesh)
        if replicated_bytes is None:
            replicated_bytes = int(
                float(flags.flag("FLAGS_mesh_lint_replicated_mb")) * 2 ** 20)
        if budget_bytes is None:
            gb = float(flags.flag("FLAGS_mesh_lint_hbm_budget_gb"))
            budget_bytes = int(gb * 2 ** 30) if gb > 0 else 0
        self.replicated_bytes = replicated_bytes
        self.budget_bytes = budget_bytes  # 0 = budget check off

    # ------------------------------------------------- family 2: collectives
    def lint_callable(self, fn, *in_avals, site=""):
        """Abstractly trace `fn` (jax.make_jaxpr under the mesh's axis env)
        and walk the jaxpr for collective congruence.  Never executes.

        The global RNG state is restored after the trace: an op fn that
        draws keys (dropout, sampling) must not shift the live training
        stream just because the lint looked at it (same contract as the
        verifier's differential _replay).  The trace also SUSPENDS static
        capture — linting a funnel-routed callable while a program_guard
        is active must not record the traced ops (or their tracers) into
        the program under capture (same rule as Program.record's op
        bodies and the pipeline's shape probes)."""
        from paddle_tpu._core import random as _rnd

        from .program import suspend_capture

        axis_env = [(n, s) for n, s in self.axes.items()]
        rng_state = _rnd.get_rng_state()
        try:
            with suspend_capture():
                closed = jax.make_jaxpr(fn, axis_env=axis_env)(*in_avals)
        except NameError as e:
            # make_jaxpr raises 'unbound axis name: X' for a collective
            # whose axis neither the mesh nor any shard_map binds — that
            # failure IS the mismatched-collective-axis violation.
            _COUNTERS["collectives_checked"] += 1
            return [MeshViolation(
                "unknown-axis",
                f"collective references an axis no mesh binds: {e} "
                f"(live mesh axes: {sorted(self.axes) or 'none'})", site)]
        except ValueError as e:
            if "axis_index_groups" in str(e):
                # jax validates the partition property itself at trace
                # time; surface it as the named violation instead of a
                # silent skip
                _COUNTERS["collectives_checked"] += 1
                return [MeshViolation(
                    "bad-groups",
                    f"collective axis_index_groups rejected at abstract "
                    f"trace: {e}", site)]
            _COUNTERS["trace_skips"] += 1
            return []
        except Exception:
            # host-only op / data-dependent capture: nothing to walk
            _COUNTERS["trace_skips"] += 1
            return []
        finally:
            _rnd.set_rng_state(rng_state)
        return self._walk_jaxpr(closed.jaxpr, dict(self.axes), site, 0)

    def _walk_jaxpr(self, jaxpr, bound, site, cond_depth):
        v = []
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim == "shard_map":
                v += self._check_shard_map_mesh(eqn, site)
                inner_bound = dict(bound)
                inner_bound.update(_axis_sizes(eqn.params["mesh"]))
                inner = eqn.params["jaxpr"]
                inner = getattr(inner, "jaxpr", inner)
                v += self._walk_jaxpr(inner, inner_bound, site, cond_depth)
                continue
            if prim in _COLLECTIVE_PRIMS:
                v += self._check_collective(eqn, bound, site, cond_depth)
            elif prim == "sharding_constraint":
                v += self._check_constraint(eqn, site)
            # generic recursion into sub-jaxprs (cond branches, while
            # cond/body, scan/pjit/remat bodies, custom_* rules)
            for key, val in eqn.params.items():
                depth = cond_depth + (1 if key in _CONDITIONAL_PARAM_KEYS else 0)
                for sub in (val if isinstance(val, (list, tuple)) else (val,)):
                    sub_jaxpr = getattr(sub, "jaxpr", sub)
                    if hasattr(sub_jaxpr, "eqns"):
                        v += self._walk_jaxpr(sub_jaxpr, bound,
                                              f"{site}/{prim}" if site else prim,
                                              depth)
        return v

    def _eqn_axes(self, eqn):
        names = eqn.params.get("axes", eqn.params.get("axis_name", ()))
        if not isinstance(names, (tuple, list, frozenset, set)):
            names = (names,)
        return [n for n in names if isinstance(n, str)]

    def _check_collective(self, eqn, bound, site, cond_depth):
        v = []
        prim = eqn.primitive.name
        _COUNTERS["collectives_checked"] += 1
        axes = self._eqn_axes(eqn)
        for name in axes:
            if name not in bound:
                v.append(MeshViolation(
                    "unknown-axis",
                    f"{prim} over axis {name!r}, but the live mesh axes are "
                    f"{sorted(bound) or 'none'} — the collective would never "
                    "rendezvous", site))
        if cond_depth > 0:
            v.append(MeshViolation(
                "conditional-collective",
                f"{prim} over {axes or '?'} is reachable only under a "
                "data-dependent predicate (lax.cond branch / while body): "
                "devices whose predicate disagrees skip the rendezvous — "
                "the distributed deadlock/SIGSEGV class.  Hoist the "
                "collective out of the branch or make the predicate "
                "mesh-uniform", site))
        if prim == "ppermute" and axes and axes[0] in bound:
            v += self._check_perm(eqn.params.get("perm", ()),
                                  axes[0], bound[axes[0]], site)
        groups = eqn.params.get("axis_index_groups")
        if groups and axes and axes[0] in bound:
            v += self._check_groups(groups, axes[0], bound[axes[0]], prim, site)
        return v

    @staticmethod
    def _check_perm(perm, axis, size, site):
        """jax traces any perm; a duplicate src/dst or out-of-range index
        is a silent runtime corruption/deadlock.  Require a valid partial
        permutation: unique sources, unique destinations, all in range."""
        v = []
        srcs = [p[0] for p in perm]
        dsts = [p[1] for p in perm]
        bad = [p for p in perm
               if not (0 <= p[0] < size and 0 <= p[1] < size)]
        if bad:
            v.append(MeshViolation(
                "bad-permutation",
                f"ppermute over {axis!r} (size {size}) has out-of-range "
                f"pairs {bad} — ranks beyond the axis never participate",
                site))
        if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
            dup_s = sorted({s for s in srcs if srcs.count(s) > 1})
            dup_d = sorted({d for d in dsts if dsts.count(d) > 1})
            v.append(MeshViolation(
                "bad-permutation",
                f"ppermute over {axis!r} is not a partial permutation "
                f"(duplicate sources {dup_s}, duplicate destinations "
                f"{dup_d}) — participation is non-uniform and the result "
                "rank-dependent", site))
        return v

    @staticmethod
    def _check_groups(groups, axis, size, prim, site):
        v = []
        flat = [i for g in groups for i in g]
        sizes = {len(g) for g in groups}
        if len(sizes) > 1:
            v.append(MeshViolation(
                "bad-groups",
                f"{prim} axis_index_groups over {axis!r} have non-uniform "
                f"sizes {sorted(sizes)} — participation differs per group",
                site))
        if sorted(flat) != list(range(size)):
            v.append(MeshViolation(
                "bad-groups",
                f"{prim} axis_index_groups over {axis!r} do not partition "
                f"range({size}): {groups} — some ranks never rendezvous",
                site))
        return v

    def _check_shard_map_mesh(self, eqn, site):
        """A shard_map binds its own mesh; axis names that collide with the
        session mesh at a DIFFERENT size mean the op was built for another
        topology (participation would be non-uniform)."""
        v = []
        for name, size in _axis_sizes(eqn.params["mesh"]).items():
            if self.axes and name in self.axes and self.axes[name] != size:
                v.append(MeshViolation(
                    "axis-size-mismatch",
                    f"shard_map binds axis {name!r} with size {size}, but "
                    f"the session mesh has {name!r} size "
                    f"{self.axes[name]} — the op was built for a different "
                    "topology", site))
            elif self.axes and name not in self.axes:
                v.append(MeshViolation(
                    "unknown-axis",
                    f"shard_map binds axis {name!r} which the session mesh "
                    f"does not have (mesh axes: {sorted(self.axes)}) — "
                    "collectives over it will not line up with the "
                    "session topology", site))
        return v

    def _check_constraint(self, eqn, site):
        _COUNTERS["constraints_checked"] += 1
        sharding = eqn.params.get("sharding")
        spec = getattr(sharding, "spec", None)
        if spec is None or not self.axes:
            return []
        v = []
        for names in _spec_entries(spec):
            for name in names:
                if name not in self.axes:
                    v.append(MeshViolation(
                        "unknown-axis",
                        f"sharding_constraint places over axis {name!r}, "
                        f"not a live mesh axis ({sorted(self.axes)})", site))
        return v

    # -------------------------------------------- family 1: placements
    def lint_placements(self, named, site=""):
        """`named`: iterable of (name, aval-or-array, placement) where
        placement is a NamedSharding, PartitionSpec, placements list
        (Shard/Replicate/Partial), or None (treated as replicated)."""
        from paddle_tpu.distributed.auto_parallel.placement import (
            Placement, Shard)

        v = []
        mesh_size = int(np.prod(list(self.axes.values()))) if self.axes else 1
        for name, aval, placement in named:
            _COUNTERS["placements_checked"] += 1
            here = f"{site}:{name}" if site else name
            ndim = len(aval.shape)
            entries = None  # per-tensor-dim tuple of mesh axis names
            if placement is not None and isinstance(placement, (list, tuple)) \
                    and placement and isinstance(placement[0], Placement):
                # reference placements: one entry per MESH dim
                entries = [()] * ndim
                for mesh_dim, p in enumerate(placement):
                    if mesh_dim >= len(self.axes):
                        v.append(MeshViolation(
                            "bad-shard-dim",
                            f"{len(placement)} placements for a "
                            f"{len(self.axes)}-dim mesh", here))
                        continue
                    if isinstance(p, Shard):
                        axis_name = list(self.axes)[mesh_dim]
                        if p.dim >= ndim or p.dim < -ndim:
                            v.append(MeshViolation(
                                "bad-shard-dim",
                                f"Shard(dim={p.dim}) on a rank-{ndim} "
                                f"tensor of shape {tuple(aval.shape)}", here))
                        else:
                            entries[p.dim % ndim] += (axis_name,)
            else:
                spec = getattr(placement, "spec", placement)
                if spec is not None and not hasattr(spec, "__iter__"):
                    spec = None
                if spec is not None:
                    ents = _spec_entries(spec)
                    if len(ents) > ndim:
                        v.append(MeshViolation(
                            "bad-shard-dim",
                            f"PartitionSpec{tuple(spec)} has "
                            f"{len(ents)} entries for a rank-{ndim} tensor "
                            f"of shape {tuple(aval.shape)}", here))
                        ents = ents[:ndim]
                    entries = ents + [()] * (ndim - len(ents))
                else:
                    entries = [()] * ndim

            used: dict = {}
            for dim, names in enumerate(entries):
                for axis_name in names:
                    if self.axes and axis_name not in self.axes:
                        v.append(MeshViolation(
                            "unknown-axis",
                            f"placed over axis {axis_name!r}, not a live "
                            f"mesh axis ({sorted(self.axes)})", here))
                        continue
                    if axis_name in used:
                        v.append(MeshViolation(
                            "duplicate-axis",
                            f"mesh axis {axis_name!r} shards both dim "
                            f"{used[axis_name]} and dim {dim}", here))
                    used[axis_name] = dim
                    axsz = self.axes.get(axis_name, 1)
                    if axsz > 1 and aval.shape[dim] % axsz != 0:
                        v.append(MeshViolation(
                            "indivisible-shard",
                            f"dim {dim} (size {aval.shape[dim]}) is not "
                            f"divisible by axis {axis_name!r} (size "
                            f"{axsz}) — GSPMD pads and the pad is computed "
                            "and re-synced on every use", here))
            if (mesh_size > 1 and not used
                    and _nbytes(aval) >= self.replicated_bytes > 0):
                nb = _nbytes(aval)
                v.append(MeshViolation(
                    "replicated-giant",
                    f"{_fmt_bytes(nb)} tensor of shape "
                    f"{tuple(aval.shape)} is fully replicated on a "
                    f"{mesh_size}-device mesh — {_fmt_bytes(nb)} of HBM "
                    f"per device, {_fmt_bytes(nb * mesh_size)} total; "
                    "shard it or raise FLAGS_mesh_lint_replicated_mb if "
                    "intentional", here))
        return v

    # -------------------------------------- family 4: per-device memory
    def shard_factor(self, aval, placement) -> int:
        """How many ways `placement` divides the tensor across the mesh."""
        factor = 1
        spec = getattr(placement, "spec", placement)
        if spec is None or not hasattr(spec, "__iter__"):
            return 1
        for names in _spec_entries(spec):
            for name in names:
                factor *= self.axes.get(name, 1)
        return max(1, factor)

    def estimate_device_bytes(self, groups, site=""):
        """groups: {group_name: [(name, aval, placement), ...]} — returns
        (violations, {group: per-device bytes, "total": ...}).  The budget
        check fires on the total when FLAGS_mesh_lint_hbm_budget_gb > 0."""
        _COUNTERS["memory_estimates"] += 1
        est = {}
        for group, named in groups.items():
            total = 0
            for _name, aval, placement in named:
                total += _nbytes(aval) // self.shard_factor(aval, placement)
            est[group] = total
        est["total"] = sum(est.values())
        v = []
        if self.budget_bytes and est["total"] > self.budget_bytes:
            parts = ", ".join(f"{g}={_fmt_bytes(b)}" for g, b in est.items()
                              if g != "total")
            v.append(MeshViolation(
                "over-budget",
                f"estimated {_fmt_bytes(est['total'])} of HBM per device "
                f"({parts}) exceeds the "
                f"FLAGS_mesh_lint_hbm_budget_gb budget of "
                f"{_fmt_bytes(self.budget_bytes)}", site))
        return v, est

    # ------------------------------------------- family 3 + program IR
    def lint_program(self, program, fetch_vids=()):
        """Collective congruence per recorded op + use-after-donation on
        the fetch set (the Executor donates state buffers whenever the
        program carries writes; fetching a write target returns the
        donated input's stale alias)."""
        v = []
        _COUNTERS["donation_checks"] += 1
        redefined = {vid for op in program.global_block().ops
                     for vid in op.out_vids}
        if program.writes:
            for vid in fetch_vids:
                if (vid in program.writes and vid in program.param_inits
                        and vid not in redefined):
                    var = program._var_by_vid.get(vid)
                    name = var.name if var is not None else vid
                    v.append(MeshViolation(
                        "use-after-donation",
                        f"fetch of state var '{name}' (vid {vid}) returns "
                        "the PRE-update buffer of a donated, in-place-"
                        "written state input — the alias is dead the "
                        "moment the dispatch commits.  Fetch the updated "
                        f"value (vid {program.writes[vid]}) instead", name))
        for i, op in enumerate(program.global_block().ops):
            in_avals = []
            ok = True
            for spec in op.arg_spec:
                if spec[0] != "var":
                    continue
                var = program._var_by_vid.get(spec[1])
                if var is None:
                    ok = False  # structural breakage: ProgramVerifier's job
                    break
                in_avals.append(jax.ShapeDtypeStruct(var._value.shape,
                                                     var._value.dtype))
            if ok:
                v += self.lint_callable(op.fn, *in_avals,
                                        site=f"op#{i} {op.type}")
        return v

    # -------------------------------------------------- entry: train step
    def _named_state(self, step):
        """(name, aval, placement) triples for a TrainStep's state, using
        the SAME sharding resolution the step will apply — the lint is
        predictive, not post-hoc."""
        names = {}
        model_sd = step.model.state_dict()
        for n, t in model_sd.items():
            names[id(t)] = n
        out = []
        sharded = hasattr(step, "_param_sharding")
        for t in (step._state or []):
            name = names.get(id(t), getattr(t, "name", "") or "opt_state")
            val = t._value
            if sharded:
                sh = getattr(val, "sharding", None)
                from jax.sharding import NamedSharding

                if not isinstance(sh, NamedSharding):
                    sh = step._param_sharding(t) if id(t) in names else None
                    if sh is None and val.ndim > 0:
                        # optimizer accumulator: resolve like _place_state
                        sh = step._acc_sharding(
                            val, step._param_sharding(t))
                out.append((name, val, sh))
            else:
                out.append((name, val, getattr(val, "sharding", None)
                            if hasattr(val, "sharding") else None))
        return out

    def lint_train_step(self, step, *batch):
        """Families 1-4 over a (Sharded)TrainStep: state placements, the
        step jaxpr's collectives/constraints, the donation contract, and
        the per-device memory estimate.  `batch`: example values or
        ShapeDtypeStructs (nothing is executed)."""
        from paddle_tpu._core import random as rng_mod  # noqa: F401
        from paddle_tpu._core.tensor import Tensor

        step._ensure_built()
        v = []
        named = self._named_state(step)
        v += self.lint_placements(named, site="train_step.state")

        # donation contract: state buffers are donated (donate_argnums=0);
        # one buffer donated twice is UB, and a batch leaf aliasing a
        # donated buffer is read-after-donation by construction
        _COUNTERS["donation_checks"] += 1
        seen: dict = {}
        for name, val, _sh in named:
            key = id(val)
            if key in seen and seen[key] != name:
                v.append(MeshViolation(
                    "use-after-donation",
                    f"state entries '{seen[key]}' and '{name}' share ONE "
                    "buffer — the compiled step donates it twice "
                    "(undefined behavior; alias the Tensors, not the "
                    "buffer)", f"train_step.state:{name}"))
            seen[key] = name
        batch_leaves = jax.tree_util.tree_leaves(
            [b._value if isinstance(b, Tensor) else b for b in batch])
        for i, b in enumerate(batch_leaves):
            if id(b) in seen:
                v.append(MeshViolation(
                    "use-after-donation",
                    f"batch leaf #{i} aliases donated state buffer "
                    f"'{seen[id(b)]}' — the batch input is dead after the "
                    "dispatch donates it", "train_step.batch"))

        # collective congruence of the whole step jaxpr
        def aval(x):
            val = x._value if isinstance(x, Tensor) else x
            if isinstance(val, jax.ShapeDtypeStruct):
                return val
            import jax.numpy as jnp

            val = jnp.asarray(val)
            return jax.ShapeDtypeStruct(val.shape, val.dtype)

        state_avals = [jax.ShapeDtypeStruct(val.shape, val.dtype)
                       for _n, val, _s in named]
        batch_avals = jax.tree_util.tree_map(
            aval, batch, is_leaf=lambda x: isinstance(x, Tensor))
        key_aval = jax.eval_shape(
            lambda: jax.random.fold_in(jax.random.key(0), 0))
        v += self.lint_callable(step._compiled, state_avals,
                                list(batch_avals), key_aval,
                                site="train_step.step_fn")

        # per-device memory: params vs optimizer moments
        model_names = set(step.model.state_dict())
        params = [e for e in named if e[0] in model_names]
        opt = [e for e in named if e[0] not in model_names]
        groups = {"params": params, "optimizer": opt}
        mv, est = self.estimate_device_bytes(groups, site="train_step")
        v += mv
        return v, est

    # ----------------------------------------------------- entry: engine
    def lint_engine(self, engine):
        """Families 1/3/4 over a serving.GenerationEngine: model state and
        KV-pool placements, pool donation aliasing, per-device pool bytes.
        Nothing is dispatched."""
        v = []
        named = []
        for n, t in engine.model.state_dict().items():
            val = t._value
            named.append((n, val, getattr(val, "sharding", None)))
        from paddle_tpu.ops.paged_attention import pool_parts

        d_sharding = getattr(engine, "_d_pool_sharding", None)
        pool_lists = [
            ("k", engine._kpools, engine._pool_sharding),
            ("v", engine._vpools, engine._pool_sharding),
            ("draft_k", getattr(engine, "_d_kpools", None) or [], d_sharding),
            ("draft_v", getattr(engine, "_d_vpools", None) or [], d_sharding),
        ]
        pool_named, scale_named = [], []
        for tag, pools, sharding in pool_lists:
            for i, pool in enumerate(pools):
                for part, arr in pool_parts(pool):
                    dest = pool_named if part == "payload" else scale_named
                    dest.append((f"{tag}pool[{i}].{part}", arr, sharding))
        # multi-tenant LoRA: the adapter pack's slot-stacked A/B + scaling
        # arrays are engine state too — placements and per-device bytes go
        # through the same path as params (nn/lora.py AdapterPack.parts)
        pack = getattr(engine, "_pack", None)
        pack_named = []
        if pack is not None:
            pack_named = [(name, arr, getattr(arr, "sharding", None))
                          for name, arr in pack.parts()]

        v += self.lint_placements(named, site="engine.params")
        v += self.lint_placements(pool_named, site="engine.pools")
        if pack_named:
            v += self.lint_placements(pack_named, site="engine.adapter_pack")

        _COUNTERS["donation_checks"] += 1
        seen: dict = {}
        for name, data, _sh in pool_named:
            if id(data) in seen:
                v.append(MeshViolation(
                    "use-after-donation",
                    f"pools '{seen[id(data)]}' and '{name}' share one "
                    "buffer — the decode step donates both pool lists "
                    "(donate_argnums=(1, 2)); a shared buffer is donated "
                    "twice per dispatch", f"engine.pools:{name}"))
            seen[id(data)] = name

        groups = {"params": named, "kv_pools": pool_named}
        if scale_named:  # QuantPool scales ride alongside the int8 payload
            groups["kv_scales"] = scale_named
        if pack_named:  # adapter bytes count against the HBM budget too
            groups["adapter_pack"] = pack_named
        mv, est = self.estimate_device_bytes(groups, site="engine")
        v += mv
        return v, est


# --------------------------------------------------------------------------
# one-shot conveniences (the Executor / TrainStep / engine wiring points)


def _finish(violations, header, raise_on_error):
    _COUNTERS["entries_linted"] += 1
    if violations:
        _COUNTERS["entries_failed"] += 1
        _COUNTERS["violations"] += len(violations)
        if raise_on_error:
            raise MeshLintError(violations, header=header)
    return violations


def lint_program(program, fetch_vids=(), mesh=None, raise_on_error=False,
                 **kwargs):
    linter = MeshLinter(mesh=mesh, **kwargs)
    return _finish(linter.lint_program(program, fetch_vids),
                   "Mesh lint failed (Program)", raise_on_error)


def lint_train_step(step, *batch, mesh=None, raise_on_error=False, **kwargs):
    # the step's OWN mesh is the authority: a plain TrainStep (mesh-less,
    # deliberately single-device) built while a multi-device session mesh
    # happens to be active must NOT be judged against that session mesh —
    # its replicated params are correct, not replication blowups
    if mesh is None:
        mesh = getattr(step, "mesh", None) or {}
    linter = MeshLinter(mesh=mesh, **kwargs)
    violations, est = linter.lint_train_step(step, *batch)
    _finish(violations, "Mesh lint failed (TrainStep)", raise_on_error)
    return violations, est


def lint_engine(engine, mesh=None, raise_on_error=False, **kwargs):
    # same authority rule as lint_train_step: an engine constructed with
    # mesh=None is single-device BY CONTRACT regardless of session state
    if mesh is None:
        mesh = getattr(engine, "mesh", None) or {}
    linter = MeshLinter(mesh=mesh, **kwargs)
    violations, est = linter.lint_engine(engine)
    _finish(violations, "Mesh lint failed (GenerationEngine)", raise_on_error)
    return violations, est


def _chain_avals(spec):
    """Abstract engine-shaped args of a DecodeChainSpec's canonical
    (kc, vc, q, kn, vn, tables, lens) signature — ShapeDtypeStructs only,
    so the lint trace never allocates a pool."""
    import jax.numpy as jnp

    from paddle_tpu.ops import paged_attention as pa

    sds = jax.ShapeDtypeStruct
    pool_dt = jnp.int8 if spec.kv == "int8" else jnp.dtype(spec.dtype)
    pool_shape = (spec.num_blocks, spec.num_kv_heads, spec.block_size,
                  spec.head_dim)
    if spec.kv == "int8":
        def quant():
            return pa.QuantPool(
                sds(pool_shape, pool_dt),
                sds((spec.num_blocks, spec.num_kv_heads), jnp.float32))

        kc, vc = quant(), quant()
    else:
        kc, vc = sds(pool_shape, pool_dt), sds(pool_shape, pool_dt)
    dt = jnp.dtype(spec.dtype)
    return (kc, vc,
            sds((spec.batch, spec.num_heads, spec.head_dim), dt),
            sds((spec.batch, spec.num_kv_heads, spec.head_dim), dt),
            sds((spec.batch, spec.num_kv_heads, spec.head_dim), dt),
            sds((spec.batch, spec.max_blocks), jnp.int32),
            sds((spec.batch,), jnp.int32))


def lint_decode_chain(spec, config, mesh=None, raise_on_error=False,
                      **kwargs):
    """Statically check a fused decode-chain kernel's collectives BEFORE
    an engine adopts the config (docs/MESH_LINT.md kernel-collective
    check): abstractly trace ``spec.build(config)`` over engine-shaped
    avals and walk the jaxpr — shard_map mesh congruence, collective
    axis/size checks, conditional collectives — without ever executing
    the kernel.  A head-local sharded chain walks clean (zero in-kernel
    collectives is the layout's contract); anything else is a named
    violation the adopt path turns into a counted disable.  Same
    authority rule as lint_engine: the spec's OWN mesh judges it — a
    single-device spec lints mesh-less regardless of session state."""
    if mesh is None:
        mesh = getattr(spec, "mesh", None) or {}
    linter = MeshLinter(mesh=mesh, **kwargs)
    try:
        fn = spec.build(config)
    except Exception as e:
        violations = [MeshViolation(
            "unknown-axis",
            f"decode-chain build rejected the config before trace: {e}",
            spec.label())]
        return _finish(violations, "Mesh lint failed (decode chain)",
                       raise_on_error)
    violations = linter.lint_callable(fn, *_chain_avals(spec),
                                      site=spec.label())
    return _finish(violations, "Mesh lint failed (decode chain)",
                   raise_on_error)
