"""Weight-decay regularizers (reference: python/paddle/regularizer.py).

`L1Decay` / `L2Decay` instances are accepted wherever the reference takes
them: as an optimizer's `weight_decay=` (applied to every trainable
parameter without its own regularizer) and as a per-parameter override
(`param.regularizer = L1Decay(...)`, the ParamAttr path) — the
per-parameter setting takes priority, matching the reference's
append_regularization_ops resolution order.

TPU-native: the penalty gradient folds into the grad inside the one
compiled optimizer update (L2: coeff * p; L1: coeff * sign(p)) — there is
no separate graph op to append.
"""

from __future__ import annotations

__all__ = ["WeightDecayRegularizer", "L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    """Base class; subclasses define the penalty gradient."""

    coeff: float = 0.0

    def _grad_term(self, value):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self.coeff})"


class L1Decay(WeightDecayRegularizer):
    """L1 penalty: loss += coeff * sum|w|; grad term coeff * sign(w)."""

    def __init__(self, coeff: float = 0.0):
        self.coeff = float(coeff)

    def _grad_term(self, value):
        import jax.numpy as jnp

        return self.coeff * jnp.sign(value)


class L2Decay(WeightDecayRegularizer):
    """L2 penalty: loss += 0.5 * coeff * sum(w^2); grad term coeff * w.

    (The reference folds the 1/2 into the coefficient exactly the same
    way: the applied gradient is coeff * w.)
    """

    def __init__(self, coeff: float = 0.0):
        self.coeff = float(coeff)

    def _grad_term(self, value):
        return self.coeff * value
