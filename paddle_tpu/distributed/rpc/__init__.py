"""paddle.distributed.rpc equivalent (reference:
python/paddle/distributed/rpc/rpc.py — init_rpc, rpc_sync, rpc_async,
shutdown, get_worker_info/get_all_worker_infos, over a brpc agent).

TPU-native redesign: workers exchange endpoints through the framework's
native TCPStore, then serve pickled fn calls over a plain TCP socket
thread — RPC here is control-plane (orchestration, PS-style coordination),
never tensor compute, so a simple length-prefixed pickle protocol is the
right weight."""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
from concurrent.futures import Future

__all__ = [
    "init_rpc", "shutdown", "rpc_sync", "rpc_async",
    "get_worker_info", "get_all_worker_infos", "get_current_worker_info",
    "WorkerInfo",
]

_DEFAULT_TIMEOUT = 30.0


class WorkerInfo:
    """reference rpc.py WorkerInfo(name, rank, ip, port)."""

    def __init__(self, name, rank, ip, port):
        self.name = name
        self.rank = rank
        self.ip = ip
        self.port = port

    def __repr__(self):
        return f"WorkerInfo(name={self.name}, rank={self.rank}, ip={self.ip}, port={self.port})"


class _State:
    store = None
    server_sock = None
    server_thread = None
    workers = {}  # name -> WorkerInfo
    current = None
    stopping = False


_S = _State()


def _recv_all(conn, n):
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        buf += chunk
    return buf


def _send_msg(conn, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    conn.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_msg(conn):
    (n,) = struct.unpack("<Q", _recv_all(conn, 8))
    return pickle.loads(_recv_all(conn, n))


def _serve_loop(sock):
    while not _S.stopping:
        try:
            conn, _ = sock.accept()
        except OSError:
            return
        threading.Thread(target=_handle, args=(conn,), daemon=True).start()


def _handle(conn):
    try:
        with conn:
            fn, args, kwargs = _recv_msg(conn)
            try:
                result = fn(*args, **kwargs)
                reply = ("ok", result)
            except Exception as e:  # noqa: BLE001 — errors travel to caller
                reply = ("err", e)
            try:
                _send_msg(conn, reply)
            except Exception:  # unpicklable result/exception
                _send_msg(
                    conn,
                    ("err", RuntimeError(f"rpc reply not picklable: {reply[1]!r:.500}")),
                )
    except (ConnectionError, EOFError, OSError):
        pass


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """reference rpc.py:73 — register this worker, exchange infos, barrier."""
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None else rank
    world_size = (
        int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) if world_size is None else world_size
    )
    master_endpoint = master_endpoint or os.environ.get("PADDLE_MASTER_ENDPOINT", "127.0.0.1:18765")

    from paddle_tpu.distributed.bootstrap import host_or_connect

    if rank == 0:
        try:
            _S.store_server, _S.store = host_or_connect(master_endpoint, True, timeout_ms=60_000)
        except OSError:
            _S.store_server = None  # another rank-0 process already hosts it
            _, _S.store = host_or_connect(master_endpoint, False, timeout_ms=60_000)
    else:
        _S.store_server, _S.store = host_or_connect(master_endpoint, False, timeout_ms=60_000)

    # serve on an ephemeral port
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind(("0.0.0.0", 0))
    sock.listen(64)
    my_port = sock.getsockname()[1]
    my_ip = os.environ.get("POD_IP")
    if not my_ip:
        if world_size > 1:  # must advertise a reachable address
            my_ip = socket.gethostbyname(socket.gethostname())
        else:
            my_ip = "127.0.0.1"
    _S.server_sock = sock
    _S.stopping = False
    _S.server_thread = threading.Thread(target=_serve_loop, args=(sock,), daemon=True)
    _S.server_thread.start()

    info = (name, rank, my_ip, my_port)
    _S.store.set(f"rpc/worker/{rank}", pickle.dumps(info))
    _S.current = WorkerInfo(*info)

    for r in range(world_size):
        w = pickle.loads(_S.store.get(f"rpc/worker/{r}", timeout_ms=120_000))
        _S.workers[w[0]] = WorkerInfo(*w)


def get_worker_info(name):
    return _S.workers[name]


def get_all_worker_infos():
    return list(_S.workers.values())


def get_current_worker_info():
    return _S.current


def rpc_async(to, fn, args=None, kwargs=None, timeout=_DEFAULT_TIMEOUT):
    """reference rpc.py:183 — returns a Future."""
    w = _S.workers[to]
    fut = Future()

    def call():
        try:
            with socket.create_connection((w.ip, w.port), timeout=timeout) as conn:
                conn.settimeout(timeout)
                _send_msg(conn, (fn, tuple(args or ()), dict(kwargs or {})))
                status, payload = _recv_msg(conn)
            if status == "ok":
                fut.set_result(payload)
            else:
                fut.set_exception(payload)
        except Exception as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(target=call, daemon=True).start()
    return fut


def rpc_sync(to, fn, args=None, kwargs=None, timeout=_DEFAULT_TIMEOUT):
    """reference rpc.py:143."""
    return rpc_async(to, fn, args, kwargs, timeout).result(timeout)


def shutdown():
    """reference rpc.py shutdown — barrier then stop serving."""
    if _S.store is None:
        return
    from paddle_tpu.distributed.bootstrap import store_barrier

    try:
        store_barrier(_S.store, "rpc/shutdown", len(_S.workers), timeout_ms=30_000)
    except Exception:
        pass
    _S.stopping = True
    try:
        _S.server_sock.close()
    except Exception:
        pass
    _S.store.close()
    server = getattr(_S, "store_server", None)
    if server is not None:
        server.stop()
    _S.store = None
    _S.workers.clear()
