"""Persistent + async parameter-server tiers.

Reference: paddle/fluid/distributed/ps/table/ssd_sparse_table.cc (rocksdb-
backed accessor table with a hot-row memory cache), async/geo-SGD update
modes (paddle/fluid/distributed/ps/service/, the_one_ps.py geo strategy).

TPU-native redesign, host-side by construction (the PS tier exists exactly
for state that does NOT fit device HBM):

- `SSDSparseTable`: disk-backed sparse rows.  Storage is N bucket files of
  fixed-size records `[int64 id | f32 row*dim | f32 acc*dim]` with an
  in-memory {id -> offset} index per bucket; records are written in place
  (update) or appended (first write).  The index is a pure cache: after a
  crash it is rebuilt by scanning record headers, so a kill -9 loses at
  most rows not yet flushed (nothing, in write_through mode).  A bounded
  LRU keeps hot rows in RAM; evictions write back (the rocksdb+memcache
  split of the reference, with the same durability story).
- `AsyncPsClient`: pushes are applied by a background thread; pulls are
  allowed to run ahead of at most `max_staleness` pending pushes (the
  async-SGD staleness bound of the reference's async mode).
- `GeoPsClient`: geo-SGD — train against a local table copy and push the
  accumulated DELTA of touched rows every `geo_steps` steps (reference
  geo strategy), then refresh from the global table.
"""

from __future__ import annotations

import os
import queue
import struct
import threading
from collections import OrderedDict

import numpy as np

__all__ = ["SSDSparseTable", "AsyncPsClient", "GeoPsClient"]

_HDR = struct.Struct("<q")  # row id per record: the crash-rebuild anchor


class _Bucket:
    """One record file: fixed-size [id|row|acc] records, offset index."""

    def __init__(self, path, dim):
        self.path = path
        self.dim = dim
        self.rec_size = _HDR.size + 2 * 4 * dim
        exists = os.path.exists(path)
        self.fp = open(path, "r+b" if exists else "w+b")
        self.index: dict[int, int] = {}
        if exists:
            self._rebuild_index()

    def _rebuild_index(self):
        """Scan record headers — works with no sidecar, including after an
        unclean shutdown (a torn trailing record is truncated away)."""
        self.fp.seek(0, os.SEEK_END)
        size = self.fp.tell()
        n_complete = size // self.rec_size
        self.fp.seek(0)
        for i in range(n_complete):
            hdr = self.fp.read(_HDR.size)
            (rid,) = _HDR.unpack(hdr)
            self.index[rid] = i * self.rec_size
            self.fp.seek((i + 1) * self.rec_size)
        if size != n_complete * self.rec_size:
            self.fp.truncate(n_complete * self.rec_size)

    def read(self, rid):
        off = self.index.get(rid)
        if off is None:
            return None
        self.fp.seek(off + _HDR.size)
        buf = self.fp.read(2 * 4 * self.dim)
        arr = np.frombuffer(buf, np.float32).copy()
        return arr[: self.dim], arr[self.dim:]

    def write(self, rid, row, acc, sync=False):
        off = self.index.get(rid)
        if off is None:
            self.fp.seek(0, os.SEEK_END)
            off = self.fp.tell()
            self.index[rid] = off
        self.fp.seek(off)
        self.fp.write(_HDR.pack(rid))
        self.fp.write(np.asarray(row, np.float32).tobytes())
        self.fp.write(np.asarray(acc, np.float32).tobytes())
        if sync:
            self.fp.flush()
            os.fsync(self.fp.fileno())

    def ids(self):
        return list(self.index)

    def close(self):
        self.fp.flush()
        self.fp.close()


class SSDSparseTable:
    """Disk-backed accessor table with an LRU hot-row cache.

    Drop-in for SparseTable (pull/push/n_rows/state_dict) so PsClient /
    SparseEmbedding / MeshShardedEmbedding spill tiers work unchanged.

    write_through=True makes every push durable before it returns (the
    crash-consistency mode); otherwise dirty rows ride the LRU and are
    written on eviction / flush() / close().
    """

    def __init__(self, dim, path, optimizer="adagrad", lr=0.01,
                 n_buckets=16, cache_rows=100_000, write_through=False,
                 initializer=None, name="ssd_emb"):
        self.dim = int(dim)
        self.name = name
        self._opt = optimizer
        self._lr = float(lr)
        self._wt = bool(write_through)
        self._cap = int(cache_rows)
        self._init = initializer or (
            lambda rng, dim: (rng.standard_normal(dim) * 0.01).astype(np.float32)
        )
        self._lock = threading.RLock()
        os.makedirs(path, exist_ok=True)
        self._buckets = [
            _Bucket(os.path.join(path, f"bucket_{b:04d}.bin"), self.dim)
            for b in range(int(n_buckets))
        ]
        # LRU: rid -> [row, acc, dirty]
        self._cache: OrderedDict[int, list] = OrderedDict()

    # ------------------------------------------------------------ internals
    def _bucket(self, rid):
        return self._buckets[rid % len(self._buckets)]

    def _load(self, rid):
        """Row into cache (from disk or fresh); returns the cache slot."""
        slot = self._cache.get(rid)
        if slot is not None:
            self._cache.move_to_end(rid)
            return slot
        rec = self._bucket(rid).read(rid)
        if rec is None:
            from . import _row_rng

            row = self._init(_row_rng(rid), self.dim).astype(np.float32)
            acc = np.zeros(self.dim, np.float32)
            if self._wt:
                self._bucket(rid).write(rid, row, acc, sync=True)
        else:
            row, acc = rec
        slot = [row, acc, rec is None and not self._wt]
        self._cache[rid] = slot
        self._evict()
        return slot

    def _evict(self):
        while len(self._cache) > self._cap:
            rid, (row, acc, dirty) = self._cache.popitem(last=False)
            if dirty:
                self._bucket(rid).write(rid, row, acc)

    # ------------------------------------------------------------- core API
    def pull(self, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.empty((len(ids), self.dim), np.float32)
        with self._lock:
            for i, rid in enumerate(ids):
                out[i] = self._load(int(rid))[0]
        return out

    def push(self, ids, grads):
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        with self._lock:
            for rid, g in zip(ids, grads):
                rid = int(rid)
                slot = self._load(rid)
                row, acc, _ = slot
                if self._opt == "adagrad":
                    acc += g * g
                    row -= self._lr * g / (np.sqrt(acc) + 1e-8)
                else:  # sgd
                    row -= self._lr * g
                if self._wt:
                    self._bucket(rid).write(rid, row, acc, sync=True)
                    slot[2] = False
                else:
                    slot[2] = True

    def push_delta(self, ids, deltas):
        """row -= delta (geo-SGD merge; bypasses the optimizer rule)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        deltas = np.asarray(deltas, np.float32).reshape(len(ids), self.dim)
        with self._lock:
            for rid, d in zip(ids, deltas):
                rid = int(rid)
                slot = self._load(rid)
                slot[0] -= d
                if self._wt:
                    self._bucket(rid).write(rid, slot[0], slot[1], sync=True)
                    slot[2] = False
                else:
                    slot[2] = True

    # ------------------------------------------------------- mgmt / durability
    def flush(self):
        with self._lock:
            for rid, slot in self._cache.items():
                if slot[2]:
                    self._bucket(rid).write(rid, slot[0], slot[1])
                    slot[2] = False
            for b in self._buckets:
                b.fp.flush()
                os.fsync(b.fp.fileno())

    def close(self):
        self.flush()
        with self._lock:
            for b in self._buckets:
                b.close()

    def n_rows(self):
        with self._lock:
            on_disk = set()
            for b in self._buckets:
                on_disk.update(b.ids())
            on_disk.update(self._cache)
            return len(on_disk)

    def cached_rows(self):
        with self._lock:
            return len(self._cache)

    def state_dict(self):
        """Full materialization — for parity with SparseTable / checkpoints
        of SMALL tables; big tables should be copied at the file level."""
        self.flush()
        with self._lock:
            rows, acc = {}, {}
            for b in self._buckets:
                for rid in b.ids():
                    r, a = b.read(rid)
                    rows[rid], acc[rid] = r, a
            for rid, slot in self._cache.items():
                rows[rid], acc[rid] = slot[0].copy(), slot[1].copy()
            return {"rows": rows, "acc": acc}

    def set_state_dict(self, state):
        with self._lock:
            self._cache.clear()
            for rid, row in state["rows"].items():
                a = state.get("acc", {}).get(rid)
                self._bucket(int(rid)).write(
                    int(rid), row,
                    a if a is not None else np.zeros(self.dim, np.float32))
            self.flush()


class AsyncPsClient:
    """Asynchronous push with a bounded staleness window.

    push() enqueues and returns immediately; a background thread applies
    updates in order.  pull() waits only until at most `max_staleness`
    pushes are pending — the async-SGD staleness bound (reference async
    mode; max_staleness=0 degenerates to fully-synchronous)."""

    def __init__(self, client, max_staleness=4):
        self._client = client
        self._limit = int(max_staleness)
        self._q: queue.Queue = queue.Queue()
        self._err = None
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            try:
                self._client.push(*item)
            except Exception as e:  # surfaced on the next pull/push
                self._err = e
            finally:
                self._q.task_done()

    def _raise_pending_error(self):
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def pending(self):
        return self._q.unfinished_tasks

    def push(self, ids, grads):
        self._raise_pending_error()
        self._q.put((np.asarray(ids), np.asarray(grads)))

    def pull(self, ids):
        # staleness bound: let the worker drain to within the window
        while self.pending() > self._limit:
            import time

            time.sleep(0.001)
        self._raise_pending_error()
        return self._client.pull(ids)

    def wait(self):
        self._q.join()
        self._raise_pending_error()

    def close(self):
        self._q.put(None)
        self._q.join()


class GeoPsClient:
    """Geo-SGD: train against a local copy, push accumulated row DELTAS
    every `geo_steps` barriers, then refresh the touched rows (reference
    geo strategy: delta push beats gradient push for its staleness class)."""

    def __init__(self, client, dim, geo_steps=8, lr=0.01, optimizer="sgd"):
        from . import SparseTable

        self._client = client
        self._local = SparseTable(dim, optimizer=optimizer, lr=lr,
                                  name="geo_local")
        # local rows initialize FROM the global table on first touch
        self._local.pull = self._pull_into_local(self._local.pull)
        self._base: dict[int, np.ndarray] = {}
        self._geo = int(geo_steps)
        self._step = 0
        self.dim = int(dim)

    def _pull_into_local(self, orig_pull):
        def pull(ids):
            self._ensure_rows(np.asarray(ids, np.int64).reshape(-1))
            return orig_pull(ids)

        return pull

    def _ensure_rows(self, ids_arr):
        """Seed _local._rows AND _base together from the global table for
        any id not yet base-tracked — the ONE place the two registries are
        populated, so they cannot drift apart (a local row without a base
        snapshot would train but never sync).  An id already materialized
        locally keeps its row; only its missing base is recorded."""
        missing = [int(i) for i in ids_arr if int(i) not in self._base]
        if not missing:
            return
        rows = self._client.pull(np.asarray(missing, np.int64))
        with self._local._lock:
            for rid, row in zip(missing, rows):
                if rid not in self._local._rows:
                    self._local._rows[rid] = row.copy()
                self._base[rid] = row.copy()

    def pull(self, ids):
        return self._local.pull(ids)

    def push(self, ids, grads):
        # Rows FIRST touched via push() would bypass the wrapped pull and
        # never enter _base — sync() would skip them forever, silently
        # losing their training (and the local SparseTable drops pushes to
        # rows it never materialized).  Seed row + base snapshot from the
        # global table first, so this push lands and the next sync()
        # propagates it.
        self._ensure_rows(np.asarray(ids, np.int64).reshape(-1))
        self._local.push(ids, grads)
        self._step += 1
        if self._step % self._geo == 0:
            self.sync()

    def sync(self):
        """Push deltas of every touched row; refresh local from global."""
        with self._local._lock:
            touched = {rid: row for rid, row in self._local._rows.items()
                       if rid in self._base}
        if not touched:
            return
        ids = np.asarray(sorted(touched), np.int64)
        # raw row deltas (reference geo strategy pushes deltas, not grads)
        deltas = np.stack([self._base[int(i)] - touched[int(i)] for i in ids])
        self._client.push_delta(ids, deltas)
        fresh = self._client.pull(ids)
        with self._local._lock:
            for rid, row in zip(ids, fresh):
                self._local._rows[int(rid)] = row.copy()
                self._base[int(rid)] = row.copy()

