"""Parameter-server mode — minimal sparse-embedding analog.

Reference: paddle/fluid/distributed/ps/ (35k LoC: brpc PS services, accessor
tables with memory/SSD storage, async + geo-SGD modes, GPU-PS) plus the
python side python/paddle/distributed/ps/ and fleet/runtime/the_one_ps.py.

SCOPE DECISION (round-2): the reference's PS pillar exists for CPU-cluster
sparse recommendation training — billions of embedding rows, async updates,
SSD spill.  A TPU-first framework trains dense models with collectives on
ICI; the PS capability that still matters on TPU is the HOST-RESIDENT sparse
embedding table too large for HBM, pulled/pushed per batch.  That slice is
implemented here, for real:

- `SparseTable`: host (numpy) embedding table with lazy row creation and
  row-wise SGD/Adagrad updates — the accessor-table analog, memory tier.
- `SSDSparseTable` (disk_table.py): the ssd_sparse_table.cc analog — disk
  bucket files of id-tagged records, crash-rebuildable index, LRU hot-row
  cache, write-through durability mode.
- `AsyncPsClient` / `GeoPsClient` (disk_table.py): async pushes with a
  bounded staleness window, and geo-SGD local-delta training.
- `PsServer` / `PsClient`: pull/push served over paddle_tpu.distributed.rpc
  (the brpc PS service analog); single-process mode short-circuits to the
  local table so the layer works without a cluster.
- `SparseEmbedding`: an nn.Layer whose forward pulls rows into the device
  program and whose backward pushes per-row gradients back to the table —
  the distributed-lookup-table op pair (pull_sparse/push_sparse).

Dense PS tables and GPU-PS have no counterpart and are deliberately out of
scope — collective training covers them on TPU.
"""

from __future__ import annotations

import threading

import numpy as np

__all__ = ["SparseTable", "PsServer", "PsClient", "SparseEmbedding",
           "MeshShardedEmbedding", "SSDSparseTable", "AsyncPsClient",
           "GeoPsClient"]


def __getattr__(name):
    # lazy: sharded.py pulls in jax; the host-tier classes must not
    if name == "MeshShardedEmbedding":
        from .sharded import MeshShardedEmbedding

        return MeshShardedEmbedding
    if name in ("SSDSparseTable", "AsyncPsClient", "GeoPsClient"):
        from . import disk_table

        return getattr(disk_table, name)
    raise AttributeError(name)


def _row_rng(rid):
    """Per-id deterministic init stream: a row's fresh value must not depend
    on the ORDER rows were first touched (crash-resume / async replicas
    would otherwise diverge on re-created rows)."""
    return np.random.default_rng((int(rid) * 2654435761) & 0xFFFFFFFF)


class SparseTable:
    """Host-resident embedding table with lazy rows (accessor-table analog)."""

    def __init__(self, dim, initializer=None, optimizer="sgd", lr=0.01, name="emb"):
        self.dim = int(dim)
        self.name = name
        self._rows: dict[int, np.ndarray] = {}
        self._acc: dict[int, np.ndarray] = {}
        self._lock = threading.Lock()
        self._opt = optimizer
        self._lr = float(lr)
        self._init = initializer or (
            lambda rng, dim: (rng.standard_normal(dim) * 0.01).astype(np.float32)
        )

    def pull(self, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.empty((len(ids), self.dim), np.float32)
        with self._lock:
            for i, rid in enumerate(ids):
                row = self._rows.get(int(rid))
                if row is None:
                    row = self._init(_row_rng(rid), self.dim)
                    self._rows[int(rid)] = row
                out[i] = row
        return out

    def push(self, ids, grads):
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        with self._lock:
            for rid, g in zip(ids, grads):
                rid = int(rid)
                row = self._rows.get(rid)
                if row is None:
                    continue
                if self._opt == "adagrad":
                    acc = self._acc.setdefault(rid, np.zeros(self.dim, np.float32))
                    acc += g * g
                    row -= self._lr * g / (np.sqrt(acc) + 1e-8)
                else:  # sgd
                    row -= self._lr * g

    def push_delta(self, ids, deltas):
        """row -= delta (geo-SGD merge; bypasses the optimizer rule)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        deltas = np.asarray(deltas, np.float32).reshape(len(ids), self.dim)
        with self._lock:
            for rid, d in zip(ids, deltas):
                rid = int(rid)
                row = self._rows.get(rid)
                if row is None:
                    row = self._init(_row_rng(rid), self.dim)
                    self._rows[rid] = row
                row -= d

    def n_rows(self):
        with self._lock:
            return len(self._rows)

    def state_dict(self):
        with self._lock:
            return {"rows": dict(self._rows), "acc": dict(self._acc)}

    def set_state_dict(self, state):
        with self._lock:
            self._rows = dict(state["rows"])
            self._acc = dict(state.get("acc", {}))


class PsServer:
    """Hosts tables behind the rpc service (brpc PS service analog).

    Run `init_rpc(name, ...)` first; then workers address tables by
    (server_name, table_name) through PsClient."""

    _tables: dict[str, SparseTable] = {}

    def __init__(self):
        self.tables = PsServer._tables

    @classmethod
    def register_table(cls, table: SparseTable):
        cls._tables[table.name] = table
        return table

    # rpc entry points (module-level functions are pickled by name)


def _ps_pull(table_name, ids):
    return PsServer._tables[table_name].pull(ids)


def _ps_push(table_name, ids, grads):
    PsServer._tables[table_name].push(ids, grads)
    return True


def _ps_push_delta(table_name, ids, deltas):
    PsServer._tables[table_name].push_delta(ids, deltas)
    return True


class PsClient:
    """pull_sparse / push_sparse against a local or remote table."""

    def __init__(self, table: SparseTable | None = None, server: str | None = None, table_name: str = "emb"):
        if (table is None) == (server is None):
            raise ValueError("pass exactly one of table= (local) or server= (rpc)")
        self._table = table
        self._server = server
        self._table_name = table.name if table is not None else table_name

    def pull(self, ids):
        if self._table is not None:
            return self._table.pull(ids)
        from paddle_tpu.distributed import rpc

        return rpc.rpc_sync(self._server, _ps_pull, args=(self._table_name, np.asarray(ids)))

    def push(self, ids, grads):
        if self._table is not None:
            return self._table.push(ids, grads)
        from paddle_tpu.distributed import rpc

        return rpc.rpc_sync(self._server, _ps_push, args=(self._table_name, np.asarray(ids), np.asarray(grads)))

    def push_delta(self, ids, deltas):
        if self._table is not None:
            return self._table.push_delta(ids, deltas)
        from paddle_tpu.distributed import rpc

        return rpc.rpc_sync(self._server, _ps_push_delta, args=(self._table_name, np.asarray(ids), np.asarray(deltas)))


class SparseEmbedding:
    """Distributed-lookup-table layer (pull_sparse fwd / push_sparse bwd).

    Not an nn.Layer subclass on purpose: its weight lives in the host table,
    not in state_dict — matching the reference where lookup-table params
    belong to the PS, not the trainer program."""

    def __init__(self, client: PsClient, dim: int):
        self.client = client
        self.dim = int(dim)

    def __call__(self, ids):
        import jax.numpy as jnp

        from paddle_tpu._core.autograd import apply
        from paddle_tpu._core.tensor import Tensor
        from paddle_tpu.tensor._ops_common import ensure_tensor

        ids_t = ensure_tensor(ids)
        ids_np = np.asarray(ids_t._value)
        rows = self.client.pull(ids_np)  # [n, dim] host
        rows_dev = Tensor(jnp.asarray(rows.reshape(ids_np.shape + (self.dim,))))
        rows_dev.stop_gradient = False

        # the device-side compute is an identity carrying the rows; a grad
        # hook pushes row grads back to the table (push_sparse)
        out = apply("ps_pull_sparse", lambda v: v, rows_dev)

        client, dim = self.client, self.dim

        def _push(grad):
            g = np.asarray(grad._value, np.float32).reshape(-1, dim)
            client.push(ids_np.reshape(-1), g)
            return grad

        out.register_hook(_push)
        return out
