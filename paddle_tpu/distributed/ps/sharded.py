"""Mesh-sharded embedding table — the TPU-native parameter-server successor.

Reference: paddle/fluid/distributed/ps/table/memory_sparse_table.h (sharded
accessor tables), brpc_ps_server.h pull_sparse/push_sparse services, and the
distributed-lookup-table op pair.

TPU-native redesign (VERDICT r3 #7): instead of brpc servers, the table is a
ROW-SHARDED device array over a mesh axis.  Lookup and update are ONE
compiled shard_map program each:

  1. each rank buckets its local ids by owner shard (range partitioning),
  2. `lax.all_to_all` exchanges the id buckets (the pull_sparse RPC),
  3. owners gather their rows and all-to-all them back,
  4. update: the same routing carries per-row GRADIENTS to the owner, which
     applies a SelectedRows-style scatter update (only touched rows change —
     the lazy-row semantics of the reference's accessor tables; adagrad
     second moments live sharded next to the rows).

The host `SparseTable` remains the SPILL TIER: ids >= num_rows (or an
explicit overflow range) are served from host memory, so a vocabulary can
exceed device HBM exactly like the reference's memory/SSD tiering.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.distributed.shard_map_compat import axis_size as _axis_size

__all__ = ["MeshShardedEmbedding"]


def _routed_exchange(ids_local, axis, local_rows, cap):
    """Bucket ids by owner shard and all-to-all them; returns everything
    needed to route payloads both directions with STATIC shapes.

    ids_local: [n] int32 global row ids (must be < w * local_rows).
    Returns (recv_ids [w, cap], recv_mask [w, cap], order, so, pos, inv,
    valid) — `order` is the owner-sort permutation, shared by id and
    payload routing so they can never drift apart.
    """
    import jax.numpy as jnp
    from jax import lax

    n = ids_local.shape[0]
    w = _axis_size(axis)
    owner = jnp.clip(ids_local // local_rows, 0, w - 1)
    order = jnp.argsort(owner, stable=True)
    inv = jnp.argsort(order)
    so = owner[order]
    ids_sorted = ids_local[order]
    # position of each request inside its destination bucket
    first = jnp.searchsorted(so, so, side="left")
    pos = jnp.arange(n, dtype=jnp.int32) - first.astype(jnp.int32)
    valid = pos < cap  # requests beyond capacity are dropped (cap=n: never)

    buckets = jnp.zeros((w, cap), jnp.int32).at[so, pos].set(
        ids_sorted, mode="drop")
    bmask = jnp.zeros((w, cap), jnp.bool_).at[so, pos].set(
        valid, mode="drop")
    recv_ids = lax.all_to_all(buckets, axis, split_axis=0, concat_axis=0)
    recv_mask = lax.all_to_all(bmask, axis, split_axis=0, concat_axis=0)
    return recv_ids, recv_mask, order, so, pos, inv, valid


class MeshShardedEmbedding:
    """Row-sharded device embedding with all-to-all pull/push.

    Usage (mesh axis 'dp' with 8 shards):
        table = MeshShardedEmbedding(10_000_000, 16, mesh, axis="dp")
        rows = table.pull(ids)                 # [n, dim] device rows
        ...loss... ; g = d(loss)/d(rows)
        table.push(ids, g)                     # sparse per-shard update
    """

    def __init__(self, num_rows, dim, mesh, axis="dp", optimizer="adagrad",
                 lr=0.05, capacity=None, spill_table=None, seed=0,
                 init_scale=0.01):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        if hasattr(mesh, "jax_mesh"):
            mesh = mesh.jax_mesh
        if not isinstance(mesh, Mesh):
            raise TypeError(f"mesh must be a jax Mesh/ProcessMesh, got {type(mesh)}")
        if axis not in mesh.axis_names:
            raise ValueError(f"axis {axis!r} not in mesh axes {mesh.axis_names}")
        self.mesh, self.axis = mesh, axis
        self.w = int(mesh.shape[axis])
        self.num_rows, self.dim = int(num_rows), int(dim)
        self.local_rows = -(-self.num_rows // self.w)
        self.lr = float(lr)
        self.optimizer = optimizer
        if optimizer not in ("sgd", "adagrad"):
            raise ValueError("optimizer must be 'sgd' or 'adagrad'")
        self.capacity = capacity  # None -> per-call n (no drops)
        self.spill = spill_table

        padded = self.local_rows * self.w
        self._row_sharding = NamedSharding(mesh, PartitionSpec(axis))
        key = jax.random.PRNGKey(seed)
        # initialize SHARDED (jit with out_shardings): the full table never
        # materializes on one device — the point at 10M+ rows
        init = jax.jit(
            lambda k: jax.random.normal(k, (padded, self.dim), jnp.float32)
            * init_scale,
            out_shardings=self._row_sharding,
        )
        self.weight = init(key)
        self._acc = (
            jax.jit(lambda: jnp.zeros((padded, self.dim), jnp.float32),
                    out_shardings=self._row_sharding)()
            if optimizer == "adagrad" else None
        )
        self._pull_cache: dict = {}
        self._push_cache: dict = {}

    # ----------------------------------------------------------- programs
    def _pull_program(self, cap):
        import jax
        import jax.numpy as jnp
        from jax import lax

        from paddle_tpu.distributed.shard_map_compat import shard_map
        from jax.sharding import PartitionSpec as P

        axis, local_rows = self.axis, self.local_rows

        def body(w_local, ids_local):
            r = lax.axis_index(axis)
            recv_ids, recv_mask, _order, so, pos, inv, valid = _routed_exchange(
                ids_local, axis, local_rows, cap)
            local_idx = jnp.clip(recv_ids - r * local_rows, 0, local_rows - 1)
            rows = w_local[local_idx] * recv_mask[..., None].astype(w_local.dtype)
            back = lax.all_to_all(rows, axis, split_axis=0, concat_axis=0)
            rows_sorted = back[so, pos] * valid[:, None].astype(w_local.dtype)
            return rows_sorted[inv]

        return jax.jit(shard_map(
            body, mesh=self.mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=P(axis),
            check_vma=False,
        ))

    def _push_program(self, cap):
        import jax
        import jax.numpy as jnp
        from jax import lax

        from paddle_tpu.distributed.shard_map_compat import shard_map
        from jax.sharding import PartitionSpec as P

        axis, local_rows, lr = self.axis, self.local_rows, self.lr
        adagrad = self.optimizer == "adagrad"

        def body(w_local, acc_local, ids_local, g_local):
            r = lax.axis_index(axis)
            recv_ids, recv_mask, order, so, pos, _inv, valid = _routed_exchange(
                ids_local, axis, local_rows, cap)
            gs = g_local[order]  # the id-routing permutation routes payloads
            gsend = jnp.zeros((_axis_size(axis), cap, g_local.shape[-1]),
                              g_local.dtype).at[so, pos].set(
                gs * valid[:, None].astype(g_local.dtype), mode="drop")
            grecv = lax.all_to_all(gsend, axis, split_axis=0, concat_axis=0)
            idx = jnp.clip(recv_ids - r * local_rows, 0, local_rows - 1).reshape(-1)
            gf = (grecv * recv_mask[..., None].astype(grecv.dtype)).reshape(-1, g_local.shape[-1])
            # SelectedRows-style lazy update: ONLY the routed rows change
            if adagrad:
                acc_new = acc_local.at[idx].add(gf * gf)
                denom = jnp.sqrt(acc_new[idx]) + 1e-8
                w_new = w_local.at[idx].add(-lr * gf / denom)
                return w_new, acc_new
            w_new = w_local.at[idx].add(-lr * gf)
            return w_new, acc_local

        return jax.jit(shard_map(
            body, mesh=self.mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis)),
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        ), donate_argnums=(0, 1))

    # -------------------------------------------------------------- public
    def _split_spill(self, ids_np):
        dev_mask = ids_np < self.num_rows
        return dev_mask, ids_np[~dev_mask]

    def _pad_global(self, ids_np):
        """Pad the flat id batch to a multiple of the shard width so the
        P(axis) input spec tiles evenly; padded ids hit row 0, masked out."""
        n = len(ids_np)
        pad = (-n) % self.w
        if pad:
            ids_np = np.concatenate([ids_np, np.zeros(pad, np.int32)])
        return ids_np, n

    def _check_capacity(self, padded, cap):
        """A configured capacity smaller than the batch can overflow a
        destination bucket — that would SILENTLY drop lookups/updates, so
        refuse loudly here (host-side, ids are host arrays already)."""
        if self.capacity is None:
            return  # cap == per-rank n: overflow is impossible
        per_rank = len(padded) // self.w
        for r in range(self.w):
            shard = padded[r * per_rank:(r + 1) * per_rank]
            counts = np.bincount(
                np.clip(shard // self.local_rows, 0, self.w - 1),
                minlength=self.w)
            worst = int(counts.max()) if counts.size else 0
            if worst > cap:
                raise ValueError(
                    f"capacity={cap} overflows: rank {r} routes {worst} ids "
                    f"to one owner shard; raise capacity (or leave it None "
                    f"for the always-safe per-call bound)")

    def pull(self, ids):
        """ids: any int array -> [*, dim] float32 rows (device for in-range
        ids; spill-tier host rows merged in for overflow ids)."""
        import jax.numpy as jnp

        ids_np = np.asarray(ids, np.int64).reshape(-1)
        shape = np.asarray(ids).shape
        dev_mask, spill_ids = self._split_spill(ids_np)
        padded, n = self._pad_global(
            np.where(dev_mask, ids_np, 0).astype(np.int32))
        cap = self.capacity or len(padded) // self.w
        self._check_capacity(padded, cap)
        key = (len(padded), cap)
        if key not in self._pull_cache:
            self._pull_cache[key] = self._pull_program(cap)
        dev_rows = self._pull_cache[key](self.weight, jnp.asarray(padded))
        if not spill_ids.size:
            # hot path: stay on device, no host round-trip
            return dev_rows[:n].reshape(shape + (self.dim,))
        if self.spill is None:
            raise IndexError(
                f"ids >= num_rows={self.num_rows} and no spill table")
        rows = np.array(dev_rows)[:n]
        rows[~dev_mask] = self.spill.pull(spill_ids)
        return jnp.asarray(rows.reshape(shape + (self.dim,)))

    def push(self, ids, grads):
        """Sparse update: grads routed to owner shards, touched rows only."""
        import jax.numpy as jnp

        ids_np = np.asarray(ids, np.int64).reshape(-1)
        g_np = np.asarray(grads, np.float32).reshape(len(ids_np), self.dim)
        dev_mask, spill_ids = self._split_spill(ids_np)
        if spill_ids.size:
            if self.spill is None:
                raise IndexError(
                    f"ids >= num_rows={self.num_rows} and no spill table")
            self.spill.push(spill_ids, g_np[~dev_mask])
        dev_g = np.where(dev_mask[:, None], g_np, 0.0).astype(np.float32)
        padded, n = self._pad_global(
            np.where(dev_mask, ids_np, 0).astype(np.int32))
        pad = len(padded) - n
        if pad:
            dev_g = np.concatenate([dev_g, np.zeros((pad, self.dim), np.float32)])
        cap = self.capacity or len(padded) // self.w
        self._check_capacity(padded, cap)
        key = (len(padded), cap)
        if key not in self._push_cache:
            self._push_cache[key] = self._push_program(cap)
        acc = self._acc if self._acc is not None else jnp.zeros((0, self.dim), np.float32)
        self.weight, acc_new = self._push_cache[key](
            self.weight, acc, jnp.asarray(padded), jnp.asarray(dev_g))
        if self._acc is not None:
            self._acc = acc_new

    # ---------------------------------------------------------- checkpoint
    def state_dict(self):
        out = {"weight": np.asarray(self.weight)[: self.num_rows],
               "num_rows": self.num_rows, "dim": self.dim,
               "optimizer": self.optimizer}
        if self._acc is not None:
            out["acc"] = np.asarray(self._acc)[: self.num_rows]
        if self.spill is not None:
            out["spill"] = self.spill.state_dict()
        return out

    def set_state_dict(self, state):
        import jax
        import jax.numpy as jnp

        padded = self.local_rows * self.w
        w = np.zeros((padded, self.dim), np.float32)
        w[: self.num_rows] = state["weight"]
        self.weight = jax.device_put(jnp.asarray(w), self._row_sharding)
        if self._acc is not None and "acc" in state:
            a = np.zeros((padded, self.dim), np.float32)
            a[: self.num_rows] = state["acc"]
            self._acc = jax.device_put(jnp.asarray(a), self._row_sharding)
        if self.spill is not None and "spill" in state:
            self.spill.set_state_dict(state["spill"])
