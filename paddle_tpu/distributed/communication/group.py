"""Communication groups.

Reference: python/paddle/distributed/communication/group.py:22 (Group),
backed by C++ ProcessGroups (paddle/fluid/distributed/collective/
process_group.h:47) with one NCCL communicator per group ring.

TPU-native: a Group names a slice of the device mesh — either a mesh axis
(the common hybrid-parallel case: the 'dp'/'mp'/'pp' subgroups HCG builds) or
an explicit rank list.  Collectives over a Group compile to XLA collectives
on ICI/DCN instead of NCCL rings.  Under single-controller SPMD the "ranks"
are devices, and the per-rank tensors of the NCCL world are the shards of a
jax.Array along the group's axis.
"""

from __future__ import annotations

import jax

__all__ = ["Group", "new_group", "get_group", "destroy_process_group", "is_available"]

_group_registry: dict[int, "Group"] = {}
_next_gid = [0]


class Group:
    def __init__(self, ranks=None, mesh=None, axis=None, gid=None, pg=None, name=None):
        """Either (mesh, axis) — a mesh-axis group — or explicit ranks."""
        self.mesh = mesh
        self.axis = axis
        if ranks is None and mesh is not None and axis is not None:
            # ranks along the axis from the caller's perspective: size of axis
            self._ranks = list(range(mesh.get_dim_size(axis)))
        else:
            self._ranks = list(ranks) if ranks is not None else list(range(jax.device_count()))
        if gid is None:
            gid = _next_gid[0]
            _next_gid[0] += 1
        self.id = gid
        self.pg = pg
        self._name = name or f"group_{gid}"
        _group_registry[gid] = self

    @property
    def nranks(self):
        return len(self._ranks)

    @property
    def world_size(self):
        return self.nranks

    @property
    def ranks(self):
        return list(self._ranks)

    @property
    def name(self):
        return self._name

    @property
    def rank(self):
        """Caller's rank in this group.  Single-controller SPMD has no
        per-device caller; process-level rank is the process index."""
        import jax

        pid = jax.process_index()
        return self._ranks.index(pid) if pid in self._ranks else -1

    def get_group_rank(self, rank):
        return self._ranks.index(rank) if rank in self._ranks else -1

    def is_member(self):
        return True

    def __repr__(self):
        ax = f", axis={self.axis!r}" if self.axis else ""
        return f"Group(id={self.id}, nranks={self.nranks}{ax})"


def new_group(ranks=None, backend=None, timeout=None, mesh=None, axis=None):
    """Create a group (reference communication/group.py new_group)."""
    return Group(ranks=ranks, mesh=mesh, axis=axis)


def get_group(gid: int):
    return _group_registry.get(gid)


def destroy_process_group(group=None):
    if group is None:
        _group_registry.clear()
    else:
        _group_registry.pop(group.id, None)


def is_available() -> bool:
    return True
