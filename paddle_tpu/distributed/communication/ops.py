"""Collective operations.

Reference: python/paddle/distributed/communication/{all_reduce,all_gather,
broadcast,reduce,scatter,reduce_scatter,all_to_all,send,recv,batch_isend_irecv}.py
backed by ProcessGroupNCCL async tasks.

TPU-native execution modes:

1. **Axis mode (the real collective path).**  Inside a distributed program
   (shard_map with manual mesh axes — the fleet engines set an axis scope),
   each collective lowers to the XLA collective on ICI: psum / all_gather /
   ppermute / all_to_all.  These are differentiable and fuse into the step
   program — the replacement for NCCL ring kernels + comm streams.
   The autograd tape records them like any op, so hand-written Megatron-style
   code (mp_ops) backprops correctly.

2. **Process mode.**  Outside any axis scope, the rank universe is the
   process set (multi-controller).  With one process the collective is a
   no-op on the local value (world size 1), matching the reference's
   single-rank fast path (communication/all_reduce.py returns immediately
   when nranks == 1).  Multi-host eager collectives outside compiled programs
   bootstrap via jax.distributed; they are compiled per (shape, dtype, ring)
   as tiny executables — see SURVEY.md §2.3 ProcessGroup mapping.
"""

from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu._core.autograd import apply
from paddle_tpu._core.tensor import Tensor

from .group import Group
from .watchdog import static_check as _static_check

__all__ = [
    "ReduceOp",
    "all_reduce",
    "all_gather",
    "all_gather_object",
    "broadcast",
    "reduce",
    "scatter",
    "reduce_scatter",
    "alltoall",
    "alltoall_single",
    "send",
    "recv",
    "isend",
    "irecv",
    "barrier",
    "wait",
    "collective_axis_scope",
    "current_axis_scope",
]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class _AxisScope(threading.local):
    def __init__(self):
        self.axes: dict[str, str] = {}  # logical group axis -> mesh axis name


_scope = _AxisScope()


@contextlib.contextmanager
def collective_axis_scope(axes: dict):
    """Declare active manual mesh axes (engines call this inside shard_map
    bodies): {'dp': 'dp', 'mp': 'model', ...} logical → mesh axis name."""
    prev = dict(_scope.axes)
    _scope.axes.update(axes)
    try:
        yield
    finally:
        _scope.axes = prev


def current_axis_scope():
    return dict(_scope.axes)


class _Task:
    """Completed-task handle (reference ProcessGroup task.wait())."""

    def __init__(self, value=None):
        self._value = value

    def wait(self):
        return True

    def is_completed(self):
        return True


def _axis_for(group):
    """Resolve a Group (or None = world) to the active mesh axis name.

    group=None inside a multi-axis scope means "the world": reduce-type
    callers accept the returned tuple of all axes; shape-changing collectives
    must reject it (ambiguous order) rather than silently no-op.
    """
    if group is None:
        if len(_scope.axes) == 1:
            return next(iter(_scope.axes.values()))
        if len(_scope.axes) > 1:
            return tuple(_scope.axes.values())
        return None
    ax = getattr(group, "axis", None)
    if ax is not None and (ax in _scope.axes or ax in _scope.axes.values()):
        return _scope.axes.get(ax, ax)
    return None


def _single_axis(ax, opname):
    if isinstance(ax, tuple):
        raise RuntimeError(
            f"{opname} with group=None is ambiguous inside a multi-axis SPMD "
            f"scope {sorted(_scope.axes)}; pass an explicit group"
        )
    return ax


def _my_rank():
    return jax.process_index()


def _world(group):
    if group is not None:
        return group.nranks
    return jax.process_count()


def _pprod(v, ax):
    """Cross-rank elementwise product with correct sign/zero handling
    (exp-sum-log alone NaNs on negatives and zeros)."""
    vf = v.astype(jnp.float32) if jnp.issubdtype(v.dtype, jnp.integer) else v
    neg_count = lax.psum(jnp.where(vf < 0, 1.0, 0.0), ax)
    sign = jnp.where(jnp.mod(neg_count, 2.0) == 1.0, -1.0, 1.0)
    has_zero = lax.pmin(jnp.abs(vf), ax) == 0
    mag = jnp.exp(lax.psum(jnp.log(jnp.where(vf == 0, 1.0, jnp.abs(vf))), ax))
    out = jnp.where(has_zero, 0.0, sign * mag)
    if jnp.issubdtype(v.dtype, jnp.integer):
        out = jnp.round(out)
    return out.astype(v.dtype)


def _reduce_fn(op):
    return {
        ReduceOp.SUM: lambda v, ax: lax.psum(v, ax),
        ReduceOp.MAX: lambda v, ax: lax.pmax(v, ax),
        ReduceOp.MIN: lambda v, ax: lax.pmin(v, ax),
        ReduceOp.AVG: lambda v, ax: lax.pmean(v, ax),
        ReduceOp.PROD: _pprod,
    }[op]


def _process_group_for(group):
    """Multi-controller ring for eager collectives (jax.distributed world)."""
    from paddle_tpu.distributed.collective import ProcessGroup

    key = tuple(group.ranks) if group is not None else None
    pg = _pg_cache.get(key)
    if pg is None:
        pg = ProcessGroup(ranks=list(group.ranks) if group is not None else None)
        _pg_cache[key] = pg
    return pg


_pg_cache: dict = {}


def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    _static_check("all_reduce", tensor, group)
    ax = _axis_for(group)
    if ax is not None:
        red = _reduce_fn(op)
        out = apply("all_reduce", lambda v: red(v, ax), tensor)
        tensor._bind(out._value)
        tensor._grad_node = out._grad_node
        tensor._out_index = out._out_index
        return _Task(tensor)
    if _world(group) == 1:
        return _Task(tensor)
    op_name = {ReduceOp.SUM: "sum", ReduceOp.MAX: "max", ReduceOp.MIN: "min", ReduceOp.AVG: "avg"}.get(op, "sum")
    return _process_group_for(group).allreduce(tensor, op_name)


def all_gather(tensor_list, tensor: Tensor, group=None, sync_op=True, axis=0):
    _static_check("all_gather", tensor, group)
    ax = _axis_for(group)
    ax = _single_axis(ax, "all_gather")
    if ax is not None:
        out = apply("all_gather", lambda v: lax.all_gather(v, ax), tensor)
        if tensor_list is not None:
            for i in range(out.shape[0]):
                tensor_list.append(out[i])
            return _Task(tensor_list)
        return out
    if _world(group) == 1:
        if tensor_list is not None:
            tensor_list.append(tensor)
            return _Task(tensor_list)
        from paddle_tpu.tensor.manipulation import unsqueeze

        return unsqueeze(tensor, 0)
    task = _process_group_for(group).allgather(tensor)
    gathered = Tensor(task.result())
    if tensor_list is not None:
        for i in range(gathered.shape[0]):
            tensor_list.append(gathered[i])
        return _Task(tensor_list)
    return gathered


def all_gather_object(object_list, obj, group=None):
    if _world(group) == 1:
        object_list.append(obj)
        return _Task(object_list)
    raise NotImplementedError(
        "this collective has no eager multi-controller path yet; run it "
        "inside the distributed step (axis mode) or use "
        "paddle_tpu.distributed.collective.ProcessGroup directly"
    )


def broadcast(tensor: Tensor, src=0, group=None, sync_op=True):
    _static_check("broadcast", tensor, group)
    ax = _axis_for(group)
    ax = _single_axis(ax, "broadcast")
    if ax is not None:
        src_in_group = src if group is None else group.get_group_rank(src)
        if src_in_group < 0:
            raise ValueError(f"src rank {src} is not a member of {group}")
        out = apply(
            "broadcast",
            lambda v: lax.all_gather(v, ax)[src_in_group],
            tensor,
        )
        tensor._bind(out._value)
        tensor._grad_node = out._grad_node
        tensor._out_index = out._out_index
        return _Task(tensor)
    if _world(group) == 1:
        return _Task(tensor)
    return _process_group_for(group).broadcast(tensor, src=src)


def reduce(tensor: Tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """All ranks reduce; only dst keeps the result (reference reduce).  In
    SPMD the masked variant costs the same as all_reduce."""
    _static_check("reduce", tensor, group)
    ax = _axis_for(group)
    ax = _single_axis(ax, "reduce")
    if ax is not None:
        red = _reduce_fn(op)
        dst_in_group = dst if group is None else group.get_group_rank(dst)
        if dst_in_group < 0:
            raise ValueError(f"dst rank {dst} is not a member of {group}")

        def f(v):
            s = red(v, ax)
            return jnp.where(lax.axis_index(ax) == dst_in_group, s, v)

        out = apply("reduce", f, tensor)
        tensor._bind(out._value)
        tensor._grad_node = out._grad_node
        tensor._out_index = out._out_index
        return _Task(tensor)
    if _world(group) == 1:
        return _Task(tensor)
    raise NotImplementedError(
        "this collective has no eager multi-controller path yet; run it "
        "inside the distributed step (axis mode) or use "
        "paddle_tpu.distributed.collective.ProcessGroup directly"
    )


def scatter(tensor: Tensor, tensor_list=None, src=0, group=None, sync_op=True):
    _static_check("scatter", tensor, group)
    ax = _axis_for(group)
    ax = _single_axis(ax, "scatter")
    if ax is not None:
        if tensor_list is None:
            raise ValueError("scatter needs tensor_list on src in axis mode")
        from paddle_tpu.tensor.manipulation import stack

        stacked = stack(tensor_list, axis=0)
        out = apply("scatter", lambda v: v[lax.axis_index(ax)], stacked)
        tensor._bind(out._value)
        tensor._grad_node = out._grad_node
        tensor._out_index = out._out_index
        return _Task(tensor)
    if _world(group) == 1:
        if tensor_list:
            tensor._bind(tensor_list[0]._value)
        return _Task(tensor)
    raise NotImplementedError(
        "this collective has no eager multi-controller path yet; run it "
        "inside the distributed step (axis mode) or use "
        "paddle_tpu.distributed.collective.ProcessGroup directly"
    )


def reduce_scatter(tensor: Tensor, tensor_or_tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    _static_check("reduce_scatter", tensor, group)
    ax = _axis_for(group)
    ax = _single_axis(ax, "reduce_scatter")
    if ax is not None:
        from paddle_tpu.tensor.manipulation import concat

        src = tensor_or_tensor_list
        if isinstance(src, (list, tuple)):
            src = concat(list(src), axis=0)
        out = apply(
            "reduce_scatter",
            lambda v: lax.psum_scatter(v, ax, scatter_dimension=0, tiled=True),
            src,
        )
        tensor._bind(out._value)
        tensor._grad_node = out._grad_node
        tensor._out_index = out._out_index
        return _Task(tensor)
    if _world(group) == 1:
        src = tensor_or_tensor_list
        if isinstance(src, (list, tuple)):
            src = src[0]
        tensor._bind(src._value)
        return _Task(tensor)
    raise NotImplementedError(
        "this collective has no eager multi-controller path yet; run it "
        "inside the distributed step (axis mode) or use "
        "paddle_tpu.distributed.collective.ProcessGroup directly"
    )


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    _static_check("alltoall", in_tensor_list[0] if in_tensor_list else None, group)
    ax = _axis_for(group)
    ax = _single_axis(ax, "alltoall")
    if ax is not None:
        from paddle_tpu.tensor.manipulation import stack

        stacked = stack(list(in_tensor_list), axis=0)  # [n, ...]
        out = apply(
            "alltoall",
            lambda v: lax.all_to_all(v, ax, split_axis=0, concat_axis=0, tiled=False),
            stacked,
        )
        for i in range(out.shape[0]):
            out_tensor_list.append(out[i])
        return _Task(out_tensor_list)
    if _world(group) == 1:
        out_tensor_list.extend(in_tensor_list)
        return _Task(out_tensor_list)
    raise NotImplementedError(
        "this collective has no eager multi-controller path yet; run it "
        "inside the distributed step (axis mode) or use "
        "paddle_tpu.distributed.collective.ProcessGroup directly"
    )


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None, out_split_sizes=None, group=None, sync_op=True):
    _static_check("alltoall_single", in_tensor, group)
    ax = _axis_for(group)
    ax = _single_axis(ax, "alltoall_single")
    if ax is not None:
        out = apply(
            "alltoall_single",
            lambda v: lax.all_to_all(v, ax, split_axis=0, concat_axis=0, tiled=True),
            in_tensor,
        )
        out_tensor._bind(out._value)
        out_tensor._grad_node = out._grad_node
        out_tensor._out_index = out._out_index
        return _Task(out_tensor)
    if _world(group) == 1:
        out_tensor._bind(in_tensor._value)
        return _Task(out_tensor)
    raise NotImplementedError(
        "this collective has no eager multi-controller path yet; run it "
        "inside the distributed step (axis mode) or use "
        "paddle_tpu.distributed.collective.ProcessGroup directly"
    )


def _p2p_impl(tensor, group, peer, is_send):
    ax = _axis_for(group)
    if ax is not None:
        raise NotImplementedError(
            "point-to-point inside SPMD programs is expressed with "
            "lax.ppermute (see fleet pipeline engine)"
        )
    if _world(group) == 1:
        return _Task(tensor)
    pg = _process_group_for(group)
    return pg.send(tensor, peer) if is_send else pg.recv(tensor, peer)


def send(tensor: Tensor, dst=0, group=None, sync_op=True):
    _static_check("p2p", tensor, group, peers_hint=sorted([_my_rank(), dst]))
    return _p2p_impl(tensor, group, dst, is_send=True)


def recv(tensor: Tensor, src=0, group=None, sync_op=True):
    _static_check("p2p", tensor, group, peers_hint=sorted([src, _my_rank()]))
    return _p2p_impl(tensor, group, src, is_send=False)


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group, sync_op=False)


def barrier(group=None):
    if _world(group) == 1:
        return _Task()
    from jax.experimental import multihost_utils

    from .watchdog import comm_watch

    with comm_watch("barrier", group=group):
        multihost_utils.sync_global_devices("paddle_tpu_barrier")
    return _Task()


def wait(tensor, group=None, use_calc_stream=True):
    """Stream-sync placeholder: XLA's async collectives are ordered by the
    compiler; block on the value instead (reference waits on comm stream).
    The block is watchdog-guarded: on a multi-host mesh a dead peer turns
    this wait into the visible hang (reference CommTask::IsTimeout)."""
    if isinstance(tensor, Tensor) and hasattr(tensor._value, "block_until_ready"):
        from .watchdog import comm_watch

        with comm_watch("wait", group=group):
            tensor._value.block_until_ready()
    return _Task(tensor)


def _next_obj_seq(store, kind, src, rank):
    """Store-allocated per-(kind, src, reader) sequence number.

    Living in the rendezvous store (not process memory), the counters
    survive elastic restarts, so a restarted rank resumes at the next
    unconsumed payload instead of silently re-reading generation-old
    pickles (the reference keys these exchanges off the store too:
    python/paddle/distributed/communication/serialization_utils.py).
    A reader that runs ahead of the writer blocks on get() and times
    out loudly rather than deserializing a stale value."""
    role = "src" if rank == src else f"r{rank}"
    return store.add(f"objseq/{kind}/{src}/{role}", 1)


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Gather to dst (reference: communication/gather.py).  In SPMD axis
    mode XLA collectives are rank-symmetric, so this lowers to all_gather
    (every rank materializes the list; dst semantics are free).  World-1:
    the local value."""
    if _world(group) == 1:
        if gather_list is not None:
            gather_list.append(tensor)
        return _Task(tensor)
    out = all_gather(gather_list if gather_list is not None else [], tensor, group)
    return out


def broadcast_object_list(object_list, src=0, group=None):
    """reference: communication/broadcast.py broadcast_object_list — pickled
    host objects ride the rendezvous store (control plane), not ICI."""
    if _world(group) == 1:
        return _Task(object_list)
    import pickle

    from paddle_tpu.distributed import env as _env
    from .watchdog import get_rendezvous_store

    store = get_rendezvous_store()
    if store is None:
        raise RuntimeError("broadcast_object_list needs a rendezvous store (set_rendezvous_store/launch) outside world-1")
    rank = _env.get_rank()
    key = f"bcast_obj/{src}/{_next_obj_seq(store, 'bcast', src, rank)}"
    if rank == src:
        store.set(key, pickle.dumps(list(object_list)))
    else:
        payload = pickle.loads(store.get(key))
        object_list[:] = payload
    return _Task(object_list)


def scatter_object_list(out_object_list, in_object_list=None, src=0, group=None):
    """reference: communication/scatter.py scatter_object_list."""
    if _world(group) == 1:
        out_object_list[:] = [in_object_list[0] if in_object_list else None]
        return _Task(out_object_list)
    import pickle

    from paddle_tpu.distributed import env as _env
    from .watchdog import get_rendezvous_store

    store = get_rendezvous_store()
    if store is None:
        raise RuntimeError("scatter_object_list needs a rendezvous store (set_rendezvous_store/launch) outside world-1")
    rank, world = _env.get_rank(), _env.get_world_size()
    key = f"scatter_obj/{src}/{_next_obj_seq(store, 'scatter', src, rank)}"
    if rank == src:
        store.set(key, pickle.dumps(list(in_object_list)))
        out_object_list[:] = [in_object_list[rank]]
    else:
        payload = pickle.loads(store.get(key))
        out_object_list[:] = [payload[rank]]
    return _Task(out_object_list)


def get_backend(group=None):
    """reference: communication/group.py get_backend — the comm transport.
    XLA collectives ride ICI/DCN via the jax backend; report it."""
    return "xla:" + jax.default_backend()


__all__ += ["gather", "broadcast_object_list", "scatter_object_list", "get_backend"]
