"""Comm watchdog + cross-rank consistency checks.

Reference: every async NCCL collective is wrapped in a CommTask with
IsTimeout/AbortComm (paddle/phi/core/distributed/comm_task.h:127,147),
monitored by a background CommTaskManager (comm_task_manager.h:37); static
and on-device dynamic cross-rank shape/dtype checks live in
paddle/phi/core/distributed/check/{static_check,nccl_dynamic_check}.cc.

TPU-native redesign: collectives are compiled into SPMD programs, so a hung
collective shows up as a host thread blocked in a device wait (a missing /
crashed peer host never arrives at the XLA collective).  The watchdog
therefore wraps the HOST blocking points — barriers, rendezvous waits,
compiled-step executions on multi-host meshes — in `comm_watch(...)`
contexts tracked by a daemon CommTaskManager that logs a loud diagnostic
(task name, group, elapsed, creation stack) when a task exceeds its timeout
and optionally aborts the process so a stuck multi-host job fails fast
instead of hanging silently.

Cross-rank static checks (`static_check`) exchange a shape/dtype digest
through the rendezvous TCPStore before a collective (enabled via
FLAGS_check_collective_shapes) — the analog of static_check.cc, catching
mismatched-shape collective calls across ranks at the API layer since
mismatches inside a compiled SPMD program are impossible by construction.
"""

from __future__ import annotations

import os
import threading
import time
import traceback

__all__ = [
    "CommTask",
    "CommTaskManager",
    "comm_watch",
    "static_check",
    "default_timeout",
    "set_rendezvous_store",
    "get_rendezvous_store",
]

_DEFAULT_TIMEOUT = float(os.environ.get("FLAGS_comm_timeout_s", "1800"))


def default_timeout() -> float:
    return _DEFAULT_TIMEOUT


class CommTask:
    __slots__ = ("name", "group_desc", "timeout", "started", "done", "stack", "reported")

    def __init__(self, name, group_desc, timeout):
        self.name = name
        self.group_desc = group_desc
        self.timeout = timeout
        self.started = time.monotonic()
        self.done = False
        self.reported = False
        # frame summaries only; formatting happens in the timeout report
        # (this runs on every watched wait — keep it cheap)
        self.stack = traceback.extract_stack(limit=12)

    def is_timeout(self) -> bool:
        return not self.done and (time.monotonic() - self.started) > self.timeout

    def elapsed(self) -> float:
        return time.monotonic() - self.started


class CommTaskManager:
    """Background scanner (reference comm_task_manager.h:37)."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self, scan_interval=2.0):
        self._tasks: list[CommTask] = []
        self._tasks_lock = threading.Lock()
        self._interval = scan_interval
        self._thread = None

    @classmethod
    def instance(cls) -> "CommTaskManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name="paddle_tpu_comm_watchdog", daemon=True
            )
            self._thread.start()

    def register(self, task: CommTask):
        with self._tasks_lock:
            self._tasks.append(task)
        self._ensure_thread()

    def complete(self, task: CommTask):
        task.done = True
        with self._tasks_lock:
            try:
                self._tasks.remove(task)
            except ValueError:
                pass

    def _loop(self):
        import sys

        while True:
            time.sleep(self._interval)
            with self._tasks_lock:
                tasks = list(self._tasks)
            for t in tasks:
                if t.is_timeout() and not t.reported:
                    t.reported = True
                    msg = (
                        f"\n[paddle_tpu comm watchdog] collective task "
                        f"'{t.name}' (group={t.group_desc}) has been blocked "
                        f"for {t.elapsed():.0f}s (timeout {t.timeout:.0f}s) — "
                        f"a peer rank is likely hung or dead.\nTask created at:\n"
                        + "".join(traceback.format_list(t.stack[:-1]))
                    )
                    print(msg, file=sys.stderr, flush=True)
                    if os.environ.get("FLAGS_comm_timeout_abort", "0") in ("1", "true", "True"):
                        print(
                            "[paddle_tpu comm watchdog] FLAGS_comm_timeout_abort "
                            "set: aborting process.",
                            file=sys.stderr,
                            flush=True,
                        )
                        os._exit(124)


class comm_watch:
    """Context manager guarding one blocking comm operation."""

    def __init__(self, name, group=None, timeout=None):
        desc = "world"
        if group is not None:
            desc = getattr(group, "_name", None) or f"ranks={getattr(group, 'ranks', '?')}"
        self._task = CommTask(name, desc, timeout if timeout is not None else _DEFAULT_TIMEOUT)

    def __enter__(self):
        CommTaskManager.instance().register(self._task)
        return self._task

    def __exit__(self, *exc):
        CommTaskManager.instance().complete(self._task)
        return False


# -------------------------------------------------------------------------
# cross-rank static checks
# -------------------------------------------------------------------------

_store = None
_check_seq: dict = {}  # (op_name, group_id) -> sequence counter


def set_rendezvous_store(store):
    """Called by the launcher / init_parallel_env with the TCPStore client."""
    global _store
    _store = store


def get_rendezvous_store():
    return _store


def _checks_enabled() -> bool:
    try:
        from paddle_tpu._core import flags

        if flags.flag("FLAGS_check_collective_shapes"):
            return True
    except Exception:
        pass
    return os.environ.get("FLAGS_check_collective_shapes", "0") in ("1", "true", "True")


def static_check(op_name, tensor, group=None, rank=None, world=None, timeout=30.0, peers_hint=None):
    """Exchange (shape, dtype) digests through the store; raise on mismatch.

    Reference static_check.cc CheckShape/CheckDataType.  No-op unless
    FLAGS_check_collective_shapes is set and a store + multi-process world
    exist.  Scoped to the GROUP's ranks (keys carry the group id and a
    per-(op, group) sequence number so unrelated collectives never compare).
    """
    if not _checks_enabled() or _store is None:
        return
    import jax

    rank = jax.process_index() if rank is None else rank
    if peers_hint is not None:
        # point-to-point: exactly the two endpoints compare, keyed by pair
        peers = sorted(set(int(r) for r in peers_hint))
        gid = "p2p_" + "_".join(str(r) for r in peers)
        if rank not in peers:
            return
    elif group is not None:
        if getattr(group, "mesh", None) is not None:
            # mesh-axis group: the collective compiles into one SPMD program
            # where cross-rank shape mismatch is impossible by construction,
            # and group.ranks are axis-local indices (not process ranks)
            return
        peers = list(getattr(group, "ranks", []) or [])
        gid = getattr(group, "id", None)
        gid = "g" if gid is None else gid
        if peers and rank not in peers:
            return  # this process doesn't participate
    else:
        world = jax.process_count() if world is None else world
        peers = list(range(world))
        gid = "world"
    if len(peers) <= 1 or tensor is None:
        return
    if isinstance(tensor, (list, tuple)):
        if not tensor:
            return
        tensor = tensor[0]
    v = tensor._value if hasattr(tensor, "_value") else tensor
    digest = f"{tuple(v.shape)}|{v.dtype}"
    seq_key = (op_name, gid)
    _check_seq.setdefault(seq_key, 0)
    _check_seq[seq_key] += 1
    seq = _check_seq[seq_key]
    key = f"ccheck/{gid}/{op_name}/{seq}/{rank}"
    _store.set(key, digest.encode())
    for r in peers:
        if r == rank:
            continue
        k = f"ccheck/{gid}/{op_name}/{seq}/{r}"
        try:
            # native TCPStoreClient.get blocks server-side up to timeout_ms
            try:
                other = _store.get(k, timeout_ms=int(timeout * 1000))
            except TypeError:
                other = _store.get(k)
        except (TimeoutError, KeyError):
            raise TimeoutError(
                f"static_check: rank {r} never published its shape/dtype "
                f"for {op_name} (seq {seq})"
            )
        if isinstance(other, str):
            other = other.encode()
        if other.decode() != digest:
            raise RuntimeError(
                f"cross-rank mismatch in {op_name}: rank {rank} has {digest}, "
                f"rank {r} has {other.decode()} — collective would deadlock "
                f"or corrupt data"
            )
