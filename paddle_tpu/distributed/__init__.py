"""paddle.distributed surface (reference: python/paddle/distributed/__init__.py).

TPU-native architecture: SPMD-first.  Collectives compile into XLA programs
over the device mesh (ICI/DCN); jax.distributed is the coordination service.
The imperative ProcessGroup-style API is provided on top of compiled
collective executables (see communication.py) for Fleet-style code.
"""

from . import fleet  # noqa: F401
from . import auto_parallel  # noqa: F401
from .auto_parallel import (  # noqa: F401
    Partial,
    Placement,
    ProcessMesh,
    Replicate,
    Shard,
    dtensor_from_fn,
    get_mesh,
    reshard,
    set_mesh,
    shard_layer,
    shard_tensor,
    unshard_dtensor,
)
from .sharded_step import ShardedTrainStep, shard_batch  # noqa: F401
from . import communication  # noqa: F401
from .communication import (  # noqa: F401
    Group,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    alltoall,
    alltoall_single,
    barrier,
    broadcast,
    destroy_process_group,
    get_group,
    irecv,
    is_available,
    isend,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    wait,
)
from . import sharding  # noqa: F401
from . import checkpoint  # noqa: F401
from . import utils  # noqa: F401
from . import launch  # noqa: F401
from . import rpc  # noqa: F401
from . import auto_tuner  # noqa: F401
from .parallel import DataParallel  # noqa: F401
from .env import (  # noqa: F401
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
    is_initialized,
)

# compat tier: enums, split, shard_optimizer, DistModel bridge, spawn,
# gloo trio, PS entry configs (reference __all__ closure)
from .compat import (  # noqa: F401,E402
    CountFilterEntry,
    DistAttr,
    ParallelMode,
    ProbabilityEntry,
    ReduceType,
    ShowClickEntry,
    gloo_barrier,
    gloo_init_parallel_env,
    gloo_release,
    shard_optimizer,
    spawn,
    split,
    to_static,
)
from .communication.ops import (  # noqa: F401,E402
    broadcast_object_list,
    gather,
    get_backend,
    scatter_object_list,
)
from . import io  # noqa: F401,E402
from .auto_parallel.engine import Strategy  # noqa: F401,E402
from .checkpoint import (CheckpointManager, load_state_dict,  # noqa: F401,E402
                         save_state_dict)
from paddle_tpu.io import InMemoryDataset, QueueDataset  # noqa: F401,E402
