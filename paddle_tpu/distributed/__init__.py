"""paddle.distributed surface (reference: python/paddle/distributed/__init__.py).

TPU-native architecture: SPMD-first.  Collectives compile into XLA programs
over the device mesh (ICI/DCN); jax.distributed is the coordination service.
The imperative ProcessGroup-style API is provided on top of compiled
collective executables (see communication.py) for Fleet-style code.
"""

from . import fleet  # noqa: F401
from .env import (  # noqa: F401
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
    is_initialized,
)
