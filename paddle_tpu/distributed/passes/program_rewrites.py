"""Program-REWRITING distributed passes: recompute, gradient-merge, sharding.

Reference: python/paddle/distributed/passes/auto_parallel_recompute.py
(re-inserts forward subgraphs before their grad ops),
auto_parallel_gradient_merge.py (accumulator vars + k-step conditional
apply rewritten into the main program), auto_parallel_sharding.py
(partitions param/grad/opt-state vars over the sharding group and inserts
the matching collectives).

TPU-native mechanics over the static Program IR (static/program.py): a
captured training step has a recognizable spine —

    forward ops ... -> [grad super-op] -> [optimizer_update super-op]
                                            writes: param/acc <- outputs

so each pass is a genuine transform of that op list:

- **RecomputeProgramRewrite** splits the forward into segments, replaces
  each with ONE composite op running the segment under `jax.checkpoint`,
  and REBUILDS the grad super-op over the transformed prefix (its fn closes
  over a snapshot of the op list, so rewriting the forward alone would not
  change the backward) — jax.grad through the checkpointed composites then
  rematerializes instead of storing segment interiors.
- **GradientMergeProgramRewrite** adds counter/accumulator STATE variables
  to the program, inserts an accumulate op after the grad op, and wraps
  optimizer_update in a lax.cond that applies the (averaged) merged grads
  only on every k-th step.
- **ShardingProgramRewrite** wraps the grad/optimizer_update outputs in
  `with_sharding_constraint` over the sharding axis (ZeRO: stage 1 = opt
  state, stage 2 = + grads, stage 3 = + params) so GSPMD partitions the
  update dataflow when the program runs under a mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "RecomputeProgramRewrite",
    "GradientMergeProgramRewrite",
    "ShardingProgramRewrite",
]


def _base_type(t):
    """Strip pass-inserted namespaces ('zero::gradient_merge::optimizer_update'
    -> 'optimizer_update') so the rewrites COMPOSE in any order."""
    return t.rsplit("::", 1)[-1]


def _find_superops(program):
    """(forward_ops, grad_op, update_op) — grad/update may be None; matched
    by base type so already-rewritten (namespaced) super-ops still anchor."""
    ops = program.global_block().ops
    grad_i = next((i for i, op in enumerate(ops) if _base_type(op.type) == "grad"), None)
    upd_i = next((i for i, op in enumerate(ops)
                  if _base_type(op.type) == "optimizer_update"), None)
    fwd_end = grad_i if grad_i is not None else len(ops)
    return (
        list(ops[:fwd_end]),
        ops[grad_i] if grad_i is not None else None,
        ops[upd_i] if upd_i is not None else None,
    )


def _run_ops(ops, env):
    for op in ops:
        var_vals = [env[s[1]] for s in op.arg_spec if s[0] == "var"]
        out = op.fn(*var_vals)
        for vid, v in zip(op.out_vids, jax.tree_util.tree_leaves(out)):
            env[vid] = v
    return env


def _tuple_tree(n):
    return jax.tree_util.tree_structure(tuple(range(max(n, 1))))


def _make_segment_op(seg_ops, keep_vids, type_):
    """One composite Operator replacing `seg_ops`, emitting only the segment
    outputs in `keep_vids` (interior activations die — that is the point)."""
    from paddle_tpu.static.program import Operator

    produced = {vid for op in seg_ops for vid in op.out_vids}
    in_vids = []
    for op in seg_ops:
        for vid in op.input_vids():
            if vid not in produced and vid not in in_vids:
                in_vids.append(vid)
    out_vids = [vid for op in seg_ops for vid in op.out_vids if vid in keep_vids]

    def seg_fn(*vals):
        env = _run_ops(seg_ops, dict(zip(in_vids, vals)))
        return tuple(env[vid] for vid in out_vids)

    return Operator(
        type=type_,
        fn=jax.checkpoint(seg_fn),
        arg_spec=[("var", vid) for vid in in_vids],
        kwargs={},
        out_vids=out_vids,
        out_tree=_tuple_tree(len(out_vids)),
    )


class RecomputeProgramRewrite:
    """Reference auto_parallel_recompute.py as a Program transform.

    `segments`: number of equal checkpointed chunks the forward is cut
    into.  `fetch_vids`: vars the caller will fetch (they must survive as
    segment outputs; the grad target and all write/late-op inputs are kept
    automatically)."""

    def __init__(self, segments=2, fetch_vids=()):
        if segments < 1:
            raise ValueError("segments must be >= 1")
        self.segments = int(segments)
        self.fetch_vids = tuple(fetch_vids)

    def apply(self, program) -> int:
        from paddle_tpu.static.autodiff import build_grad_fn
        from paddle_tpu.static.program import Operator

        fwd_ops, grad_op, _upd = _find_superops(program)
        if len(fwd_ops) < 2:
            return 0
        block = program.global_block()
        post_ops = block.ops[len(fwd_ops):]

        # values that must survive the rewrite
        keep = set(self.fetch_vids)
        keep.update(program.writes.keys())
        keep.update(program.writes.values())
        for op in post_ops:
            keep.update(op.input_vids())
        if grad_op is not None and getattr(grad_op, "grad_meta", None):
            keep.add(grad_op.grad_meta["target_vid"])
        # the loss itself (last forward op's outputs) stays fetchable
        keep.update(fwd_ops[-1].out_vids)

        # cut into `segments` chunks; a chunk's outputs consumed by a LATER
        # chunk must also survive as composite outputs
        n = min(self.segments, len(fwd_ops))
        bounds = [round(i * len(fwd_ops) / n) for i in range(n + 1)]
        chunks = [fwd_ops[bounds[i]:bounds[i + 1]] for i in range(n)]
        chunks = [c for c in chunks if c]
        new_fwd = []
        for ci, chunk in enumerate(chunks):
            later_needs = set(keep)
            for later in chunks[ci + 1:]:
                for op in later:
                    later_needs.update(op.input_vids())
            new_fwd.append(_make_segment_op(chunk, later_needs, "recompute::segment"))

        block.ops = new_fwd + list(post_ops)
        program.version += 1

        # rebuild the grad super-op over the checkpointed prefix
        if grad_op is not None and getattr(grad_op, "grad_meta", None):
            meta = grad_op.grad_meta
            fn = build_grad_fn(program, meta["target_vid"], meta["wrt_vids"],
                               meta["in_vids"], ops=new_fwd)
            constraints = getattr(grad_op, "sharding_constraints", None)
            if constraints:
                # sharding ran first: re-apply its output constraints so
                # recompute-after-sharding keeps ZeRO gradient placement
                inner = fn

                def fn(*vals, _inner=inner, _cs=constraints):
                    flat = list(jax.tree_util.tree_leaves(_inner(*vals)))
                    for pos, sh in _cs.items():
                        flat[pos] = jax.lax.with_sharding_constraint(flat[pos], sh)
                    return tuple(flat)

            idx = block.ops.index(grad_op)
            new_grad = Operator(grad_op.type, fn, grad_op.arg_spec,
                                grad_op.kwargs, grad_op.out_vids, grad_op.out_tree)
            new_grad.grad_meta = dict(meta)
            if constraints:
                new_grad.sharding_constraints = dict(constraints)
            block.ops[idx] = new_grad
        return len(new_fwd)


class GradientMergeProgramRewrite:
    """Reference auto_parallel_gradient_merge.py as a Program transform."""

    def __init__(self, k_steps=2, avg=True):
        if k_steps < 1:
            raise ValueError("k_steps must be >= 1")
        self.k = int(k_steps)
        self.avg = bool(avg)

    def apply(self, program) -> int:
        from paddle_tpu.static.program import Operator

        if self.k == 1:
            return 0
        _fwd, grad_op, upd_op = _find_superops(program)
        if grad_op is None or upd_op is None:
            raise ValueError(
                "gradient-merge rewrite needs a captured training step "
                "(grad + optimizer_update super-ops); got neither — build "
                "the program with optimizer.minimize(loss)")
        block = program.global_block()
        meta = getattr(grad_op, "grad_meta", None)
        # grad-op outputs are (grads..., loss): accumulate only the grads
        n_grads = len(meta["wrt_vids"]) if meta else len(grad_op.out_vids)
        grad_vids = list(grad_op.out_vids[:n_grads])
        k, avg = self.k, self.avg

        # ---- new state: step counter + one accumulator per gradient
        counter = program.new_var(jax.ShapeDtypeStruct((), jnp.int32), "gm_counter",
                                  persistable=True)
        program.param_inits[counter._vid] = jnp.zeros((), jnp.int32)
        acc_vars = []
        for i, gvid in enumerate(grad_vids):
            gvar = program._var_by_vid[gvid]
            acc = program.new_var(
                jax.ShapeDtypeStruct(gvar._value.shape, gvar._value.dtype),
                f"gm_acc_{i}", persistable=True)
            program.param_inits[acc._vid] = jnp.zeros(gvar._value.shape,
                                                      gvar._value.dtype)
            acc_vars.append(acc)

        # ---- accumulate op: inserted right after the grad op
        n_g = len(grad_vids)

        def acc_fn(counter_val, *rest):
            accs, grads = rest[:n_g], rest[n_g:]
            new_accs = tuple(a + g for a, g in zip(accs, grads))
            nxt = counter_val + jnp.int32(1)
            boundary = (nxt % jnp.int32(k)) == 0
            merged = tuple((a / jnp.asarray(k, a.dtype)) if avg else a
                           for a in new_accs)
            kept = tuple(jnp.where(boundary, jnp.zeros_like(a), a)
                         for a in new_accs)
            new_counter = jnp.where(boundary, jnp.int32(0), nxt)
            return (new_counter, boundary) + kept + merged

        new_counter = program.new_var(jax.ShapeDtypeStruct((), jnp.int32), "gm_counter_next")
        boundary = program.new_var(jax.ShapeDtypeStruct((), jnp.bool_), "gm_boundary")
        kept_vars = [
            program.new_var(jax.ShapeDtypeStruct(a._value.shape, a._value.dtype),
                            f"gm_kept_{i}")
            for i, a in enumerate(acc_vars)
        ]
        merged_vars = [
            program.new_var(jax.ShapeDtypeStruct(a._value.shape, a._value.dtype),
                            f"gm_merged_{i}")
            for i, a in enumerate(acc_vars)
        ]
        out_vids = ([new_counter._vid, boundary._vid]
                    + [v._vid for v in kept_vars] + [v._vid for v in merged_vars])
        acc_op = Operator(
            "gradient_merge::accumulate", acc_fn,
            [("var", counter._vid)] + [("var", v._vid) for v in acc_vars]
            + [("var", vid) for vid in grad_vids],
            {}, out_vids, _tuple_tree(len(out_vids)),
        )
        gi = block.ops.index(grad_op)
        block.ops.insert(gi + 1, acc_op)
        program.add_write(counter, new_counter)
        for a, kpt in zip(acc_vars, kept_vars):
            program.add_write(a, kpt)

        # ---- conditional optimizer update: grads -> merged, under lax.cond
        grad_set = set(grad_vids)
        merged_by_grad = dict(zip(grad_vids, (v._vid for v in merged_vars)))
        grad_pos = [i for i, s in enumerate(upd_op.arg_spec)
                    if s[0] == "var" and s[1] in grad_set]
        if not grad_pos:
            raise ValueError("optimizer_update does not consume the grad vars")
        first_g, last_g = grad_pos[0], grad_pos[-1]
        orig_fn = upd_op.fn
        n_out = len(upd_op.out_vids)

        def cond_update(boundary_val, *vals):
            def apply(vs):
                return tuple(orig_fn(*vs))

            def skip(vs):
                olds = vs[:first_g] + vs[last_g + 1:]  # params + accs
                return tuple(olds[:n_out])

            return jax.lax.cond(boundary_val, apply, skip, vals)

        new_spec = [("var", boundary._vid)] + [
            ("var", merged_by_grad.get(s[1], s[1])) if s[0] == "var" else s
            for s in upd_op.arg_spec
        ]
        ui = block.ops.index(upd_op)
        block.ops[ui] = Operator(
            "gradient_merge::" + upd_op.type, cond_update, new_spec,
            upd_op.kwargs, upd_op.out_vids, upd_op.out_tree,
        )
        program.version += 1
        return 2


class ShardingProgramRewrite:
    """Reference auto_parallel_sharding.py as a Program transform: ZeRO
    stage-N sharding constraints on the update dataflow (GSPMD inserts the
    reduce-scatter/all-gather collectives when the program runs in a mesh).
    """

    def __init__(self, mesh, stage=1, axis="dp"):
        from jax.sharding import Mesh

        from paddle_tpu.distributed.auto_parallel import ProcessMesh

        if isinstance(mesh, ProcessMesh):
            mesh = mesh.jax_mesh
        if not isinstance(mesh, Mesh):
            raise TypeError(f"mesh must be a jax Mesh/ProcessMesh, got {type(mesh)}")
        if stage not in (1, 2, 3):
            raise ValueError("stage must be 1, 2 or 3")
        if axis not in mesh.axis_names:
            raise ValueError(f"axis {axis!r} not in mesh axes {mesh.axis_names}")
        self.mesh = mesh
        self.stage = int(stage)
        self.axis = axis

    def _spec_for(self, shape):
        from jax.sharding import NamedSharding, PartitionSpec

        size = self.mesh.shape[self.axis]
        if shape and shape[0] % size == 0 and shape[0] >= size:
            return NamedSharding(self.mesh, PartitionSpec(self.axis))
        return None  # indivisible leading dim: leave replicated

    def _constrain_outputs(self, program, op, positions, new_type,
                           barrier_inputs=False):
        """Wrap op.fn so selected flat outputs carry sharding constraints.

        barrier_inputs ties all inputs together with an optimization
        barrier before the op computes: the ZeRO reshard collectives this
        op's constraints introduce must not interleave with collectives
        still in flight from the producing chain (pipeline ppermutes) —
        XLA:CPU's in-process communicator deadlocks on such cross-chain
        overlap, and on TPU the barrier costs nothing measurable next to
        the update itself."""
        from paddle_tpu.static.program import Operator

        shardings = {}
        for pos in positions:
            var = program._var_by_vid.get(op.out_vids[pos])
            if var is None:
                continue
            s = self._spec_for(tuple(var._value.shape))
            if s is not None:
                shardings[pos] = s
        if not shardings:
            return None
        orig_fn = op.fn

        def fn(*vals):
            if barrier_inputs and vals:
                vals = jax.lax.optimization_barrier(tuple(vals))
            out = orig_fn(*vals)
            flat = list(jax.tree_util.tree_leaves(out))
            for pos, sh in shardings.items():
                flat[pos] = jax.lax.with_sharding_constraint(flat[pos], sh)
            return tuple(flat)

        new_op = Operator(new_type, fn, op.arg_spec, op.kwargs,
                          op.out_vids, _tuple_tree(len(op.out_vids)))
        if getattr(op, "grad_meta", None):
            new_op.grad_meta = dict(op.grad_meta)
        # later passes that rebuild this op's fn (recompute) re-apply these
        new_op.sharding_constraints = dict(shardings)
        return new_op

    def apply(self, program) -> int:
        _fwd, grad_op, upd_op = _find_superops(program)
        if upd_op is None:
            raise ValueError(
                "sharding rewrite needs an optimizer_update super-op — "
                "build the program with optimizer.minimize(loss)")
        block = program.global_block()
        changed = 0

        # stage >= 1: optimizer state (accumulator outputs) sharded.
        # update outputs are (new_params..., new_accs...): accs are the
        # outputs whose vids are written to non-parameter state vars.
        param_vids = {v._vid for v in program.all_parameters()}
        write_to_target = {src: tgt for tgt, src in program.writes.items()}
        acc_pos, param_pos = [], []
        for i, vid in enumerate(upd_op.out_vids):
            tgt = write_to_target.get(vid)
            if tgt is None:
                continue
            (param_pos if tgt in param_vids else acc_pos).append(i)
        positions = list(acc_pos)
        if self.stage >= 3:
            positions += param_pos
        new_upd = self._constrain_outputs(program, upd_op, positions,
                                          "zero::" + upd_op.type,
                                          barrier_inputs=True)
        if new_upd is not None:
            block.ops[block.ops.index(upd_op)] = new_upd
            changed += 1

        # stage >= 2: gradients sharded too (GSPMD then materializes the
        # reduce-scatter form of the DP gradient sync)
        if self.stage >= 2 and grad_op is not None:
            new_grad = self._constrain_outputs(
                program, grad_op, range(len(grad_op.out_vids)),
                "zero::" + grad_op.type)
            if new_grad is not None:
                block.ops[block.ops.index(grad_op)] = new_grad
                changed += 1
        program.version += 1
        return changed
