"""Distributed program passes.

Reference: python/paddle/distributed/passes/ (22 files) — PassBase/
PassManager over static Programs: auto_parallel_amp, auto_parallel_fp16,
auto_parallel_recompute, auto_parallel_sharding, auto_parallel_grad_clip,
auto_parallel_gradient_merge, pipeline_scheduler_pass (FThenB/1F1B/VPP).

TPU-native redesign: there is no per-rank ProgramDesc to rewrite — the
"program" is (model, optimizer, step options) that jit compiles as one
piece, so a pass is a TRANSFORMATION OF THAT TRIPLE applied before
compilation.  The pass surface (names, ordering, PassManager) matches the
reference so strategy configs port over; the mechanics are the framework's
native features (amp.decorate, recompute wrappers, ZeRO accumulator
sharding, GradientMergeOptimizer, PipelineStack schedules).
"""

from __future__ import annotations

__all__ = ["PassBase", "PassManager", "PassContext", "new_pass", "register_pass"]

_PASS_REGISTRY: dict = {}


class PassContext:
    """What a pass may transform: the (model, optimizer, attrs) triple."""

    def __init__(self, model=None, optimizer=None, **attrs):
        self.model = model
        self.optimizer = optimizer
        self.attrs = dict(attrs)


class PassBase:
    name = "base"

    def __init__(self, **attrs):
        self.attrs = dict(attrs)

    def check(self, ctx: PassContext) -> bool:
        return True

    def apply(self, ctx: PassContext) -> PassContext:
        raise NotImplementedError


def register_pass(name):
    def deco(cls):
        cls.name = name
        _PASS_REGISTRY[name] = cls
        return cls

    return deco


def new_pass(name, attrs=None):
    if name not in _PASS_REGISTRY:
        raise ValueError(f"unknown pass {name!r}; registered: {sorted(_PASS_REGISTRY)}")
    return _PASS_REGISTRY[name](**(attrs or {}))


class PassManager:
    def __init__(self, passes):
        self._passes = list(passes)

    @property
    def names(self):
        return [p.name for p in self._passes]

    def apply(self, ctx_or_model, optimizer=None, **attrs):
        ctx = ctx_or_model if isinstance(ctx_or_model, PassContext) else PassContext(ctx_or_model, optimizer, **attrs)
        for p in self._passes:
            if p.check(ctx):
                ctx = p.apply(ctx) or ctx
        return ctx


# --------------------------------------------------------------- the passes


@register_pass("auto_parallel_amp")
class AmpPass(PassBase):
    """O1 autocast around the step (reference auto_parallel_amp.py)."""

    def apply(self, ctx):
        ctx.attrs["amp_level"] = self.attrs.get("level", "O1")
        ctx.attrs["amp_dtype"] = self.attrs.get("dtype", "bfloat16")
        ctx.attrs["amp_enabled"] = True
        return ctx


class Fp16ProgramRewrite:
    """TRUE program transform (reference auto_parallel_fp16.py rewrites the
    ProgramDesc op-by-op inserting casts): every white-listed Operator in a
    captured Program is replaced by an `fp16::`-prefixed clone whose body
    casts float32 variable inputs to the low dtype, computes there, and
    casts the result back — the Variable avals (and so every consumer) are
    untouched, XLA fuses the cast pairs into the surrounding ops."""

    WHITE = {"matmul", "mm", "bmm", "mv", "addmm", "einsum", "linear",
             "conv2d", "conv1d", "conv3d", "flash_attention"}

    def __init__(self, dtype="bfloat16"):
        self.dtype = dtype

    def apply(self, program) -> int:
        import jax
        import jax.numpy as jnp

        from paddle_tpu._core.dtype import to_jax_dtype
        from paddle_tpu.static.program import Operator

        low = to_jax_dtype(self.dtype)
        block = program.global_block()
        n = 0
        for i, op in enumerate(list(block.ops)):
            if op.type not in self.WHITE:
                continue

            def make(fn, low=low):
                def wrapped(*vals):
                    downcast = False
                    cast = []
                    for v in vals:
                        if hasattr(v, "dtype") and v.dtype == jnp.float32:
                            cast.append(v.astype(low))
                            downcast = True
                        else:
                            cast.append(v)
                    out = fn(*cast)
                    if not downcast:
                        # natively-low-precision program: outputs keep their
                        # recorded avals — no silent fp32 upcast
                        return out
                    return jax.tree_util.tree_map(
                        lambda o: o.astype(jnp.float32)
                        if hasattr(o, "dtype") and o.dtype == low
                        else o,
                        out,
                    )

                return wrapped

            clone = Operator(
                "fp16::" + op.type, make(op.fn), op.arg_spec, op.kwargs,
                op.out_vids, op.out_tree,
            )
            # later fusion patterns read this to keep their replacement
            # kernels in the low dtype (the type prefix alone doesn't say
            # WHICH low dtype was chosen)
            clone.fp16_low = low
            block.ops[i] = clone
            n += 1
        if n:
            program.version += 1
        return n


@register_pass("auto_parallel_fp16")
class Fp16Pass(PassBase):
    """Dual-mode like the reference pass family: given a captured Program
    (attrs main_program) it REWRITES it (Fp16ProgramRewrite cast
    insertion); given the (model, optimizer) triple it decorates params to
    the low dtype with the optimizer keeping fp32 masters
    (auto_parallel_fp16.py + mix_precision_utils)."""

    def apply(self, ctx):
        dtype = self.attrs.get("dtype", "bfloat16")
        prog = ctx.attrs.get("main_program")
        if prog is not None:
            ctx.attrs["fp16_rewritten_ops"] = Fp16ProgramRewrite(dtype).apply(prog)
            ctx.attrs["amp_level"] = "O1"
            ctx.attrs["amp_dtype"] = dtype
            return ctx
        from paddle_tpu import amp

        amp.decorate(ctx.model, level="O2", dtype=dtype)
        ctx.attrs["amp_level"] = "O2"
        ctx.attrs["amp_dtype"] = dtype
        return ctx


@register_pass("auto_parallel_recompute")
class RecomputePass(PassBase):
    """Dual-mode (reference auto_parallel_recompute.py): given a captured
    Program it REWRITES it — forward segments become jax.checkpoint
    composites and the grad super-op is rebuilt over them
    (program_rewrites.RecomputeProgramRewrite); given the (model, optimizer)
    triple it wraps the named sublayers in eager recompute."""

    def apply(self, ctx):
        prog = ctx.attrs.get("main_program")
        if prog is not None:
            from .program_rewrites import RecomputeProgramRewrite

            ctx.attrs["recompute_segments"] = RecomputeProgramRewrite(
                segments=int(self.attrs.get("segments", 2)),
                fetch_vids=self.attrs.get("fetch_vids", ()),
            ).apply(prog)
            return ctx
        from paddle_tpu.distributed.fleet.recompute import recompute_wrap

        targets = self.attrs.get("layers")
        model = ctx.model
        if targets is None and hasattr(model, "config") and hasattr(model.config, "use_recompute"):
            model.config.use_recompute = True
            return ctx
        for name in targets or []:
            sub = model
            parts = name.split(".")
            for p_ in parts[:-1]:
                sub = getattr(sub, p_)
            setattr(sub, parts[-1], recompute_wrap(getattr(sub, parts[-1])))
        return ctx


@register_pass("auto_parallel_sharding")
class ShardingPass(PassBase):
    """Dual-mode (reference auto_parallel_sharding.py): given a captured
    Program (+ mesh attr) it REWRITES the update dataflow with ZeRO
    sharding constraints (program_rewrites.ShardingProgramRewrite); given
    the triple it sets the accumulator-sharding policy ShardedTrainStep
    reads."""

    def apply(self, ctx):
        stage = int(self.attrs.get("stage", 1))
        prog = ctx.attrs.get("main_program")
        mesh = self.attrs.get("mesh") or ctx.attrs.get("mesh")
        if prog is not None and mesh is None:
            # never silently change modes: a program without a mesh is a
            # misconfiguration, not a request for the eager-policy branch
            raise ValueError(
                "auto_parallel_sharding on a captured Program needs a "
                "'mesh' attr (jax Mesh or ProcessMesh)")
        if prog is not None:
            from .program_rewrites import ShardingProgramRewrite

            ctx.attrs["sharding_rewritten_ops"] = ShardingProgramRewrite(
                mesh, stage=stage, axis=self.attrs.get("axis", "dp"),
            ).apply(prog)
            ctx.attrs["sharding_stage"] = stage
            return ctx
        ctx.optimizer._zero_stage = stage
        ctx.attrs["sharding_stage"] = stage
        return ctx


@register_pass("auto_parallel_gradient_merge")
class GradientMergePass(PassBase):
    """Dual-mode (reference auto_parallel_gradient_merge.py): given a
    captured Program it REWRITES it — accumulator/counter state vars, an
    accumulate op after the grad super-op, and a lax.cond-gated optimizer
    update (program_rewrites.GradientMergeProgramRewrite); given the triple
    it swaps in the k-step merging optimizer wrapper."""

    def apply(self, ctx):
        k = int(self.attrs.get("k_steps", 1))
        prog = ctx.attrs.get("main_program")
        if prog is not None:
            from .program_rewrites import GradientMergeProgramRewrite

            ctx.attrs["gradient_merge_rewritten_ops"] = GradientMergeProgramRewrite(
                k_steps=k, avg=self.attrs.get("avg", True)).apply(prog)
            return ctx
        from paddle_tpu.incubate.optimizer import GradientMergeOptimizer

        if k > 1:
            ctx.optimizer = GradientMergeOptimizer(ctx.optimizer, k_steps=k, avg=self.attrs.get("avg", True))
        return ctx


@register_pass("auto_parallel_grad_clip")
class GradClipPass(PassBase):
    """Global-norm clip on the optimizer (reference auto_parallel_grad_clip.py
    — GSPMD makes the cross-axis norm a plain compiled reduction)."""

    def apply(self, ctx):
        from paddle_tpu.nn import ClipGradByGlobalNorm

        ctx.optimizer._grad_clip = ClipGradByGlobalNorm(float(self.attrs.get("clip_norm", 1.0)))
        return ctx


@register_pass("pipeline_scheduler")
class PipelineSchedulerPass(PassBase):
    """Select the pipeline schedule (reference pipeline_scheduler_pass.py
    FThenB/1F1B) on every PipelineStack in the model."""

    def apply(self, ctx):
        from paddle_tpu.distributed.fleet.meta_parallel import PipelineStack

        schedule = self.attrs.get("schedule", "1F1B")
        n = 0
        for sub in ctx.model.sublayers(include_self=True):
            if isinstance(sub, PipelineStack):
                # set_schedule validates against the registered schedule
                # names (incl. ZB-H1) and drops the stack's cached steps
                sub.set_schedule(schedule)
                if "num_microbatches" in self.attrs:
                    sub._num_microbatches = int(self.attrs["num_microbatches"])
                n += 1
        ctx.attrs["pipeline_stacks"] = n
        return ctx
