"""MoE all-to-all dispatch primitives.

Reference: python/paddle/distributed/utils/moe_utils.py (global_scatter /
global_gather backed by paddle/fluid/operators/collective/global_scatter_op.cu.cc
— variable-count NCCL all-to-all).

TPU-native redesign: XLA requires static shapes, so dispatch uses **fixed
expert capacity** (GShard): tensors are [world * chunk, d] with equal chunks
per destination rank, exchanged with a single `lax.all_to_all` on the expert-
parallel mesh axis.  `local_count`/`global_count` arguments are accepted for
API parity and validated to be capacity-uniform when provided.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from paddle_tpu.tensor._ops_common import apply, ensure_tensor
from paddle_tpu.distributed.communication.ops import _axis_for, _world


def _exchange(x, group, direction):
    """x: [world * chunk, ...] -> all-to-all over leading dim."""
    from paddle_tpu.distributed.communication.ops import _single_axis

    ax = _single_axis(_axis_for(group), f"global_{direction}")
    if ax is None:
        if _world(group) == 1:
            return ensure_tensor(x)
        from paddle_tpu.distributed.communication.ops import _no_multihost

        _no_multihost()
    return apply(
        f"global_{direction}",
        lambda v: lax.all_to_all(v, ax, split_axis=0, concat_axis=0, tiled=True),
        ensure_tensor(x),
    )


def global_scatter(x, local_count=None, global_count=None, group=None):
    """Send row-chunks of `x` to ranks of the EP group (chunk i -> rank i)."""
    return _exchange(x, group, "scatter")


def global_gather(x, local_count=None, global_count=None, group=None):
    """Inverse of global_scatter (rows return to their source rank)."""
    return _exchange(x, group, "gather")
