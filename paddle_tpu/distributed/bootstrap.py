"""Shared TCPStore bootstrap helpers used by launch, rpc, and elastic
(reference: the TCPStore-based rendezvous in
paddle/phi/core/distributed/store/tcp_store.h + the barrier patterns in
python/paddle/distributed/parallel.py).

One implementation of: 'rank 0 hosts the store, everyone connects' and the
counter-plus-done-key barrier, so the three consumers cannot drift."""

from __future__ import annotations

__all__ = ["host_or_connect", "store_barrier", "register_member", "list_members"]


def host_or_connect(endpoint, is_host, timeout_ms=120_000):
    """Return (server_or_None, client). The host starts a TCPStoreServer on
    the endpoint's port; everyone (host included) connects a client."""
    from paddle_tpu import _native

    host, port = endpoint.split(":")
    server = None
    if is_host:
        server = _native.TCPStoreServer(int(port))
    client = _native.TCPStoreClient(host, int(port), timeout_ms=timeout_ms)
    return server, client


def store_barrier(client, key, n, timeout_ms=600_000):
    """All n participants call; returns when everyone arrived."""
    arrived = client.add(f"barrier/{key}/count", 1)
    if arrived >= n:
        client.set(f"barrier/{key}/done", b"1")
    else:
        client.get(f"barrier/{key}/done", timeout_ms=timeout_ms)


def register_member(client, namespace, member_id):
    """Atomically append member_id to a membership list (per-index keys —
    the store only has set/get/add, so read-modify-write of one list key
    would lose concurrent registrations)."""
    idx = client.add(f"{namespace}/count", 1) - 1
    client.set(f"{namespace}/member/{idx}", str(member_id).encode())
    return idx


def list_members(client, namespace, timeout_ms=5_000):
    n = client.add(f"{namespace}/count", 0)
    out = []
    for i in range(int(n)):
        out.append(client.get(f"{namespace}/member/{i}", timeout_ms=timeout_ms).decode())
    return out
