from .group_sharded import group_sharded_parallel, save_group_sharded_model  # noqa: F401
