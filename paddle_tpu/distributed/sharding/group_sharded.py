"""GroupSharded (ZeRO) public API.

Reference: python/paddle/distributed/sharding/group_sharded.py:40
group_sharded_parallel(model, optimizer, level in {'os','os_g','p_g_os'})
wrapping GroupShardedOptimizerStage2 / GroupShardedStage2 / Stage3
(fleet/meta_parallel/sharding/*) — per-rank parameter/grad/optimizer-state
partitions with broadcast/reduce hooks.

TPU-native: the three levels are sharding DECLARATIONS consumed when the
step compiles (ShardedTrainStep):
  'os'     (stage 1): optimizer state sharded over the data axis.
  'os_g'   (stage 2): + gradients materialized sharded (XLA reduce-scatters
           into the sharded update instead of all-reducing).
  'p_g_os' (stage 3): + parameters stored sharded over the data axis;
           XLA all-gathers them just-in-time per layer (the reference's
           param broadcast + release in Stage3.forward hooks).
State partitioning, comm scheduling and overlap all come from the compiled
program rather than Python hooks.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]

_LEVELS = {"os": 1, "os_g": 2, "p_g_os": 3}


def _shard_params_over_dp(model, mesh, dp_axis="dp"):
    """Stage 3: give every parameter an extra dp-sharded dim placement."""
    from paddle_tpu.distributed.auto_parallel.api import placements_to_spec

    dp = mesh.get_dim_size(dp_axis)
    for p in model.parameters():
        v = p._value
        if v.ndim == 0:
            continue
        if getattr(p, "process_mesh", None) is not None and p.placements is not None:
            spec = list(placements_to_spec(p.process_mesh, p.placements))
        else:
            spec = []
        spec += [None] * (v.ndim - len(spec))
        for d in sorted(range(v.ndim), key=lambda i: -v.shape[i]):
            if spec[d] is None and v.shape[d] % dp == 0 and v.shape[d] >= dp:
                spec[d] = dp_axis
                break
        p._bind(jax.device_put(v, NamedSharding(mesh.jax_mesh, PartitionSpec(*spec))))


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2**23,
                           segment_size=2**20, sync_comm=False, dp_group=None,
                           exclude_layer=None, mesh=None, dp_axis="dp"):
    """Declare ZeRO sharding for model/optimizer (reference group_sharded.py:40)."""
    if level not in _LEVELS:
        raise ValueError(f"level must be one of {list(_LEVELS)}, got {level!r}")
    stage = _LEVELS[level]
    optimizer._zero_stage = stage

    if stage >= 3:
        from paddle_tpu.distributed.auto_parallel import get_mesh

        m = mesh or get_mesh()
        if m is not None and dp_axis in m.dim_names:
            _shard_params_over_dp(model, m, dp_axis)

    if offload:
        # TPU HBM↔host offload is a compiler placement decision; record intent.
        optimizer._offload = True
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Gather-and-save (reference save_group_sharded_model): arrays are
    global jax.Arrays, so plain save already writes full tensors."""
    import paddle_tpu as paddle

    paddle.save(model.state_dict(), output + ".pdparams")
    if optimizer is not None:
        paddle.save(optimizer.state_dict(), output + ".pdopt")
