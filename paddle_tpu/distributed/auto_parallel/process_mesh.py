"""ProcessMesh — logical device mesh for semi-auto parallelism.

Reference: paddle/phi/core/distributed/auto_parallel/process_mesh.h and
python/paddle/distributed/auto_parallel/process_mesh.py:71.

TPU-native: thin wrapper around jax.sharding.Mesh.  The reference's "process
ids" are LOGICAL ranks: id i selects the i-th device of jax.devices() (global
device order), NOT the device whose .id equals i — multi-host global device
ids are non-contiguous (e.g. per-process offsets of 2048 on CPU), so only
positional indexing gives every process the same mesh.  Ids outside
range(len(jax.devices())) fall back to lookup by literal device .id.
dim_names are the mesh axis names used by PartitionSpec / shard_map
collectives.  A global default mesh (context manager) mirrors the
reference's auto_parallel default-mesh stack.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

__all__ = ["ProcessMesh", "get_mesh", "set_mesh"]

_default_mesh = None


class ProcessMesh:
    def __init__(self, mesh, dim_names=None, shape=None):
        if isinstance(mesh, Mesh):
            self._jax_mesh = mesh
            self._shape = list(mesh.devices.shape)
            self._dim_names = list(mesh.axis_names)
            self._ids = [int(getattr(d, "id", i)) for i, d in enumerate(mesh.devices.flat)]
            return
        arr = np.asarray(mesh)
        if shape is not None:
            arr = arr.reshape(shape)
        self._shape = list(arr.shape)
        self._ids = [int(i) for i in arr.flatten()]
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self._dim_names = list(dim_names)
        devices = list(jax.devices())
        try:
            if all(0 <= i < len(devices) for i in self._ids):
                # paddle ProcessMesh ids are LOGICAL ranks: index positionally
                # into the global device order (multi-host global device ids
                # are not contiguous — e.g. cpu procs offset by 2048)
                dev_arr = np.array([devices[i] for i in self._ids], dtype=object).reshape(self._shape)
            else:
                dev_by_id = {int(getattr(d, "id", i)): d for i, d in enumerate(devices)}
                dev_arr = np.array([dev_by_id[i] for i in self._ids], dtype=object).reshape(self._shape)
            self._jax_mesh = Mesh(dev_arr, tuple(self._dim_names))
        except KeyError:
            # Process ids beyond the visible device set (multi-host spec
            # written on one host): keep the logical mesh; jax_mesh resolves
            # lazily when the full device set is visible.
            self._jax_mesh = None

    # ------------------------------------------------------------ properties
    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return list(self._ids)

    @property
    def mesh(self):
        return np.asarray(self._ids).reshape(self._shape)

    @property
    def jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            raise RuntimeError(
                "ProcessMesh references device ids not visible to this process"
            )
        return self._jax_mesh

    def get_dim_size(self, name):
        return self._shape[self._dim_names.index(name)]

    def get_mesh_with_dim(self, dim_name, index=None):
        """Sub-mesh along one axis (reference process_mesh.py get_mesh_with_dim):
        moves `dim_name` to the front; with `index`, selects that slice."""
        axis = self._dim_names.index(dim_name)
        order = [axis] + [i for i in range(self.ndim) if i != axis]
        arr = self.mesh.transpose(order)
        names = [self._dim_names[i] for i in order]
        if index is not None:
            return ProcessMesh(arr[index], names[1:])
        return ProcessMesh(arr, names)

    def __eq__(self, other):
        return (
            isinstance(other, ProcessMesh)
            and self._shape == other._shape
            and self._ids == other._ids
            and self._dim_names == other._dim_names
        )

    def __hash__(self):
        return hash((tuple(self._shape), tuple(self._ids), tuple(self._dim_names)))

    def __repr__(self):
        return f"ProcessMesh(shape={self._shape}, dim_names={self._dim_names})"

    def __enter__(self):
        global _default_mesh
        self._prev = _default_mesh
        _default_mesh = self
        return self

    def __exit__(self, *exc):
        global _default_mesh
        _default_mesh = self._prev


def set_mesh(mesh):
    global _default_mesh
    if isinstance(mesh, Mesh):
        mesh = ProcessMesh(mesh)
    _default_mesh = mesh
    return _default_mesh


def get_mesh():
    return _default_mesh
