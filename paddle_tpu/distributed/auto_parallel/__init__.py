from .placement import Partial, Placement, Replicate, Shard  # noqa: F401
from .process_mesh import ProcessMesh, get_mesh, set_mesh  # noqa: F401
from .api import (  # noqa: F401
    dtensor_from_fn,
    placements_to_spec,
    reshard,
    shard_layer,
    shard_tensor,
    sharding_of,
    spec_to_placements,
    unshard_dtensor,
)
from .engine import Engine, Strategy  # noqa: F401
