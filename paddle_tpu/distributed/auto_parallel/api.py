"""Semi-auto-parallel dygraph API: shard_tensor / reshard / shard_layer.

Reference: python/paddle/distributed/auto_parallel/api.py (shard_tensor :765,
reshard :874, shard_layer :973, dtensor_from_fn) over DistTensor
(paddle/phi/core/distributed/auto_parallel/dist_tensor.h:39) with explicit
SPMD rules + reshard function library (reshard/*.cc).

TPU-native: DistTensor(ProcessMesh, placements) ≅ jax.Array with a
NamedSharding.  The reference's per-op SPMD rules and pairwise reshard
converters collapse into GSPMD — annotate inputs/outputs, XLA propagates
shardings and inserts collectives on ICI.  `reshard` is a device_put to the
target sharding (XLA emits the all-gather/all-to-all/slice program).

`Partial` note: at rest, a single-controller jax.Array cannot carry a
pending-reduction state, so materializing a Partial placement eagerly folds
the reduction immediately (semantically the reshard the reference would do on
first use).  Inside compiled programs (shard_map), real deferred partials
arise naturally and are reduced by lax.psum.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from paddle_tpu._core.tensor import Parameter, Tensor
from .placement import Partial, Placement, Replicate, Shard
from .process_mesh import ProcessMesh, get_mesh

__all__ = [
    "shard_tensor",
    "reshard",
    "shard_layer",
    "dtensor_from_fn",
    "unshard_dtensor",
    "placements_to_spec",
    "spec_to_placements",
    "sharding_of",
]


def placements_to_spec(mesh: ProcessMesh, placements) -> PartitionSpec:
    """placements (one per mesh dim) → PartitionSpec (one entry per tensor dim).

    Mirrors the conversion the reference does between dim_map and placements
    (python/paddle/distributed/auto_parallel/placement_type.py)."""
    by_tensor_dim: dict[int, list[str]] = {}
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            by_tensor_dim.setdefault(pl.dim, []).append(mesh.dim_names[mesh_dim])
    if not by_tensor_dim:
        return PartitionSpec()
    max_dim = max(by_tensor_dim)
    entries = []
    for d in range(max_dim + 1):
        axes = by_tensor_dim.get(d)
        if axes is None:
            entries.append(None)
        elif len(axes) == 1:
            entries.append(axes[0])
        else:
            entries.append(tuple(axes))
    return PartitionSpec(*entries)


def spec_to_placements(mesh: ProcessMesh, spec: PartitionSpec, ndim: int):
    placements = [Replicate() for _ in mesh.dim_names]
    for tdim, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            placements[mesh.dim_names.index(ax)] = Shard(tdim)
    return placements


def _normalize_placements(mesh: ProcessMesh, placements):
    if placements is None:
        return [Replicate() for _ in range(mesh.ndim)]
    out = list(placements)
    if len(out) > mesh.ndim:
        raise ValueError(f"{len(out)} placements for a {mesh.ndim}-d mesh")
    while len(out) < mesh.ndim:
        out.append(Replicate())
    for p in out:
        if not isinstance(p, Placement):
            raise TypeError(f"placement must be Shard/Replicate/Partial, got {p!r}")
    return out


def sharding_of(mesh: ProcessMesh, placements) -> NamedSharding:
    placements = _normalize_placements(mesh, placements)
    return NamedSharding(mesh.jax_mesh, placements_to_spec(mesh, placements))


def _mark_dist(t: Tensor, mesh: ProcessMesh, placements):
    t.process_mesh = mesh
    t.placements = list(placements)
    return t


def shard_tensor(data, mesh: ProcessMesh = None, placements=None, dtype=None, stop_gradient=None):
    """Create a distributed tensor from data + mesh + placements
    (reference api.py:765)."""
    if mesh is None:
        mesh = get_mesh()
    placements = _normalize_placements(mesh, placements)
    if isinstance(data, Tensor):
        val = data._value
        if stop_gradient is None:
            stop_gradient = data.stop_gradient
    else:
        val = jnp.asarray(data, dtype=None if dtype is None else dtype)
        if stop_gradient is None:
            stop_gradient = True
    if any(p.is_partial() for p in placements):
        # fold pending reduction eagerly (see module docstring)
        placements = [Replicate() if p.is_partial() else p for p in placements]
    val = jax.device_put(val, sharding_of(mesh, placements))
    if isinstance(data, Parameter):
        # Parameters shard IN PLACE so optimizer/layer references stay valid
        # (reference keeps EagerParamBase identity when converting to dist).
        data._bind(val)
        data.stop_gradient = stop_gradient
        return _mark_dist(data, mesh, placements)
    out = Tensor(val, stop_gradient=stop_gradient)
    return _mark_dist(out, mesh, placements)


def reshard(x: Tensor, mesh: ProcessMesh = None, placements=None) -> Tensor:
    """Convert a dist tensor to new placements (reference api.py:874; C++
    pairwise converter library reshard/*.cc → one XLA resharding here)."""
    if mesh is None:
        mesh = get_mesh()
    placements = _normalize_placements(mesh, placements)
    tgt = [Replicate() if p.is_partial() else p for p in placements]
    val = jax.device_put(x._value, sharding_of(mesh, tgt))
    out = Tensor(val, stop_gradient=x.stop_gradient)
    return _mark_dist(out, mesh, tgt)


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs) -> Tensor:
    """Build a dist tensor by calling a creation fn (reference api.py
    dtensor_from_fn) — created then placed; XLA shards the materialization."""
    out = fn(*args, **kwargs)
    return shard_tensor(out, mesh, placements)


def unshard_dtensor(x: Tensor) -> Tensor:
    """Gather a dist tensor back to a replicated dense tensor."""
    mesh = getattr(x, "process_mesh", None)
    if mesh is None:
        return x
    val = jax.device_put(x._value, sharding_of(mesh, None))
    out = Tensor(val, stop_gradient=x.stop_gradient)
    out.process_mesh = None
    out.placements = None
    return out


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None, input_fn=None, output_fn=None):
    """Shard a Layer's parameters across a mesh (reference api.py:973).

    shard_fn(sublayer_name, sublayer, mesh) replaces parameters with dist
    params via shard_tensor; the default replicates every parameter.
    input_fn/output_fn hook the forward to shard inputs / outputs.
    """
    from paddle_tpu.nn import Layer

    if not isinstance(layer, Layer):
        raise TypeError("shard_layer expects a paddle_tpu.nn.Layer")

    def _default_shard(name, sub, mesh):
        for pname, p in list(sub._parameters.items()):
            if p is None or getattr(p, "process_mesh", None) is not None:
                continue
            sharded = shard_tensor(p, mesh, None, stop_gradient=p.stop_gradient)
            sub._parameters[pname] = sharded

    fn = shard_fn or _default_shard
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)

    if input_fn is not None:
        layer.register_forward_pre_hook(lambda lyr, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(lambda lyr, inputs, outputs: output_fn(outputs, process_mesh))
    return layer
