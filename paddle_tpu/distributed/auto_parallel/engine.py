"""Auto-parallel static Engine.

Reference: python/paddle/distributed/auto_parallel/static/engine.py:59
(Engine.fit/evaluate/predict/prepare), whose pipeline is
Completer (completion.py:210, dist-attr propagation) -> Parallelizer
(pass application) -> Partitioner (partitioner.py:41, per-rank program
split) -> Resharder (reshard.py:1006, comm insertion) -> executor.

TPU-native redesign — the same four roles, one compiler:

- **Completion**: user annotations (shard_tensor / shard_layer placements)
  become NamedShardings on parameters; every un-annotated tensor's layout is
  PROPAGATED by XLA's GSPMD sharding-propagation pass over the whole-step
  program, which is exactly the Completer's fixed-point dist-attr walk done
  inside the compiler.
- **Partition**: jit over the mesh splits the program per device; there is
  no per-rank python program object to materialize.
- **Reshard**: mismatched producer/consumer layouts become collective ops
  inserted by GSPMD; explicit `dist.reshard` calls lower to sharding
  constraints.
- **Execution**: one donated-state compiled step (ShardedTrainStep), the
  PirInterpreter analog.

The Engine therefore keeps the reference's *surface* (fit/evaluate/predict/
prepare, dataloader integration, logs) while the 40k-LoC
planner/partitioner/resharder subsystem collapses into GSPMD — SURVEY.md §7
design stance ("SPMD rules largely delegated to GSPMD propagation").
"""

from __future__ import annotations

import numpy as np

from paddle_tpu._core.tensor import Tensor

__all__ = ["Engine", "Strategy"]


class Strategy:
    """Auto-parallel strategy knobs (reference auto_parallel/strategy.py).

    Only the knobs meaningful on the XLA path are live; the rest are
    accepted for config compatibility."""

    def __init__(self, config=None):
        config = config or {}
        self.auto_mode = config.get("auto_mode", "semi")
        self.seed = config.get("seed", None)
        # sharding (ZeRO) sub-config
        sharding = config.get("sharding", {})
        self.sharding_degree = sharding.get("degree", 1)
        self.sharding_stage = sharding.get("stage", 1)
        self.sharding_enable = sharding.get("enable", False)
        self.amp = config.get("amp", {})
        self.gradient_merge = config.get("gradient_merge", {})
        self.recompute = config.get("recompute", {})
        self.pipeline = config.get("pipeline", {})
        # overrides merged into the auto-mode tuner_cfg (hbm_gb, candidate
        # lists, ...) — the reference reads these from the tuner json
        self.tuner = config.get("tuner", {})


class Engine:
    """Minimal-complete Engine: fit/evaluate/predict over a ProcessMesh."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None, strategy=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else ([metrics] if metrics else [])
        self._strategy = strategy or Strategy()
        self._mesh = None
        self._train_step = None
        self._eval_fn = None
        self.history = {"loss": []}

    # ------------------------------------------------------------------ mesh
    def _infer_mesh(self):
        """Mesh = the one used by param annotations, else the default world
        mesh from fleet.auto context (reference get_default_process_mesh).
        auto_mode="auto": the tuner picks dp/mp/pp and applies it first."""
        if self._mesh is not None:
            return self._mesh
        if self._strategy.auto_mode == "auto":
            import jax

            n = jax.device_count()
            plan = self._auto_plan(n)
            return self._apply_plan(plan, n)
        for p in self._model.parameters():
            if getattr(p, "process_mesh", None) is not None:
                self._mesh = p.process_mesh
                return self._mesh
        from .process_mesh import get_mesh

        mesh = get_mesh()
        if mesh is None:
            import jax

            from . import ProcessMesh

            mesh = ProcessMesh(np.arange(jax.device_count()), ["dp"])
        self._mesh = mesh
        return mesh

    def _batch_spec(self, mesh):
        from jax.sharding import PartitionSpec

        if "dp" in mesh.dim_names:
            return PartitionSpec("dp")
        return PartitionSpec()

    # --------------------------------------------------------------- prepare
    def prepare(self, inputs_spec=None, labels_spec=None, mode="train"):
        """Build (but don't run) the compiled step for `mode`."""
        mesh = self._infer_mesh()
        if mode == "train":
            self._ensure_train_step(mesh)
        return self

    # ------------------------------------------------------------- auto mode
    def _model_cfg_estimate(self):
        """Derive the tuner's model_cfg from the live model (reference reads
        it from the tuner json; here introspection keeps them in sync)."""
        cfg = getattr(self._model, "config", None)
        out = {}
        for src, dst in (
            ("hidden_size", "hidden_size"),
            ("num_hidden_layers", "num_layers"),
            ("num_layers", "num_layers"),
            ("num_attention_heads", "num_attention_heads"),
            ("vocab_size", "vocab_size"),
            ("intermediate_size", "intermediate_size"),
            ("max_position_embeddings", "seq_length"),
        ):
            v = getattr(cfg, src, None)
            if v is not None:
                out.setdefault(dst, int(v))
        out["num_params"] = sum(
            int(np.prod(p.shape)) for p in self._model.parameters()
        )
        return out

    def _model_parallel_fns(self):
        """Known model families' mp/pp appliers (the reference's planner
        rewrites programs; here placements are applied by family)."""
        name = type(self._model).__name__
        if name == "LlamaForCausalLM":
            from paddle_tpu.models.llama import pipeline_llama, shard_llama

            return shard_llama, pipeline_llama
        if name == "GPTForCausalLM":
            from paddle_tpu.models.gpt import pipeline_gpt, shard_gpt

            return shard_gpt, pipeline_gpt
        return None, None

    def _auto_plan(self, n_devices):
        """Full-auto mode (reference engine.py:59 `auto` + tuner.py:19):
        grid-search dp/mp/pp with the pruner + analytic HBM model, pick the
        surviving plan with the most data parallelism (fewest cross-device
        activations), pp as last resort."""
        from paddle_tpu.distributed.auto_tuner.tuner import AutoTuner

        shard_fn, pipeline_fn = self._model_parallel_fns()
        model_cfg = self._model_cfg_estimate()
        tuner_cfg = {
            "num_devices": n_devices,
            "num_gpus": n_devices,
            "model_cfg": model_cfg,
            "sharding_degree": [1],
            "sharding_stage": [self._strategy.sharding_stage or 1],
            "use_recompute": [False],
            "micro_batch_size": [1],
            "task_limit": 10_000,
        }
        tuner_cfg.update(self._strategy.tuner)
        # capability guards come AFTER user overrides: a plan the engine
        # cannot APPLY (no mp shard fn / no pipeline fn for this model
        # family; sharding axis not mesh-materialized here) must never be
        # reported as selected
        if shard_fn is None:
            tuner_cfg["mp_degree"] = [1]
        if pipeline_fn is None:
            tuner_cfg["pp_degree"] = [1]
        tuner_cfg["sharding_degree"] = [1]
        tuner = AutoTuner(tuner_cfg)
        best = best_key = None
        while True:
            cand = tuner.search_once()
            if cand is None:
                break
            tuner.add_cfg(cand)
            key = (cand["dp_degree"], -cand["pp_degree"], -cand["mp_degree"])
            if best is None or key > best_key:
                best, best_key = cand, key
        if best is None:
            best = {"dp_degree": n_devices, "mp_degree": 1, "pp_degree": 1}
        return best

    def _apply_plan(self, plan, n_devices):
        from . import ProcessMesh

        axes, shape = [], []
        for name, deg in (
            ("dp", plan.get("dp_degree", 1)),
            ("pp", plan.get("pp_degree", 1)),
            ("mp", plan.get("mp_degree", 1)),
        ):
            if deg > 1:
                axes.append(name)
                shape.append(int(deg))
        if not axes:
            axes, shape = ["dp"], [1]
        used = int(np.prod(shape))
        mesh = ProcessMesh(np.arange(used).reshape(shape), axes)
        shard_fn, pipeline_fn = self._model_parallel_fns()
        if "mp" in axes and shard_fn is not None:
            shard_fn(self._model, mesh, mp_axis="mp")
        if "pp" in axes and pipeline_fn is not None:
            pipeline_fn(self._model, mesh, pp_axis="pp",
                        num_microbatches=plan.get("pp_degree"))
            # the pipeline stack replaces block parameters with stacked
            # ones: point the optimizer at the new parameter set (lazy
            # accumulators key per-param, so state starts fresh)
            if self._optimizer is not None:
                self._optimizer._parameter_list = list(self._model.parameters())
        self._mesh = mesh
        self._plan = dict(plan)
        return mesh

    def _ensure_train_step(self, mesh):
        if self._train_step is not None:
            return
        from paddle_tpu.distributed.sharded_step import ShardedTrainStep

        # strategy-driven transforms (reference engine.py Parallelizer
        # applying the distributed passes before compilation)
        gm = self._strategy.gradient_merge or {}
        if gm.get("enable"):
            from paddle_tpu.incubate.optimizer import GradientMergeOptimizer

            if not isinstance(self._optimizer, GradientMergeOptimizer):
                self._optimizer = GradientMergeOptimizer(
                    self._optimizer, k_steps=int(gm.get("k_steps", 1)),
                    avg=gm.get("avg", True))
        rc = self._strategy.recompute or {}
        if rc.get("enable"):
            from paddle_tpu.distributed.passes import PassContext, new_pass

            layers = rc.get("layers")
            cfg = getattr(self._model, "config", None)
            if layers is None and not hasattr(cfg, "use_recompute"):
                raise ValueError(
                    "strategy.recompute.enable needs 'layers' (sublayer "
                    "names to wrap) for models without a config."
                    "use_recompute switch — otherwise it would be a "
                    "silent no-op")
            new_pass("auto_parallel_recompute",
                     {"layers": layers}).apply(
                PassContext(self._model, self._optimizer))

        loss_obj = self._loss

        def loss_fn(model, *batch):
            *inputs, labels = batch
            out = model(*inputs)
            return loss_obj(out, labels)

        self._train_step = ShardedTrainStep(
            self._model,
            self._optimizer,
            loss_fn,
            mesh,
            batch_spec=self._batch_spec(mesh),
            zero_stage=self._strategy.sharding_stage if self._strategy.sharding_enable else 0,
        )

    # ------------------------------------------------------------------- fit
    def fit(self, train_data, epochs=1, batch_size=None, steps_per_epoch=None, verbose=0, collate_fn=None):
        """Train over a Dataset / DataLoader / (x, y) arrays (reference
        engine.py fit's dataloader handling, simplified)."""
        mesh = self._infer_mesh()
        self._ensure_train_step(mesh)
        loader = self._as_loader(train_data, batch_size, collate_fn)
        logs = {"loss": []}
        for epoch in range(epochs):
            for step, batch in enumerate(loader):
                if steps_per_epoch is not None and step >= steps_per_epoch:
                    break
                batch_t = [b if isinstance(b, Tensor) else Tensor(np.asarray(b)) for b in batch]
                loss = self._train_step(*batch_t)
                lv = float(np.asarray(loss.astype("float32")._value if isinstance(loss, Tensor) else loss))
                logs["loss"].append(lv)
                if verbose:
                    print(f"[auto_parallel.Engine] epoch {epoch} step {step}: loss {lv:.5f}")
        self.history["loss"].extend(logs["loss"])
        return logs

    # ------------------------------------------------------- evaluate/predict
    def _compiled_forward(self):
        if self._eval_fn is None:
            from paddle_tpu.jit import to_static

            self._eval_fn = to_static(self._model)
        return self._eval_fn

    def evaluate(self, eval_data, batch_size=None, steps=None, verbose=0, collate_fn=None):
        from paddle_tpu._core.autograd import no_grad

        loader = self._as_loader(eval_data, batch_size, collate_fn)
        fwd = self._compiled_forward()
        was_training = getattr(self._model, "training", False)
        self._model.eval()
        losses = []
        try:
            with no_grad():
                for step, batch in enumerate(loader):
                    if steps is not None and step >= steps:
                        break
                    *inputs, labels = [b if isinstance(b, Tensor) else Tensor(np.asarray(b)) for b in batch]
                    out = fwd(*inputs)
                    losses.append(float(np.asarray(self._loss(out, labels).astype("float32")._value)))
        finally:
            if was_training:
                self._model.train()
        return {"loss": losses}

    def predict(self, test_data, batch_size=None, steps=None, verbose=0, collate_fn=None):
        from paddle_tpu._core.autograd import no_grad

        loader = self._as_loader(test_data, batch_size, collate_fn, labeled=False)
        fwd = self._compiled_forward()
        was_training = getattr(self._model, "training", False)
        self._model.eval()
        outs = []
        try:
            with no_grad():
                for step, batch in enumerate(loader):
                    if steps is not None and step >= steps:
                        break
                    inputs = [b if isinstance(b, Tensor) else Tensor(np.asarray(b)) for b in batch]
                    outs.append(fwd(*inputs))
        finally:
            if was_training:
                self._model.train()
        return outs

    # ---------------------------------------------------------------- saving
    def save(self, path, training=True):
        import paddle_tpu as paddle

        state = {"model": dict(self._model.state_dict())}
        if training and self._optimizer is not None:
            state["optimizer"] = self._optimizer.state_dict()
        paddle.save(state, path + ".pdparams")

    def load(self, path):
        import paddle_tpu as paddle

        state = paddle.load(path + ".pdparams")
        self._model.set_state_dict(state["model"])
        if "optimizer" in state and self._optimizer is not None:
            self._optimizer.set_state_dict(state["optimizer"])

    # ------------------------------------------------------------------ misc
    @staticmethod
    def _as_loader(data, batch_size, collate_fn, labeled=True):
        from paddle_tpu.io import DataLoader, Dataset

        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size or 1, collate_fn=collate_fn)
        if isinstance(data, (tuple, list)):
            arrays = [np.asarray(a._value if isinstance(a, Tensor) else a) for a in data]
            n = arrays[0].shape[0]
            bs = batch_size or n

            class _ArrayLoader:
                def __iter__(self):  # re-iterable: fit() loops it per epoch
                    for i in range(0, n, bs):
                        yield tuple(a[i : i + bs] for a in arrays)

            return _ArrayLoader()
        raise TypeError(f"unsupported data type {type(data)}")

    @property
    def main_program(self):
        """The reference returns the annotated ProgramDesc; here the program
        IS the jitted step — expose the compiled step for introspection."""
        return self._train_step
