"""Distributed surface compat tier (reference:
python/paddle/distributed/__init__.py __all__).

The substantial machinery lives elsewhere (auto_parallel/, fleet/,
communication/, ps/, checkpoint/); this module supplies the remaining
reference exports: mode/type enums, the Megatron `split` op, shard_optimizer
and the dygraph->static DistModel bridge, spawn, the gloo_* CPU-barrier trio
(over the native TCPStore), and the PS sparse-table entry configs.
"""

from __future__ import annotations

__all__ = [
    "ParallelMode",
    "ReduceType",
    "DistAttr",
    "split",
    "shard_optimizer",
    "to_static",
    "spawn",
    "gloo_init_parallel_env",
    "gloo_barrier",
    "gloo_release",
    "CountFilterEntry",
    "ProbabilityEntry",
    "ShowClickEntry",
]


class ParallelMode:
    """reference: python/paddle/distributed/parallel.py ParallelMode."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


class ReduceType:
    """reference: auto_parallel placement reduce types (dist_attr.h)."""

    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


class DistAttr:
    """Tensor distribution attribute (reference:
    paddle/phi/core/distributed/auto_parallel/dist_attr.h TensorDistAttr):
    carries (process_mesh, placements); sharding_of() maps it onto a
    NamedSharding for GSPMD."""

    def __init__(self, mesh=None, sharding_specs=None, placements=None):
        self.process_mesh = mesh
        self.sharding_specs = sharding_specs
        self.placements = placements

    def sharding(self):
        from .auto_parallel.api import sharding_of

        return sharding_of(self.process_mesh, self.placements)


def split(x, size, operation="linear", axis=0, num_partitions=1, gather_out=True, weight_attr=None, bias_attr=None, name=None):
    """Megatron column/row split op (reference:
    python/paddle/distributed/collective.py split): one-call model-parallel
    linear/embedding over the mp group.  Builds the matching mpu layer and
    applies it; with no hybrid mp group (single process), falls back to the
    plain layer — the reference's nranks==1 path.
    """
    import paddle_tpu as paddle
    from .fleet.fleet import get_hybrid_communicate_group

    try:
        hcg = get_hybrid_communicate_group()
        mp = hcg.get_model_parallel_world_size()
    except Exception:
        mp = 1
    if operation == "linear":
        in_f, out_f = int(size[0]), int(size[1])
        if mp > 1:
            from .fleet.layers.mpu.mp_layers import ColumnParallelLinear, RowParallelLinear

            if axis == 1:
                layer = ColumnParallelLinear(in_f, out_f, weight_attr=weight_attr, has_bias=bias_attr is not False, gather_output=gather_out)
            else:
                layer = RowParallelLinear(in_f, out_f, weight_attr=weight_attr, has_bias=bias_attr is not False, input_is_parallel=False)
        else:
            layer = paddle.nn.Linear(in_f, out_f, weight_attr=weight_attr, bias_attr=bias_attr)
        return layer(x)
    if operation == "embedding":
        num_emb, emb_dim = int(size[0]), int(size[1])
        if mp > 1:
            from .fleet.layers.mpu.mp_layers import VocabParallelEmbedding

            layer = VocabParallelEmbedding(num_emb, emb_dim, weight_attr=weight_attr)
        else:
            layer = paddle.nn.Embedding(num_emb, emb_dim, weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"split: unknown operation {operation!r}")


def shard_optimizer(optimizer, shard_fn=None):
    """reference: python/paddle/distributed/auto_parallel/api.py
    shard_optimizer — mark optimizer states for sharded placement.

    GSPMD path: ShardedTrainStep already lays optimizer accumulators out
    with their parameters' shardings (distributed/sharded_step.py).  This
    records an optional per-state shard_fn consulted when states are
    created: shard_fn(accumulator_name, param, accumulator) -> placements.
    """
    optimizer._shard_fn = shard_fn
    if shard_fn is not None:
        from .auto_parallel.api import shard_tensor  # noqa: F401 (applied lazily)

        orig_acc = optimizer._acc

        def sharded_acc(name, p, init=None, dtype=None):
            t = orig_acc(name, p, init, dtype)
            try:
                placements = shard_fn(name, p, t)
            except TypeError:
                placements = None
            if placements is not None and getattr(p, "_dist_mesh", None) is not None:
                from .auto_parallel.api import _mark_dist

                _mark_dist(t, p._dist_mesh, placements)
            return t

        optimizer._acc = sharded_acc
    return optimizer


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """Dygraph semi-auto -> static engine bridge (reference:
    python/paddle/distributed/auto_parallel/api.py to_static -> DistModel):
    wraps the layer in an Engine-backed DistModel running the whole step as
    one GSPMD executable."""
    from .auto_parallel.engine import Engine

    eng = Engine(layer, loss=loss, optimizer=optimizer, strategy=strategy)

    class DistModel:
        def __init__(self):
            self._engine = eng
            self._mode = "train"
            self._loader = loader
            self._model = layer

        def train(self):
            self._mode = "train"

        def eval(self):
            self._mode = "eval"

        def predict(self):
            self._mode = "predict"

        def __call__(self, *inputs):
            if self._mode == "train":
                if len(inputs) < 2:
                    raise ValueError("DistModel train step expects (*inputs, labels)")
                mesh = self._engine._infer_mesh()
                self._engine._ensure_train_step(mesh)
                return self._engine._train_step(*inputs)
            if self._mode == "eval" and loss is not None and len(inputs) >= 2:
                *feats, labels = inputs
                out = self._engine._compiled_forward()(*feats)
                return loss(out, labels)
            return self._engine._compiled_forward()(*inputs)

        def state_dict(self):
            return self._model.state_dict()

        def dist_main_program(self, mode=None):
            return self._engine.main_program

    return DistModel()


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference: python/paddle/distributed/spawn.py — start nprocs worker
    processes with fabricated cluster env and run func(rank) in each.  On
    TPU the per-process world is CPU/virtual-device based (tests' fake
    cluster strategy, SURVEY §4); multi-chip SPMD does not need spawn."""
    import multiprocessing as mp
    import os

    import socket

    if nprocs <= 0:
        env_n = os.environ.get("PADDLE_TRAINERS_NUM")
        if env_n:
            nprocs = int(env_n)
        else:
            # reference spawn defaults to the visible device count
            # (python/paddle/distributed/spawn.py _get_default_nprocs).
            # Query it in a THROWAWAY subprocess: jax.device_count() in this
            # parent would initialize the TPU runtime here and lock the
            # chips away from the spawned trainers.
            import subprocess
            import sys

            try:
                out = subprocess.run(
                    [sys.executable, "-c", "import jax; print(jax.device_count())"],
                    capture_output=True, text=True, timeout=120,
                )
                nprocs = max(1, int(out.stdout.strip().splitlines()[-1]))
            except Exception:
                nprocs = 1
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nprocs),
            "PADDLE_MASTER": f"127.0.0.1:{port}",
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(port),
            "RANK": str(rank),
            "WORLD_SIZE": str(nprocs),
        }
        p = ctx.Process(target=_spawn_entry, args=(func, args, env), daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        bad = [p.exitcode for p in procs if p.exitcode]
        if bad:
            raise RuntimeError(f"spawn: worker exited with {bad}")
    return procs


def _spawn_entry(func, args, env):
    import os

    os.environ.update(env)
    func(*args)


# ------------------------------------------------------------------- gloo
_gloo = {"store": None, "server": None, "rank": 0, "world": 1}


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """reference: paddle.distributed.gloo_init_parallel_env — CPU-only
    barrier group.  The native TCPStore plays gloo's role."""
    from .bootstrap import host_or_connect

    server, client = host_or_connect(server_endpoint, is_host=(int(rank_id) == 0))
    _gloo.update(store=client, server=server, rank=int(rank_id), world=int(rank_num))


def gloo_barrier():
    from .bootstrap import store_barrier

    if _gloo["store"] is None:
        raise RuntimeError("gloo_barrier before gloo_init_parallel_env")
    _gloo["seq"] = _gloo.get("seq", 0) + 1
    store_barrier(_gloo["store"], f"gloo_barrier/{_gloo['seq']}", _gloo["world"])


def gloo_release():
    _gloo.update(store=None, server=None)


# ------------------------------------------------- PS sparse-table entries
class CountFilterEntry:
    """reference: python/paddle/distributed/entry_attr.py CountFilterEntry —
    admit a sparse feature into the table after `count_filter` shows."""

    def __init__(self, count_filter):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self._count_filter = int(count_filter)

    def _to_attr(self):
        return f"count_filter_entry:{self._count_filter}"


class ProbabilityEntry:
    """reference: entry_attr.py ProbabilityEntry — admit with probability."""

    def __init__(self, probability):
        if not 0 <= probability <= 1:
            raise ValueError("probability must be in [0, 1]")
        self._probability = float(probability)

    def _to_attr(self):
        return f"probability_entry:{self._probability}"


class ShowClickEntry:
    """reference: entry_attr.py ShowClickEntry — show/click-weighted entry."""

    def __init__(self, show_name, click_name):
        self._show = str(show_name)
        self._click = str(click_name)

    def _to_attr(self):
        return f"show_click_entry:{self._show}:{self._click}"
