"""Distributed whole-step training via GSPMD.

Reference counterpart: the fleet hybrid-parallel step (SURVEY.md §3.5 —
python/paddle/distributed/fleet/meta_parallel/*, reducer.cc, sharding
stage-1/2 optimizers) and the semi-auto dist codegen branch
(paddle/phi/api/yaml/generator/dist_api_gen.py: InferSpmd → Reshard → local
kernel).

TPU-native design: instead of per-op SPMD rules + NCCL process groups, the
ENTIRE imperative train step (forward, loss.backward(), optimizer.step()) is
traced into one XLA program over a jax.sharding.Mesh:

- DP: batch arguments sharded over the 'dp' mesh axis — gradient allreduce is
  whatever GSPMD inserts (reduce-scatter/all-reduce on ICI), replacing the
  bucketed EagerReducer.
- TP/MP: parameters carry NamedShardings (via shard_tensor placements or a
  model shard_fn); XLA propagates and places the Megatron collectives.
- ZeRO (sharding stage 1/2): optimizer accumulators are additionally sharded
  over 'dp'; XLA materializes the reduce-scatter(grads)/all-gather(params)
  dataflow of DygraphShardingOptimizer.
- State is donated, so parameter/optimizer-state updates are in-place in HBM
  like the reference's in-place optimizer kernels.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from paddle_tpu._core import random as rng_mod
from paddle_tpu._core.tensor import Tensor
from paddle_tpu.jit import TrainStep

from .auto_parallel import ProcessMesh

__all__ = ["ShardedTrainStep", "shard_batch"]


def _as_process_mesh(mesh) -> ProcessMesh:
    if isinstance(mesh, ProcessMesh):
        return mesh
    if isinstance(mesh, Mesh):
        return ProcessMesh(mesh)
    raise TypeError(f"mesh must be ProcessMesh or jax Mesh, got {type(mesh)}")


def shard_batch(mesh: ProcessMesh, batch_vals, spec):
    """Place batch arrays with `spec` (a PartitionSpec or one per leaf)."""
    leaves, tree = jax.tree_util.tree_flatten(batch_vals)
    # On jax<0.6 PartitionSpec subclasses tuple: a single spec must not be
    # mistaken for a per-leaf list (its entries would be char-splatted).
    if (isinstance(spec, (list, tuple)) and not isinstance(spec, PartitionSpec)
            and len(spec) == len(leaves)):
        specs = list(spec)
    else:
        specs = [spec] * len(leaves)
    placed = []
    for v, s in zip(leaves, specs):
        if not isinstance(s, PartitionSpec):
            s = PartitionSpec(s) if isinstance(s, str) else PartitionSpec(*s)
        # drop spec entries beyond the array rank
        entries = list(s)[: getattr(v, "ndim", 0)]
        placed.append(jax.device_put(v, NamedSharding(mesh.jax_mesh, PartitionSpec(*entries))))
    return jax.tree_util.tree_unflatten(tree, placed)


class ShardedTrainStep(TrainStep):
    """TrainStep over a device mesh.

    Usage:
        mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
        shard_llama(model, mesh)                      # params get placements
        step = ShardedTrainStep(model, opt, loss_fn, mesh,
                                batch_spec=PartitionSpec("dp"))
        loss = step(input_ids, labels)

    zero_stage: 0 = replicated optimizer state (over dp); 1/2 = accumulators
    sharded over 'dp' on their largest divisible dim (stage 2's grad sharding
    is implicit — XLA is free to reduce-scatter into the sharded update).

    comm_overlap=True decomposes each replicated parameter's dp grad sync
    from GSPMD's single fused all-reduce into reduce-scatter + an explicit
    ring all-gather of (dp-1) collective-permute hops
    (fleet.meta_parallel.schedules.overlap_grad_sync): every hop is an
    independent async collective XLA's latency-hiding scheduler can overlap
    with the optimizer math of already-arrived chunks — and, under a ZB-H1
    pipeline stack, with the W-pass ticks it does not depend on.  Values
    are bit-identical (a gather of shards reassociates nothing); the chain
    is statically checked by the mesh lint like every other collective
    (docs/PIPELINE.md, docs/MESH_LINT.md).
    """

    def __init__(
        self,
        model,
        optimizer,
        loss_fn,
        mesh,
        batch_spec=PartitionSpec("dp"),
        zero_stage: int = 1,
        dp_axis: str = "dp",
        scaler=None,
        comm_overlap: bool = False,
    ):
        super().__init__(model, optimizer, loss_fn, scaler=scaler)
        self.mesh = _as_process_mesh(mesh)
        self.batch_spec = batch_spec
        # group_sharded_parallel records its level on the optimizer
        self.zero_stage = getattr(optimizer, "_zero_stage", zero_stage)
        self.dp_axis = dp_axis if dp_axis in self.mesh.dim_names else None
        self.comm_overlap = comm_overlap

    # ---------------------------------------------------------------- state
    def _param_sharding(self, t: Tensor) -> NamedSharding:
        from .auto_parallel.api import placements_to_spec

        if getattr(t, "process_mesh", None) is not None and t.placements is not None:
            return NamedSharding(t.process_mesh.jax_mesh, placements_to_spec(t.process_mesh, t.placements))
        sh = getattr(t._value, "sharding", None)
        if isinstance(sh, NamedSharding):
            return sh
        return NamedSharding(self.mesh.jax_mesh, PartitionSpec())

    def _acc_sharding(self, acc_val, param_sharding: NamedSharding) -> NamedSharding:
        """Accumulator sharding = like its parameter, plus (stage>=1) sharded
        over dp on the largest dim not already sharded and divisible by dp."""
        spec = list(param_sharding.spec)
        spec += [None] * (acc_val.ndim - len(spec))
        used = {ax for e in spec if e is not None for ax in (e if isinstance(e, tuple) else (e,))}
        if self.zero_stage >= 1 and self.dp_axis is not None and self.dp_axis not in used and acc_val.ndim > 0:
            dp = self.mesh.get_dim_size(self.dp_axis)
            cands = sorted(range(acc_val.ndim), key=lambda d: -acc_val.shape[d])
            for d in cands:
                if spec[d] is None and acc_val.shape[d] % dp == 0 and acc_val.shape[d] >= dp:
                    spec[d] = self.dp_axis
                    break
        return NamedSharding(self.mesh.jax_mesh, PartitionSpec(*spec))

    def _place_state(self):
        """After eager warmup: pin every state tensor to its mesh sharding."""
        model_state = list(self.model.state_dict().values())
        for t in model_state:
            t._bind(jax.device_put(t._value, self._param_sharding(t)))
        param_sh = {}
        for p in self.optimizer._parameter_list:
            param_sh[id(p)] = self._param_sharding(p)
        for (name, pid), acc in self.optimizer._accumulators.items():
            psh = param_sh.get(pid, NamedSharding(self.mesh.jax_mesh, PartitionSpec()))
            if acc._value.ndim == 0 or acc._value.shape == ():
                sh = NamedSharding(self.mesh.jax_mesh, PartitionSpec())
            else:
                sh = self._acc_sharding(acc._value, psh)
            acc._bind(jax.device_put(acc._value, sh))

    # ------------------------------------------------- comm/compute overlap
    def _post_backward(self):
        """Traced between backward and optimizer.step: rewrite each
        replicated parameter's gradient through the overlap chain.
        TP-sharded parameters keep GSPMD's own layout (their grads are
        already partial-sharded; re-ringing them over dp would just churn
        layouts), as do sparse SelectedRows grads."""
        if not self.comm_overlap or self.dp_axis is None:
            return
        from paddle_tpu.distributed.fleet.meta_parallel.schedules import (
            overlap_grad_sync,
        )

        for p in self.optimizer._parameter_list:
            g = getattr(p, "grad", None)
            if g is None or not hasattr(g, "_value"):
                continue
            sh = self._param_sharding(p)
            if any(e is not None for e in sh.spec):
                continue
            synced = overlap_grad_sync(g._value, self.mesh.jax_mesh,
                                       self.dp_axis)
            p.grad = Tensor(synced, stop_gradient=True)

    # ----------------------------------------------------------------- call
    def _shard_batch_tensors(self, batch):
        out = []
        for b in batch:
            if isinstance(b, Tensor):
                out.append(Tensor(shard_batch(self.mesh, b._value, self.batch_spec), stop_gradient=b.stop_gradient))
            else:
                out.append(shard_batch(self.mesh, b, self.batch_spec))
        return tuple(out)

    def __call__(self, *batch):
        batch = self._shard_batch_tensors(batch)
        if self._compiled is None:
            with self.mesh.jax_mesh:
                loss = self._eager_step(*batch)
                self._state = self._collect_state()
                self._place_state()
                self._build()
                # mesh lint BEFORE the first sharded dispatch: placements,
                # collective congruence, donation, per-device HBM estimate
                # — all abstract, so a dead-axis collective is a named
                # error here, never an 8-device rendezvous hang
                self._maybe_mesh_lint(batch)
            return loss
        with self.mesh.jax_mesh:
            return super().__call__(*batch)
