"""Per-chip HBM estimate for a transformer under a hybrid-parallel config
(reference: python/paddle/distributed/auto_tuner/memory_cost_model.py
get_metric_memory).

Accounts bytes the way the TPU ShardedTrainStep lays state out:
- params: bf16, sharded over mp, stacked stages over pp, and (if sharding
  aka zero-1/3) over the sharding axis
- grads: bf16 like params (over dp with zero-2+ they shard too)
- optimizer moments + fp32 master weights: sharded over dp*sharding (zero-1)
- activations: per microbatch, seq*hidden*layers-per-stage terms with the
  1F1B in-flight multiplier, divided by mp (tensor-parallel activations)
"""

from __future__ import annotations

__all__ = ["get_metric_memory"]


def _param_count(model_cfg):
    if "num_params" in model_cfg:
        return float(model_cfg["num_params"])
    h = model_cfg.get("hidden_size", 1024)
    l = model_cfg.get("num_layers", 12)
    v = model_cfg.get("vocab_size", 32000)
    inter = model_cfg.get("intermediate_size", 4 * h)
    per_layer = 4 * h * h + 3 * h * inter  # attn qkv/o + swiglu mlp
    return float(l * per_layer + v * h)


def get_metric_memory(model_cfg, cfg):
    """Estimated bytes per chip."""
    mp = cfg.get("mp_degree", 1)
    pp = cfg.get("pp_degree", 1)
    dp = cfg.get("dp_degree", 1)
    sh = cfg.get("sharding_degree", 1)
    stage = cfg.get("sharding_stage", 1)
    mbs = cfg.get("micro_batch_size", 1)
    recompute = cfg.get("use_recompute", False)

    n_params = _param_count(model_cfg)
    base = n_params / (mp * pp)  # per mp x pp shard, before data-axis sharding
    bytes_params = base * 2 / (max(dp * sh, 1) if stage >= 3 else 1)  # bf16
    bytes_grads = base * 2 / (max(dp * sh, 1) if stage >= 2 else 1)
    # zero-1: moments (2x fp32) + master weights (fp32) sharded over dp*sh
    bytes_opt = base * 12 / max(dp * sh, 1)

    h = model_cfg.get("hidden_size", 1024)
    l = model_cfg.get("num_layers", 12)
    s = model_cfg.get("seq_length", 2048)
    layers_per_stage = max(l // pp, 1)
    # bf16 activations per layer ≈ s*h*(16 + 2*inter/h) bytes without
    # recompute; with full recompute only boundary activations persist
    inter = model_cfg.get("intermediate_size", 4 * h)
    act_per_layer = s * h * (16 + 2 * inter / h) * 2 / mp
    if recompute:
        act_per_layer = s * h * 4 / mp  # boundary only
    inflight = min(pp, cfg.get("num_micro_batches", pp))  # 1F1B warmup depth
    bytes_act = mbs * act_per_layer * layers_per_stage * max(inflight, 1)

    overhead = 1.5 * (1024**3)  # XLA workspace + framework
    return bytes_params + bytes_grads + bytes_opt + bytes_act + overhead
