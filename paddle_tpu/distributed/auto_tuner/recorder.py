"""Trial recorder (reference: python/paddle/distributed/auto_tuner/
recorder.py — HistoryRecorder storing per-config metrics, sort + csv
export)."""

from __future__ import annotations

import csv

__all__ = ["HistoryRecorder"]


class HistoryRecorder:
    """reference recorder.py HistoryRecorder."""

    def __init__(self):
        self.history = []

    def add_cfg(self, **kwargs):
        self.history.append(dict(kwargs))

    def sort_metric(self, direction="max", metric="throughput"):
        self.history.sort(
            key=lambda c: c.get(metric) if c.get(metric) is not None else float("-inf"),
            reverse=(direction == "max"),
        )

    def get_best(self, metric="throughput", direction="max"):
        valid = [c for c in self.history if c.get(metric) is not None and not c.get("error")]
        if not valid:
            return None, True
        best = (max if direction == "max" else min)(valid, key=lambda c: c[metric])
        return best, False

    def store_history(self, path="./history.csv"):
        if not self.history:
            return
        keys = sorted({k for c in self.history for k in c})
        with open(path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=keys)
            w.writeheader()
            for c in self.history:
                w.writerow(c)

    def load_history(self, path="./history.csv"):
        def conv(v):
            if v == "":
                return None
            if v in ("True", "False"):
                return v == "True"
            for cast in (int, float):
                try:
                    return cast(v)
                except ValueError:
                    continue
            return v

        try:
            with open(path, newline="") as f:
                self.history = [{k: conv(v) for k, v in row.items()} for row in csv.DictReader(f)]
        except FileNotFoundError:
            pass
