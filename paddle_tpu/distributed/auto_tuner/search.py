"""Search algorithms (reference: python/paddle/distributed/auto_tuner/
search.py — GridSearch over default_candidates, pruned)."""

from __future__ import annotations

import itertools

from .prune import run_prunes

__all__ = ["GridSearch", "default_candidates"]


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def default_candidates(tuner_cfg):
    """reference utils.py default_candidates: derive axis candidates from
    device count + model config."""
    n = tuner_cfg.get("num_gpus") or tuner_cfg.get("num_devices", 8)
    cands = {
        "dp_degree": tuner_cfg.get("dp_degree", "auto"),
        "mp_degree": tuner_cfg.get("mp_degree", "auto"),
        "pp_degree": tuner_cfg.get("pp_degree", "auto"),
        "sharding_degree": tuner_cfg.get("sharding_degree", "auto"),
        "sharding_stage": tuner_cfg.get("sharding_stage", [1]),
        "micro_batch_size": tuner_cfg.get("micro_batch_size", "auto"),
        "use_recompute": tuner_cfg.get("use_recompute", [False, True]),
    }
    out = {}
    for k, v in cands.items():
        if v == "auto":
            if k == "micro_batch_size":
                gbs = tuner_cfg.get("model_cfg", {}).get("global_batch_size", 8)
                out[k] = _divisors(gbs)
            else:
                out[k] = _divisors(n)
        elif isinstance(v, (list, tuple)):
            out[k] = list(v)
        else:
            out[k] = [v]
    return out


class GridSearch:
    """reference search.py GridSearch: iterate the cartesian product in a
    fixed priority order, yielding unpruned configs."""

    def __init__(self, tuner_cfg):
        self.tuner_cfg = tuner_cfg
        cands = tuner_cfg.get("candidates") or default_candidates(tuner_cfg)
        keys = list(cands.keys())
        self._configs = [dict(zip(keys, vals)) for vals in itertools.product(*cands.values())]
        self._idx = 0

    def search_once(self, history_cfgs):
        while self._idx < len(self._configs):
            cfg = self._configs[self._idx]
            self._idx += 1
            if not run_prunes(self.tuner_cfg, cfg, history_cfgs):
                return cfg
        return None
