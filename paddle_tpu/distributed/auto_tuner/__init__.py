"""paddle.distributed.auto_tuner equivalent (reference:
python/paddle/distributed/auto_tuner/ — tuner.py AutoTuner, search.py
GridSearch, prune.py rule registry, memory_cost_model.py, recorder.py).

Searches hybrid-parallel configs (dp/mp/pp/sharding/micro-batch) for a
model + mesh, pruning invalid or memory-infeasible points with a TPU HBM
model, and records trial results.  TPU-first: the memory model counts
bf16 params/grads/master-weights and activation bytes per microbatch the
way a ShardedTrainStep lays them out (zero-1 optimizer sharding over dp,
params over mp, stacked stages over pp)."""

from .tuner import AutoTuner  # noqa: F401
from .search import GridSearch  # noqa: F401
from .prune import register_prune, prune_by_memory, prune_by_mp, prune_by_pp  # noqa: F401
from .recorder import HistoryRecorder  # noqa: F401
from .memory_cost_model import get_metric_memory  # noqa: F401

__all__ = [
    "AutoTuner", "GridSearch", "HistoryRecorder",
    "register_prune", "prune_by_memory", "prune_by_mp", "prune_by_pp",
    "get_metric_memory",
]
