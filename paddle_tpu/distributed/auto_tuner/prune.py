"""Prune rules (reference: python/paddle/distributed/auto_tuner/prune.py —
a registry of predicate functions applied to candidate configs)."""

from __future__ import annotations

_PRUNES = []

__all__ = ["register_prune", "prune_by_mp", "prune_by_pp", "prune_by_memory", "run_prunes"]


def register_prune(fn):
    """reference prune.py register_prune decorator."""
    _PRUNES.append(fn)
    return fn


def run_prunes(tuner_cfg, cfg, history):
    """True = prune (reject) this candidate."""
    return any(p(tuner_cfg, cfg, history) for p in _PRUNES)


@register_prune
def prune_by_num_gpus(tuner_cfg, cfg, history):
    n = tuner_cfg.get("num_gpus") or tuner_cfg.get("num_devices", 8)
    degree = (
        cfg.get("dp_degree", 1)
        * cfg.get("mp_degree", 1)
        * cfg.get("pp_degree", 1)
        * cfg.get("sharding_degree", 1)
    )
    return degree != n


@register_prune
def prune_by_mp(tuner_cfg, cfg, history):
    """mp must divide head count and hidden size (reference prune.py
    prune_by_mp)."""
    mp = cfg.get("mp_degree", 1)
    model = tuner_cfg.get("model_cfg", {})
    heads = model.get("num_attention_heads")
    hidden = model.get("hidden_size")
    if heads and heads % mp != 0:
        return True
    if hidden and hidden % mp != 0:
        return True
    vocab = model.get("vocab_size")
    if vocab and vocab % mp != 0:
        return True
    return False


@register_prune
def prune_by_pp(tuner_cfg, cfg, history):
    """pp must divide layer count; micro-batches must divide per-dp batch
    (reference prune.py prune_by_pp / prune_by_mbs)."""
    pp = cfg.get("pp_degree", 1)
    layers = tuner_cfg.get("model_cfg", {}).get("num_layers")
    if layers and layers % pp != 0:
        return True
    gbs = tuner_cfg.get("model_cfg", {}).get("global_batch_size")
    dp = cfg.get("dp_degree", 1) * cfg.get("sharding_degree", 1)
    mbs = cfg.get("micro_batch_size", 1)
    if gbs:
        if gbs % dp != 0:
            return True
        if (gbs // dp) % mbs != 0:
            return True
    return False


@register_prune
def prune_by_memory(tuner_cfg, cfg, history):
    """Reject configs whose estimated per-chip HBM exceeds the budget
    (reference prune.py prune_by_memory + memory_cost_model.py)."""
    from .memory_cost_model import get_metric_memory

    budget = tuner_cfg.get("max_mem_usage_gb", tuner_cfg.get("hbm_gb", 16))
    est = get_metric_memory(tuner_cfg.get("model_cfg", {}), cfg)
    return est > budget * (1024**3)


@register_prune
def prune_by_history(tuner_cfg, cfg, history):
    """Skip configs already tried (reference prune.py history check)."""
    key = tuple(sorted(cfg.items()))
    return any(tuple(sorted((k, v) for k, v in h.items() if k in cfg)) == key for h in history)
