"""AutoTuner driver (reference:
python/paddle/distributed/auto_tuner/tuner.py:19)."""

from __future__ import annotations

from .search import GridSearch, default_candidates

__all__ = ["AutoTuner"]


class AutoTuner:
    """reference tuner.py:19 — search_once()/add_cfg() protocol."""

    def __init__(self, tuner_cfg):
        self.cur_task_id = 1
        self.task_limit = tuner_cfg.get("task_limit", 100)
        algo = tuner_cfg.get("search_algo", {"name": "grid"})
        name = algo["name"] if isinstance(algo, dict) else algo
        if name != "grid":
            raise NotImplementedError(f"search_algo {name!r} (grid only)")
        tuner_cfg.setdefault("candidates", default_candidates(tuner_cfg))
        self.algo = GridSearch(tuner_cfg)
        self.history_cfgs = []

    def search_once(self):
        if self.cur_task_id > self.task_limit:
            return None
        cfg = self.algo.search_once(self.history_cfgs)
        self.cur_task_id += 1
        return cfg

    def add_cfg(self, cfg):
        self.history_cfgs.append(cfg)
