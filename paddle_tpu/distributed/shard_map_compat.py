"""shard_map across jax versions.

jax>=0.6 exposes ``jax.shard_map(f, mesh=, in_specs=, out_specs=,
axis_names=, check_vma=)``; older jax ships
``jax.experimental.shard_map.shard_map`` which takes ``check_rep`` instead
of ``check_vma`` and spells partial-manual as ``auto`` (the complement of
the manual axes) instead of ``axis_names``.  This adapter translates the
new-style kwargs the callers in this package use, so a jax<0.6 runtime
runs them instead of failing at import or with an opaque TypeError.
"""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map_new

    _NEW_API = True
except ImportError:  # jax<0.6
    from jax.experimental.shard_map import shard_map as _shard_map_old

    _NEW_API = False

__all__ = ["shard_map", "axis_size"]


def axis_size(axis_name):
    """``jax.lax.axis_size`` across jax versions: jax<0.6 has no axis_size;
    ``psum(1, axis)`` constant-folds to the mapped axis size there."""
    import jax.lax as _lax

    if hasattr(_lax, "axis_size"):
        return _lax.axis_size(axis_name)
    return _lax.psum(1, axis_name)


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=None, **kwargs):
    if _NEW_API:
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return _shard_map_new(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kwargs)
    if axis_names is not None and set(axis_names) != set(mesh.axis_names):
        # The old API spells partial-manual as `auto` = the complement set,
        # but its partial-auto tracing has no autodiff rules (jvp raises
        # NotImplementedError), so callers that differentiate through the
        # region (pipeline 1F1B) cannot use it.  Full-manual is semantically
        # safe here instead: specs never mention the would-be-auto axes, so
        # inputs replicate and outputs are per-rank identical over them —
        # at worst duplicated compute on those axes, never wrong values.
        kwargs["check_rep"] = False
    elif check_vma is not None:
        kwargs["check_rep"] = check_vma
    return _shard_map_old(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)
