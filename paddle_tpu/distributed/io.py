"""paddle.distributed.io (reference: python/paddle/distributed/io.py):
persistable save/load helpers for distributed programs."""

from __future__ import annotations

__all__ = ["save_persistables", "load_persistables", "is_persistable"]


def is_persistable(var) -> bool:
    return bool(getattr(var, "persistable", False))


def save_persistables(executor, dirname, main_program=None, filename=None):
    """Save a static Program's persistables (reference io.py
    save_persistables) through static.io's serializer."""
    import os

    from paddle_tpu.static.compat import serialize_persistables, save_to_file

    os.makedirs(dirname, exist_ok=True)
    blob = serialize_persistables(None, None, executor, main_program)
    save_to_file(os.path.join(dirname, filename or "__params__"), blob)


def load_persistables(executor, dirname, main_program=None, filename=None):
    import os

    from paddle_tpu.static.compat import deserialize_persistables, load_from_file

    blob = load_from_file(os.path.join(dirname, filename or "__params__"))
    return deserialize_persistables(main_program, blob, executor)
