"""paddle.distributed.launch equivalent — see main.py."""

from .main import launch, main  # noqa: F401

__all__ = ["launch", "main"]
