"""Distributed launcher (reference: python/paddle/distributed/launch/main.py:20
`launch()`, controllers/collective.py, controllers/master.py).

`python -m paddle_tpu.distributed.launch [--nnodes N] [--nproc_per_node P]
[--master host:port] [--rank R] [--log_dir dir] [--elastic_level L]
script.py args...`

TPU-native redesign: the HTTP/etcd master is replaced by the framework's
own native TCPStore (paddle_tpu/_native/tcp_store.cc) — rank 0's launcher
hosts it; every launcher registers its pod, barriers, then spawns local
worker processes with the PADDLE_* / jax.distributed environment.  On TPU
pods the normal deployment is one process per host (nproc_per_node=1) and
XLA owns intra-host chips; nproc_per_node>1 is the CPU/debug path."""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
import uuid

__all__ = ["launch", "main", "terminate_procs"]


def _parse_args(argv=None):
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch")
    p.add_argument("--master", default=None, help="rank0 endpoint host:port (TCPStore)")
    p.add_argument("--nnodes", type=int, default=1, help="number of nodes (pods)")
    p.add_argument("--rank", type=int, default=None, help="this node's rank; -1 = auto-assign")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--log_dir", default="log")
    p.add_argument("--job_id", default=None)
    p.add_argument("--devices", default=None, help="visible device ids, comma-separated")
    p.add_argument("--elastic_level", type=int, default=0,
                   help=">0: restart failed local workers up to this many times")
    p.add_argument("--run_mode", default="collective")
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class _Master:
    """Rendezvous over the native TCPStore: node rank assignment + barrier +
    worker endpoint exchange (reference controllers/master.py HTTP/etcd
    masters).

    Exactly one launcher — the one started with --rank 0 — hosts the store
    (it must be up before peers can connect, so it always claims rank 0
    first).  Other launchers either pass an explicit distinct rank or omit
    --rank to auto-assign; auto-assigned ranks start at 1 because rank 0
    is always the host's.  Mixing auto-assign with explicit ranks > 0 is
    not supported."""

    def __init__(self, endpoint, nnodes, is_host):
        from paddle_tpu.distributed.bootstrap import host_or_connect
        from paddle_tpu.distributed.communication.watchdog import set_rendezvous_store

        self.nnodes = nnodes
        self.server, self.client = host_or_connect(endpoint, is_host)
        # cross-rank static checks (watchdog.static_check) ride this store
        set_rendezvous_store(self.client)

    def assign_rank(self, requested):
        if requested is not None and requested >= 0:
            return requested
        # counter yields 1, 2, ... — rank 0 is always the hosting launcher
        return self.client.add("launch/next_rank", 1)

    def barrier(self, key, n):
        from paddle_tpu.distributed.bootstrap import store_barrier

        store_barrier(self.client, f"launch/{key}", n)

    def put(self, key, value: str):
        self.client.set(key, value.encode())

    def get(self, key) -> str:
        return self.client.get(key, timeout_ms=600_000).decode()

    def close(self):
        self.client.close()
        if self.server:
            self.server.stop()


def _local_ip():
    import socket

    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


_CACHED_FREE_PORT = None


def _free_port():
    # one stable port per launcher process so all local workers agree
    global _CACHED_FREE_PORT
    if _CACHED_FREE_PORT is None:
        import socket

        with socket.socket() as s:
            s.bind(("", 0))
            _CACHED_FREE_PORT = s.getsockname()[1]
    return _CACHED_FREE_PORT


def _worker_env(args, node_rank, local_rank, world_size, master_host):
    env = dict(os.environ)
    global_rank = node_rank * args.nproc_per_node + local_rank
    coord_port = int(os.environ.get("PADDLE_COORD_PORT", "8476"))
    env.update(
        PADDLE_TRAINER_ID=str(global_rank),
        PADDLE_TRAINERS_NUM=str(world_size),
        PADDLE_LOCAL_RANK=str(local_rank),
        PADDLE_LOCAL_SIZE=str(args.nproc_per_node),
        PADDLE_NNODES=str(args.nnodes),
        PADDLE_NODE_RANK=str(node_rank),
        PADDLE_MASTER=f"{master_host}:{coord_port}",
        MASTER_ADDR=master_host,
        MASTER_PORT=str(coord_port),
        RANK=str(global_rank),
        WORLD_SIZE=str(world_size),
        PADDLE_JOB_ID=args.job_id or "default",
        POD_IP=os.environ.get("POD_IP", _local_ip()),
        PADDLE_MASTER_ENDPOINT=(args.master if args.master else f"{master_host}:{_free_port()}"),
    )
    if args.devices is not None:
        devs = args.devices.split(",")
        env["TPU_VISIBLE_DEVICES"] = devs[local_rank % len(devs)]
    return env


def _spawn(args, node_rank, world_size, master_host):
    os.makedirs(args.log_dir, exist_ok=True)
    procs = []
    for lr in range(args.nproc_per_node):
        env = _worker_env(args, node_rank, lr, world_size, master_host)
        grank = env["PADDLE_TRAINER_ID"]
        logf = open(os.path.join(args.log_dir, f"workerlog.{grank}"), "ab")
        cmd = [sys.executable, "-u", args.script, *args.script_args]
        procs.append((subprocess.Popen(cmd, env=env, stdout=logf, stderr=subprocess.STDOUT), logf))
    return procs


def terminate_procs(procs, grace_s=10):
    """SIGTERM every live worker, wait up to `grace_s` total, SIGKILL the
    stragglers, close their log files.  `procs` is [(Popen, logfile)].
    Shared by the launcher's watch loop and the serving cluster's
    shutdown/elastic paths (serving/cluster.py) — one definition of
    'stop these workers cleanly, then forcefully'."""
    for p, _ in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.time() + grace_s
    for p, logf in procs:
        try:
            p.wait(max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
        logf.close()


_kill = terminate_procs


def launch(argv=None):
    """Entry (reference launch/main.py:20)."""
    args = _parse_args(argv)
    args.job_id = args.job_id or f"job-{uuid.uuid4().hex[:8]}"
    world_size = args.nnodes * args.nproc_per_node

    if args.nnodes > 1:
        if args.master is None:
            raise SystemExit("--master host:port is required for multi-node launch")
        host = args.master.split(":")[0]
        # node rank 0 hosts the store; detect by explicit --rank 0 or local ip
        is_host = args.rank == 0
        master = _Master(args.master, args.nnodes, is_host)
        node_rank = master.assign_rank(args.rank)
        master.put(f"launch/node/{node_rank}", os.uname().nodename)
        master.barrier("start", args.nnodes)
        master_host = host
    else:
        master = None
        node_rank = 0
        master_host = "127.0.0.1"

    attempts = 0
    status = 0
    while True:
        procs = _spawn(args, node_rank, world_size, master_host)
        status = _watch(procs)
        if status == 0:
            break
        attempts += 1
        if attempts > args.elastic_level:
            break
        print(f"[launch] workers failed (exit {status}); restart {attempts}/{args.elastic_level}",
              flush=True)
        time.sleep(2)

    if master:
        master.barrier("finish", args.nnodes)
        master.close()
    return status


def _watch(procs):
    """Monitor workers; on any failure kill the rest (reference
    controllers/controller.py watch loop)."""
    try:
        while True:
            alive = False
            for p, _ in procs:
                rc = p.poll()
                if rc is None:
                    alive = True
                elif rc != 0:
                    _kill(procs)
                    return rc
            if not alive:
                return 0
            time.sleep(0.5)
    except KeyboardInterrupt:
        _kill(procs)
        return 130


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
