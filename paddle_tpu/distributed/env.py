"""Process/environment info for distributed runs.

Reference: python/paddle/distributed/parallel.py (env-var driven rank info,
init_parallel_env at :943 building TCPStore + ProcessGroups).  TPU-native:
jax.distributed is the coordination service (TCPStore equivalent); under
single-controller SPMD, world size is the device count, and "rank" for IO
sharding purposes is the process index.
"""

from __future__ import annotations

import os

import jax

__all__ = [
    "get_rank",
    "get_world_size",
    "init_parallel_env",
    "is_initialized",
    "parallel_device_count",
    "ParallelEnv",
]

_initialized = False


def init_parallel_env():
    """Initialize multi-host coordination (jax.distributed).  Single-host /
    single-process runs are already 'initialized' — SPMD needs no process
    group objects; collectives compile into the program."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", "1")))
    proc_id = int(os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK", "0")))
    if coord and nprocs > 1:
        port = os.environ.get("MASTER_PORT", "8476")
        jax.distributed.initialize(
            coordinator_address=f"{coord.split(':')[0]}:{port}",
            num_processes=nprocs,
            process_id=proc_id,
        )
    _initialized = True
    return ParallelEnv()


def is_initialized() -> bool:
    return _initialized


def get_rank(group=None) -> int:
    if group is not None:
        return group.get_group_rank(jax.process_index())
    return jax.process_index()


def get_world_size(group=None) -> int:
    if group is not None:
        return group.world_size
    return int(os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", jax.process_count())))


def parallel_device_count() -> int:
    return jax.device_count()


class ParallelEnv:
    """Reference paddle.distributed.ParallelEnv surface."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return int(os.environ.get("FLAGS_selected_tpus", "0"))

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "127.0.0.1:6170").split(",")

    @property
    def nrings(self):
        return 1
