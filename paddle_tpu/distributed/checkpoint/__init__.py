"""Distributed checkpoint: sharded save + cross-topology reshard-on-load.

Reference: python/paddle/distributed/checkpoint/save_state_dict.py:77 and
load_state_dict.py — each rank writes its local shards plus a global
metadata file; load reshards when the parallel topology changed.

TPU-native: a sharded value is a jax.Array with a (Named)Sharding; its
`addressable_shards` give (device, index, data) directly, so save writes one
npz per process holding every locally-addressable unique shard.  Load builds
the target jax.Array with `jax.make_array_from_callback(target_sharding)` —
the callback assembles each requested region from whichever saved shards
overlap it (pure slicing math, no collectives), which IS reshard-on-load for
any source/target topology pair.  `async_save` offloads file IO to a
background thread after device→host transfer.
"""

from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu._core.tensor import Tensor
from paddle_tpu.framework.io_utils import (atomic_write, spawn_async_write,
                                           wait_async_save)
from .metadata import Metadata, ShardRecord, TensorMetadata

__all__ = [
    "save_state_dict",
    "load_state_dict",
    "Metadata",
    "CheckpointManager",
    "checkpoint_stats",
    "wait_async_save",
]

_META_FILE = "metadata.json"


def _proc_index() -> int:
    return jax.process_index()


def _flat_entries(state_dict, prefix=""):
    """Yield (flat_name, container, key, value) so loaders can write back
    into the caller's (possibly nested) dict."""
    out = []
    for k, v in state_dict.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.extend(_flat_entries(v, key + "."))
        elif v is None:
            continue
        else:
            out.append((key, state_dict, k, v))
    return out


def _flatten_state(state_dict, prefix=""):
    return {name: v for name, _, _, v in _flat_entries(state_dict, prefix)}


def _unique_shards(arr: jax.Array):
    """Yield (global_offset, np_data) for each distinct shard this process
    can address (replicas deduped)."""
    seen = set()
    for sh in arr.addressable_shards:
        idx = sh.index  # tuple of slices
        offset = tuple(0 if s.start is None else int(s.start) for s in idx)
        if offset in seen:
            continue
        seen.add(offset)
        yield offset, np.asarray(sh.data)


def build_shard_snapshot(state_dict, fname=None):
    """Device→host snapshot of a (possibly nested) state dict: returns
    (arrays, metadata) where `arrays` maps npz keys to host numpy copies of
    every locally-addressable unique shard and `metadata` records their
    global placement.  This is the synchronous half of a save — once it
    returns, training may mutate the live tensors; writing the snapshot to
    disk can happen on a background thread (CheckpointManager does exactly
    that)."""
    if fname is None:
        fname = f"data_rank{_proc_index()}.npz"
    flat = _flatten_state(state_dict)
    md = Metadata()
    arrays = {}
    for name, t in flat.items():
        val = t._value if isinstance(t, Tensor) else jnp.asarray(t)
        if not hasattr(val, "addressable_shards"):
            val = jnp.asarray(val)
        tm = TensorMetadata(name, list(val.shape), str(np.dtype(val.dtype)))
        for offset, data in _unique_shards(val):
            key = f"{name}@{'_'.join(map(str, offset))}"
            arrays[key] = data
            tm.shards.append(
                ShardRecord(fname, key, list(offset), list(data.shape))
            )
        md.tensors[name] = tm
    return arrays, md, fname


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0, async_save=False, unique_id=None):
    """Write `path/data_rank{R}.npz` + `path/metadata.json`.

    Each file is written atomically (temp file + os.replace, the
    framework.io_utils.save protocol), so neither can be individually torn
    by a crash.  NOTE the npz/metadata PAIR is not transactional: a crash
    between the two replaces of an overwrite-in-place re-save can leave new
    shards with old metadata — whole-checkpoint atomicity (fresh dir +
    manifest + single rename) is CheckpointManager's job.  The async path
    runs on a SUPERVISED thread: join it via the returned Thread or
    `wait_async_save()`, which re-raises any background failure instead of
    losing the checkpoint silently."""
    os.makedirs(path, exist_ok=True)
    rank = _proc_index()
    arrays, md, fname = build_shard_snapshot(state_dict)

    def _write():
        with atomic_write(os.path.join(path, fname)) as f:
            np.savez(f, **arrays)
        if rank == coordinator_rank:
            # NOTE multi-host: ranks would first all-gather shard records;
            # single-controller JAX already addresses every shard here.
            with atomic_write(os.path.join(path, _META_FILE), "w") as f:
                f.write(md.to_json())

    if async_save:
        return spawn_async_write(_write, path)
    _write()
    return None


class _LazyFiles:
    def __init__(self, path):
        self.path = path
        self._files = {}

    def get(self, fname, key):
        if fname not in self._files:
            self._files[fname] = np.load(os.path.join(self.path, fname))
        return self._files[fname][key]


def _assemble_region(tm: TensorMetadata, files: _LazyFiles, region):
    """region: tuple of slices into the global tensor; gather overlapping
    saved shards into one np array."""
    starts = [0 if s.start is None else int(s.start) for s in region]
    stops = [int(dim) if s.stop is None else int(s.stop) for s, dim in zip(region, tm.global_shape)]
    shape = [b - a for a, b in zip(starts, stops)]
    out = np.zeros(shape, dtype=np.dtype(tm.dtype))
    filled = np.zeros(shape, dtype=bool) if tm.shards else None
    for rec in tm.shards:
        r_starts = rec.global_offset
        r_stops = [o + s for o, s in zip(rec.global_offset, rec.local_shape)]
        inter_start = [max(a, ra) for a, ra in zip(starts, r_starts)]
        inter_stop = [min(b, rb) for b, rb in zip(stops, r_stops)]
        if any(a >= b for a, b in zip(inter_start, inter_stop)):
            continue
        src = files.get(rec.file, rec.key)
        if src.dtype.kind == "V" and src.dtype.itemsize == out.dtype.itemsize:
            # npz round-trips extension dtypes (ml_dtypes bfloat16) as raw
            # void records; the bytes are exact — view them back
            src = src.view(out.dtype)
        src_slices = tuple(
            slice(a - ro, b - ro) for a, b, ro in zip(inter_start, inter_stop, r_starts)
        )
        dst_slices = tuple(
            slice(a - so, b - so) for a, b, so in zip(inter_start, inter_stop, starts)
        )
        out[dst_slices] = src[src_slices]
        if filled is not None:
            filled[dst_slices] = True
    if filled is not None and not filled.all():
        raise ValueError(f"checkpoint is missing data for tensor '{tm.name}' region {region}")
    return out


def load_state_dict(state_dict, path, process_group=None, coordinator_rank=0, unique_id=None):
    """Fill `state_dict`'s tensors in place from the checkpoint at `path`,
    resharding to each tensor's CURRENT sharding (possibly a different mesh/
    placement/world size than at save time)."""
    with open(os.path.join(path, _META_FILE)) as f:
        md = Metadata.from_json(f.read())
    files = _LazyFiles(path)

    for name, container, key, t in _flat_entries(state_dict):
        if name not in md.tensors:
            raise KeyError(f"tensor '{name}' not found in checkpoint {path}")
        tm = md.tensors[name]
        target = t._value if isinstance(t, Tensor) else t
        if list(target.shape) != list(tm.global_shape):
            raise ValueError(
                f"shape mismatch for '{name}': state {list(target.shape)} vs saved {tm.global_shape}"
            )
        sharding = getattr(target, "sharding", None)
        dtype = target.dtype

        if sharding is not None and hasattr(sharding, "device_set") and len(sharding.device_set) > 1:
            def cb(index, tm=tm, dtype=dtype):
                return _assemble_region(tm, files, index).astype(dtype)

            new_val = jax.make_array_from_callback(tuple(tm.global_shape), sharding, cb)
        else:
            full = tuple(slice(0, d) for d in tm.global_shape)
            new_val = jnp.asarray(_assemble_region(tm, files, full), dtype=dtype)
            if sharding is not None:
                new_val = jax.device_put(new_val, sharding)
        if isinstance(t, Tensor):
            t._bind(new_val)
        else:
            container[key] = new_val
    return state_dict


from .manager import CheckpointManager, checkpoint_stats  # noqa: E402
